"""Pipeline parallelism: layer sharding over ``pipe``, composed with tp/dp.

Stacked layer params ([L, ...] leading dim) shard over ``pipe`` so each
stage holds L/n_stages layers; activations travel stage-to-stage with
``lax.ppermute`` (neighbor ICI hop). The shard_map is **partial-manual**
(``axis_names={pipe}``): only the pipe axis is manual, every other mesh
axis (model/data/slice/seq) stays in GSPMD's hands, so tensor-parallel
weights keep their Megatron PartitionSpecs *inside* each stage and XLA
inserts the tp collectives — pp×tp×dp composition without hand-written
per-axis communication. Each stage body runs under ``auto_axes`` so the
unmodified model block code compiles exactly as it does in the plain
GSPMD train step.

Two schedules, one loop:

- ``n_chunks=1`` — classic gpipe: T = n_micro + n_stages - 1 ticks, ramp
  garbage (n_stages-1) full-stage ticks.
- ``n_chunks=v>1`` — interleaved/circular (the Megatron-LM interleaved
  schedule, arXiv:2104.04473 §2.2, expressed as a static SPMD ring): each
  stage holds v non-contiguous layer chunks (virtual stage j = c·S + s),
  microbatches hop the ring v times, one chunk application per tick. Per
  tick each device computes 1/v of a stage, so the compute-then-discard
  ramp shrinks from (S-1) stage-ticks to (S-1) *chunk*-ticks — v× less
  wasted FLOPs — at the cost of (v-1) extra ring round-trips of ppermute
  traffic (tiny: one activation block per hop, on ICI).

Schedule derivation (why one in-flight state per device suffices): device
s's local item counter is k = t - s; item k is (round r, chunk c, slot i)
= (k // (v·S·?)…) — concretely r = k // (v·S), c = (k % (v·S)) // S,
i = k % S, micro = r·S + i. Stage s+1 runs the same item one tick later,
and the wrap from stage S-1 chunk c to stage 0 chunk c+1 also lands
exactly one tick later, so the state ppermuted each tick is always the
one consumed next tick. Requires n_micro % n_stages == 0 (Megatron's
constraint) and n_layers % (n_stages·n_chunks) == 0.

Everything is shape-static and differentiable (ppermute transposes to the
reverse permutation; dynamic_index transposes to scatter-add), so the same
construct serves the training backward pass.

Embedding and the LM head are cheap relative to blocks and stay outside
the pipeline (sharded by their own tp specs); only the decoder blocks are
staged.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .topology import AXIS_PIPE


def interleave_layer_order(n_layers: int, n_stages: int,
                           n_chunks: int) -> list[int]:
    """Physical storage order for the stacked layer dim such that a plain
    contiguous P(pipe) shard of the leading dim hands stage s exactly its
    virtual stages {c·n_stages + s : c}. new_position (s, c, l) holds
    logical layer (c·n_stages + s)·Lv + l."""
    lv = n_layers // (n_stages * n_chunks)
    order = []
    for s in range(n_stages):
        for c in range(n_chunks):
            base = (c * n_stages + s) * lv
            order.extend(range(base, base + lv))
    return order


def to_pipeline_layout(blocks, n_layers: int, n_stages: int, n_chunks: int):
    """Permute stacked block params from logical layer order into the
    interleaved storage order (no-op permutation for n_chunks=1)."""
    idx = jnp.array(interleave_layer_order(n_layers, n_stages, n_chunks))
    return jax.tree.map(lambda a: a[idx], blocks)


def from_pipeline_layout(blocks, n_layers: int, n_stages: int, n_chunks: int):
    """Inverse of to_pipeline_layout (checkpoint export back to logical)."""
    order = interleave_layer_order(n_layers, n_stages, n_chunks)
    inv = [0] * n_layers
    for new, old in enumerate(order):
        inv[old] = new
    idx = jnp.array(inv)
    return jax.tree.map(lambda a: a[idx], blocks)


def pipeline_apply(stage_fn: Callable, n_chunks: int, n_micro: int,
                   stage_params, x_micro, *, axis_name: str = AXIS_PIPE):
    """Run microbatches through the stage ring (inside partial-manual
    shard_map over ``axis_name``).

    stage_fn(chunk_params, x) -> y : applies ONE chunk's layers; chunk
    params arrive as ``stage_params`` leading-dim slices of size
    layers_per_chunk (stage_params: [n_chunks·layers_per_chunk, ...]).
    x_micro: [n_micro, mb, ...] (stage 0 consumes it; other stages see the
    same array — partial-manual keeps it unsplit over pipe). Returns
    [n_micro, mb, ...] with every stage holding the final outputs
    (broadcast from the last stage via psum so the loss runs replicated).
    """
    n_stages = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    # micro-count divisibility is Megatron's interleaving constraint; the
    # v=1 gpipe schedule (micro = k) takes any n_micro
    assert n_chunks == 1 or n_micro % n_stages == 0, (n_micro, n_stages)
    ticks = n_micro * n_chunks + n_stages - 1
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    # reshape this stage's layers into chunks: [v, Lv, ...]
    chunked = jax.tree.map(
        lambda a: a.reshape(n_chunks, a.shape[0] // n_chunks, *a.shape[1:]),
        stage_params)

    state = jnp.zeros_like(x_micro[0])
    outputs = jnp.zeros_like(x_micro)

    for t in range(ticks):                       # static schedule
        k = t - stage                            # this device's item counter
        valid = jnp.logical_and(k >= 0, k < n_micro * n_chunks)
        kc = jnp.clip(k, 0, n_micro * n_chunks - 1)
        r = kc // (n_chunks * n_stages)
        c = (kc % (n_chunks * n_stages)) // n_stages
        i = kc % n_stages
        micro = r * n_stages + i

        # stage 0 chunk 0 feeds fresh microbatches; everyone else consumes
        # the state that arrived via ppermute last tick
        feeding = jnp.logical_and(stage == 0, c == 0)
        fresh = lax.dynamic_index_in_dim(x_micro, micro, 0, keepdims=False)
        state_in = jnp.where(feeding, fresh, state)

        chunk_params = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
            chunked)
        y = stage_fn(chunk_params, state_in)

        # last stage, last chunk: this micro is done
        done = jnp.logical_and(
            valid, jnp.logical_and(stage == n_stages - 1, c == n_chunks - 1))
        outputs = lax.dynamic_update_index_in_dim(
            outputs,
            jnp.where(done,
                      y,
                      lax.dynamic_index_in_dim(outputs, micro, 0,
                                               keepdims=False)),
            micro, 0)
        state = lax.ppermute(y, axis_name, perm)

    # broadcast final outputs from the last stage to every stage. f32 for
    # the wire: XLA CPU's ChangeOpDataType pass CHECK-fails cloning a bf16
    # all-reduce out of a manual subgroup (compiler bug); on TPU the cast
    # is fused and the psum rides ICI either way.
    outputs = jnp.where(stage == n_stages - 1, outputs, 0.0)
    return lax.psum(outputs.astype(jnp.float32),
                    axis_name).astype(x_micro.dtype)


def pipelined_blocks(block_fn: Callable, mesh, n_layers: int, n_micro: int,
                     n_chunks: int = 1, state_spec: P = None):
    """Wrap a scanned-block body into a pipelined apply over the mesh.

    block_fn(layer_params, x) -> x : ONE layer (unmodified model code — it
    runs under auto_axes, so tp specs on the weights behave exactly as in
    the plain GSPMD step).
    Returns fn(blocks_stacked, x [B, S, ...]) -> same shape, where
    ``blocks_stacked`` has leading dim L in **interleaved storage order**
    (to_pipeline_layout) sharded over ``pipe``; remaining dims keep their
    tensor-parallel specs. The batch splits into n_micro microbatches.
    ``state_spec`` is the per-micro activation sharding over the NON-pipe
    axes (defaults to batch over (slice, data)).
    """
    from .topology import AXIS_DATA, AXIS_SLICE

    n_stages = mesh.shape[AXIS_PIPE]
    assert n_layers % (n_stages * n_chunks) == 0, \
        (n_layers, n_stages, n_chunks)

    if state_spec is None:
        state_spec = P((AXIS_SLICE, AXIS_DATA))

    auto = tuple(n for n in mesh.axis_names if n != AXIS_PIPE)

    def stage_fn(chunk_params, x):
        def body(h, lp):
            return block_fn(lp, h), None

        def chunk(chunk_params, x):
            out, _ = lax.scan(body, x, chunk_params)
            return out
        # auto_axes over every NON-pipe axis: hand them back to GSPMD for
        # the chunk body so tp collectives are inferred (pipe itself stays
        # manual), then pin the carry back to its explicit sharding (the
        # scan-carry type must be stable).
        return jax.sharding.auto_axes(
            chunk, axes=auto, out_sharding=state_spec)(chunk_params, x)

    def apply(blocks_stacked, x):
        from jax.sharding import NamedSharding

        B = x.shape[0]
        assert B % n_micro == 0, (B, n_micro)
        micro = x.reshape(n_micro, B // n_micro, *x.shape[1:])
        micro = jax.lax.with_sharding_constraint(
            micro, NamedSharding(mesh, P(*([None] + list(state_spec)))))
        # Partial-manual: in/out specs name ONLY the manual (pipe) axis;
        # the tp/dp/sp shardings ride the arrays themselves and stay under
        # GSPMD inside the region.
        out = jax.shard_map(
            partial(pipeline_apply, stage_fn, n_chunks, n_micro),
            mesh=mesh,
            in_specs=(jax.tree.map(lambda _: P(AXIS_PIPE), blocks_stacked),
                      P()),
            out_specs=P(),
            axis_names={AXIS_PIPE},
            check_vma=False,
        )(blocks_stacked, micro)
        return out.reshape(B, *x.shape[1:])

    return apply
