"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context path for the flagship workload (SURVEY.md §5 "long-context /
sequence parallelism" — absent in the reference; first-class here). Q stays
put; K/V blocks rotate around the ``seq`` mesh axis via ``lax.ppermute``
(ICI neighbor exchange), with flash-style running-max/denominator
accumulation in fp32 so the result is exact regardless of ring order.
Compute for step i overlaps the collective for step i+1 under XLA's
latency-hiding scheduler — communication cost ~ O(S/n per step), matching
the blockwise-parallel formulation in PAPERS.md (Liu et al., ring attention).

Used inside ``shard_map`` (models/train.py); each device sees its local
[B, S/n, H, D] block. GQA is handled by repeating K/V heads locally.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1.0e30  # mask value; finite so exp() underflows instead of NaN-ing


def _block_attn(q, k, v, q_pos, kv_pos, scale, causal):
    """One Q-block × KV-block flash partial: returns (o, m, l) in fp32.

    q: [B, Sq, H, D]   k/v: [B, Sk, H, D]   positions: [Sq], [Sk]
    """
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if causal:
        mask = q_pos[:, None] >= kv_pos[None, :]          # [Sq, Sk]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                               # [B, H, Sq]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m == NEG_INF → p rows are exp(0)=1 garbage; zero them
    p = jnp.where((m > NEG_INF / 2)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)                               # [B, H, Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o, m, l


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   scale: float | None = None):
    """Exact attention with K/V rotating around ``axis_name``.

    Args (per-device blocks, inside shard_map):
      q: [B, Sq, Hq, D] — local query block (global seq sharded over axis)
      k, v: [B, Sk, Hkv, D] — local key/value block
    Returns [B, Sq, Hq, D] in q.dtype.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Sq, Hq, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    if scale is None:
        scale = D ** -0.5
    if Hq != Hkv:                                          # GQA: repeat KV heads
        rep = Hq // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    q_pos = my * Sq + jnp.arange(Sq)
    perm = [(i, (i + 1) % n) for i in range(n)]            # shard i → i+1

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        kv_block = (my - i) % n                            # whose block we hold
        kv_pos = kv_block * Sk + jnp.arange(Sk)
        o_i, m_i, l_i = _block_attn(q, k_cur, v_cur, q_pos, kv_pos, scale, causal)
        m_new = jnp.maximum(m, m_i)
        c_old = jnp.exp(m - m_new)                         # [B, H, Sq]
        c_new = jnp.exp(m_i - m_new)
        l = l * c_old + l_i * c_new
        o = o * c_old.transpose(0, 2, 1)[..., None] \
            + o_i * c_new.transpose(0, 2, 1)[..., None]
        if n > 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        return o, m_new, l, k_cur, v_cur

    o0 = jnp.zeros((B, Sq, Hq, D), jnp.float32)
    m0 = jnp.full((B, Hq, Sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hq, Sq), jnp.float32)
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v))

    l = l.transpose(0, 2, 1)[..., None]                    # [B, Sq, H, 1]
    o = o / jnp.where(l > 0, l, 1.0)
    return o.astype(q.dtype)


def dense_attention(q, k, v, *, causal: bool = True,
                    scale: float | None = None):
    """Single-device exact attention (same contract, no mesh axis) — the
    n=1 specialization used by entry()'s single-chip forward."""
    D = q.shape[-1]
    if scale is None:
        scale = D ** -0.5
    Hq, Hkv = q.shape[2], k.shape[2]
    if Hq != Hkv:
        rep = Hq // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        mask = jnp.arange(Sq)[:, None] >= jnp.arange(Sk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)
