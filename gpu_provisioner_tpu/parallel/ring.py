"""Ring attention: exact causal attention over a sequence-sharded axis.

Long-context path for the flagship workload (SURVEY.md §5 "long-context /
sequence parallelism" — absent in the reference; first-class here). Q stays
put; K/V blocks rotate around the ``seq`` mesh axis via ``lax.ppermute``
(ICI neighbor exchange), with flash-style running-max/denominator
accumulation in fp32 so the result is exact regardless of ring order.
Compute for step i overlaps the collective for step i+1 under XLA's
latency-hiding scheduler — communication cost ~ O(S/n per step), matching
the blockwise-parallel formulation in PAPERS.md (Liu et al., ring attention).

Used inside ``shard_map`` (models/train.py); each device sees its local
[B, S/n, H, D] block. GQA is handled by repeating K/V heads locally.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1.0e30  # mask value; finite so exp() underflows instead of NaN-ing


def _block_attn(q, k, v, q_pos, kv_pos, scale, causal):
    """One Q-block × KV-block flash partial: returns (o, m, l) in fp32.

    q: [B, Sq, H, D]   k/v: [B, Sk, H, D]   positions: [Sq], [Sk]
    """
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * scale
    if causal:
        mask = q_pos[:, None] >= kv_pos[None, :]          # [Sq, Sk]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                               # [B, H, Sq]
    p = jnp.exp(s - m[..., None])
    # fully-masked rows: m == NEG_INF → p rows are exp(0)=1 garbage; zero them
    p = jnp.where((m > NEG_INF / 2)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)                               # [B, H, Sq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o, m, l


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   scale: float | None = None, impl: str = "dense"):
    """Exact attention with K/V rotating around ``axis_name``.

    Args (per-device blocks, inside shard_map):
      q: [B, Sq, Hq, D] — local query block (global seq sharded over axis)
      k, v: [B, Sk, Hkv, D] — local key/value block
    Returns [B, Sq, Hq, D] in q.dtype.

    ``impl="flash"`` runs each ring step's local attention through the
    Pallas flash kernel (ops/flash_attention.py) instead of the dense
    einsum. The global causal mask decomposes per step by block position:
    the step whose K/V block sits on this device's diagonal is a local
    causal call, blocks from earlier positions are full (non-causal)
    calls, later blocks contribute nothing — a 3-way ``lax.switch`` on the
    traced block index. Partials merge by their logsumexp (the kernel
    emits it; its cotangent folds into Δ in the backward), so the result
    is exact and the O(S_local²) inner work gets the same 2-3× the
    single-chip kernel shows.
    """
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, Sq, Hq, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    if scale is None:
        scale = D ** -0.5
    if impl == "flash":
        return _ring_flash(q, k, v, axis_name=axis_name, causal=causal,
                           scale=scale, n=n, my=my)
    if Hq != Hkv:                                          # GQA: repeat KV heads
        rep = Hq // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    q_pos = my * Sq + jnp.arange(Sq)
    perm = [(i, (i + 1) % n) for i in range(n)]            # shard i → i+1

    def step(i, carry):
        o, m, l, k_cur, v_cur = carry
        kv_block = (my - i) % n                            # whose block we hold
        kv_pos = kv_block * Sk + jnp.arange(Sk)
        o_i, m_i, l_i = _block_attn(q, k_cur, v_cur, q_pos, kv_pos, scale, causal)
        m_new = jnp.maximum(m, m_i)
        c_old = jnp.exp(m - m_new)                         # [B, H, Sq]
        c_new = jnp.exp(m_i - m_new)
        l = l * c_old + l_i * c_new
        o = o * c_old.transpose(0, 2, 1)[..., None] \
            + o_i * c_new.transpose(0, 2, 1)[..., None]
        if n > 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        return o, m_new, l, k_cur, v_cur

    o0 = jnp.zeros((B, Sq, Hq, D), jnp.float32)
    m0 = jnp.full((B, Hq, Sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hq, Sq), jnp.float32)
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v))

    l = l.transpose(0, 2, 1)[..., None]                    # [B, Sq, H, 1]
    o = o / jnp.where(l > 0, l, 1.0)
    return o.astype(q.dtype)


def _lse_merge(o, L, o_i, lse_i):
    """Merge a normalized partial (o_i, lse_i) into the running (o, L):
    O = (O·w + O_i·w_i)/(w+w_i), L = M + log(w+w_i), w = exp(L−M). The
    NEG_INF sentinel marks fully-masked partials (weight 0); both guards
    below exist so masked×masked merges stay finite."""
    o_i = o_i.astype(jnp.float32)
    M = jnp.maximum(L, lse_i)
    w_old = jnp.where(L > NEG_INF / 2, jnp.exp(L - M), 0.0)
    w_new = jnp.where(lse_i > NEG_INF / 2, jnp.exp(lse_i - M), 0.0)
    z = w_old + w_new
    wo = (w_old / jnp.where(z > 0, z, 1.0)).transpose(0, 2, 1)[..., None]
    wn = (w_new / jnp.where(z > 0, z, 1.0)).transpose(0, 2, 1)[..., None]
    o = o * wo + o_i * wn
    L = jnp.where(z > 0, M + jnp.log(jnp.where(z > 0, z, 1.0)), NEG_INF)
    return o, L


def _ring_flash(q, k, v, *, axis_name, causal, scale, n, my):
    """Ring loop with the Pallas kernel per step, merging normalized
    partials by logsumexp: O = (O₁·w₁ + O₂·w₂)/(w₁+w₂), L = M + log Σw,
    w_i = exp(L_i − M). Fully-masked steps carry L = NEG_INF → weight 0."""
    from ..ops.flash_attention import flash_attention_with_lse

    B, Sq, Hq, D = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def diag(k_cur, v_cur):
        return flash_attention_with_lse(q, k_cur, v_cur, causal=True,
                                        scale=scale)

    def full(k_cur, v_cur):
        return flash_attention_with_lse(q, k_cur, v_cur, causal=False,
                                        scale=scale)

    def masked(k_cur, v_cur):
        return (jnp.zeros((B, Sq, Hq, D), q.dtype),
                jnp.full((B, Hq, Sq), NEG_INF, jnp.float32))

    def step(i, carry):
        o, L, k_cur, v_cur = carry
        kv_block = (my - i) % n
        if causal:
            # 0: diagonal (local causal) · 1: earlier block (full) · 2: later
            case = jnp.where(kv_block == my, 0, jnp.where(kv_block < my, 1, 2))
            o_i, lse_i = lax.switch(case, [diag, full, masked], k_cur, v_cur)
        else:
            o_i, lse_i = full(k_cur, v_cur)
        o, L = _lse_merge(o, L, o_i, lse_i)
        if n > 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        return o, L, k_cur, v_cur

    o0 = jnp.zeros((B, Sq, Hq, D), jnp.float32)
    L0 = jnp.full((B, Hq, Sq), NEG_INF, jnp.float32)
    o, _, _, _ = lax.fori_loop(0, n, step, (o0, L0, k, v))
    return o.astype(q.dtype)


def dense_attention_with_lse(q, k, v, *, causal: bool = True,
                             scale: float | None = None,
                             window: int | None = None, sinks: int = 0):
    """Single-device exact attention returning (out, lse [B,Hq,Sq]) — the
    canonical dense implementation; the lse output is the merge handle the
    flash-ring path needs, and XLA dead-code-eliminates it for callers that
    drop it. Fully-masked rows yield zeros (not uniform-softmax garbage)
    and lse = NEG_INF, matching the Pallas kernel's convention.

    ``window``: sliding-window attention (Mistral-style) — query i attends
    keys in (i - window, i]; composes with ``causal`` (which SWA models
    always set). ``sinks``: StreamingLLM attention sinks — keys at
    positions < sinks additionally stay attendable (an OR against the
    window bound, never widening causality)."""
    D = q.shape[-1]
    if scale is None:
        scale = D ** -0.5
    Hq, Hkv = q.shape[2], k.shape[2]
    if Hq != Hkv:
        rep = Hq // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal or window is not None:
        Sq, Sk = q.shape[1], k.shape[1]
        q_pos = jnp.arange(Sq)[:, None]
        k_pos = jnp.arange(Sk)[None, :]
        mask = jnp.ones((Sq, Sk), jnp.bool_)
        if causal:
            mask = mask & (q_pos >= k_pos)
        if window is not None:
            in_win = k_pos > q_pos - window
            if sinks:
                in_win = in_win | (k_pos < sinks)
            mask = mask & in_win
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    p = jnp.where((m > NEG_INF / 2)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o = (o / jnp.where(l > 0, l, 1.0).transpose(0, 2, 1)[..., None]
         ).astype(q.dtype)
    lse = jnp.where(l > 0, m + jnp.log(jnp.where(l > 0, l, 1.0)), NEG_INF)
    return o, lse


def dense_attention(q, k, v, *, causal: bool = True,
                    scale: float | None = None, window: int | None = None,
                    sinks: int = 0):
    """Single-device exact attention (same contract, no mesh axis) — the
    n=1 specialization used by entry()'s single-chip forward."""
    return dense_attention_with_lse(q, k, v, causal=causal, scale=scale,
                                    window=window, sinks=sinks)[0]


# --- zigzag ring: balanced causal schedule ---------------------------------

def zigzag_order(seq_len: int, n: int):
    """Permutation placing global chunk pair (i, 2n-1-i) on shard i.

    Contiguous causal sharding is imbalanced: shard 0's queries see almost
    nothing (its ring steps are mostly fully-masked) while shard n-1 works
    every step — lockstep SPMD pays the max, so ~half the ring's FLOPs are
    wasted. Pairing the i-th-earliest with the i-th-latest chunk gives every
    shard an identical causal workload: per step, exactly two chunk-pair
    attentions are live on every device (the zigzag schedule used for
    long-context Llama training). Returns (perm, inv) index arrays: apply
    ``x[:, perm]`` before the seq-sharded shard_map, ``out[:, inv]`` after.
    """
    assert seq_len % (2 * n) == 0, (seq_len, n)
    chunk = seq_len // (2 * n)
    order = []
    for i in range(n):
        order.extend(range(i * chunk, (i + 1) * chunk))
        j = 2 * n - 1 - i
        order.extend(range(j * chunk, (j + 1) * chunk))
    perm = jnp.array(order)
    inv = jnp.zeros_like(perm).at[perm].set(jnp.arange(seq_len))
    return perm, inv


def zigzag_ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                          scale: float | None = None, impl: str = "flash"):
    """Ring attention over zigzag-ordered shards (inside shard_map; the
    caller permuted the global sequence with ``zigzag_order``).

    Local layout: [B, 2*chunk, H, D] = (early chunk ``my``, late chunk
    ``2n-1-my``). With kv pair from origin shard j each step:

    - q_late × kv_early: ALWAYS fully visible (every early chunk precedes
      every late chunk) — one unconditional call;
    - q_early × kv_early: full if j<my, diagonal if j==my, masked if j>my;
    - q_late × kv_late: masked if j<my, diagonal if j==my, full if j>my
      (later j means an EARLIER late chunk 2n-1-j).

    Exactly two live chunk-pairs per device per step — the causal ring's
    total work, perfectly balanced. Partials merge by logsumexp like
    ``_ring_flash``; the per-pair compute is the Pallas kernel when shapes
    tile (flash_attention_with_lse falls back to dense-with-lse below
    kernel-tiling sizes, so this is also the small-shape path).
    """
    from ..ops.flash_attention import flash_attention_with_lse

    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    B, S2, Hq, D = q.shape
    half = S2 // 2
    if scale is None:
        scale = D ** -0.5
    if not causal:                        # balanced already; plain ring
        return ring_attention(q, k, v, axis_name=axis_name, causal=False,
                              scale=scale, impl=impl)
    pair_attn = (flash_attention_with_lse if impl == "flash"
                 else dense_attention_with_lse)

    qa, qb = q[:, :half], q[:, half:]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def diag(qc, kc, vc):
        return pair_attn(qc, kc, vc, causal=True, scale=scale)

    def full(qc, kc, vc):
        return pair_attn(qc, kc, vc, causal=False, scale=scale)

    def masked(qc, kc, vc):
        return (jnp.zeros(qc.shape, qc.dtype),
                jnp.full((B, Hq, half), NEG_INF, jnp.float32))

    merge = _lse_merge

    def step(t, carry):
        oa, La, ob, Lb, k_cur, v_cur = carry
        j = (my - t) % n
        ka, kb = k_cur[:, :half], k_cur[:, half:]
        va, vb = v_cur[:, :half], v_cur[:, half:]

        # q_late × kv_early: unconditionally visible
        o_i, lse_i = full(qb, ka, va)
        ob, Lb = merge(ob, Lb, o_i, lse_i)

        # q_early × kv_early: full / diag / masked by ring position
        case_a = jnp.where(j == my, 1, jnp.where(j < my, 0, 2))
        o_i, lse_i = lax.switch(case_a, [full, diag, masked], qa, ka, va)
        oa, La = merge(oa, La, o_i, lse_i)

        # q_late × kv_late: masked / diag / full (reversed order)
        case_b = jnp.where(j == my, 1, jnp.where(j < my, 2, 0))
        o_i, lse_i = lax.switch(case_b, [full, diag, masked], qb, kb, vb)
        ob, Lb = merge(ob, Lb, o_i, lse_i)

        if n > 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
        return oa, La, ob, Lb, k_cur, v_cur

    oa0 = jnp.zeros((B, half, Hq, D), jnp.float32)
    ob0 = jnp.zeros((B, half, Hq, D), jnp.float32)
    L0 = jnp.full((B, Hq, half), NEG_INF, jnp.float32)
    oa, _, ob, _, _, _ = lax.fori_loop(
        0, n, step, (oa0, L0, ob0, L0, k, v))
    return jnp.concatenate([oa, ob], axis=1).astype(q.dtype)
