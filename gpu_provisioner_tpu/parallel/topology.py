"""Topology discovery: provisioner labels → JAX device mesh.

This closes the loop the reference leaves implicit (SURVEY.md §2c): the
controller stamps ``tpu.kaito.sh/{accelerator,topology,chips,hosts,
worker-index,slice-group}`` onto nodes (catalog.SliceShape.node_labels), GKE
projects them into TPU pods, and this module consumes them to bootstrap
``jax.distributed`` and build the device mesh the training step shards over.

Axis convention (scaling-book ordering — slowest-varying interconnect
outermost):

    (slice, data, pipe, seq, expert, model)

``slice`` spans slices over DCN (multi-slice data parallelism — the
"N NodeClaims → N slices" configuration in BASELINE.json); the rest ride
ICI within one slice. Batch is sharded over (slice, data), pipeline
stages over ``pipe`` (layer-sharded gpipe, parallel/pipeline.py), sequence
over ``seq`` (ring attention), MoE experts over ``expert`` (all-to-all
dispatch), and dense parameters over ``model`` (tensor parallelism).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from math import prod
from typing import Mapping, Optional, Sequence

from ..apis import labels as wk

AXIS_SLICE = "slice"
AXIS_DATA = "data"
AXIS_PIPE = "pipe"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_MODEL = "model"
MESH_AXES = (AXIS_SLICE, AXIS_DATA, AXIS_PIPE, AXIS_SEQ, AXIS_EXPERT,
             AXIS_MODEL)

# GKE injects these into TPU pods (the downward-API half of the contract;
# TPU_WORKER_HOSTNAMES is the same variable the Cloud TPU runtime uses).
ENV_WORKER_ID = "TPU_WORKER_ID"
ENV_WORKER_HOSTNAMES = "TPU_WORKER_HOSTNAMES"
COORDINATOR_PORT = 8476  # jax.distributed default


class TopologyError(Exception):
    """Labels/env describe no usable slice topology."""


@dataclass(frozen=True)
class SliceTopology:
    """One worker's view of the slice(s) it belongs to.

    Mirrors what the provisioner wrote at Create time
    (providers/instance.py → catalog.SliceShape.node_labels) plus the
    per-worker identity GKE adds.
    """

    generation: str           # "v5e" | "v5p" | ...
    topology: str             # ICI topology, e.g. "2x4" / "2x2x4"
    chips: int                # chips in THIS slice
    hosts: int                # worker VMs in this slice
    worker_index: int = 0     # this host's index within the slice
    worker_hostnames: tuple[str, ...] = ()
    num_slices: int = 1       # DCN-connected slices (multi-slice DP)
    slice_index: int = 0      # which slice this worker's node pool is
    slice_group: str = ""     # tpu.kaito.sh/slice-group value
    coordinator: str = ""     # global coordinator override (multi-slice)

    @property
    def chips_per_host(self) -> int:
        return self.chips // max(1, self.hosts)

    @property
    def ici_dims(self) -> tuple[int, ...]:
        return tuple(int(d) for d in self.topology.split("x"))

    @property
    def total_chips(self) -> int:
        return self.chips * self.num_slices

    def coordinator_address(self) -> str:
        """Where jax.distributed's coordinator runs: the explicit override
        when set (required for multi-slice, where each slice only knows its
        own hostnames), else host 0 of this slice."""
        if self.coordinator:
            addr = self.coordinator
            return addr if ":" in addr else f"{addr}:{COORDINATOR_PORT}"
        if self.num_slices > 1:
            raise TopologyError(
                "multi-slice topology needs an explicit coordinator "
                "(slice-local hostnames can't name the global host 0) — "
                "set TPU_KAITO_COORDINATOR / SliceTopology.coordinator")
        if self.worker_hostnames:
            return f"{self.worker_hostnames[0]}:{COORDINATOR_PORT}"
        return f"localhost:{COORDINATOR_PORT}"

    def distributed_init_args(self) -> dict:
        """kwargs for ``jax.distributed.initialize``; process ids are
        globally unique across slices (slice-major ordering)."""
        return {
            "coordinator_address": self.coordinator_address(),
            "num_processes": self.hosts * self.num_slices,
            "process_id": self.slice_index * self.hosts + self.worker_index,
        }

    @classmethod
    def from_node_labels(cls, labels: Mapping[str, str],
                         environ: Optional[Mapping[str, str]] = None,
                         num_slices: Optional[int] = None) -> "SliceTopology":
        """Build from the ``tpu.kaito.sh/*`` labels the provisioner stamped.

        Multi-slice identity (slice-index / num-slices / coordinator) is
        read from the labels the instance provider stamps at create
        (providers/instance.py:_slice_group_identity) — env vars are only a
        fallback/override. ``environ`` additionally supplies the per-worker
        identity (worker id/hostnames) that labels cannot carry pod-portably.
        """
        env = environ if environ is not None else os.environ
        try:
            generation = labels[wk.TPU_ACCELERATOR_LABEL]
            topology = labels[wk.TPU_TOPOLOGY_LABEL]
            chips = int(labels[wk.TPU_CHIPS_LABEL])
            hosts = int(labels[wk.TPU_HOSTS_LABEL])
            worker = int(labels.get(wk.TPU_WORKER_INDEX_LABEL,
                                    env.get(ENV_WORKER_ID, "0")))
            slice_index = int(
                env.get("TPU_KAITO_SLICE_INDEX")
                or labels.get(wk.TPU_SLICE_INDEX_LABEL, "0"))
            if num_slices is None:
                num_slices = int(
                    env.get("TPU_KAITO_NUM_SLICES")
                    or labels.get(wk.TPU_NUM_SLICES_LABEL, "1"))
        except KeyError as e:
            raise TopologyError(
                f"node labels missing {e.args[0]!r} — was this node "
                f"provisioned by tpu-provisioner? "
                f"(have: {sorted(labels)})") from e
        except ValueError as e:
            raise TopologyError(
                f"non-integer topology label/env value: {e}") from e
        hostnames = tuple(h for h in env.get(ENV_WORKER_HOSTNAMES, "").split(",") if h)
        return cls(generation=generation, topology=topology, chips=chips,
                   hosts=hosts, worker_index=worker,
                   worker_hostnames=hostnames, num_slices=num_slices,
                   slice_index=slice_index,
                   slice_group=labels.get(wk.TPU_SLICE_GROUP_LABEL, ""),
                   coordinator=(env.get("TPU_KAITO_COORDINATOR")
                                or labels.get(wk.TPU_COORDINATOR_LABEL, "")))

    @classmethod
    def from_env(cls, environ: Optional[Mapping[str, str]] = None) -> "SliceTopology":
        """Build from env alone (labels projected via downward API as
        ``TPU_KAITO_<NAME>`` variables, the chart's pod-spec convention)."""
        env = environ if environ is not None else os.environ
        labels = {
            wk.TPU_ACCELERATOR_LABEL: env.get("TPU_KAITO_ACCELERATOR", ""),
            wk.TPU_TOPOLOGY_LABEL: env.get("TPU_KAITO_TOPOLOGY", ""),
            wk.TPU_CHIPS_LABEL: env.get("TPU_KAITO_CHIPS", ""),
            wk.TPU_HOSTS_LABEL: env.get("TPU_KAITO_HOSTS", ""),
        }
        labels = {k: v for k, v in labels.items() if v}
        # num_slices=None → from_node_labels reads TPU_KAITO_NUM_SLICES /
        # the num-slices label itself (one parse path, one error message)
        return cls.from_node_labels(labels, environ=env)


def drop_foreign_backend_factories() -> None:
    """Deregister non-builtin JAX backend factories before first backend init.

    Site hooks (e.g. axon register) can wrap ``get_backend`` so the first
    ``jax.devices(...)`` call — even ``jax.devices("cpu")`` — initializes
    EVERY registered plugin; a wedged/broken accelerator client then hangs
    the process rather than raising. Builtin factories ("tpu", "cuda", ...)
    stay: they are part of MLIR's known-platform set and fail fast when the
    hardware is absent."""
    try:
        from jax._src import xla_bridge as xb
        for plat in [p for p in xb._backend_factories
                     if p not in ("cpu", "tpu", "cuda", "rocm", "gpu")]:
            xb._backend_factories.pop(plat, None)
    except Exception:
        pass  # private API moved — callers fall back to probing


def mesh_shape_for(n_devices: int, *, num_slices: int = 1,
                   sp: int = 1, tp: int = 1, ep: int = 1, pp: int = 1,
                   dp: Optional[int] = None
                   ) -> tuple[int, int, int, int, int, int]:
    """Factor ``n_devices`` into (slice, data, pipe, seq, expert, model).

    ``dp`` defaults to whatever is left after the other axes are taken.
    Raises TopologyError on non-divisibility so a bad deployment config
    fails at mesh build, not as a cryptic XLA reshape error.
    """
    if n_devices % num_slices:
        raise TopologyError(f"{n_devices} devices not divisible by "
                            f"num_slices={num_slices}")
    per_slice = n_devices // num_slices
    if per_slice % (sp * tp * ep * pp):
        raise TopologyError(f"{per_slice} devices/slice not divisible by "
                            f"sp*tp*ep*pp={sp}*{tp}*{ep}*{pp}")
    inferred = per_slice // (sp * tp * ep * pp)
    if dp is None:
        dp = inferred
    elif dp != inferred:
        raise TopologyError(f"dp={dp} inconsistent: {num_slices}sl×{dp}dp×"
                            f"{pp}pp×{sp}sp×{ep}ep×{tp}tp != {n_devices}")
    return (num_slices, dp, pp, sp, ep, tp)


def make_mesh(n_devices: Optional[int] = None, *, num_slices: int = 1,
              sp: int = 1, tp: int = 1, ep: int = 1, pp: int = 1,
              dp: Optional[int] = None,
              devices: Optional[Sequence] = None):
    """Build the (slice, data, pipe, seq, expert, model) ``jax.sharding.Mesh``.

    Uses ``mesh_utils.create_device_mesh`` for ICI-aware device ordering on
    real TPU topologies, falling back to a plain reshape (CPU meshes, odd
    factorizations). Import of jax is deferred so control-plane-only
    deployments never pay for it.
    """
    import jax
    import numpy as np
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = list(devices)[:n_devices]
    shape = mesh_shape_for(n_devices, num_slices=num_slices, sp=sp, tp=tp,
                           ep=ep, pp=pp, dp=dp)
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices))
    except (ValueError, AssertionError, NotImplementedError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, MESH_AXES)


def mesh_from_topology(topo: SliceTopology, *, sp: int = 1, tp: int = 1,
                       devices: Optional[Sequence] = None):
    """Mesh for a discovered slice topology: ``slice`` axis = num_slices,
    remaining chips split dp × sp × tp."""
    return make_mesh(topo.total_chips if devices is None else None,
                     num_slices=topo.num_slices, sp=sp, tp=tp,
                     devices=devices)
