"""Instance provider + GCP client layer (L1/L2 of the layer map, SURVEY.md §1).

``gcp`` holds the cloud resource models, the narrow API seams and LRO helpers
(the TPU analog of pkg/providers/instance/azure_client.go + armutils.go);
``rest`` the real HTTP implementations; ``instance`` the NodeClaim ⇄ node-pool
mapping (the TPU analog of pkg/providers/instance/instance.go).
"""

from .gcp import (  # noqa: F401
    NodePool, NodePoolConfig, NodePoolsAPI, Operation, PlacementPolicy,
    QueuedResource, QueuedResourcesAPI, poll_until_done,
    NP_PROVISIONING, NP_RUNNING, NP_STOPPING, NP_ERROR, NP_RECONCILING,
    QR_ACCEPTED, QR_ACTIVE, QR_CREATING, QR_FAILED, QR_SUSPENDED, QR_WAITING,
)
from .instance import (  # noqa: F401
    Instance, InstanceProvider, STATE_CREATING, STATE_DELETING, STATE_FAILED,
    STATE_SUCCEEDED, nodepool_name_valid, parse_nodepool_from_provider_id,
)
from .operations import (  # noqa: F401
    BackoffLadder, OperationTracker, TrackedOperation,
    OP_CREATE, OP_DELETE, PHASE_FAILED, PHASE_IN_PROGRESS, PHASE_SUCCEEDED,
)
