"""Read-through instance cache: TTL + singleflight + negative caching (L2).

The provisioning hot loop is dominated by cloud round-trips: every lifecycle
reconcile and GC pass re-drives ``nodepools.get``/``queued.get`` for claims
whose cloud state changes on the order of minutes. ``ReadThroughCache`` sits
in front of those point lookups:

- **TTL**: a fetched entry serves reads for ``ttl`` seconds. The TTL is
  additionally bounded by a hard ``max_age`` guard (the analog of GC's
  ``_cache_too_stale``): even a misconfigured ttl can never serve an entry
  older than ``max_age``.
- **Singleflight**: concurrent readers of the same key while a fetch is in
  flight await the one fetch instead of issuing their own (the reconcile
  storm for a hot claim costs ONE cloud GET per TTL window, not one per
  worker). Waiters are shielded — a cancelled reconcile never kills the
  fetch other waiters share.
- **Negative caching**: a NotFound answer is cached for ``negative_ttl`` so
  retry loops probing a dead resource don't hammer the API. Any other error
  is never cached.
- **Explicit invalidation**: mutations (create/delete/state transition)
  call ``invalidate(key)``, which both drops the entry AND detaches any
  in-flight fetch so a read racing the mutation cannot re-populate the
  cache with pre-mutation state (the same lesson as the provider's pool
  snapshot: invalidate-after-poll-under-the-lock).

Counters are kept per instance and aggregated into module-level registries
(``CACHE_STATS``, ``CLOUD_CALLS``) that ``controllers/metrics.py`` samples
at scrape time — mirroring how transport.py's ``BREAKERS`` registry feeds
the breaker gauges without this layer importing prometheus.
"""

from __future__ import annotations

import asyncio
from collections import defaultdict
from typing import Awaitable, Callable, Optional

from ..runtime import probes

# ---------------------------------------------------------------- registries

# cache name -> {"hits" | "misses" | "coalesced" | "negative_hits" |
#                "invalidations": count}, aggregated across instances so
# /metrics survives provider re-construction (tests, restarts).
CACHE_STATS: dict[str, dict[str, int]] = {}

# "scope.method" -> cumulative cloud API calls, aggregated across seams.
CLOUD_CALLS: dict[str, int] = defaultdict(int)

_STAT_KEYS = ("hits", "misses", "coalesced", "negative_hits", "invalidations")


def _default_negative(exc: Exception) -> bool:
    """A cloud 404 in the APIError taxonomy (duck-typed: this module must
    not import providers.gcp — controllers.metrics imports us)."""
    return bool(getattr(exc, "not_found", False))


class ReadThroughCache:
    """TTL + singleflight + negative cache in front of an async point fetch.

    ``fetch(key)`` is the cold path (e.g. ``nodepools.get``). ``ttl == 0``
    disables positive caching but keeps singleflight coalescing — the right
    mode for externally-advancing state machines (queued resources) where
    the win is collapsing a concurrent reconcile burst, not serving stale
    ladder states.
    """

    # Sweep trigger: a long-lived operator churning through claim names
    # accumulates one-shot (mostly negative) entries for keys never probed
    # again; past this size every store sweeps the expired ones. Live,
    # in-window entries are naturally bounded by the fleet size.
    MAX_ENTRIES = 4096

    def __init__(self, name: str, fetch: Callable[[str], Awaitable],
                 ttl: float = 1.0, negative_ttl: float = 0.5,
                 max_age: float = 30.0,
                 negative: Callable[[Exception], bool] = _default_negative):
        self.name = name
        self.fetch = fetch
        self.ttl = ttl
        self.negative_ttl = negative_ttl
        self.max_age = max_age
        self._negative = negative
        # key -> (stamp, value, cached_error)  (error XOR value populated)
        self._entries: dict[str, tuple[float, object, Optional[Exception]]] = {}
        self._inflight: dict[str, asyncio.Task] = {}
        self.stats: dict[str, int] = {k: 0 for k in _STAT_KEYS}
        self._agg = CACHE_STATS.setdefault(name, {k: 0 for k in _STAT_KEYS})

    # ------------------------------------------------------------- internals
    def _count(self, stat: str) -> None:
        self.stats[stat] += 1
        self._agg[stat] += 1

    @staticmethod
    def _now() -> float:
        return asyncio.get_event_loop().time()

    # ------------------------------------------------------------------ read
    async def get(self, key: str):
        ent = self._entries.get(key)
        if ent is not None:
            stamp, value, err = ent
            age = self._now() - stamp
            window = self.negative_ttl if err is not None else self.ttl
            if age < min(window, self.max_age):
                if err is not None:
                    self._count("negative_hits")
                    raise err
                self._count("hits")
                return value
            self._entries.pop(key, None)  # expired

        task = self._inflight.get(key)
        if task is not None:
            self._count("coalesced")
        else:
            self._count("misses")
            task = asyncio.ensure_future(self._do_fetch(key))
            # assigned before the task first runs (single-threaded loop), so
            # _do_fetch's identity check below always sees its own entry
            self._inflight[key] = task
        # shield: one waiter's cancellation must not kill the shared fetch
        value, err = await asyncio.shield(task)
        if err is not None:
            raise err
        return value

    async def _do_fetch(self, key: str):
        """Runs the cold fetch once; returns ``(value, error)`` instead of
        raising so no waiter-set cancellation can leave an unretrieved task
        exception. Populates the cache only if this fetch is still the
        registered in-flight one — ``invalidate`` detaches it."""
        try:
            value, err = await self.fetch(key), None
        except Exception as e:  # noqa: BLE001 — classified below
            value, err = None, e
        if self._inflight.get(key) is asyncio.current_task():
            del self._inflight[key]
            if err is None:
                if self.ttl > 0:
                    self._store(key, value, None)
            elif self._negative(err) and self.negative_ttl > 0:
                self._store(key, None, err)
        return value, err

    def _store(self, key: str, value, err: Optional[Exception]) -> None:
        if len(self._entries) >= self.MAX_ENTRIES:
            self._sweep()
        self._entries[key] = (self._now(), value, err)

    def _sweep(self) -> None:
        """Drop every expired entry — keys that will never be re-read
        (departed claims' negative entries) must not accumulate forever."""
        now = self._now()
        for k, (stamp, _, err) in list(self._entries.items()):
            window = self.negative_ttl if err is not None else self.ttl
            if now - stamp >= min(window, self.max_age):
                del self._entries[k]

    # ------------------------------------------------------------ mutations
    def invalidate(self, key: str) -> None:
        """Drop the entry and detach any in-flight fetch for ``key``.

        Detaching (not cancelling) means racing waiters still get their
        answer — they started reading before the mutation, stale-read
        semantics no worse than an uncached read issued at the same moment —
        but the result is NOT stored, so no read started before a delete can
        resurrect the deleted resource in the cache."""
        self._count("invalidations")
        self._entries.pop(key, None)
        self._inflight.pop(key, None)

    def __len__(self) -> int:
        return len(self._entries)


class TTLMemo:
    """TTL set-memo: remembers that a key was "bad" for ``ttl`` seconds.

    The placement engine's per-zone stockout memo — after one claim eats a
    RESOURCE_EXHAUSTED from a zone, ``mark(zone)`` makes ``active(zone)``
    true for the TTL window so the N claims queued behind it skip the zone
    instead of serially re-probing a dry pool. Consults count into
    ``CACHE_STATS`` under ``name`` (hits = memo suppressed a probe,
    misses = no active memo) so /metrics sees memo effectiveness the same
    way it sees the read-through caches.
    """

    def __init__(self, name: str, ttl: float = 5.0):
        self.name = name
        self.ttl = ttl
        self._stamps: dict[str, float] = {}
        self.stats: dict[str, int] = {k: 0 for k in _STAT_KEYS}
        self._agg = CACHE_STATS.setdefault(name, {k: 0 for k in _STAT_KEYS})

    def _count(self, stat: str) -> None:
        self.stats[stat] += 1
        self._agg[stat] += 1

    @staticmethod
    def _now() -> float:
        return asyncio.get_event_loop().time()

    def mark(self, key: str) -> None:
        self._stamps[key] = self._now()

    def active(self, key: str) -> bool:
        stamp = self._stamps.get(key)
        if stamp is not None and self.ttl > 0 and self._now() - stamp < self.ttl:
            self._count("hits")
            return True
        if stamp is not None:  # expired — next probe is live again
            self._stamps.pop(key, None)
            self._count("invalidations")
        self._count("misses")
        return False

    def clear(self, key: str) -> None:
        if self._stamps.pop(key, None) is not None:
            self._count("invalidations")

    def remaining(self, key: str) -> float:
        """Seconds of suppression left for ``key`` (0.0 when no live memo).
        A pure read — no stats counting, no expiry side effects — so wake
        scheduling can peek without skewing memo-effectiveness metrics."""
        stamp = self._stamps.get(key)
        if stamp is None or self.ttl <= 0:
            return 0.0
        return max(0.0, self.ttl - (self._now() - stamp))

    def __len__(self) -> int:
        return len(self._stamps)

    def live(self) -> dict[str, float]:
        """Every key with a live memo → seconds of suppression remaining.
        Pure read like ``remaining`` (no stats, no expiry) — the flight
        recorder snapshots this into diagnostic bundles."""
        now = self._now()
        return {k: round(self.ttl - (now - stamp), 4)
                for k, stamp in self._stamps.items()
                if self.ttl > 0 and now - stamp < self.ttl}


class CountingAPI:
    """Transparent per-endpoint call counter around a cloud API seam
    (``NodePoolsAPI`` / ``QueuedResourcesAPI``).

    Every awaited method increments both an instance counter (bench/test
    isolation) and the module-level ``CLOUD_CALLS`` aggregate that
    ``controllers/metrics.py`` exports. Non-coroutine attributes (fake
    helpers like ``fail``/``pools``) pass through untouched.
    """

    def __init__(self, inner, scope: str):
        self._inner = inner
        self.scope = scope
        self.calls: dict[str, int] = defaultdict(int)

    def __getattr__(self, name: str):
        attr = getattr(self._inner, name)
        if not asyncio.iscoroutinefunction(attr):
            return attr
        scope = self.scope

        mutating = (name in ("begin_create", "begin_delete")
                    or (scope == "queuedresources"
                        and name in ("create", "delete")))

        async def counted(*args, **kwargs):
            # resolve at call time so test monkeypatches on the inner fake
            # (e.g. counted list() spies) keep working through the wrapper
            self.calls[name] += 1
            CLOUD_CALLS[f"{scope}.{name}"] += 1
            if mutating:
                # one chokepoint covers every cloud mutation the provider
                # can issue — the schedfuzz fence-before-mutate contract
                probes.emit("cloud-mutate", f"{scope}.{name}")
            return await getattr(self._inner, name)(*args, **kwargs)

        counted.__name__ = name
        return counted

    def total(self) -> int:
        return sum(self.calls.values())
