"""GCP resource models, narrow API seams, and LRO helpers.

Design lifted from what makes the reference testable: a 4-method interface in
front of the cloud SDK (azure_client.go:42-47 — BeginCreateOrUpdate / Get /
BeginDelete / NewListPager) plus thin poll-until-done CRUD helpers
(armutils.go:28-101). Here there are two seams:

- ``NodePoolsAPI``       GKE node pools (container.googleapis.com) — the
                         direct analog of the AKS AgentPools API; used for all
                         on-demand/spot slices.
- ``QueuedResourcesAPI`` Cloud TPU queued resources (tpu.googleapis.com) — no
                         Azure analog; adds a WAITING→PROVISIONING→ACTIVE
                         state machine with stockout queueing, used for
                         reserved/queued capacity (SURVEY.md §7 hard part 2).

Models are hand-built dataclasses shaped like the REST payloads (camelCase via
apis.serde), not SDK imports — no GCP SDK exists in this environment and the
wire format is plain JSON anyway.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass, field
from typing import Optional, Protocol

from ..apis.serde import from_dict, to_dict

# GKE node-pool status values (container/v1 NodePool.Status).
NP_PROVISIONING = "PROVISIONING"
NP_RUNNING = "RUNNING"
NP_RECONCILING = "RECONCILING"
NP_STOPPING = "STOPPING"
NP_ERROR = "ERROR"

# Cloud TPU queued-resource states (tpu/v2 QueuedResourceState).
QR_ACCEPTED = "ACCEPTED"
QR_WAITING = "WAITING_FOR_RESOURCES"
QR_CREATING = "CREATING"
QR_ACTIVE = "ACTIVE"
QR_SUSPENDED = "SUSPENDED"
QR_FAILED = "FAILED"


@dataclass
class PlacementPolicy:
    type: str = "COMPACT"
    tpu_topology: str = ""


@dataclass
class NodePoolConfig:
    machine_type: str = ""
    disk_size_gb: int = 0
    labels: dict[str, str] = field(default_factory=dict)
    taints: list[dict] = field(default_factory=list)
    spot: bool = False
    reservation: str = ""
    image_type: str = ""  # e.g. "COS_CONTAINERD" (reference OSSKU analog)


@dataclass
class NodePool:
    name: str = ""
    config: NodePoolConfig = field(default_factory=NodePoolConfig)
    initial_node_count: int = 1
    placement_policy: Optional[PlacementPolicy] = None
    status: str = ""
    status_message: str = ""
    # serialized via apis.serde (camelCase) when sent over REST

    def to_dict(self) -> dict:
        return to_dict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "NodePool":
        return from_dict(cls, d)


@dataclass
class QueuedResource:
    name: str = ""
    accelerator_type: str = ""   # e.g. "v5p-32"
    runtime_version: str = ""
    state: str = QR_ACCEPTED
    state_message: str = ""
    node_pool: str = ""          # target node pool materialized when ACTIVE
    reservation: str = ""
    spot: bool = False


class Operation(Protocol):
    """A long-running operation (ARM poller / GCP Operation analog)."""

    async def done(self) -> bool: ...
    async def result(self): ...


class CompletedOperation:
    """An LRO that is already complete (or failed)."""

    def __init__(self, value=None, error: Optional[Exception] = None):
        self._value = value
        self._error = error

    async def done(self) -> bool:
        return True

    async def result(self):
        if self._error is not None:
            raise self._error
        return self._value


async def poll_until_done(op: Operation, interval: float = 1.0,
                          timeout: float = 1800.0, jitter: float = 0.1):
    """Block until the LRO completes and return its result.

    The analog of azcore's ``PollUntilDone`` the reference calls for both
    create and delete (armutils.go:28-40). The reference accepts blocking a
    reconcile worker for the full create; the lifecycle controller here does
    the same for node pools (minutes) but NOT for queued resources (hours) —
    those go through the async requeue path in the instance provider.
    """
    deadline = asyncio.get_event_loop().time() + timeout
    while not await op.done():
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(f"LRO not done after {timeout}s")
        await asyncio.sleep(interval * (1 + random.random() * jitter))
    return await op.result()


class NodePoolsAPI(Protocol):
    """The 4-method seam in front of GKE node pools (azure_client.go:42-47)."""

    async def begin_create(self, pool: NodePool) -> Operation: ...
    async def get(self, name: str) -> NodePool: ...
    async def begin_delete(self, name: str) -> Operation: ...
    async def list(self) -> list[NodePool]: ...


class QueuedResourcesAPI(Protocol):
    async def create(self, qr: QueuedResource) -> QueuedResource: ...
    async def get(self, name: str) -> QueuedResource: ...
    async def delete(self, name: str) -> None: ...
    async def list(self) -> list[QueuedResource]: ...


class APIError(Exception):
    """Cloud API error with an HTTP-ish status code for taxonomy mapping."""

    def __init__(self, message: str, code: int = 500):
        super().__init__(message)
        self.code = code

    @property
    def not_found(self) -> bool:
        return self.code == 404

    @property
    def conflict(self) -> bool:
        return self.code == 409

    @property
    def exhausted(self) -> bool:
        return self.code == 429

    @property
    def expired(self) -> bool:
        """410 Gone: an expired page token / compacted resource history.
        Retrying the SAME request can never succeed — callers restart the
        list from scratch (the cloud-side analog of the kube watch's
        expired-resourceVersion; provlint PL015 pins the distinct branch
        on both sides)."""
        return self.code == 410
