"""Instance provider: NodeClaim ⇄ TPU node pool mapping (L2 of the layer map).

The TPU re-design of pkg/providers/instance/instance.go. The reference maps
one NodeClaim to an AKS agent pool with exactly one GPU VM (Count=1,
instance.go:365); here one NodeClaim maps to a **slice**: a GKE TPU node pool
whose node count equals the shape's host count, with ICI topology expressed
via the pool's placement policy and surfaced as labels. Multi-host shapes
(e.g. v5p-32 = 4 hosts) therefore materialize multiple Node objects from a
single NodeClaim — the registration-wait generalizes the reference's
"exactly one node else wait" invariant (instance.go:220-225) to "all hosts
present with consistent worker indices" (SURVEY.md §7 hard part 1).

Reserved/queued capacity goes through the Cloud TPU QueuedResource state
machine instead of a blocking LRO: create() returns fast and raises a
retryable error while the queue drains, so a reconcile worker is never parked
for the hours a stockout can last (SURVEY.md §7 hard part 2 — deliberate
departure from the reference's PollUntilDone-blocks-worker model).

With an :class:`~..providers.operations.OperationTracker` wired (the
production/envtest default), the node-pool LRO path gets the same treatment:
create()/delete() are resumable state machines that register the in-flight
operation with the shared multiplexer and return immediately — one batched
``nodepools.list`` per tracker tick drives every wait, and no worker is ever
pinned for a slice-create duration. The blocking shape survives tracker-less
(direct/tooling use, and as the BENCH_pr04 baseline).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import re
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Optional

from .. import catalog as cat
from ..apis import labels as wk
from ..apis.core import Node
from ..apis.karpenter import NodeClaim
from ..apis.serde import fmt_time, now, parse_time
from ..errors import (
    CreateError, InsufficientCapacityError, NodeClaimNotFoundError,
    REASON_CREATE_IN_PROGRESS, REASON_DEGRADED_POOL, REASON_INVALID_NAME,
    REASON_INVALID_STORAGE_REQUEST, REASON_LAUNCH_FAILED,
    REASON_NODES_NOT_READY, REASON_QUEUED_PROVISIONING, REASON_STOCKOUT,
    REASON_STOCKOUT_SUPPRESSED, REASON_UNRESOLVABLE_SHAPE,
)
from ..runtime import probes
from ..runtime.apihealth import PartitionFencedError
from ..runtime.client import Client, patch_retry
from ..runtime.wakehub import SOURCE_STOCKOUT
from ..scheduling import Requirements
from .cache import CountingAPI, ReadThroughCache
from .operations import BackoffLadder, OP_DELETE, OperationTracker
from .placement import Candidate, PlacementEngine
from .gcp import (
    APIError, NodePool, NodePoolConfig, NodePoolsAPI, PlacementPolicy,
    QueuedResource, QueuedResourcesAPI, poll_until_done,
    NP_ERROR, NP_PROVISIONING, NP_RUNNING, NP_STOPPING,
    QR_ACTIVE, QR_FAILED, QR_SUSPENDED,
)

log = logging.getLogger("providers.instance")

# Cloud-neutral instance states (reference types.go uses AKS provisioning
# states Creating/Succeeded/Deleting/Failed; GKE statuses map onto them).
STATE_CREATING = "Creating"
STATE_SUCCEEDED = "Succeeded"
STATE_DELETING = "Deleting"
STATE_FAILED = "Failed"

_NP_STATE_MAP = {
    NP_PROVISIONING: STATE_CREATING,
    NP_RUNNING: STATE_SUCCEEDED,
    "RECONCILING": STATE_SUCCEEDED,
    NP_STOPPING: STATE_DELETING,
    NP_ERROR: STATE_FAILED,
}

# GKE node-pool naming constraint (RFC1035-ish, 40 chars) — the analog of the
# reference's agent-pool gate `^[a-z][a-z0-9]{0,11}$` (instance.go:50,81-84).
NODEPOOL_NAME_RE = re.compile(r"^[a-z](?:[-a-z0-9]{0,38}[a-z0-9])?$")

# Annotation selecting the queued-resource path for a NodeClaim.
PROVISIONING_MODE_ANNOTATION = "tpu.kaito.sh/provisioning-mode"
MODE_QUEUED = "queued"

# Per-claim placement attempt history: comma-joined Candidate keys
# (zone/shape/tier) already verdicted RESOURCE_EXHAUSTED, recorded durably on
# the claim so a crash-restart resumes the fallback walk at the next
# candidate instead of re-probing the ones already tried.
PLACEMENT_ATTEMPTS_ANNOTATION = "tpu.kaito.sh/placement-attempts"

_PROVIDER_ID_RE = re.compile(r"^gce://(?P<project>[^/]+)/(?P<zone>[^/]+)/(?P<instance>.+)$")


def nodepool_name_valid(name: str) -> bool:
    return bool(NODEPOOL_NAME_RE.match(name))


def instance_name(cluster: str, pool: str, worker: int) -> str:
    """GKE instance naming convention: gke-<cluster>-<pool>-<suffix>."""
    return f"gke-{cluster}-{pool}-w{worker}"


def provider_id(project: str, zone: str, instance: str) -> str:
    return f"gce://{project}/{zone}/{instance}"


def parse_nodepool_from_provider_id(pid: str, cluster: str) -> Optional[str]:
    """Extract the node-pool name from a gce:// providerID.

    Fallback only — nodes carry ``cloud.google.com/gke-nodepool`` which is
    authoritative. String-parsing providerIDs is inherently fragile (the
    reference does the same for VMSS IDs, utils.go:27-46, taking the 2nd
    '-'-token); here we strip the known ``gke-<cluster>-`` prefix and the
    ``-w<N>`` suffix instead of position-guessing.
    """
    m = _PROVIDER_ID_RE.match(pid or "")
    if not m:
        return None
    inst = m.group("instance")
    prefix = f"gke-{cluster}-"
    if not inst.startswith(prefix):
        return None
    rest = inst[len(prefix):]
    return re.sub(r"-w\d+$", "", rest) or None


@dataclass
class Instance:
    """Cloud-neutral instance model (reference: types.go:19-29) extended with
    slice fields (a TPU instance is a multi-host slice, not one VM)."""

    name: str = ""
    state: str = ""
    id: str = ""                      # providerID of worker 0
    image_id: str = ""
    type: str = ""                    # catalog shape name, e.g. tpu-v5e-8
    capacity_type: str = wk.CAPACITY_TYPE_ON_DEMAND
    tags: dict[str, str] = field(default_factory=dict)
    labels: dict[str, str] = field(default_factory=dict)
    # slice extension
    topology: str = ""
    hosts: int = 1
    chips: int = 0
    node_provider_ids: list[str] = field(default_factory=list)


@dataclass
class ProviderConfig:
    project: str = "test-project"
    zone: str = "us-central2-b"
    cluster: str = "kaito"
    # Node-appearance wait after pool create: reference does 30 × 1s + jitter
    # (instance.go:126-131); multi-host slices get more room per host.
    node_wait_attempts: int = 30
    node_wait_interval: float = 1.0
    node_wait_jitter: float = 0.1
    # Read-through cache in front of nodepools.get: a pool's status changes
    # on the order of minutes, so a ~1s TTL absorbs the reconcile-storm
    # re-reads without a visible staleness window. max_age is the hard guard
    # (GC's _cache_too_stale analog) — never serveable past it whatever ttl
    # says. negative_ttl bounds NotFound probe loops.
    cache_ttl: float = 1.0
    cache_negative_ttl: float = 0.5
    cache_max_age: float = 30.0
    # Queued-resource lookups coalesce concurrent GETs but default to NO
    # positive TTL: the QR ladder advances server-side and a cached WAITING
    # would stretch every requeue by the TTL for zero saved calls (the
    # requeue cadence already spaces them out).
    qr_cache_ttl: float = 0.0
    # Placement: the zones the provider may fall over to, most-preferred
    # first; empty keeps the legacy single-zone behavior (`zone` is the only
    # candidate, stockout maps to InsufficientCapacityError). The memo TTL
    # bounds how long one RESOURCE_EXHAUSTED verdict suppresses re-probes of
    # a dry zone/generation; the demote knobs drive spot-zone hysteresis
    # (providers/placement.py).
    zones: tuple[str, ...] = ()
    stockout_memo_ttl: float = 5.0
    spot_demote_threshold: int = 3
    spot_demote_window: float = 60.0
    # Stockout parking (default OFF — the pinned contract is that a claim
    # whose every candidate is exhausted/memo-suppressed terminates so the
    # workload controller can re-shape it). When on, a walk that was
    # suppressed WITHOUT spending a fresh probe — every skip was a live
    # stockout memo, not this claim's own attempt history — raises the
    # retryable StockoutSuppressed reason instead, and the provider's
    # WakeHub re-wakes the claim when the earliest memo expires.
    stockout_park: bool = False
    # Pre-fast-path list() (one kube Node list PER POOL, serially) — kept
    # only as the benchmark baseline (bench/bench_provision.py measures the
    # fast path against it). Never enable in production.
    legacy_list: bool = False


class InstanceProvider:
    """Create/Get/List/Delete over the node-pool + queued-resource seams."""

    # How long a pool-listing snapshot serves slice-group identity reads.
    # Determinism makes the staleness safe (see _pools_snapshot).
    POOL_SNAPSHOT_TTL = 1.0

    def __init__(self, nodepools: NodePoolsAPI, kube: Client,
                 config: Optional[ProviderConfig] = None,
                 queued: Optional[QueuedResourcesAPI] = None,
                 crashes=None, fence=None,
                 tracker: Optional[OperationTracker] = None,
                 tracer=None):
        # every cloud seam is wrapped in a per-endpoint call counter so the
        # /metrics surface (and the bench harness) can see exactly what the
        # control loops cost the cloud APIs
        self.nodepools = CountingAPI(nodepools, "nodepools")
        self.queued = (CountingAPI(queued, "queuedresources")
                       if queued is not None else None)
        self.kube = kube
        self.cfg = config or ProviderConfig()
        # Crash-point schedule (chaos.CrashPoints) marking the cut lines a
        # process death strands the most interesting state at; None in
        # production. Fencing token (leaderelection.FencingToken): checked
        # before every cloud MUTATION so a reconcile that was already in
        # flight when this replica lost the lease cannot race the new
        # leader (the controller-level fence only gates new dequeues).
        self.crashes = crashes
        self.fence = fence
        # Operation tracker (providers/operations.py): when wired, create()
        # and delete() are non-blocking resumable state machines — they
        # register the in-flight LRO with the shared multiplexer and return
        # immediately; the single tracker poller drives every wait off one
        # batched nodepools.list per tick. With no tracker (direct/tooling
        # construction, the bench baseline) the blocking paths below remain.
        self.tracker = tracker
        # claimtrace tracer (observability/tracing.py), duck-typed and
        # optional: spans cover the create/delete state-machine steps so the
        # critical-path analyzer can attribute a claim's ready-wall.
        self.tracer = tracer
        # WakeHub (runtime/wakehub.py), assigned by the boot path / envtest
        # like the fence: stockout parking arms memo-expiry wakes on it.
        self.wakehub = None
        # APIHealthGovernor (runtime/apihealth.py), assigned like the fence:
        # while the kube apiserver is PARTITIONED no cloud mutation may
        # proceed — a create whose outcome can't be recorded in kube is a
        # duplicate-pool factory once the partition heals.
        self.api_governor = None
        # Placement engine (providers/placement.py): preference-ordered
        # zone × shape × tier candidates, per-zone stockout memo, spot
        # demotion hysteresis. The default single-zone/no-tier config yields
        # exactly one candidate, keeping the legacy exhausted →
        # InsufficientCapacityError contract byte-identical.
        self.placement = PlacementEngine(
            self.cfg.zones or (self.cfg.zone,),
            stockout_ttl=self.cfg.stockout_memo_ttl,
            demote_threshold=self.cfg.spot_demote_threshold,
            demote_window=self.cfg.spot_demote_window)
        # Read-through caches (providers/cache.py): point lookups on the
        # cloud seams, singleflight-coalesced, explicitly invalidated by
        # create/delete/state transitions below.
        self._pool_cache = ReadThroughCache(
            "nodepools.get", self.nodepools.get,
            ttl=self.cfg.cache_ttl, negative_ttl=self.cfg.cache_negative_ttl,
            max_age=self.cfg.cache_max_age)
        self._qr_cache = ReadThroughCache(
            "queuedresources.get",
            self.queued.get if self.queued is not None else _no_fetch,
            ttl=self.cfg.qr_cache_ttl,
            negative_ttl=self.cfg.cache_negative_ttl,
            max_age=self.cfg.cache_max_age)
        # (timestamp, pools, {group: claim-name fingerprint at list time})
        self._pool_snapshot: Optional[
            tuple[float, list[NodePool], dict[str, frozenset]]] = None
        self._pool_snapshot_lock = asyncio.Lock()

    async def _pools_snapshot(self, group: str,
                              claim_names: frozenset) -> list[NodePool]:
        """Pool listing for slice-group identity reads, memoized for
        POOL_SNAPSHOT_TTL with single-flight: a concurrent wave of grouped
        creates does ONE cloud LIST per burst instead of one per member
        (O(groups·members) otherwise — the reference's 1000-concurrency
        lifecycle regime would melt the API quota).

        Staleness within the TTL is safe BECAUSE assignment is
        deterministic: a member whose just-stamped pool is missing from the
        snapshot is re-derived from the same (creationTimestamp, name)
        NodeClaim order every racing reconciler uses, yielding the same
        index (see _slice_group_identity). That argument requires the
        group's CLAIM SET to be stable across the window — a member deleted
        mid-burst shrinks the order and a survivor could re-derive a
        colliding index. So each snapshot records the claim-name
        fingerprint per group at list time and a read whose live fingerprint
        differs (or was never recorded) forces a refresh; the stable-set
        burst still costs one LIST. Stickiness only has to survive
        restarts, which outlive any 1s snapshot."""
        async with self._pool_snapshot_lock:
            now_s = asyncio.get_event_loop().time()
            snap = self._pool_snapshot
            if (snap is not None and now_s - snap[0] < self.POOL_SNAPSHOT_TTL
                    and snap[2].get(group) == claim_names):
                return snap[1]
            pools = await self.nodepools.list()
            # merge, don't replace: other groups' fingerprints stay valid
            # against the strictly-newer pool list (their claim sets are
            # re-certified live on their next read), so concurrent bursts
            # across groups still share one LIST instead of thrashing.
            # Prune fingerprints of groups with no pools left — a
            # long-lived provider churning through short-lived groups must
            # not accumulate dead entries forever (a pruned-but-live group
            # merely refreshes on its next read).
            live = {p.config.labels.get(wk.TPU_SLICE_GROUP_LABEL)
                    for p in pools}
            prev = snap[2] if snap is not None else {}
            fps = {g: fp for g, fp in prev.items() if g in live}
            fps[group] = claim_names
            self._pool_snapshot = (now_s, pools, fps)
            return pools

    # ------------------------------------------------------------- create
    async def create(self, nc: NodeClaim) -> Instance:
        """Resumable create. With an operation tracker wired this NEVER
        blocks on the cloud: it either consumes a completed tracked
        operation (returning the Instance), registers a new one and raises a
        retryable ``CreateError(reason="CreateInProgress")``, or — for a
        requeued reconcile whose operation is still in flight — raises the
        same after one dict lookup and zero cloud calls. Without a tracker
        the original blocking shape (LRO poll + node wait) remains for
        direct/tooling use and as the bench baseline."""
        name = nc.metadata.name
        if not nodepool_name_valid(name):
            raise CreateError(
                f"nodeclaim name {name!r} is not a valid node-pool name "
                f"(must match {NODEPOOL_NAME_RE.pattern})",
                reason=REASON_INVALID_NAME)

        reqs = Requirements.from_nodeclaim(nc)
        try:
            candidates = self.placement.candidates(
                reqs, nc.spec.resources.requests)
        except (cat.UnknownShapeError, ValueError) as e:
            # ValueError: malformed numeric requirement/request strings — same
            # terminal fate as an unknown shape, never a retry loop.
            raise CreateError(str(e), reason=REASON_UNRESOLVABLE_SHAPE) from e
        # the first candidate is exactly the legacy catalog.resolve answer
        shape = candidates[0].shape
        capacity_type = self._capacity_type(reqs)

        if self.tracker is not None:
            op = self.tracker.poke(name)
            if op is not None:
                consumed = await self._consume_tracked_create(op, name, shape)
                if consumed is not None:
                    return consumed
                # None: a resolved delete freed the name — fresh create

        if self._queued_mode(nc, reqs):
            with self._span(name, "qr-wait", shape=shape.slice_name):
                await self._ensure_queued_resource(nc, shape, capacity_type)
            # queued capacity was reserved in the primary zone — the walk
            # must not wander away from where the QueuedResource landed
            candidates = candidates[:1]

        slice_identity = await self._slice_group_identity(nc)
        chosen, op, adopted = await self._walk_candidates(
            nc, name, candidates, capacity_type, slice_identity)
        shape = chosen.shape

        if not adopted:
            # cut line: begin_create is issued but neither the tracker nor
            # the attempt annotation has recorded which candidate won
            self._crash("after_pool_begin_create", name)
            if self.tracker is not None:
                # hand the LRO + node wait to the multiplexer and free the
                # worker; the reconciler requeues (woken early by the
                # tracker-completion injection seam)
                self._register_create(name, shape.hosts)
                raise CreateError(
                    f"nodepool {name} create in progress; requeueing",
                    reason=REASON_CREATE_IN_PROGRESS)
            try:
                # poll at the node-wait cadence: the default 1s LRO poll
                # left a completed create unobserved for up to a full second
                # — at envtest/production config alike, node wait owns pacing
                await poll_until_done(op, interval=self.cfg.node_wait_interval)
            except APIError as e:
                if e.exhausted:
                    # capacity verdict arrived via the LRO, not begin_create
                    self.placement.note_stockout(chosen)
                    raise InsufficientCapacityError(
                        f"nodepool {name} ({shape.slice_name}): {e}") from e
                raise CreateError(f"creating nodepool {name}: {e}") from e

        # cut line: the create LRO has completed server-side but nothing —
        # cache invalidation, node wait, claim status — has recorded it yet
        self._crash("before_lro_done", name)
        with self._span(name, "node-wait", hosts=shape.hosts):
            nodes = await self._wait_for_nodes(name, shape.hosts)
        # state transition just happened (create LRO completed) — drop any
        # entry cached during the wait so the final read sees RUNNING
        self._pool_cache.invalidate(name)
        created = await self._get_pool(name)
        return self._to_instance(created, shape=shape, nodes=nodes)

    async def _walk_candidates(self, nc: NodeClaim, name: str,
                               candidates: list[Candidate],
                               capacity_type: str,
                               slice_identity: dict[str, str]
                               ) -> tuple[Candidate, object, bool]:
        """The fallback walk: try placement candidates in preference order
        until one accepts the create. Returns ``(chosen, op, adopted)`` —
        ``adopted`` means a conflicting in-flight create was adopted instead
        of issuing a new one (``op`` is then None).

        A candidate is skipped without a cloud probe when (a) its key is in
        the claim's durable attempt history (crash-restart resume: never
        re-probe — or worse, double-create behind — a candidate already
        verdicted) or (b) the zone/generation stockout memo holds a live
        verdict (N queued claims cost a dry zone ONE probe per TTL window,
        and both skip kinds count as observed stockouts). Exhausted across
        every candidate: single-candidate claims keep the legacy
        ``InsufficientCapacityError`` contract; multi-candidate claims get
        the terminal ``CreateError(reason=Stockout)`` the lifecycle turns
        into an Event + claim deletion instead of a retry spin."""
        attempted = self._attempted(nc)
        last_err: Optional[APIError] = None
        dry: list[str] = []
        chosen: Optional[Candidate] = None
        op = None
        adopted = False
        # Stockout parking: the shortest memo TTL among candidates skipped
        # ONLY by a live memo (not this claim's own attempt history) — those
        # become probeable again when the memo expires, so exhaustion is a
        # wait, not a verdict.
        park_wait: Optional[float] = None
        with self._span(name, "placement", candidates=len(candidates)):
            for cand in candidates:
                if cand.key in attempted:
                    dry.append(cand.key)
                    probes.emit("placement-verdict", name,
                                verdict="attempted-skip", candidate=cand.key)
                    continue
                if self.placement.suppressed(cand):
                    dry.append(cand.key)
                    probes.emit("placement-verdict", name,
                                verdict="memo-suppressed", candidate=cand.key)
                    if self.cfg.stockout_park:
                        rem = self.placement.suppressed_remaining(cand)
                        if rem > 0 and (park_wait is None or rem < park_wait):
                            park_wait = rem
                    continue
                pool = self._new_nodepool_object(
                    nc, cand.shape, capacity_type,
                    extra_labels=slice_identity,
                    zone=cand.zone, tier=cand.tier)
                try:
                    self._fence_check()
                    with self._span(name, "begin-create",
                                    hosts=cand.shape.hosts, zone=cand.zone):
                        op = await self.nodepools.begin_create(pool)
                except APIError as e:
                    if e.conflict:
                        # Crash-restart tolerance: a create from a previous
                        # incarnation (or a racing replica) owns this pool.
                        # Adopt it — resume the in-flight LRO by tracking
                        # (or polling) the pool's own state — rather than
                        # blind-waiting for nodes a pool that lands in ERROR
                        # will never produce (reference: instance.go:106-110,
                        # minus its blind wait).
                        log.info("nodepool %s create already in progress, "
                                 "adopting", name)
                        probes.emit("placement-verdict", name,
                                    verdict="conflict-adopt",
                                    candidate=cand.key)
                        if self.tracker is not None:
                            self._register_create(name, cand.shape.hosts)
                            raise CreateError(
                                f"nodepool {name} create adopted; requeueing",
                                reason=REASON_CREATE_IN_PROGRESS) from e
                        chosen, adopted = cand, True
                        break
                    if e.exhausted:
                        # zone verdict: memo it (followers skip the zone for
                        # a TTL) and record it on the claim (restart resumes
                        # at the NEXT candidate)
                        self.placement.note_stockout(cand)
                        probes.emit("placement-verdict", name,
                                    verdict="stockout", candidate=cand.key)
                        await self._record_attempt(nc, cand.key)
                        dry.append(cand.key)
                        last_err = e
                        continue
                    raise CreateError(
                        f"creating nodepool {name}: {e}") from e
                chosen = cand
                break
        if chosen is None:
            if park_wait is not None:
                probes.emit("placement-verdict", name, verdict="parked",
                            wait=round(park_wait, 4))
                # Every non-attempted candidate is only TEMPORARILY dry (a
                # live memo, no probe spent): park the claim — retryable
                # error onto the backoff ladder as the safety net, with the
                # hub wake at memo expiry as the primary wake-up.
                if self.wakehub is not None:
                    self.wakehub.wake_after(name, park_wait + 0.01,
                                            SOURCE_STOCKOUT)
                raise CreateError(
                    f"nodepool {name}: all candidates memo-suppressed; "
                    f"parked ~{park_wait:.1f}s until the earliest stockout "
                    f"memo expires",
                    reason=REASON_STOCKOUT_SUPPRESSED) from last_err
            if len(candidates) == 1:
                # legacy single-candidate contract: stockout maps to
                # InsufficientCapacityError (launch deletes the claim and
                # KAITO retries with a different shape)
                detail = last_err or "stockout memo active for the only zone"
                raise InsufficientCapacityError(
                    f"nodepool {name} ({candidates[0].shape.slice_name}): "
                    f"{detail}") from last_err
            probes.emit("placement-verdict", name, verdict="exhausted",
                        candidates=len(candidates))
            raise CreateError(
                f"nodepool {name}: capacity exhausted across all "
                f"{len(candidates)} placement candidates "
                f"({', '.join(dry)})",
                reason=REASON_STOCKOUT) from last_err
        probes.emit("placement-verdict", name,
                    verdict="fallback" if chosen is not candidates[0]
                    else "chosen", candidate=chosen.key)
        if self.tracer is not None:
            # Stamp the placement key axes on the claim's trace — the fleet
            # SLO aggregator digests time-to-ready per {zone, generation,
            # tier} off exactly these attrs.
            self.tracer.set_trace_attrs(
                name, zone=chosen.zone,
                generation=chosen.shape.generation, tier=chosen.tier)
        if chosen is not candidates[0]:
            self.placement.note_fallback(candidates[0], chosen)
            log.info("nodepool %s fell back to %s (wanted %s)",
                     name, chosen.key, candidates[0].key)
        if adopted:
            await self._adopt_inflight_create(name)
        return chosen, op, adopted

    def _attempted(self, nc: NodeClaim) -> set[str]:
        raw = nc.metadata.annotations.get(PLACEMENT_ATTEMPTS_ANNOTATION, "")
        return {k for k in raw.split(",") if k}

    async def _record_attempt(self, nc: NodeClaim, key: str) -> None:
        """Durably append ``key`` to the claim's placement attempt history.
        Best-effort: a claim not present in the store (direct provider use,
        unit tests) keeps only the in-memory record — patch_retry returns
        None on NotFound and the walk carries on."""
        attempts = self._attempted(nc) | {key}
        nc.metadata.annotations[PLACEMENT_ATTEMPTS_ANNOTATION] = \
            ",".join(sorted(attempts))

        def _mutate(obj: NodeClaim) -> bool:
            anns = obj.metadata.annotations
            merged = {k for k in
                      anns.get(PLACEMENT_ATTEMPTS_ANNOTATION, "").split(",")
                      if k}
            if key in merged:
                return False
            merged.add(key)
            anns[PLACEMENT_ATTEMPTS_ANNOTATION] = ",".join(sorted(merged))
            return True

        await patch_retry(self.kube, NodeClaim, nc.metadata.name, _mutate)

    async def _consume_tracked_create(self, op, name: str,
                                      shape: cat.SliceShape
                                      ) -> Optional[Instance]:
        """Act on the tracked operation for ``name``. Returns None when the
        parked op was a RESOLVED delete (e.g. GC reaped a previous pool
        under this name and nothing ever consumed the outcome) — the name
        is free again and the caller proceeds with a fresh create."""
        if op.kind == OP_DELETE:
            if op.in_progress:
                # this pool's teardown (finalize/GC) is still in flight —
                # the name frees up once the delete op resolves
                raise CreateError(
                    f"nodepool {name} is being deleted; requeueing",
                    reason=REASON_CREATE_IN_PROGRESS)
            # resolved teardown nobody consumed (a reaped claimless pool's
            # delete has no second delete() call): pop it or a NodeClaim
            # reusing the name would see "being deleted" forever
            self.tracker.pop(name)
            self._pool_cache.invalidate(name)
            return None
        if op.in_progress:
            raise CreateError(
                f"nodepool {name} create in progress; requeueing",
                reason=REASON_CREATE_IN_PROGRESS)
        self.tracker.pop(name)
        # terminal either way: any entry cached during the wait predates
        # the outcome (the blocking path invalidates at the same point)
        self._pool_cache.invalidate(name)
        if not op.succeeded:
            raise CreateError(op.message,
                              reason=op.reason or REASON_LAUNCH_FAILED)
        # cut line: the create LRO has completed server-side but nothing —
        # cache invalidation, node wait, claim status — has recorded it yet
        self._crash("before_lro_done", name)
        try:
            created = await self._get_pool(name)
        except APIError as e:
            if e.not_found:
                self._pool_cache.invalidate(name)
                raise CreateError(
                    f"nodepool {name} vanished after its create completed; "
                    "requeueing", reason=REASON_CREATE_IN_PROGRESS) from e
            raise CreateError(f"reading created nodepool {name}: {e}") from e
        # a fallback walk may have created the pool as a less-preferred
        # shape: the pool's own instance-type label is authoritative
        shape = cat.lookup(
            created.config.labels.get(wk.INSTANCE_TYPE_LABEL, "")) or shape
        nodes = ready_workers(await self._nodes_of_pool(name))
        return self._to_instance(created, shape=shape, nodes=nodes)

    def _register_create(self, name: str, hosts: int) -> None:
        self.tracker.track_create(name, hosts, self._create_budget(hosts))

    def _create_budget(self, hosts: int) -> float:
        """Tracked-create budget: the adoption wait (LRO phase) plus the
        host-scaled node wait — the same two budgets the blocking path
        spends sequentially."""
        attempts = self.cfg.node_wait_attempts + 5 * (hosts - 1)
        return ((self.cfg.node_wait_attempts + attempts)
                * self.cfg.node_wait_interval)

    def _delete_budget(self) -> float:
        return 2 * self.cfg.node_wait_attempts * self.cfg.node_wait_interval

    def resume_create(self, name: str, hosts: int) -> bool:
        """Recovery seam: re-register a stranded in-flight create (an LRO a
        dead incarnation issued) with the tracker, so the startup resync
        resumes it through the batched poller instead of leaving the claim
        to rediscover it via conflict adoption. Returns False when no
        tracker is wired (the lifecycle re-drive then owns resumption)."""
        if self.tracker is None:
            return False
        self._register_create(name, max(1, hosts))
        return True

    async def create_and_wait(self, nc: NodeClaim,
                              timeout: float = 120.0) -> Instance:
        """Blocking driver over the resumable state machine — for direct
        provider use (tests, tooling) with no reconciler to own the requeue
        loop. Without a tracker a single ``create()`` already blocks."""
        deadline = asyncio.get_event_loop().time() + timeout
        while True:
            try:
                return await self.create(nc)
            except CreateError as e:
                if e.reason != REASON_CREATE_IN_PROGRESS:
                    raise
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    raise
                op = (self.tracker.poke(nc.metadata.name)
                      if self.tracker is not None else None)
                if op is not None and op.in_progress:
                    try:
                        await asyncio.wait_for(op.done.wait(),
                                               timeout=remaining)
                    except asyncio.TimeoutError:
                        raise e from None
                else:
                    await asyncio.sleep(self.cfg.node_wait_interval)

    def _crash(self, point: str, key: str) -> None:
        if self.crashes is not None:
            self.crashes.hit(point, key)

    def _span(self, claim: str, name: str, **attrs):
        """Tracer span or a free no-op — the provider never requires the
        observability package."""
        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(claim, name, **attrs)

    def _fence_check(self) -> None:
        # Single-writer guard: raises FencedError for a deposed leader. The
        # error is deliberately not an APIError — it takes the generic
        # workqueue error path, which a dying incarnation's fenced workers
        # then drop on dequeue.
        if self.fence is not None:
            self.fence.check()
        # Partition fence: while the governor reports the kube apiserver
        # PARTITIONED, refuse cloud mutations outright (same generic error
        # path — rate-limited requeue; the claim retries once the governor
        # leaves PARTITIONED). The schedfuzz partition-fenced-mutate checker
        # asserts no cloud-mutate ever lands inside that mode.
        if (self.api_governor is not None
                and self.api_governor.partition_fenced()):
            raise PartitionFencedError(
                "cloud mutation refused: kube apiserver partitioned — "
                "outcome could not be recorded")
        # emitted even with no fence wired (the check ran and passed) —
        # schedfuzz's fence-before-mutate contract observes the discipline,
        # not the token
        probes.emit("fence-check", None)

    async def _adopt_inflight_create(self, name: str) -> None:
        """Resume another incarnation's in-flight create: poll the pool's
        state until it leaves PROVISIONING within the node-wait budget.

        The old behavior fell straight through to ``_wait_for_nodes``, which
        blind-waits against a pool that may have landed in ERROR — burning
        the whole wait budget (and a slice of the launch liveness budget)
        per retry on a pool that will never produce nodes. ERROR/degraded
        pools now surface as a terminal ``CreateError`` immediately; the
        retry's ``begin_create`` replaces the carcass. Reads go through the
        read-through cache (coalesced; ttl ≪ budget) and, against the fake
        cloud, drive the server-side LRO settle."""
        budget = self.cfg.node_wait_attempts * self.cfg.node_wait_interval
        ladder = BackoffLadder(budget, self.cfg.node_wait_interval)
        while True:
            try:
                pool = await self._get_pool(name)
            except APIError as e:
                if e.not_found:
                    self._pool_cache.invalidate(name)
                    raise CreateError(
                        f"nodepool {name} vanished while adopting an "
                        "in-flight create; requeueing",
                        reason=REASON_CREATE_IN_PROGRESS) from e
                raise CreateError(f"adopting nodepool {name}: {e}") from e
            if pool.status == NP_ERROR:
                self._pool_cache.invalidate(name)
                raise CreateError(
                    f"nodepool {name} is ERROR after an adopted create: "
                    f"{pool.status_message or 'unknown failure'}",
                    reason=REASON_DEGRADED_POOL)
            if pool.status == NP_STOPPING:
                self._pool_cache.invalidate(name)
                raise CreateError(
                    f"nodepool {name} is being deleted; requeueing",
                    reason=REASON_CREATE_IN_PROGRESS)
            if pool.status != NP_PROVISIONING:
                return  # RUNNING/RECONCILING — fall through to the node wait
            if ladder.expired():
                raise CreateError(
                    f"nodepool {name} still PROVISIONING after {budget:.0f}s "
                    "adopted-create wait; requeueing",
                    reason=REASON_CREATE_IN_PROGRESS)
            await ladder.sleep()

    def _queued_mode(self, nc: NodeClaim, reqs: Requirements) -> bool:
        if self.queued is None:
            return False
        mode = nc.metadata.annotations.get(PROVISIONING_MODE_ANNOTATION, "")
        capacity = reqs.get(wk.CAPACITY_TYPE_LABEL).values()
        return mode == MODE_QUEUED or wk.CAPACITY_TYPE_RESERVED in capacity

    async def _ensure_queued_resource(self, nc: NodeClaim, shape: cat.SliceShape,
                                      capacity_type: str) -> None:
        """Drive the QueuedResource state machine without blocking.

        ACTIVE → proceed to node-pool create. WAITING/CREATING/ACCEPTED →
        raise a retryable CreateError so the launch reconciler requeues with
        backoff (async analog of PollUntilDone). SUSPENDED/FAILED →
        InsufficientCapacity, which terminates the NodeClaim (launch.go:84-95).
        """
        name = nc.metadata.name
        try:
            # singleflight-coalesced: a burst of reconciles for the same
            # claim shares one in-flight cloud GET (qr_cache_ttl defaults to
            # 0 — coalescing without serving stale ladder states)
            qr = await self._qr_cache.get(name)
        except APIError as e:
            if not e.not_found:
                raise CreateError(f"getting queued resource {name}: {e}") from e
            self._qr_cache.invalidate(name)  # drop the negative entry …
            self._fence_check()
            qr = await self.queued.create(QueuedResource(
                name=name, accelerator_type=shape.slice_name, node_pool=name,
                spot=capacity_type == wk.CAPACITY_TYPE_SPOT))
            # cut line: queued capacity exists in the cloud, nothing recorded
            self._crash("after_qr_create", name)
            self._qr_cache.invalidate(name)  # … and anything raced in since
        if qr.state in (QR_SUSPENDED, QR_FAILED):
            raise InsufficientCapacityError(
                f"queued resource {name} {qr.state}: {qr.state_message}")
        if qr.state != QR_ACTIVE:
            raise CreateError(
                f"queued resource {name} is {qr.state}; requeueing",
                reason=REASON_QUEUED_PROVISIONING)

    async def _slice_group_identity(self, nc: NodeClaim) -> dict[str, str]:
        """Multi-slice identity labels for a slice-group member.

        Closes the loop VERDICT/SURVEY call out: ``SliceTopology`` consumes
        ``slice-index`` / ``num-slices`` / ``coordinator``, so the provider
        must produce them. Assignment is **sticky** (an index already stamped
        on an existing pool is authoritative — crash-restart and re-reconcile
        safe) and **deterministic** under concurrent creates: unstamped
        members take the lowest free indices in (creationTimestamp, name)
        order of the group's NodeClaims, so every racing reconciler computes
        the same assignment without coordination. The coordinator is worker 0
        of slice 0 (its GKE instance hostname is derivable from the pool name
        alone). Generalizes the label-stamp-at-create seam of the reference
        (instance.go:321-369 + registration.go:120-147 label sync).
        """
        group = nc.metadata.labels.get(wk.TPU_SLICE_GROUP_LABEL, "")
        if not group:
            return {}

        # claims FIRST (live/informer read): their name-set is the
        # freshness fingerprint the pool snapshot is validated against.
        # Deleting members are excluded: a claim in finalize must not
        # reserve an index in the assignment order — its pool can already
        # be gone server-side while the finalizer drains, and a
        # replacement member racing that window would be pushed past the
        # freed index forever (the index is sticky once stamped).
        claims = [c for c in await self.kube.list(
                      NodeClaim, labels={wk.TPU_SLICE_GROUP_LABEL: group})
                  if c.metadata.deletion_timestamp is None]
        pools = await self._pools_snapshot(
            group, frozenset(c.metadata.name for c in claims))
        used: dict[int, str] = {}          # stamped index -> pool name
        for p in pools:
            if p.config.labels.get(wk.TPU_SLICE_GROUP_LABEL) != group:
                continue
            idx = p.config.labels.get(wk.TPU_SLICE_INDEX_LABEL, "")
            if idx.isdigit():
                used[int(idx)] = p.name

        mine = next((i for i, n in used.items() if n == nc.metadata.name), None)
        ordered = sorted(claims, key=lambda c: (
            fmt_time(c.metadata.creation_timestamp)
            if c.metadata.creation_timestamp else "", c.metadata.name))
        stamped_names = set(used.values())
        unstamped = [c.metadata.name for c in ordered
                     if c.metadata.name not in stamped_names]

        free = (i for i in range(len(used) + len(unstamped) + 1)
                if i not in used)
        assignment = dict(zip(unstamped, free))
        if mine is None:
            mine = assignment.get(nc.metadata.name)
        if mine is None:  # claim not (yet) listable — lowest index no other
            taken = set(used) | set(assignment.values())  # member can hold
            mine = next(i for i in range(len(taken) + 1) if i not in taken)

        owner0 = used.get(0) or next(
            (n for n, i in assignment.items() if i == 0), None)
        if owner0 is None and mine == 0:
            owner0 = nc.metadata.name

        declared = nc.metadata.labels.get(wk.TPU_NUM_SLICES_LABEL, "")
        num_slices = (declared if declared.isdigit() and int(declared) > 0
                      else str(max(len(stamped_names | set(unstamped)),
                                   mine + 1)))
        labels = {wk.TPU_SLICE_INDEX_LABEL: str(mine),
                  wk.TPU_NUM_SLICES_LABEL: num_slices}
        # Never stamp a coordinator guess that no process-0 will serve; the
        # slice-group controller fills/repairs it on the nodes as the group
        # converges (controllers/slicegroup.py).
        if owner0 is not None:
            labels[wk.TPU_COORDINATOR_LABEL] = instance_name(
                self.cfg.cluster, owner0, 0)
        return labels

    def _capacity_type(self, reqs: Requirements) -> str:
        vals = reqs.get(wk.CAPACITY_TYPE_LABEL).values()
        return vals[0] if vals else wk.CAPACITY_TYPE_ON_DEMAND

    def _new_nodepool_object(self, nc: NodeClaim, shape: cat.SliceShape,
                             capacity_type: str,
                             extra_labels: Optional[dict[str, str]] = None,
                             zone: str = "", tier: str = "") -> NodePool:
        """Build the desired NodePool (analog: newAgentPoolObject,
        instance.go:321-369). ``zone``/``tier`` record the placement
        verdict on the pool's labels (and through them on every node the
        slice materializes); they default off so direct callers keep the
        pre-placement shape."""
        labels = {
            wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME,           # :330
            wk.KAITO_MACHINE_TYPE_LABEL: "tpu",                  # :335-339
            wk.KAITO_CREATION_TIMESTAMP_LABEL: ts_label(now()),  # :340-342
            **shape.node_labels(slice_id=nc.metadata.name, zone=zone,
                                capacity_tier=tier or capacity_type),
            **(extra_labels or {}),
        }
        for key in (wk.KAITO_WORKSPACE_LABEL, wk.KAITO_RAGENGINE_LABEL,
                    wk.TPU_SLICE_GROUP_LABEL):
            if key in nc.metadata.labels:
                labels[key] = nc.metadata.labels[key]

        disk = 0
        storage = nc.spec.resources.requests.get("storage", "")
        if storage:
            try:
                disk = parse_gi(storage)  # :344-353 storage request → disk size
            except ValueError as e:
                raise CreateError(f"invalid storage request {storage!r}: {e}",
                                  reason=REASON_INVALID_STORAGE_REQUEST) from e

        image = image_family_to_image_type(
            nc.metadata.annotations.get(wk.KAITO_NODE_IMAGE_FAMILY_ANNOTATION, ""))

        taints = [{"key": wk.TPU_TAINT, "value": "present", "effect": "NO_SCHEDULE"}]
        return NodePool(
            name=nc.metadata.name,
            config=NodePoolConfig(
                machine_type=shape.machine_type,
                disk_size_gb=disk,
                labels=labels,
                taints=taints,
                spot=(tier or capacity_type) == wk.CAPACITY_TYPE_SPOT,
                image_type=image,
            ),
            initial_node_count=shape.hosts,  # generalizes Count=1 (:365)
            placement_policy=PlacementPolicy(tpu_topology=shape.topology),
        )

    async def _wait_for_nodes(self, pool: str, hosts: int) -> list[Node]:
        """Wait for all hosts' Node objects to exist with providerIDs
        (generalizes instance.go:124-149; correlation by the GKE node-pool
        label, the analog of getNodesByName's agentpool labels :371-385).

        Polls back off exponentially along the shared ``BackoffLadder``
        (base interval ×1.5, capped, jittered) within the attempts×interval
        time budget: a provisioning wave of hundreds of concurrent creates
        polling at the base rate melts the apiserver/event loop, and a miss
        here is retryable anyway (NodesNotReady → workqueue backoff owns the
        longer wait)."""
        attempts = self.cfg.node_wait_attempts + 5 * (hosts - 1)
        budget = attempts * self.cfg.node_wait_interval
        ladder = BackoffLadder(budget, self.cfg.node_wait_interval,
                               jitter=self.cfg.node_wait_jitter)
        ready: list[Node] = []
        while True:
            # per-poll reads go through self.kube: wired behind the informer
            # (CachedListClient) this is watch-cache maintenance, not a fresh
            # apiserver LIST per iteration — hundreds of concurrent waits
            # poll for free
            nodes = await self._nodes_of_pool(pool)
            ready = ready_workers(nodes)
            if len(ready) >= hosts:
                return ready
            if ladder.expired():
                break
            await ladder.sleep()
        raise CreateError(
            f"nodepool {pool}: only {len(ready)}/{hosts} nodes appeared with "
            "providerIDs before timeout", reason=REASON_NODES_NOT_READY)

    async def _nodes_of_pool(self, pool: str) -> list[Node]:
        return await self.kube.list(Node, labels={wk.GKE_NODEPOOL_LABEL: pool})

    # ---------------------------------------------------------- get/list
    async def _get_pool(self, name: str) -> NodePool:
        """Read-through, singleflight-coalesced ``nodepools.get`` — the hot
        point lookup every lifecycle/termination reconcile re-drives."""
        return await self._pool_cache.get(name)

    async def get(self, pid: str) -> Instance:
        pool_name = await self._pool_name_for(pid)
        if pool_name is None:
            raise NodeClaimNotFoundError(f"no node pool for providerID {pid}")
        try:
            pool = await self._get_pool(pool_name)
        except APIError as e:
            if e.not_found:
                raise NodeClaimNotFoundError(f"nodepool {pool_name} not found") from e
            raise
        return await self._from_pool(pool)

    async def _pool_name_for(self, pid: str) -> Optional[str]:
        if has_index(self.kube):
            # the index applies the same predicate the scan would — an empty
            # answer is authoritative, never fall through to the O(nodes)
            # scan for it (every terminated claim's node is a permanent miss)
            nodes = await self.kube.list(Node, index=("spec.providerID", pid))
        else:
            nodes = [n for n in await self.kube.list(Node)
                     if n.spec.provider_id == pid]
        if nodes:
            pool = nodes[0].metadata.labels.get(wk.GKE_NODEPOOL_LABEL)
            if pool:
                return pool
        return parse_nodepool_from_provider_id(pid, self.cfg.cluster)

    async def list(self) -> list[Instance]:
        """All kaito-owned, nodeclaim-created instances (fromAPListToInstances
        :289-319 + ownership gates :387-413).

        Fast path: ONE bulk kube Node list grouped by the GKE node-pool
        label. With the per-pool I/O collapsed into the bulk list, the
        remaining per-pool conversion is pure CPU (catalog lookup + field
        mapping) — no fan-out machinery, just a comprehension. The
        pre-change shape — one kube list per pool, serially — cost a
        100-slice cluster ~100 sequential apiserver round-trips per GC
        tick; it survives only as the benchmark baseline
        (``cfg.legacy_list``)."""
        pools = await self.nodepools.list()
        owned = [p for p in pools
                 if pool_owned_by_kaito(p) and pool_created_from_nodeclaim(p)]
        if self.cfg.legacy_list:
            return [await self._from_pool(p) for p in owned]

        # narrowed to kaito-owned nodes (the pool's labels propagate to its
        # nodes): in a shared cluster the bulk list must not drag thousands
        # of foreign Node objects out of the informer cache per GC tick
        nodes_by_pool = _group_by_pool(await self.kube.list(
            Node, labels={wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME}))
        return [
            self._to_instance(
                p,
                shape=cat.lookup(p.config.labels.get(wk.INSTANCE_TYPE_LABEL, "")),
                nodes=nodes_by_pool.get(p.name, []))
            for p in owned
        ]

    async def _from_pool(self, pool: NodePool) -> Instance:
        nodes = await self._nodes_of_pool(pool.name)
        shape = cat.lookup(pool.config.labels.get(wk.INSTANCE_TYPE_LABEL, ""))
        return self._to_instance(pool, shape=shape, nodes=nodes)

    def _to_instance(self, pool: NodePool, shape: Optional[cat.SliceShape],
                     nodes: list[Node]) -> Instance:
        nodes = ready_workers(nodes)
        pids = [n.spec.provider_id for n in nodes]
        return Instance(
            name=pool.name,
            state=_NP_STATE_MAP.get(pool.status, STATE_CREATING),
            id=pids[0] if pids else "",
            image_id=pool.config.image_type,
            type=shape.name if shape else pool.config.machine_type,
            capacity_type=(pool.config.labels.get(wk.TPU_CAPACITY_TIER_LABEL)
                           or (wk.CAPACITY_TYPE_SPOT if pool.config.spot
                               else wk.CAPACITY_TYPE_ON_DEMAND)),
            labels=dict(pool.config.labels),
            topology=shape.topology if shape else "",
            hosts=pool.initial_node_count,
            chips=shape.chips if shape else 0,
            node_provider_ids=pids,
        )

    # ------------------------------------------------------------- delete
    async def delete_queued(self, name: str) -> None:
        """Fenced queued-resource teardown (NotFound is success). The ONE
        path every QR delete goes through — delete() and the recovery
        pass's orphan reap alike — so the fencing check and the cache
        invalidation can never be bypassed."""
        if self.queued is None:
            return
        try:
            self._fence_check()
            await self.queued.delete(name)
        except APIError as e:
            if not e.not_found:
                raise
        finally:
            # unconditionally: success AND failure paths must both drop
            # any cached QR view — a cached entry must never make a
            # retried delete() skip the queued-resource cleanup
            self._qr_cache.invalidate(name)

    async def delete(self, name: str) -> None:
        """Get-first delete: skip if already Deleting, map NotFound →
        NodeClaimNotFoundError (armutils.go:42-76).

        Queued-resource cleanup runs FIRST and unconditionally: a claim can
        die before its pool ever exists — queued capacity stuck in the
        stockout ladder until launch liveness reaps the claim — and keying
        the cleanup off a successful pool get would leak that queued
        resource forever (found by the stuck-queue chaos profile).

        With a tracker wired the delete is non-blocking: ``begin_delete``
        registers a tracked delete op and returns immediately ("still
        terminating"); subsequent calls consume the tracked outcome —
        in flight → return at zero further cloud calls, succeeded → the
        NodeClaimNotFoundError the finalizer is waiting for."""
        with self._span(name, "delete-queued"):
            await self.delete_queued(name)
        if self.tracker is not None:
            top = self.tracker.poke(name)
            if top is not None and top.kind == OP_DELETE:
                if top.in_progress:
                    return  # our own delete LRO is still running
                self.tracker.pop(name)
                self._pool_cache.invalidate(name)
                if top.succeeded:
                    # same post-completion hygiene as the blocking path:
                    # the snapshot may still list the dying pool
                    async with self._pool_snapshot_lock:
                        self._pool_snapshot = None
                    raise NodeClaimNotFoundError(f"nodepool {name} not found")
                # DeleteTimeout: fall through and re-drive the live path
        # LIVE read, deliberately around the cache: delete decisions (skip
        # if already Deleting) must never ride a stale cached status.
        try:
            pool = await self.nodepools.get(name)
        except APIError as e:
            if e.not_found:
                self._pool_cache.invalidate(name)
                if self.tracker is not None:
                    # the pool is proven gone and this claim is unwinding —
                    # nothing will ever consume an op parked under the name
                    self.tracker.discard(name)
                raise NodeClaimNotFoundError(f"nodepool {name} not found") from e
            raise
        if pool.status == NP_STOPPING:
            # an out-of-band delete is in flight: drop any cached pre-delete
            # view so get() reports Deleting, not a stale RUNNING (every
            # other observed transition invalidates — keep the symmetry)
            self._pool_cache.invalidate(name)
            if self.tracker is not None:
                # adopt the stranded/out-of-band delete LRO: the tracker's
                # completion wakes the finalizer instead of leaving it to
                # rediscover the disappearance a fixed requeue later
                self.tracker.track_delete(name, self._delete_budget())
            log.info("nodepool %s already deleting, skipping", name)
            return
        try:
            self._fence_check()
            with self._span(name, "begin-delete"):
                op = await self.nodepools.begin_delete(name)
            self._pool_cache.invalidate(name)  # state transition: Deleting
            # cut line: delete LRO issued (QR already cleaned up), unpolled
            self._crash("mid_delete_after_pool_delete", name)
            if self.tracker is not None:
                # non-blocking: hand the LRO to the multiplexer and report
                # "still terminating" — the termination requeue (woken early
                # on completion) consumes the outcome above
                self.tracker.track_delete(name, self._delete_budget())
                return
            await poll_until_done(op)
            # again after the poll: a read begun mid-delete may have cached
            # the dying pool between the first invalidation and completion
            self._pool_cache.invalidate(name)
            # belt-and-braces: the claim-set fingerprint in _pools_snapshot
            # is the primary freshness guard (a departed member changes the
            # live claim list); dropping the snapshot on OUR OWN pool
            # deletes closes the narrow window where the pool is gone but
            # the claim briefly remains. AFTER the poll and UNDER the lock:
            # dropped earlier, an in-flight refresh could list the dying
            # pool and overwrite the invalidation with pre-delete state.
            async with self._pool_snapshot_lock:
                self._pool_snapshot = None
        except APIError as e:
            if e.not_found:
                self._pool_cache.invalidate(name)
                if self.tracker is not None:
                    self.tracker.discard(name)
                raise NodeClaimNotFoundError(f"nodepool {name} not found") from e
            raise


# --------------------------------------------------------------- helpers

async def _no_fetch(name: str):
    raise APIError(f"queued resources API not configured ({name})", code=404)


def _group_by_pool(nodes: list[Node]) -> dict[str, list[Node]]:
    """Bulk Node list → per-pool buckets keyed by the GKE node-pool label —
    the one pass that replaces a kube list per pool in the fast path."""
    by_pool: dict[str, list[Node]] = defaultdict(list)
    for n in nodes:
        pool = n.metadata.labels.get(wk.GKE_NODEPOOL_LABEL)
        if pool:
            by_pool[pool].append(n)
    return by_pool


def ready_workers(nodes: list[Node]) -> list[Node]:
    """ProviderID'd nodes in worker-index order — the single normalization
    both the node wait and instance conversion need (hoisted: each used to
    filter+sort independently)."""
    return sorted((n for n in nodes if n.spec.provider_id), key=worker_index)


def ts_label(t) -> str:
    """RFC3339 isn't label-safe; use the reference's datetime label trick
    (instance.go:43-45 uses a custom layout) — here compact YYYYMMDDTHHMMSSZ."""
    return fmt_time(t).replace("-", "").replace(":", "")


def parse_ts_label(s: str):
    try:
        return parse_time(f"{s[0:4]}-{s[4:6]}-{s[6:11]}:{s[11:13]}:{s[13:]}")
    except (ValueError, IndexError):
        return None


def parse_gi(q: str) -> int:
    """Parse a Kubernetes storage Quantity to whole GiB. Raises ValueError on
    unparseable input (callers map it into the CreateError taxonomy)."""
    q = q.strip()
    for suffix, mult in (("Gi", 1), ("G", 1), ("Ti", 1024), ("T", 1000), ("Mi", 0), ("M", 0)):
        if q.endswith(suffix):
            val = float(q[: -len(suffix)])
            return int(val * mult) if mult else max(1, int(val / 1024))
    return int(float(q) / (1024 ** 3)) if q else 0


def image_family_to_image_type(family: str) -> str:
    """kaito.sh/node-image-family annotation → GKE image type (the analog of
    imageFamilyToOSSKU, instance.go:431, Ubuntu/AzureLinux → OSSKU)."""
    return {
        "": "",
        "cos": "COS_CONTAINERD",
        "ubuntu": "UBUNTU_CONTAINERD",
    }.get(family.lower(), "")


def pool_owned_by_kaito(pool: NodePool) -> bool:
    return pool.config.labels.get(wk.NODEPOOL_LABEL) == wk.KAITO_NODEPOOL_NAME


def pool_created_from_nodeclaim(pool: NodePool) -> bool:
    return wk.KAITO_CREATION_TIMESTAMP_LABEL in pool.config.labels


def worker_index(node: Node) -> int:
    try:
        return int(node.metadata.labels.get(wk.TPU_WORKER_INDEX_LABEL, "0"))
    except ValueError:
        return 0


def has_index(kube: Client) -> bool:
    """True if ``kube.list(Node, index=("spec.providerID", …))`` takes an
    index path. Walks wrapper layers (CachedListClient._indexes, ChaosClient
    .inner, raw client .store) — the index used to go undetected behind the
    informer/chaos wrappers, silently degrading ``_pool_name_for`` to the
    O(nodes) full-scan fallback."""
    seen: set[int] = set()
    while kube is not None and id(kube) not in seen:
        seen.add(id(kube))
        if (Node, "spec.providerID") in getattr(kube, "_indexes", {}):
            return True
        store = getattr(kube, "store", None)
        if store is not None and \
                (Node, "spec.providerID") in getattr(store, "_indexes", {}):
            return True
        kube = getattr(kube, "inner", None)
    return False
