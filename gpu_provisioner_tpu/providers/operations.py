"""Operation tracker: the shared LRO multiplexer behind non-blocking creates.

PR 2 made the *read* path fast; this module unblocks the *write* path. The
blocking shape — ``InstanceProvider.create()`` parked inside
``poll_until_done`` plus a per-create node-wait sleep loop — pins one
lifecycle worker for the full slice-create duration, so a 1000-claim wave
(the reference's lifecycle concurrency regime) serializes behind
``max_concurrent`` sleeping workers and polls the cloud once per in-flight
operation per interval.

``OperationTracker`` inverts that: a **single background poller** owns every
in-flight create/delete LRO and node-wait, drives them all off **one batched
``nodepools.list`` per tick** (O(1) cloud calls per tick instead of
O(in-flight) per-pool ``get``s), applies per-operation deadlines, and backs
its tick cadence off while nothing changes. Callers never block:

- ``track_create(name, hosts, budget)`` / ``track_delete(name, budget)``
  register an operation (idempotent — re-registering an in-flight op is a
  no-op, which is what a requeued reconcile does);
- ``poke(name)`` is an await-free snapshot of the operation's phase;
- ``pop(name)`` consumes a terminal operation (the caller acts on the
  outcome exactly once);
- ``subscribe(cb)`` registers an async completion callback — the
  controller-runtime wiring injects the pool's request back into the
  lifecycle workqueue, so a ``Result(requeue_after=...)`` parked claim is
  woken the tick its operation completes rather than a full requeue later.

``BackoffLadder`` is the deadline/backoff ladder ``_adopt_inflight_create``
and ``_wait_for_nodes`` each used to grow independently (base interval ×
factor, capped at budget/4, jittered, inside an overall budget) — hoisted
here so the blocking fallback paths and the tracker tick share one
implementation.

Metrics follow the providers.cache convention: this layer never imports
prometheus; module-level registries (``TRACKERS``, ``POLL_BATCHES``,
``drain_operation_waits``) are sampled by ``controllers/metrics.py`` at
scrape time.
"""

from __future__ import annotations

import asyncio
import logging
import random
import time
import weakref
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Optional

from ..apis import labels as wk
from ..apis.core import Node
from ..errors import (
    REASON_CREATE_IN_PROGRESS, REASON_CREATED, REASON_DEGRADED_POOL,
    REASON_DELETE_TIMEOUT, REASON_DELETED, REASON_DISCARDED,
    REASON_NODES_NOT_READY, REASON_SUPERSEDED,
)

log = logging.getLogger("providers.operations")

# Operation kinds.
OP_CREATE = "create"
OP_DELETE = "delete"

# Operation phases (OperationPhase): InProgress until the poller resolves the
# op, then exactly one terminal phase.
PHASE_IN_PROGRESS = "InProgress"
PHASE_SUCCEEDED = "Succeeded"
PHASE_FAILED = "Failed"

# GKE node-pool statuses the tick branches on (string literals to keep this
# module import-light; values match providers.gcp NP_*).
_NP_PROVISIONING = "PROVISIONING"
_NP_RUNNING = "RUNNING"
_NP_RECONCILING = "RECONCILING"
_NP_STOPPING = "STOPPING"
_NP_ERROR = "ERROR"

# ---------------------------------------------------------------- registries
# Live trackers (inflight gauges are point-in-time: they must be read off the
# live objects; the weak set lets test/bench trackers die naturally).
TRACKERS: "weakref.WeakSet[OperationTracker]" = weakref.WeakSet()

# Cumulative batched-poll count across tracker instances (sampled into the
# tpu_provisioner_operation_poll_batches gauge).
POLL_BATCHES = {"count": 0}

# Completed-operation wait durations, drained into the
# tpu_provisioner_operation_wait_seconds histogram at scrape time. Bounded:
# an operator whose /metrics is never scraped keeps only the newest samples
# instead of growing one tuple per operation forever.
_OPERATION_WAITS: list[tuple[str, float]] = []
_MAX_WAIT_SAMPLES = 4096


def record_operation_wait(kind: str, seconds: float) -> None:
    _OPERATION_WAITS.append((kind, seconds))
    if len(_OPERATION_WAITS) > _MAX_WAIT_SAMPLES:
        del _OPERATION_WAITS[:len(_OPERATION_WAITS) - _MAX_WAIT_SAMPLES]


def drain_operation_waits() -> list[tuple[str, float]]:
    """Hand the accumulated (kind, seconds) samples to the scraper exactly
    once each."""
    global _OPERATION_WAITS
    out, _OPERATION_WAITS = _OPERATION_WAITS, []
    return out


def loop_now() -> float:
    """The monotonic clock seam: the loop clock inside async contexts (what
    every sleep is measured against); ``time.monotonic`` outside one (sync
    unit tests of the ladder). Controllers use THIS — never naked
    ``time.monotonic()`` — so timing stays on the clock envtest's sleeps
    run against (provlint PL004)."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()


_now = loop_now  # internal shorthand, predates the public seam


# ------------------------------------------------------------ backoff ladder

class BackoffLadder:
    """Deadline + growing-interval poll ladder.

    One home for the shape two call sites each hand-rolled: start at ``base``
    seconds, grow ×``factor`` per step, cap at ``cap`` (default budget/4 —
    a poll loop must get several looks within its own budget), jitter each
    delay by up to ``jitter`` fraction, and expire at ``budget`` seconds
    from construction. ``rng`` is injectable for deterministic tests.
    """

    def __init__(self, budget: float, base: float, jitter: float = 0.0,
                 factor: float = 1.5, cap: Optional[float] = None,
                 rng: Callable[[], float] = random.random):
        self.budget = budget
        self.base = base
        self.jitter = jitter
        self.factor = factor
        self.cap = cap if cap is not None else max(base, budget / 4)
        self._rng = rng
        self.interval = base
        self.deadline = _now() + budget

    def expired(self) -> bool:
        return _now() >= self.deadline

    def next_delay(self) -> float:
        """The next sleep: current interval (jittered), then advance the
        ladder. The returned delay is never above cap·(1+jitter)."""
        delay = self.interval * (1 + self._rng() * self.jitter)
        self.interval = min(self.interval * self.factor, self.cap)
        return delay

    def reset(self) -> None:
        """Back to the base cadence (something changed; look closely again)."""
        self.interval = self.base

    async def sleep(self) -> None:
        await asyncio.sleep(self.next_delay())


# ------------------------------------------------------------- tracked ops

@dataclass
class TrackedOperation:
    """One in-flight create/delete: the tracker's unit of work and the
    caller-visible OperationPhase carrier."""

    kind: str
    name: str
    hosts: int = 1
    deadline: float = 0.0
    started: float = 0.0
    phase: str = PHASE_IN_PROGRESS
    reason: str = ""
    message: str = ""
    wait_seconds: float = 0.0
    completed_at: float = 0.0
    # First tick the create's cloud-side LRO was observed resolved (pool
    # RUNNING/RECONCILING) — splits the op's wait into its LRO and
    # node-wait phases for claimtrace attribution. 0.0 = never observed.
    lro_done_at: float = 0.0
    done: asyncio.Event = field(default_factory=asyncio.Event)

    @property
    def in_progress(self) -> bool:
        return self.phase == PHASE_IN_PROGRESS

    @property
    def succeeded(self) -> bool:
        return self.phase == PHASE_SUCCEEDED


class OperationTracker:
    """The shared LRO multiplexer: one poller task, one batched
    ``nodepools.list`` per tick, every in-flight operation resolved against
    that snapshot.

    ``nodepools`` is the provider's *counted* seam (so poll batches show up
    in the per-endpoint cloud-call accounting) and ``kube`` the same
    (informer-backed where wired) client the provider reads nodes through —
    per-op node-wait checks are watch-cache maintenance, not apiserver
    round-trips.

    The poller idles (zero cloud calls) while no operation is registered,
    wakes on registration, polls at ``interval``, and backs off ×1.5 up to
    ``max_interval`` across ticks where nothing changed — a fleet-wide wave
    polls at the base cadence exactly while state is moving. Each tick's
    list call is bounded by ``poll_timeout`` so one hung cloud call cannot
    wedge every operation behind it (the chaos hang profiles).

    Terminal operations stay parked until their caller consumes them
    (``pop``); ones with no returning caller are pruned after
    ``TERMINAL_RETENTION`` seconds.
    """

    def __init__(self, nodepools, kube, interval: float = 1.0,
                 max_interval: Optional[float] = None,
                 jitter: float = 0.1,
                 poll_timeout: Optional[float] = None):
        self.nodepools = nodepools
        self.kube = kube
        self.interval = interval
        self.max_interval = max_interval if max_interval is not None \
            else interval * 8
        self.jitter = jitter
        self.poll_timeout = poll_timeout if poll_timeout is not None \
            else max(10 * interval, 2.0)
        self._ops: dict[str, TrackedOperation] = {}
        self._subs: list[Callable[[TrackedOperation], Awaitable[None]]] = []
        self._task: Optional[asyncio.Task] = None
        # In-flight subscriber notifications: fire-and-forget from the poll
        # loop's perspective, but RETAINED so stop() can reap them — an
        # unretained notify task outliving its tracker kept injecting into
        # a dead incarnation's workqueue (provlint PL007; the PR 4 tracker
        # bug class applied to the tracker's own callbacks).
        self._notify_tasks: set[asyncio.Task] = set()
        self._wake = asyncio.Event()
        self._stopping = False
        # observability (tests, /metrics sampling)
        self.poll_batches = 0
        self.poll_errors = 0
        self.registered: dict[str, int] = {OP_CREATE: 0, OP_DELETE: 0}
        self.completed_total = 0
        TRACKERS.add(self)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._task is None or self._task.done():
            self._stopping = False
            self._task = asyncio.create_task(
                self._run(), name=f"operation-tracker/{id(self):x}")

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            # belt AND braces: py3.10's wait_for swallows a cancellation
            # that races a completed inner future (bpo-42130), so cancel
            # alone can leave the poller alive and parked on _wake forever
            # while we await it — the flag + wake makes the loop exit on
            # its own at the next resume even when the cancel is eaten
            self._stopping = True
            self._wake.set()
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        # reap in-flight subscriber notifications: completion wakes belong
        # to THIS incarnation's workqueues, which are being torn down too
        for t in list(self._notify_tasks):
            t.cancel()
        if self._notify_tasks:
            await asyncio.gather(*self._notify_tasks, return_exceptions=True)
        self._notify_tasks.clear()

    def task_alive(self) -> bool:
        return self._task is not None and not self._task.done()

    # --------------------------------------------------------- registration
    def track_create(self, name: str, hosts: int,
                     budget: float) -> TrackedOperation:
        """Register (or return the already in-flight) create for ``name``.
        A terminal op still parked under the name is replaced — the caller
        that popped it acts exactly once; a caller that *didn't* pop simply
        re-drives the wait."""
        return self._track(OP_CREATE, name, hosts, budget)

    def track_delete(self, name: str, budget: float) -> TrackedOperation:
        """Register a delete for ``name``. Supersedes any create op under
        the same name (delete wins — mirrors the cloud ledger)."""
        return self._track(OP_DELETE, name, 0, budget)

    # Parked terminal ops whose consumer never returns (a reaped claimless
    # pool's delete has exactly one delete() call) are dropped after this
    # many seconds — claim churn must not grow the op table forever.
    TERMINAL_RETENTION = 600.0

    def _prune_terminal(self) -> None:
        cutoff = _now() - self.TERMINAL_RETENTION
        for name, op in list(self._ops.items()):
            if not op.in_progress and op.completed_at < cutoff:
                del self._ops[name]

    def _track(self, kind: str, name: str, hosts: int,
               budget: float) -> TrackedOperation:
        self._prune_terminal()
        op = self._ops.get(name)
        if op is not None and op.in_progress:
            if op.kind == kind:
                return op
            if kind == OP_CREATE:
                # a delete is in flight for the name; the create caller
                # observes it via poke() — never displace a delete
                return op
            # delete supersedes create: complete the create as failed so a
            # waiter blocked on op.done (create_and_wait) is released
            self._complete(op, PHASE_FAILED, REASON_SUPERSEDED,
                           f"nodepool {name} create superseded by delete",
                           notify=False)
        op = TrackedOperation(kind=kind, name=name, hosts=hosts,
                              started=_now(), deadline=_now() + budget)
        self._ops[name] = op
        self.registered[kind] += 1
        self._wake.set()
        return op

    # ------------------------------------------------------------- queries
    def poke(self, name: str) -> Optional[TrackedOperation]:
        """Await-free phase snapshot (None if nothing tracked)."""
        return self._ops.get(name)

    def pop(self, name: str) -> Optional[TrackedOperation]:
        """Consume a TERMINAL operation; in-flight ops stay put."""
        op = self._ops.get(name)
        if op is not None and not op.in_progress:
            del self._ops[name]
            return op
        return None

    def discard(self, name: str) -> None:
        """Drop whatever is tracked under ``name``, any phase. For callers
        that just proved the resource is GONE (pool 404 on the delete path):
        nothing will ever consume the op, and an in-flight one would only
        resolve to "vanished" next tick — parked entries must not accumulate
        across claim churn."""
        op = self._ops.pop(name, None)
        if op is not None and op.in_progress:
            self._complete(op, PHASE_FAILED, REASON_DISCARDED,
                           f"nodepool {name} is gone; operation discarded",
                           notify=False)

    def inflight(self) -> dict[str, int]:
        counts = {OP_CREATE: 0, OP_DELETE: 0}
        for op in self._ops.values():
            if op.in_progress:
                counts[op.kind] += 1
        return counts

    def subscribe(self, cb: Callable[[TrackedOperation],
                                     Awaitable[None]]) -> None:
        """Async ``cb(op)`` fired once per completed operation (the
        workqueue-injection early-wake seam)."""
        self._subs.append(cb)

    # --------------------------------------------------------------- poller
    async def _run(self) -> None:
        ladder = BackoffLadder(float("inf"), self.interval,
                               jitter=self.jitter, cap=self.max_interval)
        while not self._stopping:
            if not any(op.in_progress for op in self._ops.values()):
                self._wake.clear()
                if self._stopping:
                    return
                # idle: zero cloud calls until the next registration
                await self._wake.wait()
                ladder.reset()
            if self._stopping:
                return
            # pace the next batched poll; a registration landing mid-sleep
            # interrupts it and resets the cadence — new work must not wait
            # out a backed-off interval for its first observation
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(),
                                       timeout=ladder.next_delay())
                ladder.reset()
            except asyncio.TimeoutError:
                pass
            if self._stopping:
                return
            if await self._tick():
                ladder.reset()

    async def _tick(self) -> bool:
        """One batched poll; resolves every in-flight op against it.
        Returns True when any operation changed state."""
        self.poll_batches += 1
        POLL_BATCHES["count"] += 1
        self._prune_terminal()
        try:
            pools = await asyncio.wait_for(self.nodepools.list(),
                                           timeout=self.poll_timeout)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — weather; deadlines still run
            self.poll_errors += 1
            log.debug("tracker poll failed (retrying next tick): %s", e)
            return await self._enforce_deadlines()
        by_name = {p.name: p for p in pools}
        changed = False
        for op in [o for o in self._ops.values() if o.in_progress]:
            try:
                if await self._resolve(op, by_name.get(op.name)):
                    changed = True
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — per-op; next tick retries
                log.debug("tracker resolve %s/%s failed: %s",
                          op.kind, op.name, e)
                if self._expire(op):
                    changed = True
        return changed

    async def _enforce_deadlines(self) -> bool:
        changed = False
        for op in [o for o in self._ops.values() if o.in_progress]:
            if self._expire(op):
                changed = True
        return changed

    def _expire(self, op: TrackedOperation) -> bool:
        if _now() < op.deadline:
            return False
        if op.kind == OP_DELETE:
            self._complete(op, PHASE_FAILED, REASON_DELETE_TIMEOUT,
                           f"nodepool {op.name} still present after "
                           f"{op.deadline - op.started:.0f}s delete wait")
        else:
            # retryable by convention: the consumer requeues and the retry's
            # begin_create conflict re-registers (same contract the blocking
            # adoption path had)
            self._complete(op, PHASE_FAILED, REASON_CREATE_IN_PROGRESS,
                           f"nodepool {op.name} operation still unresolved "
                           f"after {op.deadline - op.started:.0f}s; requeueing")
        return True

    async def _resolve(self, op: TrackedOperation, pool) -> bool:
        """Advance one op against the batched snapshot. True on completion."""
        if op.kind == OP_DELETE:
            if pool is None:
                self._complete(op, PHASE_SUCCEEDED, REASON_DELETED,
                               f"nodepool {op.name} deleted")
                return True
            return self._expire(op)

        # create
        if pool is None:
            self._complete(op, PHASE_FAILED, REASON_CREATE_IN_PROGRESS,
                           f"nodepool {op.name} vanished while its create "
                           "was in flight; requeueing")
            return True
        if pool.status == _NP_ERROR:
            self._complete(op, PHASE_FAILED, REASON_DEGRADED_POOL,
                           f"nodepool {op.name} is ERROR: "
                           f"{pool.status_message or 'unknown failure'}")
            return True
        if pool.status == _NP_STOPPING:
            self._complete(op, PHASE_FAILED, REASON_CREATE_IN_PROGRESS,
                           f"nodepool {op.name} is being deleted; requeueing")
            return True
        if pool.status == _NP_PROVISIONING:
            return self._expire(op)
        # RUNNING / RECONCILING: the LRO is done — now the node wait, off
        # the (informer-backed) kube client: watch-cache maintenance, not a
        # fresh apiserver LIST per op per tick
        if op.lro_done_at == 0.0:
            op.lro_done_at = _now()
        nodes = await self.kube.list(
            Node, labels={wk.GKE_NODEPOOL_LABEL: op.name})
        ready = sum(1 for n in nodes if n.spec.provider_id)
        if ready >= op.hosts:
            self._complete(op, PHASE_SUCCEEDED, REASON_CREATED,
                           f"nodepool {op.name} running with "
                           f"{ready}/{op.hosts} nodes")
            return True
        if _now() >= op.deadline:
            self._complete(op, PHASE_FAILED, REASON_NODES_NOT_READY,
                           f"nodepool {op.name}: only {ready}/{op.hosts} "
                           "nodes appeared with providerIDs before timeout")
            return True
        return False

    def _complete(self, op: TrackedOperation, phase: str, reason: str,
                  message: str, notify: bool = True) -> None:
        op.phase, op.reason, op.message = phase, reason, message
        op.completed_at = _now()
        op.wait_seconds = op.completed_at - op.started
        record_operation_wait(op.kind, op.wait_seconds)
        self.completed_total += 1
        op.done.set()
        if not notify:
            return
        for cb in list(self._subs):
            # a slow/broken subscriber must not stall the poll loop (the
            # callback just injects a workqueue item) — but the task is
            # tracked so stop() reaps it rather than leaking it
            t = asyncio.ensure_future(self._notify(cb, op))
            self._notify_tasks.add(t)
            t.add_done_callback(self._notify_tasks.discard)

    @staticmethod
    async def _notify(cb, op: TrackedOperation) -> None:
        try:
            await cb(op)
        except Exception:  # noqa: BLE001 — observability-grade seam
            log.warning("operation-tracker subscriber failed for %s/%s",
                        op.kind, op.name, exc_info=True)
