"""Capacity-aware placement: zone × generation × capacity-tier candidates.

Capacity exhaustion used to be a generic retryable error — the provisioner
could only retry *into* a dry zone, never route *around* it (ROADMAP item 3:
the fake cloud was single-zone with infinite capacity, so nothing exercised
the difference). This module makes placement a first-class decision:

- :meth:`PlacementEngine.candidates` expands the NodeClaim requirements into
  a preference-ordered candidate list — shape preference (``catalog.
  resolve_all`` order) × capacity-tier preference (``tpu.kaito.sh/
  capacity-tier`` requirement order) × zone preference (``topology.
  kubernetes.io/zone`` requirement order, else the configured zone list),
  with the zone varying fastest so a stockout falls over to a sibling zone
  before giving up a tier or a shape.
- A per-``zone/generation`` **stockout memo** (:class:`~.cache.TTLMemo`)
  remembers a RESOURCE_EXHAUSTED verdict for a TTL window, so a wave of N
  queued claims costs the dry zone ONE probe per window instead of N serial
  probes (the instance provider consults it before every candidate).
- **Spot demotion hysteresis**: zones whose spot pools keep getting
  preemption-reclaimed (≥ ``demote_threshold`` preemptions inside
  ``demote_window`` seconds) sink to the end of the spot-tier zone order, so
  a flapping spot zone stops being the first thing a reclaim wave's
  replacement claims land back on.

Counters live in module registries (``STOCKOUTS`` / ``FALLBACKS`` /
``SPOT_PREEMPTIONS``) that ``controllers/metrics.py`` samples at scrape time
— the REPAIR_STATS convention: this layer never imports prometheus.
"""

from __future__ import annotations

import asyncio
from collections import defaultdict
from dataclasses import dataclass
from typing import Iterable, Optional

from .. import catalog as cat
from ..apis import labels as wk
from ..scheduling import Requirements
from .cache import TTLMemo

# ---------------------------------------------------------------- registries

# zone -> cumulative RESOURCE_EXHAUSTED verdicts observed at begin_create.
STOCKOUTS: dict[str, int] = defaultdict(int)

# (from_zone, to_zone) -> cumulative fallback placements: the claim wanted
# from_zone (its first candidate) but landed in to_zone.
FALLBACKS: dict[tuple[str, str], int] = defaultdict(int)

# zone -> cumulative spot preemptions noted by the repair path.
SPOT_PREEMPTIONS: dict[str, int] = defaultdict(int)

# zone -> recent preemption timestamps (loop clock), the demotion evidence.
# Module-level (not per-engine) deliberately: preemptions are observed by the
# health controller, placement decisions are made by the instance provider —
# the two rendezvous here the way REPAIR_STATS rendezvous health and metrics.
_PREEMPT_TIMES: dict[str, list[float]] = defaultdict(list)

# Preference order when a claim constrains the tier axis with a non-In
# requirement (Exists / NotIn): cheapest-to-lose first.
DEFAULT_TIERS = (wk.CAPACITY_TYPE_RESERVED, wk.CAPACITY_TYPE_ON_DEMAND,
                 wk.CAPACITY_TYPE_SPOT)


def _now() -> float:
    return asyncio.get_event_loop().time()


def note_spot_preemption(zone: str) -> None:
    """Record a spot preemption against ``zone`` (called from the repair
    path when a SpotPreempted condition commits a repair). Feeds both the
    /metrics counter and the demotion hysteresis window."""
    zone = zone or "unknown"
    SPOT_PREEMPTIONS[zone] += 1
    _PREEMPT_TIMES[zone].append(_now())


@dataclass(frozen=True)
class Candidate:
    """One placement candidate: a slice shape in a zone at a capacity tier."""

    shape: cat.SliceShape
    zone: str
    tier: str

    @property
    def key(self) -> str:
        """Stable identity for the per-claim attempt history (annotation)."""
        return f"{self.zone}/{self.shape.name}/{self.tier}"

    @property
    def memo_key(self) -> str:
        """Stockout memo granularity: a zone runs dry per *generation* (the
        chip pools are per-generation), not per exact shape or tier."""
        return f"{self.zone}/{self.shape.generation}"


class PlacementEngine:
    """Preference-ordered candidate expansion + stockout memo + demotion."""

    def __init__(self, zones: Iterable[str], stockout_ttl: float = 5.0,
                 demote_threshold: int = 3, demote_window: float = 60.0):
        self.zones = [z for z in zones if z]
        if not self.zones:
            raise ValueError("PlacementEngine needs at least one zone")
        self.memo = TTLMemo("placement.stockout", ttl=stockout_ttl)
        self.demote_threshold = demote_threshold
        self.demote_window = demote_window

    # -------------------------------------------------------------- ordering
    def candidates(self, reqs: Requirements,
                   resources: Optional[dict[str, str]] = None
                   ) -> list[Candidate]:
        """Expand requirements into the fallback-walk order. The FIRST
        element is the legacy single-candidate answer (``catalog.resolve``'s
        shape, the claim's declared tier, the most-preferred zone), so the
        no-stockout path is byte-identical to pre-placement behavior.
        Raises :class:`~..catalog.UnknownShapeError` when no shape fits."""
        shapes = cat.resolve_all(reqs, resources)
        tiers = self._tiers(reqs)
        zones = reqs.preference(wk.ZONE_LABEL, self.zones)
        out: list[Candidate] = []
        for shape in shapes:
            for tier in tiers:
                for zone in self._ordered_zones(zones, tier):
                    out.append(Candidate(shape=shape, zone=zone, tier=tier))
        if not out:
            raise cat.UnknownShapeError(
                f"requirements admit no placement candidate "
                f"(zones {zones}, tiers {tiers})")
        return out

    def _tiers(self, reqs: Requirements) -> list[str]:
        """Tier axis. An explicit ``tpu.kaito.sh/capacity-tier`` requirement
        is a *ranking* (fall across tiers in its order); otherwise the claim
        gets exactly its karpenter capacity-type — tier fallback is opt-in,
        a spot claim must never silently land on-demand."""
        if reqs.has(wk.TPU_CAPACITY_TIER_LABEL):
            tiers = reqs.preference(wk.TPU_CAPACITY_TIER_LABEL, DEFAULT_TIERS)
            if tiers:
                return tiers
        vals = reqs.get(wk.CAPACITY_TYPE_LABEL).values()
        return [vals[0]] if vals else [wk.CAPACITY_TYPE_ON_DEMAND]

    def _ordered_zones(self, zones: list[str], tier: str) -> list[str]:
        if tier != wk.CAPACITY_TYPE_SPOT:
            return zones
        healthy = [z for z in zones if not self.spot_demoted(z)]
        demoted = [z for z in zones if self.spot_demoted(z)]
        return healthy + demoted

    # ------------------------------------------------------------ hysteresis
    def spot_demoted(self, zone: str) -> bool:
        """True while ``zone`` has accumulated ≥ threshold spot preemptions
        inside the sliding window — demoted, not excluded: a claim that can
        only go there still does, last."""
        times = _PREEMPT_TIMES.get(zone)
        if not times:
            return False
        cutoff = _now() - self.demote_window
        recent = [t for t in times if t >= cutoff]
        _PREEMPT_TIMES[zone] = recent
        return len(recent) >= self.demote_threshold

    # ------------------------------------------------------------------ memo
    def suppressed(self, cand: Candidate) -> bool:
        """True while the stockout memo holds a live verdict for the
        candidate's zone/generation — the walk treats it as an observed
        stockout without spending a cloud probe."""
        return self.memo.active(cand.memo_key)

    def suppressed_remaining(self, cand: Candidate) -> float:
        """Seconds until the candidate's stockout memo expires (0.0 when not
        suppressed) — the stockout-park path arms its WakeHub timer with the
        minimum of these across the skipped candidates."""
        return self.memo.remaining(cand.memo_key)

    def note_stockout(self, cand: Candidate) -> None:
        self.memo.mark(cand.memo_key)
        STOCKOUTS[cand.zone] += 1

    def note_fallback(self, wanted: Candidate, placed: Candidate) -> None:
        FALLBACKS[(wanted.zone, placed.zone)] += 1

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> dict:
        """Diagnostic view for flight-recorder bundles: live stockout memos
        (zone/generation → seconds left), cumulative stockout / fallback /
        preemption tallies, and which zones are currently spot-demoted."""
        return {
            "zones": list(self.zones),
            "stockout_memos": self.memo.live(),
            "stockouts": dict(STOCKOUTS),
            "fallbacks": {f"{a}->{b}": n
                          for (a, b), n in FALLBACKS.items()},
            "spot_preemptions": dict(SPOT_PREEMPTIONS),
            "spot_demoted": [z for z in self.zones
                             if self.spot_demoted(z)],
        }
