"""REST clients for the NodePoolsAPI / QueuedResourcesAPI seams.

The production half of the seam the fakes implement in tests — the analog of
the reference's azcore-generated AgentPools client behind its 4-method
interface (azure_client.go:42-47,102-111). Hand-built over httpx because no
GCP SDK ships in this image and the wire format is plain JSON; the
translation between our seam models (providers/gcp.py) and the
container/v1 + tpu/v2 payload shapes lives HERE so the rest of the tree
never sees wire dicts.

Endpoints (overridable for e2e staging — azure_client.go:95-100 analog):
  GKE       https://container.googleapis.com/v1/projects/{p}/locations/{l}/
            clusters/{c}/nodePools[...]
  Cloud TPU https://tpu.googleapis.com/v2/projects/{p}/locations/{l}/
            queuedResources[...]
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import replace
from typing import Optional

import httpx

from ..auth.credentials import Credentials
from ..transport import (GCP_RETRYABLE_STATUS, BreakerOpenError,
                         CircuitBreaker, TransportOptions, build_http_client,
                         request_with_retries)
from .gcp import (APIError, NodePool, Operation, QueuedResource,
                  QueuedResourcesAPI, QR_ACCEPTED)

log = logging.getLogger("gcp.rest")

GKE_ENDPOINT = "https://container.googleapis.com/v1"
TPU_ENDPOINT = "https://tpu.googleapis.com/v2"
DEFAULT_TPU_RUNTIME = "tpu-ubuntu2204-base"
OP_POLL_INTERVAL = 2.0


class _AuthedREST:
    def __init__(self, cred: Credentials, endpoint: str,
                 transport: Optional[TransportOptions] = None,
                 http: Optional[httpx.AsyncClient] = None):
        self.cred = cred
        self.endpoint = endpoint.rstrip("/")
        self.topts = transport or TransportOptions()
        if 429 in self.topts.retryable_status:
            # 429 here means stockout/quota — a lifecycle answer, not jitter
            self.topts = replace(self.topts,
                                 retryable_status=GCP_RETRYABLE_STATUS)
        self.http = http or build_http_client(self.topts)
        # One breaker per endpoint: a down GKE API must not blind the TPU
        # API client (and vice versa). State is exported on /metrics.
        self.breaker = CircuitBreaker(
            name=httpx.URL(self.endpoint).host or self.endpoint,
            failure_threshold=self.topts.breaker_threshold,
            reset_timeout=self.topts.breaker_reset)

    async def aclose(self) -> None:
        self.breaker.unregister()
        await self.http.aclose()

    async def req(self, method: str, path: str, **kw) -> dict:
        headers = {"Authorization": f"Bearer {await self.cred.token()}",
                   "Content-Type": "application/json"}
        try:
            resp = await request_with_retries(
                self.http, method, f"{self.endpoint}{path}", opts=self.topts,
                breaker=self.breaker, headers=headers, **kw)
        except BreakerOpenError as e:
            # Surface as a retryable 503: instance/controller code maps it
            # into CreateError → rate-limited requeue, so a down cloud API
            # costs one local exception per reconcile, not a retry storm.
            raise APIError(str(e), code=503) from e
        if resp.status_code == 410:
            # expired page token / compacted history: deliberately NOT in
            # RETRYABLE_STATUS (retrying the same request can never
            # succeed) and typed via APIError.expired so list consumers
            # restart from scratch instead of riding the backoff ladder —
            # the cloud-side mirror of the kube watch 410 path (PL015)
            raise APIError(f"gone (expired): {resp.text[:512]}", code=410)
        if resp.status_code >= 400:
            raise APIError(resp.text[:512], code=resp.status_code)
        return resp.json() if resp.content else {}


class RESTOperation:
    """GCP LRO handle: polls ``GET {ops_path}/{name}`` until DONE, then
    resolves via ``fetch_result`` (the created/deleted resource)."""

    def __init__(self, rest: _AuthedREST, ops_path: str, op: dict,
                 fetch_result=None):
        self.rest = rest
        self.ops_path = ops_path
        self.op = op
        self.fetch_result = fetch_result

    async def done(self) -> bool:
        if self.op.get("status") == "DONE":
            return True
        name = self.op.get("name", "")
        self.op = await self.rest.req("GET", f"{self.ops_path}/{name}")
        return self.op.get("status") == "DONE"

    # google.rpc.Status integer codes → HTTP-ish taxonomy codes. A real
    # container/v1 Operation.error carries the INT code; string enum names
    # are accepted too for robustness.
    _GRPC_TO_HTTP = {5: 404, 6: 409, 8: 429,
                     "NOT_FOUND": 404, "ALREADY_EXISTS": 409,
                     "RESOURCE_EXHAUSTED": 429}

    async def result(self):
        err = self.op.get("error")
        if err:
            # stockouts surface as operation errors with RESOURCE_EXHAUSTED
            key = err.get("code", err.get("status", ""))
            code = self._GRPC_TO_HTTP.get(key, 500)
            raise APIError(err.get("message", str(err)), code=code)
        if self.fetch_result is not None:
            return await self.fetch_result()
        return None


class GKENodePoolsClient:
    """NodePoolsAPI over container.googleapis.com (container/v1)."""

    def __init__(self, cred: Credentials, project: str, location: str,
                 cluster: str, endpoint: str = GKE_ENDPOINT,
                 transport: Optional[TransportOptions] = None,
                 http: Optional[httpx.AsyncClient] = None):
        self.rest = _AuthedREST(cred, endpoint, transport, http)
        self.parent = (f"/projects/{project}/locations/{location}"
                       f"/clusters/{cluster}")
        self.ops_path = f"/projects/{project}/locations/{location}/operations"

    @property
    def breaker(self) -> CircuitBreaker:
        return self.rest.breaker

    async def aclose(self) -> None:
        await self.rest.aclose()

    # --- seam ↔ wire translation ------------------------------------------

    def _to_wire(self, pool: NodePool) -> dict:
        cfg = pool.config
        wire_cfg: dict = {"machineType": cfg.machine_type,
                          "labels": dict(cfg.labels)}
        if cfg.disk_size_gb:
            wire_cfg["diskSizeGb"] = cfg.disk_size_gb
        if cfg.taints:
            wire_cfg["taints"] = [dict(t) for t in cfg.taints]
        if cfg.spot:
            wire_cfg["spot"] = True
        if cfg.image_type:
            wire_cfg["imageType"] = cfg.image_type
        if cfg.reservation:
            wire_cfg["reservationAffinity"] = {
                "consumeReservationType": "SPECIFIC_RESERVATION",
                "key": "compute.googleapis.com/reservation-name",
                "values": [cfg.reservation]}
        wire: dict = {"name": pool.name, "config": wire_cfg,
                      "initialNodeCount": pool.initial_node_count}
        if pool.placement_policy is not None:
            pp: dict = {"type": pool.placement_policy.type}
            if pool.placement_policy.tpu_topology:
                pp["tpuTopology"] = pool.placement_policy.tpu_topology
            wire["placementPolicy"] = pp
        return wire

    def _from_wire(self, d: dict) -> NodePool:
        cfg = d.get("config", {})
        ra = cfg.get("reservationAffinity", {})
        pool = NodePool.from_dict({
            "name": d.get("name", ""),
            "config": {
                "machineType": cfg.get("machineType", ""),
                "diskSizeGb": cfg.get("diskSizeGb", 0),
                "labels": cfg.get("labels", {}) or {},
                "taints": cfg.get("taints", []) or [],
                "spot": cfg.get("spot", False),
                "imageType": cfg.get("imageType", ""),
                "reservation": (ra.get("values") or [""])[0],
            },
            "initialNodeCount": d.get("initialNodeCount", 0),
            "placementPolicy": (
                {"type": d["placementPolicy"].get("type", "COMPACT"),
                 "tpuTopology": d["placementPolicy"].get("tpuTopology", "")}
                if "placementPolicy" in d else None),
            "status": d.get("status", ""),
            "statusMessage": d.get("statusMessage", ""),
        })
        return pool

    # --- NodePoolsAPI ------------------------------------------------------

    async def begin_create(self, pool: NodePool) -> Operation:
        op = await self.rest.req("POST", f"{self.parent}/nodePools",
                                 json={"nodePool": self._to_wire(pool)})

        async def fetch():
            return await self.get(pool.name)

        return RESTOperation(self.rest, self.ops_path, op, fetch)

    async def get(self, name: str) -> NodePool:
        d = await self.rest.req("GET", f"{self.parent}/nodePools/{name}")
        return self._from_wire(d)

    async def begin_delete(self, name: str) -> Operation:
        op = await self.rest.req("DELETE", f"{self.parent}/nodePools/{name}")
        return RESTOperation(self.rest, self.ops_path, op)

    async def list(self) -> list[NodePool]:
        d = await self.rest.req("GET", f"{self.parent}/nodePools")
        return [self._from_wire(p) for p in d.get("nodePools", [])]


class CloudTPUQueuedResourcesClient:
    """QueuedResourcesAPI over tpu.googleapis.com (tpu/v2).

    The creation LRO for a queued resource completes fast (it only enqueues);
    the interesting state machine (WAITING_FOR_RESOURCES → ... → ACTIVE)
    lives on the resource itself, which is why the seam returns the resource
    rather than an Operation (SURVEY.md §7 hard part 2: poll the QR
    asynchronously, never block a reconcile worker on it).
    """

    def __init__(self, cred: Credentials, project: str, location: str,
                 endpoint: str = TPU_ENDPOINT,
                 runtime_version: str = DEFAULT_TPU_RUNTIME,
                 transport: Optional[TransportOptions] = None,
                 http: Optional[httpx.AsyncClient] = None):
        self.rest = _AuthedREST(cred, endpoint, transport, http)
        self.parent = f"/projects/{project}/locations/{location}"
        self.runtime_version = runtime_version

    @property
    def breaker(self) -> CircuitBreaker:
        return self.rest.breaker

    async def aclose(self) -> None:
        await self.rest.aclose()

    def _to_wire(self, qr: QueuedResource) -> dict:
        node: dict = {
            "acceleratorType": qr.accelerator_type,
            "runtimeVersion": qr.runtime_version or self.runtime_version,
        }
        if qr.spot:
            node["schedulingConfig"] = {"spot": True}
        wire: dict = {"tpu": {"nodeSpec": [{
            "parent": self.parent.lstrip("/"),
            "nodeId": qr.node_pool or qr.name,
            "node": node,
        }]}}
        if qr.reservation:
            wire["reservationName"] = qr.reservation
            wire["guaranteed"] = {"reserved": True}
        return wire

    def _from_wire(self, d: dict) -> QueuedResource:
        spec = (d.get("tpu", {}).get("nodeSpec") or [{}])[0]
        node = spec.get("node", {})
        return QueuedResource(
            name=d.get("name", "").rsplit("/", 1)[-1],
            accelerator_type=node.get("acceleratorType", ""),
            runtime_version=node.get("runtimeVersion", ""),
            state=d.get("state", {}).get("state", QR_ACCEPTED),
            state_message=str(d.get("state", {}).get("stateInitiator", "")),
            node_pool=spec.get("nodeId", ""),
            reservation=d.get("reservationName", ""),
            spot=bool(node.get("schedulingConfig", {}).get("spot", False)))

    async def create(self, qr: QueuedResource) -> QueuedResource:
        await self.rest.req("POST", f"{self.parent}/queuedResources",
                            params={"queuedResourceId": qr.name},
                            json=self._to_wire(qr))
        # enqueue-LRO races the first GET occasionally; brief retry
        for attempt in range(5):
            try:
                return await self.get(qr.name)
            except APIError as e:
                if not e.not_found or attempt == 4:
                    raise
                await asyncio.sleep(0.5 * (attempt + 1))
        raise AssertionError("unreachable")

    async def get(self, name: str) -> QueuedResource:
        d = await self.rest.req("GET", f"{self.parent}/queuedResources/{name}")
        return self._from_wire(d)

    async def delete(self, name: str) -> None:
        await self.rest.req("DELETE", f"{self.parent}/queuedResources/{name}",
                            params={"force": "true"})

    async def list(self) -> list[QueuedResource]:
        d = await self.rest.req("GET", f"{self.parent}/queuedResources")
        return [self._from_wire(q) for q in d.get("queuedResources", [])]
