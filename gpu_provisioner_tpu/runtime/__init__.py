"""From-scratch controller runtime (controller-runtime/client-go analog).

The reference builds on sigs.k8s.io/controller-runtime + a patched Karpenter
operator (SURVEY.md §2b V9/V15). No Kubernetes client library exists in this
environment, so the load-bearing subset is rebuilt natively on asyncio:

- ``store``      in-memory API-server: optimistic concurrency, watch streams,
                 finalizer/deletionTimestamp semantics, field indexes.
- ``client``     the typed Client seam controllers program against (the same
                 seam lets a REST-backed client target a real apiserver later).
- ``workqueue``  rate-limited dedup queue with per-item exponential backoff.
- ``controller`` Reconciler/Controller/Manager + singleton source.
"""

from .client import (  # noqa: F401
    AlreadyExistsError, Client, ConflictError, EvictionBlockedError,
    InMemoryClient, NotFoundError, ResourceExpiredError,
    TooManyRequestsError,
)
from .controller import (  # noqa: F401
    Controller, Manager, Reconciler, Request, Result, Singleton,
)
from .store import Store, WatchEvent  # noqa: F401
from .workqueue import RateLimitingQueue  # noqa: F401
