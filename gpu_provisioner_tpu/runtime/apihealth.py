"""APIHealthGovernor: adaptive apiserver overload shedding + degraded modes.

Every reconcile in the tree rides the kube apiserver; PRs 1-15 hardened the
control plane against cloud errors, crashes, node faults and stockouts, but
apiserver brownouts/partitions had no model at all. This module is the
runtime half of PR 16's answer:

- **Signals in**: 429 Retry-After (throttling), 5xx/timeouts (failure),
  successes, watch gaps (410 Gone). They arrive from three seams: the
  :class:`GovernedClient` wrapper classifies every kube verb outcome, the
  transport's throttle-listener seam forwards Retry-After from the HTTP
  layer, and the informer reports watch gaps.
- **AIMD limit out**: an additive-increase / multiplicative-decrease rate
  the workqueues consume via :meth:`pace` before each reconcile and the
  status batcher consumes via :meth:`status_window_factor` (status writes
  shed FIRST — the batcher widens its coalescing window; meta and
  cloud-mutation writes are paced, never dropped). In HEALTHY mode
  :meth:`pace` is a no-op fast path — no overload, no shed — so the 10k
  megawave bench pays one attribute check per reconcile.
- **Degraded-mode state machine**: HEALTHY→BROWNOUT→PARTITIONED→CATCHUP,
  exposed at ``/healthz``, as the ``tpu_provisioner_degraded_mode`` gauge,
  and to the flight recorder (one bundle per degraded entry) through the
  degraded-listener seam. Transitions emit the ``api-mode`` probe so the
  schedfuzz ``partition-fenced-mutate`` checker can serialize them against
  ``cloud-mutate`` events.

Layering: runtime code — no prometheus, no observability imports. Counters
accumulate in the module-level :data:`APIHEALTH` ledger (the wakehub.WAKES
idiom) and live governors register in :data:`GOVERNORS`; both are sampled
delta-style by ``controllers/metrics.py`` at scrape time.
"""

from __future__ import annotations

import asyncio
import time
import weakref
from typing import Callable, Optional

from . import probes
from .client import (
    AlreadyExistsError, ClientError, ConflictError, EvictionBlockedError,
    NotFoundError, ResourceExpiredError, TooManyRequestsError,
)

# Mode names, in gauge-value order (tpu_provisioner_degraded_mode exports
# the ordinal: 0 healthy, 1 brownout, 2 partitioned, 3 catchup).
HEALTHY = "HEALTHY"
BROWNOUT = "BROWNOUT"
PARTITIONED = "PARTITIONED"
CATCHUP = "CATCHUP"
MODE_VALUES = {HEALTHY: 0, BROWNOUT: 1, PARTITIONED: 2, CATCHUP: 3}

# Cumulative event ledger, exported counter-by-delta at scrape time
# (tpu_provisioner_watch_gaps_total / _relists_total / _api_shed_total).
APIHEALTH: dict[str, int] = {"watch_gaps": 0, "relists": 0, "shed": 0}

# Live governors, for gauge sampling (the flightrecorder.RECORDERS idiom).
GOVERNORS: "weakref.WeakSet[APIHealthGovernor]" = weakref.WeakSet()


def note_watch_gap() -> None:
    """A watch stream answered 410 Gone / expired-resourceVersion."""
    APIHEALTH["watch_gaps"] += 1


def note_relist() -> None:
    """A gap-resync relist completed and its diff was synthesized."""
    APIHEALTH["relists"] += 1


def note_shed() -> None:
    """The governor deferred work: a paced wait or a widened status window."""
    APIHEALTH["shed"] += 1


def _default_clock() -> float:
    """Loop time on the loop; monotonic off it. Governors are read from
    sync contexts too (metrics scrape sampling GOVERNORS) — mode decay must
    not require a running event loop."""
    try:
        return asyncio.get_running_loop().time()
    except RuntimeError:
        return time.monotonic()


class PartitionFencedError(Exception):
    """A cloud mutation was refused because the apiserver is partitioned.

    While the control plane cannot write to the kube API it must not mutate
    the cloud either: a create whose outcome can't be recorded is a
    duplicate-pool factory the moment the partition heals. The provider's
    fence check raises this; the reconcile error path requeues with backoff
    and the claim retries once the governor leaves PARTITIONED."""


class APIHealthGovernor:
    """Folds apiserver health signals into an AIMD pace and a mode machine.

    Single-event-loop discipline (no awaits between check and mutate in the
    signal paths), so no lock. The mode machine is evaluated lazily — every
    signal, pace and read calls :meth:`_decay` — so it needs no background
    task and the envtest leak gate never sees it.
    """

    def __init__(self, *, rate_max: float = 256.0, rate_min: float = 2.0,
                 increase: float = 4.0, decrease: float = 0.5,
                 partition_threshold: int = 5, brownout_hold: float = 2.0,
                 catchup_hold: float = 2.0, pause_cap: float = 5.0,
                 clock: Optional[Callable[[], float]] = None):
        self.rate_max = rate_max
        self.rate_min = rate_min
        self.increase = increase
        self.decrease = decrease
        self.partition_threshold = partition_threshold
        self.brownout_hold = brownout_hold
        self.catchup_hold = catchup_hold
        self.pause_cap = pause_cap
        self._clock = clock or _default_clock
        self._mode = HEALTHY
        self._rate = rate_max
        self._tokens = rate_max
        self._last_refill: Optional[float] = None
        self._pause_until = 0.0
        self._consec_failures = 0
        self._last_bad = float("-inf")
        self._entered_at = float("-inf")
        self._listeners: list = []
        # observability (sampled by controllers/metrics.py and /healthz)
        self.throttles_total = 0
        self.failures_total = 0
        self.entries_total: dict[str, int] = {}
        GOVERNORS.add(self)

    # -- mode machine ------------------------------------------------------

    def mode(self) -> str:
        self._decay()
        return self._mode

    def mode_value(self) -> int:
        return MODE_VALUES[self.mode()]

    def partition_fenced(self) -> bool:
        """True while cloud mutations must not proceed (see
        :class:`PartitionFencedError`)."""
        return self.mode() == PARTITIONED

    def add_degraded_listener(self, fn) -> None:
        """Register ``fn(mode, **info)``, fired on entry into any
        non-HEALTHY mode (idempotent). The flight recorder's degraded-mode
        trigger attaches here — armed from outside (envtest / operator
        main) exactly like transport breaker listeners."""
        if fn not in self._listeners:
            self._listeners.append(fn)

    def remove_degraded_listener(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def _set_mode(self, mode: str, reason: str) -> None:
        if mode == self._mode:
            return
        prev, self._mode = self._mode, mode
        self._entered_at = self._clock()
        self.entries_total[mode] = self.entries_total.get(mode, 0) + 1
        if mode == HEALTHY:
            # full recovery: restore the uncapped pace immediately — the
            # additive ramp is for CATCHUP, not for steady state
            self._rate = self.rate_max
            self._tokens = self.rate_max
        probes.emit("api-mode", mode, prev=prev, reason=reason)
        if mode != HEALTHY:
            for fn in list(self._listeners):
                try:
                    fn(mode, prev=prev, reason=reason,
                       failures=self._consec_failures,
                       rate=round(self._rate, 1))
                except Exception:  # noqa: BLE001 — observability seam
                    pass

    def _decay(self) -> None:
        now = self._clock()
        if self._mode == BROWNOUT and now - self._last_bad >= self.brownout_hold:
            self._set_mode(HEALTHY, "brownout drained")
        elif (self._mode == CATCHUP
                and now - self._last_bad >= self.catchup_hold
                and now - self._entered_at >= self.catchup_hold):
            self._set_mode(HEALTHY, "catchup drained")

    # -- signals -----------------------------------------------------------

    def note_success(self) -> None:
        self._consec_failures = 0
        if self._mode == PARTITIONED:
            self._set_mode(CATCHUP, "apiserver answered")
        elif self._mode == CATCHUP:
            # additive increase: recover pace gradually through the storm
            self._rate = min(self.rate_max, self._rate + self.increase)
        self._decay()

    def note_throttle(self, retry_after: float = 0.0) -> None:
        """A 429: the apiserver is alive and saying slow down."""
        now = self._clock()
        self.throttles_total += 1
        self._last_bad = now
        self._consec_failures = 0          # an answer, not an outage
        self._rate = max(self.rate_min, self._rate * self.decrease)
        if retry_after > 0:
            self._pause_until = max(
                self._pause_until, now + min(retry_after, self.pause_cap))
        if self._mode == HEALTHY:
            self._set_mode(BROWNOUT, "throttled")
        elif self._mode == PARTITIONED:
            self._set_mode(CATCHUP, "apiserver answered (throttling)")

    def note_failure(self) -> None:
        """A 5xx / timeout / unreachable apiserver."""
        self.failures_total += 1
        self._last_bad = self._clock()
        self._consec_failures += 1
        self._rate = max(self.rate_min, self._rate * self.decrease)
        if self._consec_failures >= self.partition_threshold:
            self._set_mode(PARTITIONED, "consecutive failures")
        elif self._mode == HEALTHY:
            self._set_mode(BROWNOUT, "apiserver failure")
        self._decay()

    def note_watch_gap(self) -> None:
        """A watch expired (410) — brownout-grade evidence by itself."""
        self._last_bad = self._clock()
        if self._mode == HEALTHY:
            self._set_mode(BROWNOUT, "watch gap")

    # -- consumption -------------------------------------------------------

    async def pace(self, cost: float = 1.0) -> None:
        """Wait until the AIMD limit admits one unit of apiserver-bound
        work. No-op in HEALTHY mode: shedding is for overload, steady state
        pays one mode check."""
        while True:
            self._decay()
            now = self._clock()
            if self._mode == HEALTHY and now >= self._pause_until:
                return
            if now < self._pause_until:
                note_shed()
                await asyncio.sleep(self._pause_until - now)
                continue
            if self._last_refill is None:
                self._last_refill = now
            cap = max(self._rate, 1.0)
            self._tokens = min(
                cap, self._tokens + (now - self._last_refill) * self._rate)
            self._last_refill = now
            if self._tokens >= cost:
                self._tokens -= cost
                return
            note_shed()
            await asyncio.sleep(
                min((cost - self._tokens) / max(self._rate, 0.001), 1.0))

    def status_window_factor(self) -> float:
        """Multiplier for the status batcher's coalescing window: status
        writes shed first. 1.0 when healthy; the batcher counts a shed per
        widened window."""
        return {HEALTHY: 1.0, BROWNOUT: 4.0,
                PARTITIONED: 8.0, CATCHUP: 4.0}[self.mode()]

    def healthz_line(self) -> str:
        m = self.mode()
        if m == HEALTHY:
            return "ok"
        return (f"degraded mode={m} rate={self._rate:.0f}/s "
                f"failures={self._consec_failures}")


class GovernedClient:
    """Delegating kube-client wrapper that classifies every verb outcome
    into governor signals. Classification only — pacing is consumed at the
    workqueue/batcher layer, not per verb, so a single reconcile's handful
    of reads doesn't pay the token bucket five times.

    Semantic answers (404/409/412-class, eviction 429, 410) count as
    *success*: the apiserver did its job. Only throttling and server-side
    failure move the AIMD limit.
    """

    _SEMANTIC = (NotFoundError, ConflictError, AlreadyExistsError,
                 EvictionBlockedError, ResourceExpiredError)

    def __init__(self, inner, governor: APIHealthGovernor):
        self.inner = inner
        self.governor = governor

    @property
    def store(self):
        return self.inner.store

    def _ok(self):
        self.governor.note_success()

    def _classify(self, e: BaseException) -> None:
        if isinstance(e, TooManyRequestsError):
            self.governor.note_throttle(e.retry_after)
        elif isinstance(e, self._SEMANTIC):
            self.governor.note_success()
        elif isinstance(e, (ClientError, asyncio.TimeoutError)):
            self.governor.note_failure()

    async def get(self, cls, name, namespace=""):
        try:
            r = await self.inner.get(cls, name, namespace)
        except BaseException as e:
            self._classify(e)
            raise
        self._ok()
        return r

    async def list(self, cls, labels=None, namespace=None, index=None):
        try:
            r = await self.inner.list(cls, labels, namespace, index)
        except BaseException as e:
            self._classify(e)
            raise
        self._ok()
        return r

    async def create(self, obj):
        try:
            r = await self.inner.create(obj)
        except BaseException as e:
            self._classify(e)
            raise
        self._ok()
        return r

    async def update(self, obj):
        try:
            r = await self.inner.update(obj)
        except BaseException as e:
            self._classify(e)
            raise
        self._ok()
        return r

    async def update_status(self, obj):
        try:
            r = await self.inner.update_status(obj)
        except BaseException as e:
            self._classify(e)
            raise
        self._ok()
        return r

    async def delete(self, cls, name, namespace=""):
        try:
            r = await self.inner.delete(cls, name, namespace)
        except BaseException as e:
            self._classify(e)
            raise
        self._ok()
        return r

    async def evict(self, name, namespace="", uid=""):
        try:
            r = await self.inner.evict(name, namespace, uid)
        except BaseException as e:
            self._classify(e)
            raise
        self._ok()
        return r

    def watch(self, cls):
        return self.inner.watch(cls)

    def add_index(self, cls, name, key_fn):
        if hasattr(self.inner, "add_index"):
            self.inner.add_index(cls, name, key_fn)
