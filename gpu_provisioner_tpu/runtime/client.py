"""The typed Client seam controllers program against.

Mirrors the controller-runtime ``client.Client`` surface the reference's
controllers consume (get/list/create/update/status-update/delete + field
indexes). Two implementations share the seam: ``InMemoryClient`` (envtest and
unit tests — the reference instead hand-rolls ``pkg/fake/k8sClient.go``) and,
in production, a REST client speaking to a real apiserver. Keeping the seam
narrow is what makes the whole tree testable (SURVEY.md §7 step 3 notes the
same about the 4-method ARM seam).
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Optional, Protocol

from ..apis.meta import Object
from .store import (
    Store, StoreAlreadyExists, StoreConflict, StoreNotFound, WatchEvent,
)


class ClientError(Exception):
    pass


class NotFoundError(ClientError):
    pass


class ConflictError(ClientError):
    pass


class AlreadyExistsError(ClientError):
    pass


class EvictionBlockedError(ClientError):
    """The eviction subresource returned 429 — a PodDisruptionBudget forbids
    the disruption right now (terminator/eviction.go:199-209). Semantic, not
    throttling: the caller backs off and retries, it must not be eaten by the
    transport retry loop."""


class ResourceExpiredError(ClientError):
    """410 Gone / expired resourceVersion from a watch or list.

    The apiserver compacted past the resourceVersion the watch resumed
    from: the event stream has a hole that retrying the same watch can
    never fill. The ONLY correct recovery is a fresh relist and a diff
    against the local cache (client-go reflector Replace() semantics) —
    which is why error handlers on watch/list paths must branch on this
    type distinctly from the generic backoff ladder (provlint PL015)."""


class TooManyRequestsError(ClientError):
    """429 from the kube apiserver: throttling, not failure.

    Carries ``retry_after`` (seconds, from the Retry-After header; 0 when
    absent) so callers pace instead of backing off blindly. Feeds the
    APIHealthGovernor's AIMD limit — it must never be folded into the
    consecutive-failure accounting that opens circuit breakers."""

    def __init__(self, message: str, retry_after: float = 0.0):
        super().__init__(message)
        self.retry_after = max(retry_after, 0.0)


def ignore_not_found(exc: Optional[Exception]) -> None:
    if exc is not None and not isinstance(exc, NotFoundError):
        raise exc


class Client(Protocol):
    async def get(self, cls: type, name: str, namespace: str = "") -> Object: ...
    async def list(self, cls: type, labels: Optional[dict[str, str]] = None,
                   namespace: Optional[str] = None,
                   index: Optional[tuple[str, str]] = None) -> list[Object]: ...
    async def create(self, obj: Object) -> Object: ...
    async def update(self, obj: Object) -> Object: ...
    async def update_status(self, obj: Object) -> Object: ...
    async def delete(self, cls: type, name: str, namespace: str = "") -> None: ...
    async def evict(self, name: str, namespace: str = "",
                    uid: str = "") -> None: ...
    def watch(self, cls: type) -> "Watch": ...


_CLOSED = object()


class Watch:
    """Async iterator over a store watch queue. ``close()`` is idempotent and
    wakes any consumer blocked in ``__anext__``."""

    def __init__(self, store: Store, cls: type):
        self._store = store
        self._cls = cls
        self._q = store.watch(cls)
        self._closed = False

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        if self._closed:
            raise StopAsyncIteration
        ev = await self._q.get()
        if ev is _CLOSED or self._closed:
            raise StopAsyncIteration
        return ev

    def try_next(self) -> Optional[WatchEvent]:
        """Non-blocking pop: the next buffered event, or None when the
        stream is drained (or closed). Lets a single-task consumer (the
        informer pump) drain a burst in one scheduling slot instead of
        paying a wait_for task + timer round-trip per event — under a
        provisioning wave that per-event overhead made the pump the
        slowest stage of the whole watch path."""
        if self._closed:
            return None
        try:
            ev = self._q.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if ev is _CLOSED:
            return None
        return ev

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._store.unwatch(self._cls, self._q)
        self._q.put_nowait(_CLOSED)


_ERR_MAP = {
    StoreNotFound: NotFoundError,
    StoreConflict: ConflictError,
    StoreAlreadyExists: AlreadyExistsError,
}


def _translate(fn):
    async def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except tuple(_ERR_MAP) as e:
            raise _ERR_MAP[type(e)](str(e)) from e
    return wrapper


class InMemoryClient:
    """Client over the in-memory Store. All mutations are synchronous under the
    event loop, but the surface is async to match the REST implementation."""

    def __init__(self, store: Optional[Store] = None):
        self.store = store or Store()

    async def get(self, cls, name, namespace=""):
        return await _translate(self.store.get)(cls, name, namespace)

    async def list(self, cls, labels=None, namespace=None, index=None):
        return await _translate(self.store.list)(cls, labels, namespace, index)

    async def create(self, obj):
        return await _translate(self.store.create)(obj)

    async def update(self, obj):
        return await _translate(self.store.update)(obj)

    async def update_status(self, obj):
        return await _translate(self.store.update_status)(obj)

    async def delete(self, cls, name, namespace=""):
        return await _translate(self.store.delete)(cls, name, namespace)

    async def evict(self, name, namespace="", uid=""):
        """Pod eviction honoring PodDisruptionBudgets, like the policy/v1
        Eviction subresource does server-side; raises EvictionBlockedError
        (the 429 analog) when a matching budget has no disruptions left
        (terminator/eviction.go:199-209). ``uid`` is the delete precondition:
        a mismatch means the pod was replaced under the same name and raises
        ConflictError (the 409 the real subresource returns)."""
        from ..apis.core import Pod, PodDisruptionBudget
        pod = await _translate(self.store.get)(Pod, name, namespace)
        if uid and pod.metadata.uid != uid:
            raise ConflictError(
                f"precondition failed: uid {uid} != {pod.metadata.uid}")
        pods = await _translate(self.store.list)(Pod, None, namespace)
        for pdb in await _translate(self.store.list)(PodDisruptionBudget,
                                                     None, namespace):
            if (pdb.spec.selector.matches(pod.metadata.labels)
                    and pdb.disruptions_allowed(pods) <= 0):
                raise EvictionBlockedError(
                    f"evicting {namespace}/{name} violates "
                    f"PodDisruptionBudget {pdb.metadata.name}")
        return await _translate(self.store.delete)(Pod, name, namespace)

    def watch(self, cls) -> Watch:
        return Watch(self.store, cls)


async def patch_retry(client: Client, cls: type, name: str, mutate,
                      namespace: str = "", status: bool = False,
                      attempts: int = 5) -> Optional[Object]:
    """Optimistic-concurrency retry helper: get → mutate(obj) → update.

    ``mutate`` returns False to abort (no write). Retries on conflict, which
    is how controller-runtime's RetryOnConflict is used throughout the
    reference's sub-reconcilers.
    """
    for i in range(attempts):
        try:
            obj = await client.get(cls, name, namespace)
        except NotFoundError:
            return None
        if mutate(obj) is False:
            return obj
        try:
            if status:
                return await client.update_status(obj)
            return await client.update(obj)
        except ConflictError:
            if i == attempts - 1:
                raise
            await asyncio.sleep(0.01 * (2 ** i))
    return None
