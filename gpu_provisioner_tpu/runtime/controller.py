"""Reconciler / Controller / Manager — the control loop engine.

Replicates the controller-runtime behaviors the reference's controllers are
built on: level-triggered reconciles fed by watches, per-controller worker
pools with a rate-limited workqueue, ``Result{requeue_after}`` contracts, and
operatorpkg's singleton pattern (a controller driven by a synthetic
self-requeuing source — reference:
vendor/github.com/awslabs/operatorpkg/singleton/controller.go) used by both
GC loops (SURVEY.md §3.4).
"""

from __future__ import annotations

import asyncio
import contextlib
import dataclasses
import logging
import time
from typing import Awaitable, Callable, Optional, Protocol

from ..apis.meta import Object
from . import probes
from .client import Client
from .store import WatchEvent
from .wakehub import SOURCE_INJECT, SOURCE_WATCH, note_skipped_arm
from .workqueue import RateLimitingQueue

log = logging.getLogger("runtime.controller")


@dataclasses.dataclass(frozen=True)
class Request:
    name: str
    namespace: str = ""


@dataclasses.dataclass
class Result:
    requeue: bool = False
    requeue_after: Optional[float] = None
    # "Neither success nor failure": requeue via the success path (no
    # rate-limit climb) but KEEP the item's failure history. Used for
    # in-progress waits (a tracked create LRO) — without it, each wait lap
    # forgets the counter and a persistently-failing create retries at a
    # fixed cadence forever instead of climbing the backoff ladder.
    preserve_failures: bool = False
    # The event source expected to end this wait (wakehub.SOURCE_*). When
    # the controller's hub has ANNOUNCED a live producer for it, the
    # safety-net timer behind ``requeue_after`` is not armed at all (the
    # timer diet): the wake lands through the hub, and the arm is recorded
    # in the WAKES ledger under ``timer-arm-skipped``. None keeps the
    # legacy always-arm behavior.
    wake_source: Optional[str] = None
    # A deadline that must survive the skip (e.g. the liveness budget
    # folded under a shorter sourced park): armed INSTEAD of requeue_after
    # when the sourced timer is skipped.
    fallback_after: Optional[float] = None


class Reconciler(Protocol):
    async def reconcile(self, req: Request) -> Result: ...


MapFn = Callable[[Object], list[Request]]
Predicate = Callable[[Object], bool]


def _default_map(obj: Object) -> list[Request]:
    return [Request(name=obj.metadata.name, namespace=obj.metadata.namespace)]


@dataclasses.dataclass
class _Source:
    cls: type
    map_fn: MapFn
    predicate: Optional[Predicate]
    # Wake-source label stamped on enqueues from this watch (e.g. "node"
    # for a Node watch mapped onto claim requests) — feeds the claimtrace
    # idle-gap:woken / idle-gap:timer split and the wakes counter.
    wake_source: Optional[str] = None


SINGLETON_REQUEST = Request(name="singleton")


class Controller:
    """One reconcile loop: watch sources → workqueue → N workers.

    Robustness hardening (chaos-suite-driven):

    - ``reconcile_timeout``: per-reconcile deadline. A hung reconcile (cloud
      call that never returns, wedged poll loop) is cancelled at the
      deadline, counted, and rate-limit-requeued — it costs one worker for
      ``reconcile_timeout`` seconds, not forever.
    - ``max_retries``: per-item retry bound. After N consecutive
      rate-limited requeues the controller emits a warning (+ the
      ``reconcile_retries_exhausted`` metric via the exhausted hook),
      resets the failure counter — keeping the backoff cadence pinned at
      the cap, so the fast ladder does NOT restart — and requeues at the
      queue's max delay. With no informer resync in this runtime, dropping
      the item outright would wedge the object until an unrelated watch
      event; the slow-poll keeps liveness/GC able to converge it while
      staying O(1) calls per max_delay window. 0 disables the bound.
    - ``fence``: leadership fencing token (duck-typed ``valid()`` —
      runtime/leaderelection.FencingToken). When set and invalid, workers
      DROP dequeued items instead of reconciling: this process lost the
      lease, the new leader's watch replay owns every object now, and a
      requeue would only keep a dying incarnation's queue warm. The
      instance provider carries its own fence check for reconciles already
      in flight when leadership is lost.
    """

    def __init__(self, name: str, reconciler: Reconciler, max_concurrent: int = 10,
                 reconcile_timeout: Optional[float] = None,
                 max_retries: int = 0):
        self.name = name
        self.reconciler = reconciler
        self.max_concurrent = max_concurrent
        self.reconcile_timeout = reconcile_timeout
        self.max_retries = max_retries
        # assigned by the registry (build_controllers) / operator boot path
        # once leadership is won — construction predates the election
        self.fence = None
        # APIHealthGovernor, assigned post-construction like the fence: the
        # workqueue consumes its AIMD limit — each dequeued item waits for
        # pace() before reconciling, so a browned-out apiserver sees the
        # fleet's reconcile rate collapse instead of a retry storm
        self.governor = None
        # assigned by the registry: which shard this controller instance
        # belongs to (labels the per-shard queue-depth gauge)
        self.shard_index = 0
        # Dynamic range-ownership predicate (runtime/shardlease.py), set by
        # the registry for claim-keyed controllers in lease-sharded workers:
        # checked at DEQUEUE, so an item enqueued before a lease handoff is
        # dropped — not reconciled — the moment this worker no longer owns
        # its range. None (static sharding / single process) never drops.
        self.owns: Optional[Callable[[str], bool]] = None
        self.disowned_total = 0
        # The WakeHub this controller's wake producers announce on; gates
        # the Result.wake_source timer-arm skip. Assigned by the registry.
        self.wake_hub = None
        self.queue = RateLimitingQueue()
        self.sources: list[_Source] = []
        self.singleton = False
        self.timeouts_total = 0
        self.retries_exhausted_total = 0
        self.fenced_total = 0
        self._metrics_hook: Optional[Callable[[str, float, Optional[str]], None]] = None
        self._exhausted_hook: Optional[Callable[[str, Request, int], Awaitable[None]]] = None
        self._trace_seam: Optional[
            Callable[[str, Request, Optional[float], Optional[str]],
                     object]] = None

    def watches(self, cls: type, map_fn: Optional[MapFn] = None,
                predicate: Optional[Predicate] = None,
                wake_source: Optional[str] = None) -> "Controller":
        self.sources.append(_Source(cls, map_fn or _default_map, predicate,
                                    wake_source))
        return self

    def as_singleton(self) -> "Controller":
        self.singleton = True
        return self

    def set_metrics_hook(self, hook) -> None:
        self._metrics_hook = hook

    def set_exhausted_hook(self, hook) -> None:
        """Async ``hook(controller_name, req, failures)`` fired when an item
        exhausts ``max_retries`` (events/metrics live above the runtime
        layer; this seam keeps the dependency pointing upward)."""
        self._exhausted_hook = hook

    def set_trace_seam(self, seam) -> None:
        """``seam(controller_name, req, queue_wait_seconds, wake_source) ->
        context manager`` entered around each reconcile (same
        upward-pointing dependency rule as the metrics/exhausted hooks:
        tracing lives above the runtime layer). Because it is entered
        inside the worker task, contextvars it sets propagate into every
        await the reconciler makes — providers and clients see the active
        span."""
        self._trace_seam = seam

    async def inject(self, name: str, namespace: str = "",
                     source: str = SOURCE_INJECT) -> None:
        """External wake-up seam: enqueue a reconcile for ``name`` NOW.

        Used by completion sources outside the watch stream — the WakeHub
        fans LRO completion, node readiness, stockout-TTL expiry and
        status-flush events into this seam, so a claim parked on
        ``Result(requeue_after=...)`` is reconciled the tick its awaited
        state changes instead of a full requeue interval later. Dedup and
        processing-set semantics are the workqueue's own (an item mid-flight
        is marked dirty and re-queued after ``done``), so a wake can never
        be lost or duplicated into concurrent reconciles. ``source`` labels
        the wake for the requeue_wakes counter and idle-gap attribution —
        it matches the WakeHub sink signature ``sink(name, source=...)``."""
        await self.queue.add(Request(name=name, namespace=namespace),
                             source=source)

    # -- run --------------------------------------------------------------
    async def _pump(self, client: Client, src: _Source) -> None:
        w = client.watch(src.cls)
        try:
            async for ev in w:
                # schedfuzz seam: the moment handler-side code first
                # observes the event (predicates/map-fns read the object)
                probes.emit("handler-delivery",
                            (src.cls.KIND, ev.object.metadata.namespace,
                             ev.object.metadata.name),
                            controller=self.name)
                if src.predicate is not None and not src.predicate(ev.object):
                    continue
                for req in src.map_fn(ev.object):
                    await self.queue.add(req, source=src.wake_source
                                         or SOURCE_WATCH)
        finally:
            w.close()

    async def _reconcile_once(self, req: Request) -> Result:
        if self.reconcile_timeout is None:
            return await self.reconciler.reconcile(req)
        # wait_for CANCELS the hung reconcile at the deadline — the worker
        # is reclaimed; the item takes the normal error-backoff path.
        return await asyncio.wait_for(self.reconciler.reconcile(req),
                                      timeout=self.reconcile_timeout)

    async def _requeue_failed(self, req: Request) -> None:
        """Error path: rate-limited requeue, bounded by ``max_retries``."""
        failures = self.queue.num_requeues(req)
        if self.max_retries and failures >= self.max_retries:
            self.retries_exhausted_total += 1
            log.warning(
                "controller=%s req=%s retries exhausted after %d attempts; "
                "degrading to slow retry every %.0fs", self.name, req,
                failures, self.queue.max_delay)
            if self._exhausted_hook is not None:
                try:
                    await self._exhausted_hook(self.name, req, failures)
                except Exception:  # noqa: BLE001 — observability only
                    log.warning("controller=%s exhausted hook failed",
                                self.name, exc_info=True)
            await self.queue.reset_failures(req)
            await self.queue.add_after(req, self.queue.max_delay)
            return
        await self.queue.add_rate_limited(req)

    async def _worker(self) -> None:
        while True:
            req = await self.queue.get()
            # Always consume the queue-wait and wake-source stamps (keeps
            # the queue's maps bounded) even when no trace seam is installed.
            queue_wait = self.queue.pop_wait(req)
            wake_src = self.queue.pop_wake_source(req)
            if self.fence is not None and not self.fence.valid():
                # Deposed leader: single-writer discipline beats progress.
                # Forget as well as done: a deposed-then-re-elected
                # incarnation must not resume this item with a stale failure
                # counter pinned at max backoff — the drop is not a failure.
                self.fenced_total += 1
                probes.emit("fence-drop", req, controller=self.name)
                await self.queue.forget(req)
                await self.queue.done(req)
                continue
            if (self.owns is not None and not self.singleton
                    and not self.owns(req.name)):
                # Lease handoff window: the range moved to another worker
                # between enqueue and dequeue. Drop like a fence would —
                # the new owner's lease-gain replay re-drives the object,
                # so reconciling here would double-write.
                self.disowned_total += 1
                probes.emit("disown-drop", req, controller=self.name)
                await self.queue.forget(req)
                await self.queue.done(req)
                continue
            if self.governor is not None:
                # AIMD pacing: free in HEALTHY mode; in degraded modes this
                # is where the reconcile rate sheds. After the fence check
                # (a deposed leader must not hold a token) and before the
                # clock starts (shed wait is not reconcile time).
                await self.governor.pace()
            start = time.monotonic()
            err: Optional[str] = None
            # The seam's context manager stays open across the requeue
            # bookkeeping too, so warning logs on the error paths carry the
            # reconcile's trace/span ids.
            trace_ctx = (self._trace_seam(self.name, req, queue_wait,
                                          wake_src)
                         if self._trace_seam is not None
                         else contextlib.nullcontext())
            with trace_ctx:
                try:
                    result = await self._reconcile_once(req)
                except asyncio.CancelledError:
                    # Shutdown cancellation must propagate; a CancelledError
                    # the RECONCILER leaked (a sub-task it spawned got
                    # cancelled) is isolated and retried. Task.cancelling()
                    # is 3.11+ — on 3.10 the two are indistinguishable, so
                    # re-raise (pre-hardening behavior).
                    cancelling = getattr(asyncio.current_task(), "cancelling",
                                         None)
                    if cancelling is None or cancelling():
                        raise
                    err = "Cancelled"
                    await self.queue.done(req)
                    await self._requeue_failed(req)
                except Exception as e:  # reconcile errors → rate-limited requeue
                    # TimeoutError with a deadline configured = OUR wait_for
                    # fired (3.11+: asyncio.TimeoutError IS builtin
                    # TimeoutError; a reconciler-raised timeout with no
                    # deadline set stays a generic error).
                    if (isinstance(e, asyncio.TimeoutError)
                            and self.reconcile_timeout is not None):
                        err = "ReconcileTimeout"
                        self.timeouts_total += 1
                        log.warning(
                            "controller=%s req=%s reconcile exceeded %.1fs "
                            "deadline; cancelled and requeued", self.name, req,
                            self.reconcile_timeout)
                    else:
                        err = type(e).__name__
                        log.warning("controller=%s req=%s reconcile error: %s",
                                    self.name, req, e, exc_info=True)
                    await self.queue.done(req)
                    await self._requeue_failed(req)
                else:
                    if not (result and result.preserve_failures):
                        await self.queue.forget(req)
                    await self.queue.done(req)
                    if result and result.requeue_after is not None:
                        # Timer diet: a park annotated with an ANNOUNCED
                        # event source skips the safety-net arm entirely —
                        # the producer wakes it through the hub. The skip
                        # is ledgered (timer-arm-skipped) and any folded
                        # un-sourced deadline (liveness budget) is armed in
                        # the sourced timer's place.
                        if (result.wake_source is not None
                                and self.wake_hub is not None
                                and self.wake_hub.announced(
                                    result.wake_source)):
                            note_skipped_arm()
                            if result.fallback_after is not None:
                                await self.queue.add_after(
                                    req, result.fallback_after)
                        else:
                            await self.queue.add_after(req,
                                                       result.requeue_after)
                    elif result and result.requeue:
                        await self.queue.add_rate_limited(req)
                finally:
                    if self._metrics_hook is not None:
                        self._metrics_hook(self.name,
                                           time.monotonic() - start, err)

    async def run(self, client: Client) -> list[asyncio.Task]:
        tasks = [asyncio.create_task(self._pump(client, s), name=f"{self.name}/pump")
                 for s in self.sources]
        if self.singleton:
            await self.queue.add(SINGLETON_REQUEST)
        tasks += [asyncio.create_task(self._worker(), name=f"{self.name}/worker-{i}")
                  for i in range(self.max_concurrent)]
        return tasks


class Singleton:
    """Wrap a ``async reconcile_singleton() -> float`` (returns next interval)
    into a Reconciler."""

    def __init__(self, fn: Callable[[], Awaitable[float]]):
        self.fn = fn

    async def reconcile(self, req: Request) -> Result:
        interval = await self.fn()
        return Result(requeue_after=interval)


class Manager:
    """Holds the client, registered controllers and indexes; runs everything.

    The reference's manager additionally does leader election — disabled by
    default there (DISABLE_LEADER_ELECTION=true,
    vendor/.../operator/options/options.go:117) and single-replica in the
    chart, so a no-op here is behavior-preserving; the seam stays.
    """

    def __init__(self, client: Client):
        self.client = client
        self.controllers: list[Controller] = []
        self._tasks: list[asyncio.Task] = []
        self.started = asyncio.Event()

    def register(self, *controllers: Controller) -> "Manager":
        self.controllers.extend(controllers)
        return self

    def index(self, cls: type, name: str, key_fn) -> None:
        store = getattr(self.client, "store", None)
        if store is not None:
            store.add_index(cls, name, key_fn)

    async def start(self) -> None:
        for c in self.controllers:
            self._tasks += await c.run(self.client)
        # Yield once so watch pumps register before callers mutate state.
        await asyncio.sleep(0)
        self.started.set()

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        # Workqueue teardown AFTER the workers: each queue's delayed-heap
        # timer task must not outlive its controller (an item parked in
        # rate-limit backoff — up to max_delay=1000s — kept the timer
        # sleeping long after every worker was gone).
        for c in self.controllers:
            await c.queue.shutdown()

    async def run_forever(self) -> None:
        await self.start()
        try:
            await asyncio.Event().wait()
        finally:
            await self.stop()
