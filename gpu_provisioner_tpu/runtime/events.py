"""Event recorder: publishes corev1 Events for object lifecycle moments.

Analog of the record.EventRecorder the reference controllers use to surface
insufficient-capacity / eviction / repair events (reference: lifecycle/events.go,
terminator/events/, health/events.go). Dedupes by (involved UID, reason) with
a count bump, like the apiserver's event aggregation.
"""

from __future__ import annotations

import hashlib

from ..apis.core import Event, ObjectReference
from ..apis.meta import Object, ObjectMeta
from ..apis.serde import now
from .client import Client, NotFoundError

NORMAL = "Normal"
WARNING = "Warning"


class Recorder:
    def __init__(self, client: Client, namespace: str = "default"):
        self.client = client
        self.namespace = namespace

    async def publish(self, obj: Object, etype: str, reason: str, message: str) -> None:
        h = hashlib.sha1(f"{obj.metadata.uid}/{reason}".encode()).hexdigest()[:16]
        name = f"{obj.metadata.name}.{h}"
        ref = ObjectReference(kind=obj.KIND, namespace=obj.metadata.namespace,
                              name=obj.metadata.name, uid=obj.metadata.uid)
        try:
            ev = await self.client.get(Event, name, self.namespace)
            ev.count += 1
            ev.last_timestamp = now()
            ev.message = message
            await self.client.update(ev)
        except NotFoundError:
            await self.client.create(Event(
                metadata=ObjectMeta(name=name, namespace=self.namespace),
                involved_object=ref, reason=reason, message=message,
                type=etype, count=1, last_timestamp=now()))


class NoopRecorder:
    async def publish(self, obj, etype, reason, message) -> None:
        return None
