"""Event recorder: publishes corev1 Events for object lifecycle moments.

Analog of the record.EventRecorder the reference controllers use to surface
insufficient-capacity / eviction / repair events (reference: lifecycle/events.go,
terminator/events/, health/events.go). Dedupes by (involved UID, reason) with
a count bump, like the apiserver's event aggregation.

Two hardenings over the original:

- Concurrent ``publish`` calls for the same (uid, reason) used to race the
  get-then-create: both saw NotFound, the second create 409'd and the event
  was silently dropped as "advisory". In-process calls now coalesce behind
  a per-event-name lock, and a cross-process create/update conflict retries
  as a count bump instead of dropping.
- When a claimtrace span is active, the event carries the trace/span ids as
  annotations (``trace_ids`` seam — injected by the assembly layer so this
  module keeps pointing downward only).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
from typing import Callable, Optional

from ..apis.core import Event, ObjectReference
from ..apis.meta import Object, ObjectMeta
from ..apis.serde import now
from .client import AlreadyExistsError, Client, ConflictError, NotFoundError

NORMAL = "Normal"
WARNING = "Warning"

TRACE_ID_ANNOTATION = "tpu-provisioner.io/trace-id"
SPAN_ID_ANNOTATION = "tpu-provisioner.io/span-id"

_MAX_LOCKS = 1024
_CONFLICT_RETRIES = 5

log = logging.getLogger("events")


class Recorder:
    def __init__(self, client: Client, namespace: str = "default",
                 trace_ids: Optional[
                     Callable[[], Optional[tuple[str, str]]]] = None):
        self.client = client
        self.namespace = namespace
        self.trace_ids = trace_ids
        self._locks: dict[str, asyncio.Lock] = {}

    async def publish(self, obj: Object, etype: str, reason: str, message: str) -> None:
        """Best-effort, like client-go's recorder: an event that cannot be
        written (RBAC, conflicts, apiserver hiccups) must never fail the
        reconcile that emitted it."""
        try:
            await self._publish(obj, etype, reason, message)
        except Exception as e:  # noqa: BLE001 — events are advisory
            log.warning("dropping event %s/%s for %s: %s",
                        etype, reason, obj.metadata.name, e)

    def _lock_for(self, name: str) -> asyncio.Lock:
        if len(self._locks) > _MAX_LOCKS:
            for k in [k for k, lk in self._locks.items() if not lk.locked()]:
                self._locks.pop(k, None)
        return self._locks.setdefault(name, asyncio.Lock())

    def _annotations(self) -> dict[str, str]:
        ids = self.trace_ids() if self.trace_ids is not None else None
        if ids is None:
            return {}
        return {TRACE_ID_ANNOTATION: ids[0], SPAN_ID_ANNOTATION: ids[1]}

    async def _publish(self, obj: Object, etype: str, reason: str,
                       message: str) -> None:
        h = hashlib.sha1(f"{obj.metadata.uid}/{reason}".encode()).hexdigest()[:16]
        name = f"{obj.metadata.name}.{h}"
        ref = ObjectReference(kind=obj.KIND, namespace=obj.metadata.namespace,
                              name=obj.metadata.name, uid=obj.metadata.uid)
        notes = self._annotations()
        # In-process coalescing: the get-then-create below is not atomic,
        # so concurrent publishes for one event name must serialize here —
        # the loser of the old race 409'd and lost its count bump.
        async with self._lock_for(name):
            last: Optional[Exception] = None
            for _ in range(_CONFLICT_RETRIES):
                try:
                    ev = await self.client.get(Event, name, self.namespace)
                except NotFoundError:
                    try:
                        await self.client.create(Event(
                            metadata=ObjectMeta(name=name,
                                                namespace=self.namespace,
                                                annotations=dict(notes)),
                            involved_object=ref, reason=reason,
                            message=message, type=etype, count=1,
                            last_timestamp=now()))
                        return
                    except (AlreadyExistsError, ConflictError) as e:
                        # Another replica created it between our get and
                        # create (the 409 AlreadyExists of the old race) —
                        # fall through to a count bump.
                        last = e
                        continue
                ev.count += 1
                ev.last_timestamp = now()
                ev.message = message
                if notes:
                    ev.metadata.annotations.update(notes)
                try:
                    await self.client.update(ev)
                    return
                except ConflictError as e:  # stale resourceVersion; re-get
                    last = e
                    continue
            raise last if last is not None else ConflictError(name)


class NoopRecorder:
    async def publish(self, obj, etype, reason, message) -> None:
        return None
