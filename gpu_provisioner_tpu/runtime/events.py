"""Event recorder: publishes corev1 Events for object lifecycle moments.

Analog of the record.EventRecorder the reference controllers use to surface
insufficient-capacity / eviction / repair events (reference: lifecycle/events.go,
terminator/events/, health/events.go). Dedupes by (involved UID, reason) with
a count bump, like the apiserver's event aggregation.
"""

from __future__ import annotations

import hashlib
import logging

from ..apis.core import Event, ObjectReference
from ..apis.meta import Object, ObjectMeta
from ..apis.serde import now
from .client import Client, NotFoundError

NORMAL = "Normal"
WARNING = "Warning"

log = logging.getLogger("events")


class Recorder:
    def __init__(self, client: Client, namespace: str = "default"):
        self.client = client
        self.namespace = namespace

    async def publish(self, obj: Object, etype: str, reason: str, message: str) -> None:
        """Best-effort, like client-go's recorder: an event that cannot be
        written (RBAC, conflicts, apiserver hiccups) must never fail the
        reconcile that emitted it."""
        try:
            await self._publish(obj, etype, reason, message)
        except Exception as e:  # noqa: BLE001 — events are advisory
            log.warning("dropping event %s/%s for %s: %s",
                        etype, reason, obj.metadata.name, e)

    async def _publish(self, obj: Object, etype: str, reason: str,
                       message: str) -> None:
        h = hashlib.sha1(f"{obj.metadata.uid}/{reason}".encode()).hexdigest()[:16]
        name = f"{obj.metadata.name}.{h}"
        ref = ObjectReference(kind=obj.KIND, namespace=obj.metadata.namespace,
                              name=obj.metadata.name, uid=obj.metadata.uid)
        try:
            ev = await self.client.get(Event, name, self.namespace)
            ev.count += 1
            ev.last_timestamp = now()
            ev.message = message
            await self.client.update(ev)
        except NotFoundError:
            await self.client.create(Event(
                metadata=ObjectMeta(name=name, namespace=self.namespace),
                involved_object=ref, reason=reason, message=message,
                type=etype, count=1, last_timestamp=now()))


class NoopRecorder:
    async def publish(self, obj, etype, reason, message) -> None:
        return None
