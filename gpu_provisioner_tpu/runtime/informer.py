"""Watch-backed read cache: the informer-lite layer (V9 parity).

The reference reads through controller-runtime's cached client — informers
list once, then maintain the cache from the watch stream, so steady-state
controllers put ~zero LIST load on the apiserver even with GC loops
re-scanning every 2 minutes (vendor/.../operator/operator.go builds the
manager cache; QPS 200/burst 300 at options.go:114-115 assumes it).

``Informer`` maintains one kind's cache; ``CachedListClient`` wraps any
Client and serves ``list()`` for the cached kinds from the informers while
every other verb — crucially ``get()`` — passes through. Optimistic
concurrency stays correct: ``patch_retry``'s get→mutate→update cycle reads
the live apiserver, so a conflict retry never spins on a stale cached copy
(the one semantic landmine of reading through a cache; the reference
accepts stale reads everywhere and relies on watch latency being small).

Staleness is bounded by watch delivery plus the periodic resync (a guard
re-list reconciling missed events, like an informer's resync period). GC
tolerates it by design — its 30s leak grace exceeds any realistic lag.
Deletions missed during a watch-stream outage do NOT linger until resync:
RestWatch replays its re-list with synthesized DELETED tombstones for
objects that vanished while the stream was down (client-go reflector
Replace() parity — see rest.py), so the cache converges as soon as the
watch self-heals.
"""

from __future__ import annotations

import asyncio
import logging
import random
from typing import Optional

from ..apis.meta import Object
from . import apihealth, probes
from .client import Client, ResourceExpiredError
from .store import ADDED, DELETED, MODIFIED, WatchEvent

log = logging.getLogger("informer")

RESYNC_SECONDS = 300.0

# Gap-heal relist pacing: jittered so a fleet of informers healing off the
# same partition doesn't stampede the recovering apiserver, bounded so a
# still-dead apiserver is probed at a civilized cadence, never slower.
RELIST_JITTER_BASE = 0.05
RELIST_BACKOFF_CAP = 1.0

_RELAY_CLOSED = object()


class RelayWatch:
    """Watch handle fed by an :class:`Informer` AFTER each event is applied
    to its cache — the controller-runtime ordering guarantee (event handlers
    fire post-cache-update). Without it a controller pump subscribed to the
    raw store races the informer: a Node-ready event can enqueue a claim
    whose reconcile then LISTs a cache that doesn't hold the flip yet, sees
    stale not-ready state, and parks on its safety-net timer with the wake
    already consumed (the BENCH_pr11 idle-gap:timer tail — 0.5s parks on
    state that was already true).

    Subscription replays the current cache as synthesized ADDED events
    (store-watch ``initial_list`` parity, so objects created before a late
    subscriber still reconcile). Same contract as the store watch: event
    objects are shared and READ-ONLY; ``close()`` is idempotent and wakes a
    blocked consumer."""

    def __init__(self, informer: "Informer"):
        self._informer = informer
        self._q: asyncio.Queue = asyncio.Queue()
        self._closed = False
        for obj in informer._cache.values():
            self._q.put_nowait(WatchEvent(ADDED, obj.deepcopy()))
        informer._relays.append(self)

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        if self._closed:
            raise StopAsyncIteration
        ev = await self._q.get()
        if ev is _RELAY_CLOSED or self._closed:
            raise StopAsyncIteration
        return ev

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self in self._informer._relays:
            self._informer._relays.remove(self)
        self._q.put_nowait(_RELAY_CLOSED)


class Informer:
    """List-then-watch cache for one kind. ``start()`` returns synced."""

    def __init__(self, client: Client, cls: type,
                 resync: float = RESYNC_SECONDS):
        self.client = client
        self.cls = cls
        self.resync = resync
        self.synced = False
        # loop-time of the last watch event or successful re-list: the
        # cache's freshness signal. A healthy informer never exceeds
        # ~resync (the quiet-watch deadline forces a re-list); an age far
        # past that means the watch is wedged AND re-lists are failing —
        # decision-bearing consumers (GC, repair) bound their actions on it
        self.last_sync: float = float("-inf")
        self._cache: dict[tuple[str, str], Object] = {}
        # label inverted index, mirroring the store's (store.py _by_label):
        # per-pool node lists at fleet scale must be O(result), not
        # O(cache) — a linear items() scan under hundreds of concurrent
        # node-waits melted the event loop at 512+ claims
        self._by_label: dict[tuple[str, str], set] = {}
        # field inverted indexes (spec.providerID etc.), same O(result)
        # argument: _pool_name_for runs once per lifecycle/termination
        # reconcile — a key_fn scan over the whole Node cache per call would
        # quietly re-create the cost the index exists to remove
        self._index_fns: dict[str, object] = {}
        self._by_index: dict[tuple[str, str], set] = {}
        # post-cache-update event subscribers (RelayWatch); fan-out happens
        # in _run strictly after _upsert/_remove so a relayed event is
        # always observable through items() by the time a consumer sees it
        self._relays: list[RelayWatch] = []
        self._task: Optional[asyncio.Task] = None
        # APIHealthGovernor, assigned post-construction (envtest/operator):
        # the informer reports watch gaps to it; verb outcomes are already
        # classified by the GovernedClient beneath this cache
        self.governor = None
        # cumulative, for tests/debugging (fleet-wide totals live in the
        # apihealth.APIHEALTH ledger)
        self.watch_gaps = 0
        self.relists = 0

    def subscribe(self) -> RelayWatch:
        """A watch stream ordered AFTER this cache's updates."""
        return RelayWatch(self)

    def _apply(self, ev) -> None:
        """Apply one watch event to the cache, then fan it out to relay
        subscribers (strictly in that order — the relay's contract). Events
        lost while the stream is down are healed by :meth:`_resync`, which
        diffs the fresh list against this cache and pushes the synthesized
        ADDED/MODIFIED/DELETED back through here — so relay consumers heal
        on the same path live events take."""
        if ev.type == DELETED:
            self._remove(ev.object)
        else:
            # CLONE before retaining: watch events share ONE object
            # instance across all watchers (store.py's serde optimization)
            # — storing it as-is would let any future event consumer's
            # mutation corrupt this cache for the object's lifetime
            self._upsert(ev.object.deepcopy())
        for r in list(self._relays):
            r._q.put_nowait(ev)

    def add_index(self, name: str, key_fn) -> None:
        self._index_fns[name] = key_fn
        for key, obj in self._cache.items():  # backfill a live cache
            for v in key_fn(obj) or []:
                self._by_index.setdefault((name, v), set()).add(key)

    @staticmethod
    def _key(obj: Object) -> tuple[str, str]:
        return (obj.metadata.namespace, obj.metadata.name)

    def _upsert(self, obj: Object) -> None:
        key = self._key(obj)
        old = self._cache.get(key)
        if old is not None:
            self._unindex(key, old)
        self._cache[key] = obj
        # schedfuzz cache-apply-before-delivery contract: noted here (not in
        # _apply) so the initial re-list counts too — a relay subscriber's
        # replayed ADDEDs are backed by these upserts
        probes.emit("cache-apply",
                    (self.cls.KIND, obj.metadata.namespace,
                     obj.metadata.name))
        for lk_lv in obj.metadata.labels.items():
            self._by_label.setdefault(lk_lv, set()).add(key)
        for name, fn in self._index_fns.items():
            for v in fn(obj) or []:
                self._by_index.setdefault((name, v), set()).add(key)

    def _remove(self, obj: Object) -> None:
        key = self._key(obj)
        old = self._cache.pop(key, None)
        if old is not None:
            self._unindex(key, old)
        probes.emit("cache-apply",
                    (self.cls.KIND, obj.metadata.namespace,
                     obj.metadata.name))

    def _unindex(self, key, obj: Object) -> None:
        for lk_lv in obj.metadata.labels.items():
            self._by_label.get(lk_lv, set()).discard(key)
        for name, fn in self._index_fns.items():
            for v in fn(obj) or []:
                self._by_index.get((name, v), set()).discard(key)

    async def start(self) -> None:
        if self._task is not None:
            return
        # subscribe BEFORE listing: events landing between the list and the
        # subscription would otherwise be lost until the next resync (the
        # replayed ADDEDs the watch then delivers are idempotent upserts)
        self._watch = self.client.watch(self.cls)
        try:
            await self._resync()
        except BaseException:
            # don't leak the watch (and its background re-list task) on a
            # failed initial list — a retried start() would orphan it
            self._watch.close()
            self._watch = None
            raise
        self.synced = True
        self._task = asyncio.create_task(
            self._run(), name=f"informer-{self.cls.KIND}")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self.synced = False

    def age(self) -> float:
        """Seconds since the cache last observed the apiserver (watch event
        or successful re-list). inf before the first sync."""
        return asyncio.get_event_loop().time() - self.last_sync

    async def _resync(self, sync_events: bool = False) -> None:
        """Re-list and DIFF against the cache, synthesizing every missed
        ADDED/MODIFIED/DELETED through :meth:`_apply` — client-go reflector
        Replace() parity, tombstones included — so relay consumers (the
        controller pumps) heal through the WakeHub ``watch`` source instead
        of riding their timer safety nets. With ``sync_events`` (a 410
        gap heal), UNCHANGED objects are re-delivered as sync MODIFIEDs
        too: the full-fleet catch-up that guarantees a claim parked across
        the gap wakes even though its own object never changed.

        No error handling here by design: the caller owns the jittered
        bounded retry ladder (and the 410-vs-generic classification
        provlint PL015 pins)."""
        objs = await self.client.list(self.cls)
        fresh = {self._key(o) for o in objs}
        stale = [o for k, o in self._cache.items() if k not in fresh]
        for obj in objs:
            old = self._cache.get(self._key(obj))
            if old is None:
                self._apply(WatchEvent(ADDED, obj))
            elif (sync_events or old.metadata.resource_version
                    != obj.metadata.resource_version):
                self._apply(WatchEvent(MODIFIED, obj))
        for old in stale:
            # the delete happened while the stream was down: synthesize the
            # tombstone from the last state we knew (client-go's
            # DeletedFinalStateUnknown analog)
            self._apply(WatchEvent(DELETED, old))
        self.last_sync = asyncio.get_event_loop().time()
        self.relists += 1
        apihealth.note_relist()

    async def _run(self) -> None:
        watch = self._watch
        while True:
            loop = asyncio.get_event_loop()
            deadline = loop.time() + self.resync
            gap = False
            try:
                # event pump with a hard resync deadline: the timeout fires
                # even on a totally quiet watch, so events missed without a
                # detectable break are flushed within one resync period
                while True:
                    remaining = deadline - loop.time()
                    if remaining <= 0:
                        break
                    try:
                        ev = await asyncio.wait_for(watch.__anext__(),
                                                    remaining)
                    except (asyncio.TimeoutError, StopAsyncIteration):
                        break
                    # Batch-drain: after the blocking pop, pull the rest of
                    # the burst non-blocking. One wait_for (task + timer
                    # handle) PER EVENT made this single pump the slowest
                    # stage of the watch path during a wave — with the
                    # controllers' pumps now riding the post-cache relay,
                    # that latency was theirs too (first-reconcile delays
                    # of ~0.5s at 100 claims). Yield every 256 events so a
                    # mega-wave burst can't hold the loop.
                    burst = 0
                    while ev is not None:
                        self._apply(ev)
                        burst += 1
                        if burst % 256 == 0:
                            await asyncio.sleep(0)
                        ev = watch.try_next()
                    self.last_sync = loop.time()
            except asyncio.CancelledError:
                watch.close()
                raise
            except ResourceExpiredError as e:
                # 410 Gone / expired resourceVersion: the stream has a hole
                # no reconnect can fill — this is the gap-resync path, NOT
                # the generic backoff ladder (PL015). No punitive sleep:
                # the jittered relist below is the recovery.
                log.info("informer %s watch expired: %s", self.cls.KIND, e)
                gap = True
                self.watch_gaps += 1
                apihealth.note_watch_gap()
                if self.governor is not None:
                    self.governor.note_watch_gap()
            except Exception as e:  # noqa: BLE001 — cache must self-heal
                log.warning("informer %s watch broke: %s", self.cls.KIND, e)
                await asyncio.sleep(1.0)
            finally:
                watch.close()
            # same subscribe-before-list ordering as start(); the relist is
            # jittered (no heal stampede across informers) and bounded (a
            # still-dead apiserver is probed at RELIST_BACKOFF_CAP cadence)
            watch = self.client.watch(self.cls)
            delay = RELIST_JITTER_BASE * (0.5 + random.random())
            while True:
                try:
                    await asyncio.sleep(delay)
                    await self._resync(sync_events=gap)
                    break
                except asyncio.CancelledError:
                    watch.close()
                    raise
                except Exception as e:  # noqa: BLE001 — retried below
                    log.warning("informer %s resync failed: %s",
                                self.cls.KIND, e)
                    delay = min(delay * 2, RELIST_BACKOFF_CAP)
                    delay *= 0.5 + random.random()

    def items(self, labels: Optional[dict[str, str]] = None,
              namespace: Optional[str] = None,
              index_fn=None, index_value=None,
              index_name=None) -> list[Object]:
        """Cache snapshot with the same filter semantics as Client.list.
        Deep copies — callers mutate their listed objects freely (the
        controllers do) and must never write through into the cache.
        Label and registered-field-index queries narrow through the
        inverted maps first (O(result)); an unregistered index_fn falls
        back to the scan."""
        if index_name is not None and index_name in self._index_fns:
            keys = self._by_index.get((index_name, index_value), set())
            candidates = [(k, self._cache[k]) for k in list(keys)
                          if k in self._cache]
            index_fn = None  # membership guaranteed by index maintenance
        elif labels:
            lk, lv = next(iter(labels.items()))
            keys = self._by_label.get((lk, lv), set())
            candidates = [(k, self._cache[k]) for k in list(keys)
                          if k in self._cache]
        else:
            candidates = list(self._cache.items())
        out = []
        for (ns, _), obj in candidates:
            if namespace is not None and ns != namespace:
                continue
            if labels and any(obj.metadata.labels.get(k) != v
                              for k, v in labels.items()):
                continue
            if index_fn is not None and index_value not in index_fn(obj):
                continue
            # Object.deepcopy is the schema-aware fast clone (meta.py) —
            # generic copy.deepcopy was ~10× slower and this is the bench
            # hot path at fleet scale
            out.append(obj.deepcopy())
        return out


class CachedListClient:
    """Client wrapper: ``list()`` for cached kinds serves from informers
    once synced (and falls through before that); every other verb hits the
    inner client directly."""

    def __init__(self, inner: Client, kinds: tuple[type, ...],
                 resync: float = RESYNC_SECONDS):
        self.inner = inner
        self._informers = {cls: Informer(inner, cls, resync)
                           for cls in kinds}
        self._indexes: dict[tuple[type, str], object] = {}

    async def start(self) -> None:
        for inf in self._informers.values():
            await inf.start()

    async def stop(self) -> None:
        for inf in self._informers.values():
            await inf.stop()

    def add_index(self, cls: type, name: str, key_fn) -> None:
        self._indexes[(cls, name)] = key_fn
        inf = self._informers.get(cls)
        if inf is not None:
            inf.add_index(name, key_fn)  # O(result) map, not a key_fn scan
        if hasattr(self.inner, "add_index"):
            self.inner.add_index(cls, name, key_fn)

    def cache_age(self, cls) -> float:
        """Freshness of the cache ``list(cls)`` reads from: seconds since
        that informer last observed the apiserver. 0.0 when the kind is
        uncached or not yet synced — those reads pass through to the live
        client and are always fresh."""
        inf = self._informers.get(cls)
        if inf is None or not inf.synced:
            return 0.0
        return inf.age()

    async def list(self, cls, labels=None, namespace=None, index=None):
        inf = self._informers.get(cls)
        if inf is None or not inf.synced:
            return await self.inner.list(cls, labels, namespace, index)
        if index is not None:
            name, value = index
            key_fn = self._indexes.get((cls, name))
            if key_fn is None:
                return await self.inner.list(cls, labels, namespace, index)
            return inf.items(labels, namespace, key_fn, value,
                             index_name=name)
        return inf.items(labels, namespace)

    # --- pass-throughs ----------------------------------------------------
    async def get(self, cls, name, namespace=""):
        return await self.inner.get(cls, name, namespace)

    async def create(self, obj):
        return await self.inner.create(obj)

    async def update(self, obj):
        return await self.inner.update(obj)

    async def update_status(self, obj):
        return await self.inner.update_status(obj)

    async def delete(self, cls, name, namespace=""):
        return await self.inner.delete(cls, name, namespace)

    async def evict(self, name, namespace="", uid=""):
        return await self.inner.evict(name, namespace, uid=uid)

    def watch(self, cls):
        # Cached kinds watch through the informer's post-cache-update relay
        # (controller-runtime parity: a handler never observes an event its
        # cache can't serve yet); uncached kinds pass through as before.
        inf = self._informers.get(cls)
        if inf is not None:
            return inf.subscribe()
        return self.inner.watch(cls)
