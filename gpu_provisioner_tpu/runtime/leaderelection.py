"""Lease-based leader election (vendor/.../operator/operator.go:157-164).

The reference delegates to client-go's leaderelection via controller-runtime:
acquire a coordination.k8s.io Lease, renew it at ``renew_interval``, and if
another holder's lease has expired, take it over (bumping
``lease_transitions``). Losing the lease is fatal — the reference's manager
exits so the replica restarts into candidacy; ``on_lost`` defaults to
setting an event the operator treats as a stop signal.

Defaults mirror client-go: 15s lease, 10s renew deadline, 2s retry.
"""

from __future__ import annotations

import asyncio
import logging
import math
import os
import socket
import uuid
from typing import Callable, Optional

from ..apis.core import Lease, LeaseSpec
from ..apis.meta import ObjectMeta
from ..apis.serde import now
from .client import Client, ConflictError, NotFoundError, AlreadyExistsError

log = logging.getLogger("leaderelection")

LEASE_DURATION = 15.0
RENEW_INTERVAL = 10.0
RETRY_INTERVAL = 2.0


def default_identity() -> str:
    return f"{socket.gethostname()}_{os.getpid()}_{uuid.uuid4().hex[:8]}"


class LeaderElector:
    def __init__(self, client: Client, lease_name: str = "tpu-provisioner",
                 namespace: str = "default",
                 identity: Optional[str] = None,
                 lease_duration: float = LEASE_DURATION,
                 renew_interval: float = RENEW_INTERVAL,
                 retry_interval: float = RETRY_INTERVAL,
                 on_lost: Optional[Callable[[], None]] = None):
        self.client = client
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or default_identity()
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.retry_interval = retry_interval
        self.on_lost = on_lost
        self.leading = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    async def run_until_leading(self) -> None:
        """Block until this replica holds the lease, then keep renewing in
        the background."""
        while not await self._try_acquire():
            await asyncio.sleep(self.retry_interval)
        self.leading.set()
        log.info("leader election won", extra={"identity": self.identity,
                                               "lease": self.lease_name})
        self._task = asyncio.create_task(self._renew_loop(),
                                         name="lease-renew")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        await self._release()
        self.leading.clear()

    # --- internals ---------------------------------------------------------

    def _expired(self, lease: Lease) -> bool:
        if lease.spec.renew_time is None:
            return True
        age = (now() - lease.spec.renew_time).total_seconds()
        return age > lease.spec.lease_duration_seconds

    async def _try_acquire(self) -> bool:
        try:
            lease = await self.client.get(Lease, self.lease_name, self.namespace)
        except NotFoundError:
            fresh = Lease(
                metadata=ObjectMeta(name=self.lease_name,
                                    namespace=self.namespace),
                spec=LeaseSpec(
                    holder_identity=self.identity,
                    # Lease times are metav1.Time (second resolution) — a
                    # sub-second duration must round UP or it is born expired
                    lease_duration_seconds=max(1, math.ceil(self.lease_duration)),
                    acquire_time=now(), renew_time=now()))
            try:
                await self.client.create(fresh)
                return True
            except AlreadyExistsError:
                return False
        if lease.spec.holder_identity == self.identity:
            return await self._renew(lease)
        if not self._expired(lease):
            return False
        # expired foreign lease → steal
        lease.spec.holder_identity = self.identity
        lease.spec.acquire_time = now()
        lease.spec.renew_time = now()
        lease.spec.lease_transitions += 1
        try:
            await self.client.update(lease)
            return True
        except (ConflictError, NotFoundError):
            return False  # someone else won the race

    async def _renew(self, lease: Optional[Lease] = None) -> bool:
        try:
            if lease is None:
                lease = await self.client.get(Lease, self.lease_name,
                                              self.namespace)
            if lease.spec.holder_identity != self.identity:
                return False
            lease.spec.renew_time = now()
            await self.client.update(lease)
            return True
        except (ConflictError, NotFoundError):
            return False

    async def _renew_loop(self) -> None:
        while True:
            await asyncio.sleep(self.renew_interval)
            deadline = asyncio.get_event_loop().time() + self.lease_duration
            renewed = False
            while asyncio.get_event_loop().time() < deadline:
                if await self._renew():
                    renewed = True
                    break
                await asyncio.sleep(self.retry_interval)
            if not renewed:
                log.error("leadership lost", extra={"identity": self.identity})
                self.leading.clear()
                if self.on_lost is not None:
                    self.on_lost()
                return

    async def _release(self) -> None:
        """Voluntary release on clean shutdown so the next replica doesn't
        wait out the lease."""
        try:
            lease = await self.client.get(Lease, self.lease_name, self.namespace)
            if lease.spec.holder_identity == self.identity:
                lease.spec.holder_identity = ""
                lease.spec.renew_time = None
                await self.client.update(lease)
        except (NotFoundError, ConflictError):
            pass
