"""Lease-based leader election (vendor/.../operator/operator.go:157-164).

The reference delegates to client-go's leaderelection via controller-runtime:
acquire a coordination.k8s.io Lease, renew it at ``renew_interval``, and if
another holder's lease has expired, take it over (bumping
``lease_transitions``). Losing the lease is fatal — the reference's manager
exits so the replica restarts into candidacy; ``on_lost`` defaults to
setting an event the operator treats as a stop signal.

Fencing: a replica that loses the lease mid-reconcile must not keep mutating
the cloud while the new leader acts. ``fence()`` captures the leadership
generation at acquisition as a :class:`FencingToken`; reconcile loops and
the instance provider check it before cloud mutations. The token is local —
the cloud APIs cannot validate it server-side — which is sufficient ONLY
because the renew loop anchors its give-up deadline at the *last successful
renew*: this replica stops acting as leader no later than the instant the
lease becomes legally stealable, so a correctly-fenced deposed leader and a
new leader never overlap (clock skew between replicas aside, which the
observed-staleness expiry check below also bounds).

Defaults mirror client-go: 15s lease, 10s renew deadline, 2s retry.
"""

from __future__ import annotations

import asyncio
import logging
import math
import os
import socket
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

from ..apis.core import Lease, LeaseSpec
from ..apis.meta import ObjectMeta
from ..apis.serde import now
from .client import Client, ConflictError, NotFoundError, AlreadyExistsError

log = logging.getLogger("leaderelection")

LEASE_DURATION = 15.0
RENEW_INTERVAL = 10.0
RETRY_INTERVAL = 2.0


def default_identity() -> str:
    return f"{socket.gethostname()}_{os.getpid()}_{uuid.uuid4().hex[:8]}"


class FencedError(Exception):
    """A mutation was attempted under a fencing token that no longer matches
    the live leadership generation — the caller is a deposed leader."""


@dataclass(frozen=True)
class FencingToken:
    """Leadership generation captured at acquisition. ``valid()`` is a pure
    local check (no apiserver round-trip) — see the module docstring for why
    that is sufficient when paired with the renew-deadline anchoring."""

    elector: "LeaderElector"
    generation: int

    def valid(self) -> bool:
        return (self.elector.leading.is_set()
                and self.elector.generation == self.generation)

    def check(self) -> None:
        if not self.valid():
            raise FencedError(
                f"fencing token generation {self.generation} is stale "
                f"(holder {self.elector.identity} no longer leads)")


class LeaderElector:
    def __init__(self, client: Client, lease_name: str = "tpu-provisioner",
                 namespace: str = "default",
                 identity: Optional[str] = None,
                 lease_duration: float = LEASE_DURATION,
                 renew_interval: float = RENEW_INTERVAL,
                 retry_interval: float = RETRY_INTERVAL,
                 on_lost: Optional[Callable[[], None]] = None):
        self.client = client
        self.lease_name = lease_name
        self.namespace = namespace
        self.identity = identity or default_identity()
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.retry_interval = retry_interval
        self.on_lost = on_lost
        self.leading = asyncio.Event()
        # Bumped on every acquisition; FencingTokens capture it so a token
        # from a previous term can never validate again, even after this
        # replica re-wins the lease.
        self.generation = 0
        self._task: Optional[asyncio.Task] = None
        self._last_renew: float = 0.0
        # (holder, renew_time) last observed on a foreign lease + the local
        # monotonic time of that observation — the clock-skew guard.
        self._observed: Optional[tuple[tuple, float]] = None

    def fence(self) -> FencingToken:
        """Token for the CURRENT term; call after ``run_until_leading``."""
        if not self.leading.is_set():
            raise RuntimeError("fence() requires leadership")
        return FencingToken(self, self.generation)

    async def run_until_leading(self) -> None:
        """Block until this replica holds the lease, then keep renewing in
        the background."""
        while not await self._try_acquire():
            await asyncio.sleep(self.retry_interval)
        self._last_renew = asyncio.get_event_loop().time()
        self.generation += 1
        self.leading.set()
        log.info("leader election won", extra={"identity": self.identity,
                                               "lease": self.lease_name})
        self._task = asyncio.create_task(self._renew_loop(),
                                         name="lease-renew")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self.leading.clear()  # before the release: no fenced-valid window
        await self._release()

    # --- internals ---------------------------------------------------------

    def _expired(self, lease: Lease) -> bool:
        if lease.spec.renew_time is None:
            return True
        age = (now() - lease.spec.renew_time).total_seconds()
        if age > lease.spec.lease_duration_seconds:
            return True
        # Clock-skew tolerance: a renew_time AHEAD of our clock (negative
        # age) must not extend the holder's term past what we can verify —
        # otherwise a skewed holder wedges candidacy for the skew + the
        # lease duration. Judge staleness by how long WE have observed this
        # (holder, renew_time) pair unchanged on our own monotonic clock
        # (client-go's observedTime): a live holder bumps renew_time every
        # renew_interval < lease_duration, so a pair that survives a full
        # lease_duration of local time is dead whatever its clock claims.
        key = (lease.spec.holder_identity, lease.spec.renew_time)
        mono = asyncio.get_event_loop().time()
        if self._observed is None or self._observed[0] != key:
            self._observed = (key, mono)
            return False
        return mono - self._observed[1] > lease.spec.lease_duration_seconds

    async def _try_acquire(self) -> bool:
        try:
            lease = await self.client.get(Lease, self.lease_name, self.namespace)
        except NotFoundError:
            fresh = Lease(
                metadata=ObjectMeta(name=self.lease_name,
                                    namespace=self.namespace),
                spec=LeaseSpec(
                    holder_identity=self.identity,
                    # Lease times are metav1.Time (second resolution) — a
                    # sub-second duration must round UP or it is born expired
                    lease_duration_seconds=max(1, math.ceil(self.lease_duration)),
                    acquire_time=now(), renew_time=now()))
            try:
                await self.client.create(fresh)
                return True
            except AlreadyExistsError:
                return False
        if lease.spec.holder_identity == self.identity:
            return await self._renew(lease)
        if not self._expired(lease):
            return False
        # expired foreign lease → steal
        lease.spec.holder_identity = self.identity
        lease.spec.acquire_time = now()
        lease.spec.renew_time = now()
        lease.spec.lease_transitions += 1
        try:
            await self.client.update(lease)
            return True
        except (ConflictError, NotFoundError):
            return False  # someone else won the race

    async def _renew(self, lease: Optional[Lease] = None) -> bool:
        try:
            if lease is None:
                lease = await self.client.get(Lease, self.lease_name,
                                              self.namespace)
            if lease.spec.holder_identity != self.identity:
                return False
            lease.spec.renew_time = now()
            await self.client.update(lease)
            self._last_renew = asyncio.get_event_loop().time()
            return True
        except (ConflictError, NotFoundError):
            return False

    async def _renew_loop(self) -> None:
        loop = asyncio.get_event_loop()
        while True:
            await asyncio.sleep(self.renew_interval)
            # The give-up deadline is anchored at the LAST SUCCESSFUL renew,
            # not the start of this retry loop: the lease becomes legally
            # stealable lease_duration after its renew_time, and the last
            # renew was renew_interval ago — granting ourselves a fresh
            # lease_duration from now would keep this replica acting as
            # leader for up to renew_interval AFTER a rival may already hold
            # the lease (the dual-writer window fencing exists to close).
            deadline = self._last_renew + self.lease_duration
            renewed = False
            while (remaining := deadline - loop.time()) > 0:
                try:
                    # a hung renew call must not let us overshoot the
                    # deadline either — bound it by the remaining budget
                    if await asyncio.wait_for(self._renew(),
                                              timeout=remaining):
                        renewed = True
                        break
                except asyncio.TimeoutError:
                    break
                await asyncio.sleep(min(self.retry_interval,
                                        max(0.0, deadline - loop.time())))
            if not renewed:
                log.error("leadership lost", extra={"identity": self.identity})
                self.leading.clear()  # invalidates every outstanding fence
                if self.on_lost is not None:
                    self.on_lost()
                return

    async def _release(self) -> None:
        """Voluntary release on clean shutdown so the next replica doesn't
        wait out the lease."""
        try:
            lease = await self.client.get(Lease, self.lease_name, self.namespace)
            if lease.spec.holder_identity == self.identity:
                lease.spec.holder_identity = ""
                lease.spec.renew_time = None
                await self.client.update(lease)
        except (NotFoundError, ConflictError):
            pass
