"""Ordering probes: the schedfuzz observation seam (analysis/schedfuzz.py)
plus the flight recorder's event tap (observability/flightrecorder.py).

The interleaving explorer checks happens-before contracts the control plane
already relies on — cache-apply before handler delivery, meta patch before
status patch, fence check before cloud mutate, ``WakeHub.stop()`` before any
late wake. Those contracts live at seams spread across runtime/, providers/
and controllers/; this module is the one place they report to. Since PR 14
the same seam also feeds the flight recorder's bounded ring of semantic
control-plane events, so ``emit`` fans out to a small tuple of sinks.

Design constraints, in order:

- **Zero cost disarmed.** ``emit()`` is a module-global ``None`` check; the
  call sites pay a few attribute loads for the arguments. With neither a
  fuzz probe armed nor a recorder sink added, ``_active`` is ``None`` and
  nothing allocates or iterates — the probes are passive the same way the
  claimtrace tracer is (tests/test_fleet.py pins this structurally).
- **No layering leak.** runtime code must not import analysis/ or
  observability/ (or anything above itself — provgraph PG001 enforces
  exactly that); the explorer and the recorder both arm the seam from
  outside via :func:`arm` / :func:`add_sink`.
- **Synchronous.** A probe fires inline at the seam it observes, so the
  checker sees events in true program order — the whole point. Probe
  callbacks must not await, block, or raise (a raising probe is a bug in
  the harness, not the product; ``emit`` lets it propagate so the fuzz run
  fails loudly instead of silently dropping evidence — recorder sinks
  guard their own bodies for the same reason).
"""

from __future__ import annotations

from typing import Callable, Optional

# probe(event: str, key, **info) — armed by analysis/schedfuzz, or by tests.
Probe = Callable[..., None]

# The legacy single slot (schedfuzz's arm/disarm nesting contract) and the
# persistent sinks (flight recorders). ``_active`` is the merged tuple —
# rebuilt on every arm/disarm/add/remove, so the emit fast path stays ONE
# module-global load and ``None`` check.
_probe: Optional[Probe] = None
_sinks: tuple[Probe, ...] = ()
_active: Optional[tuple[Probe, ...]] = None


def _rebuild() -> None:
    global _active
    merged = (() if _probe is None else (_probe,)) + _sinks
    _active = merged or None


def arm(probe: Probe) -> Optional[Probe]:
    """Install ``probe`` as the active fuzz sink; returns the previous one
    so nested harnesses can restore it. Recorder sinks are unaffected."""
    global _probe
    prev = _probe
    _probe = probe
    _rebuild()
    return prev


def disarm(prev: Optional[Probe] = None) -> None:
    """Remove the active fuzz probe (or restore ``prev`` from :func:`arm`)."""
    global _probe
    _probe = prev
    _rebuild()


def armed() -> bool:
    return _probe is not None


def add_sink(sink: Probe) -> None:
    """Append a persistent sink (a flight recorder). Idempotent."""
    global _sinks
    if sink not in _sinks:
        _sinks = _sinks + (sink,)
        _rebuild()


def remove_sink(sink: Probe) -> None:
    """Detach a persistent sink; unknown sinks are a no-op (teardown paths
    call this unconditionally). Equality, not identity — callers pass bound
    methods, and each attribute access builds a fresh (but ``==``) one."""
    global _sinks
    if sink in _sinks:
        _sinks = tuple(s for s in _sinks if s != sink)
        _rebuild()


def emit(event: str, key, **info) -> None:
    a = _active
    if a is not None:
        for p in a:
            p(event, key, **info)
