"""Ordering probes: the schedfuzz observation seam (analysis/schedfuzz.py).

The interleaving explorer checks happens-before contracts the control plane
already relies on — cache-apply before handler delivery, meta patch before
status patch, fence check before cloud mutate, ``WakeHub.stop()`` before any
late wake. Those contracts live at seams spread across runtime/, providers/
and controllers/; this module is the one place they report to.

Design constraints, in order:

- **Zero cost disarmed.** ``emit()`` is a module-global ``None`` check; the
  call sites pay a few attribute loads for the arguments. Nothing here
  allocates, imports analysis code, or runs by default — the probes are
  passive the same way the claimtrace tracer is.
- **No layering leak.** runtime code must not import analysis/ (or anything
  above itself — provgraph PG001 enforces exactly that); the explorer arms
  the seam from outside via :func:`arm`.
- **Synchronous.** A probe fires inline at the seam it observes, so the
  checker sees events in true program order — the whole point. Probe
  callbacks must not await, block, or raise (a raising probe is a bug in
  the harness, not the product; ``emit`` lets it propagate so the fuzz run
  fails loudly instead of silently dropping evidence).
"""

from __future__ import annotations

from typing import Callable, Optional

# probe(event: str, key, **info) — armed by analysis/schedfuzz, or by tests.
Probe = Callable[..., None]

_probe: Optional[Probe] = None


def arm(probe: Probe) -> Optional[Probe]:
    """Install ``probe`` as the active sink; returns the previous one so
    nested harnesses can restore it."""
    global _probe
    prev = _probe
    _probe = probe
    return prev


def disarm(prev: Optional[Probe] = None) -> None:
    """Remove the active probe (or restore ``prev`` from :func:`arm`)."""
    global _probe
    _probe = prev


def armed() -> bool:
    return _probe is not None


def emit(event: str, key, **info) -> None:
    p = _probe
    if p is not None:
        p(event, key, **info)
