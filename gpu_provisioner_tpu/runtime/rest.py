"""REST-backed kube Client: the production implementation of the Client seam.

The reference gets this from controller-runtime (cached client + informers);
here it is a deliberate informer-lite: ``watch()`` does ListAndWatch with
automatic re-list on stream breakage, matching InMemoryClient's replay
semantics (runtime/store.py:69-82), and reads are direct (no cache) — the
controller set's QPS is bounded by the workqueue, not list fan-out, at the
scales this provisioner serves (one NodeClaim per KAITO workspace).

Auth: in-cluster service-account token (projected, re-read on rotation —
same pattern as auth/credentials.py) or a minimal kubeconfig (token /
client-cert user). TLS via the cluster CA.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import os
import random
import ssl
import tempfile
from dataclasses import dataclass, field, replace
from typing import Optional

import httpx

from ..apis.meta import Object, object_from_manifest
from ..transport import (TransportOptions, build_http_client,
                         parse_retry_after, request_with_retries)
from . import apihealth
from .client import (AlreadyExistsError, ClientError, ConflictError,
                     EvictionBlockedError, NotFoundError,
                     ResourceExpiredError, TooManyRequestsError)
from .store import ADDED, DELETED, MODIFIED, WatchEvent

log = logging.getLogger("rest")

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"
TOKEN_REREAD_SECONDS = 60.0

# Irregular kind → resource plurals would go here; the only wrinkle among
# registered kinds is sibilant endings (KaitoNodeClass → kaitonodeclasses).
_PLURALS: dict[str, str] = {}


def _pluralize(kind: str) -> str:
    lower = kind.lower()
    if lower.endswith(("s", "x", "z", "ch", "sh")):
        return lower + "es"
    return lower + "s"


def resource_path(cls: type, namespace: str = "", name: str = "") -> str:
    """Build the API path for a registered kind."""
    gv = cls.API_VERSION
    base = f"/api/{gv}" if "/" not in gv else f"/apis/{gv}"
    plural = _PLURALS.get(cls.KIND, _pluralize(cls.KIND))
    if cls.NAMESPACED and namespace:
        base = f"{base}/namespaces/{namespace}"
    path = f"{base}/{plural}"
    return f"{path}/{name}" if name else path


@dataclass
class KubeConnection:
    """Where and how to reach the apiserver."""

    server: str
    token: str = ""
    token_file: str = ""
    ca_file: str = ""
    client_cert: str = ""      # PEM path (kubeconfig client-certificate)
    client_key: str = ""
    namespace: str = "default"
    # client-go exec credential plugin (the auth a `gcloud container clusters
    # get-credentials` kubeconfig uses: gke-gcloud-auth-plugin). Run at most
    # once per TOKEN_REREAD_SECONDS; the returned ExecCredential token is
    # cached like the projected-token path.
    exec_argv: tuple = ()
    exec_env: tuple = ()       # extra (name, value) pairs from the kubeconfig

    _cached_token: str = field(default="", repr=False)
    _token_at: float = field(default=0.0, repr=False)
    _token_fetched: bool = field(default=False, repr=False)

    @classmethod
    def in_cluster(cls) -> "KubeConnection":
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        if not host:
            raise ClientError("not in-cluster: KUBERNETES_SERVICE_HOST unset")
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        ns = "default"
        ns_file = f"{SA_DIR}/namespace"
        if os.path.exists(ns_file):
            ns = open(ns_file).read().strip()
        return cls(server=f"https://{host}:{port}",
                   token_file=f"{SA_DIR}/token",
                   ca_file=f"{SA_DIR}/ca.crt", namespace=ns)

    @classmethod
    def from_kubeconfig(cls, path: Optional[str] = None) -> "KubeConnection":
        import yaml
        path = path or os.environ.get("KUBECONFIG",
                                      os.path.expanduser("~/.kube/config"))
        with open(path) as f:
            kc = yaml.safe_load(f)
        ctx_name = kc.get("current-context", "")
        ctx = next(c["context"] for c in kc["contexts"] if c["name"] == ctx_name)
        cluster = next(c["cluster"] for c in kc["clusters"]
                       if c["name"] == ctx["cluster"])
        user = next(u["user"] for u in kc["users"] if u["name"] == ctx["user"])

        def materialize(data_key: str, file_key: str, src: dict) -> str:
            if file_key in src:
                return src[file_key]
            if data_key in src:
                f = tempfile.NamedTemporaryFile(delete=False, suffix=".pem")
                f.write(base64.b64decode(src[data_key]))
                f.close()
                return f.name
            return ""

        exec_cfg = user.get("exec") or {}
        exec_argv = tuple([exec_cfg["command"], *(exec_cfg.get("args") or [])]
                          if exec_cfg else [])
        exec_env = tuple((e["name"], e["value"])
                         for e in exec_cfg.get("env") or [])

        return cls(
            server=cluster["server"],
            ca_file=materialize("certificate-authority-data",
                                "certificate-authority", cluster),
            token=user.get("token", ""),
            client_cert=materialize("client-certificate-data",
                                    "client-certificate", user),
            client_key=materialize("client-key-data", "client-key", user),
            exec_argv=exec_argv,
            exec_env=exec_env,
            namespace=ctx.get("namespace", "default"))

    def _exec_token(self) -> str:
        """Run the kubeconfig's exec credential plugin and pull the bearer
        token out of the ExecCredential it prints (client-go's exec auth)."""
        import subprocess
        env = dict(os.environ, **dict(self.exec_env))
        out = subprocess.run(list(self.exec_argv), env=env, check=True,
                             capture_output=True, timeout=60).stdout
        tok = json.loads(out).get("status", {}).get("token", "")
        if not tok and not self.client_cert:
            # cert-based ExecCredentials (clientCertificateData) are not
            # supported; fail loudly rather than re-running the plugin per
            # request and sending unauthenticated calls. With a static
            # client cert configured, mTLS carries the auth and an empty
            # token is fine.
            raise ClientError(
                f"exec plugin {self.exec_argv[0]} returned no bearer token")
        return tok

    def _stale(self, loop_time: float) -> bool:
        # exec path: fetched-flag, not token truthiness — a plugin may
        # validly yield no token (mTLS) and must not re-run per call.
        # token-file path: truthiness — an empty projected token (kubelet
        # mid-rotation) must retry on the next call, not cache for 60s.
        fresh = self._token_fetched if self.exec_argv else bool(self._cached_token)
        return not fresh or loop_time - self._token_at > TOKEN_REREAD_SECONDS

    def bearer(self, loop_time: float) -> str:
        if self.token:
            return self.token
        if not self.token_file and not self.exec_argv:
            return ""
        if self._stale(loop_time):
            if self.exec_argv:
                self._cached_token = self._exec_token()
            else:
                self._cached_token = open(self.token_file).read().strip()
            self._token_at = loop_time
            self._token_fetched = True
        return self._cached_token

    def build_http(self, opts: Optional[TransportOptions] = None) -> httpx.AsyncClient:
        verify: object = True
        if self.ca_file or self.client_cert:
            # client cert loads even without a custom CA (cluster cert signed
            # by a system CA) — mTLS must not silently depend on ca_file
            ctx = ssl.create_default_context(cafile=self.ca_file or None)
            if self.client_cert:
                ctx.load_cert_chain(self.client_cert, self.client_key or None)
            verify = ctx
        return build_http_client(opts, verify=verify, base_url=self.server)


def _error_for(resp: httpx.Response, verb: str) -> ClientError:
    body = resp.text[:512]
    if resp.status_code == 404:
        return NotFoundError(body)
    if resp.status_code == 409:
        # POST conflicts mean the object exists; PUT conflicts mean a stale
        # resourceVersion — the two distinct retry paths upstream. The evict
        # verb's 409 is a uid-precondition failure (pod replaced under the
        # same name) and maps to ConflictError like a stale write.
        return AlreadyExistsError(body) if verb == "create" else ConflictError(body)
    if resp.status_code == 429:
        if verb == "evict":
            # A PDB verdict, not apiserver throttling (terminator/eviction.go:199).
            return EvictionBlockedError(body)
        # genuine throttling that survived the transport's retry budget:
        # surface it typed, with the server's pacing hint, so the
        # APIHealthGovernor sheds instead of the breaker judging it
        return TooManyRequestsError(f"{verb}: HTTP 429: {body}",
                                    retry_after=parse_retry_after(resp))
    if resp.status_code == 410:
        # expired resourceVersion / compacted history: ONLY a relist-and-
        # diff recovers — never the generic backoff ladder (PL015)
        return ResourceExpiredError(f"{verb}: HTTP 410 Gone: {body}")
    return ClientError(f"{verb}: HTTP {resp.status_code}: {body}")


class RestClient:
    """Client protocol implementation over the Kubernetes REST API."""

    def __init__(self, conn: KubeConnection,
                 transport: Optional[TransportOptions] = None,
                 http: Optional[httpx.AsyncClient] = None):
        self.conn = conn
        self.topts = transport or TransportOptions()
        self.http = http or conn.build_http(self.topts)
        self._indexes: dict[tuple[type, str], object] = {}
        self._token_lock = asyncio.Lock()

    # index emulation: same registration surface as Store.add_index; REST has
    # no server-side field indexes for these, so list filters client-side.
    def add_index(self, cls: type, name: str, key_fn) -> None:
        self._indexes[(cls, name)] = key_fn

    async def _headers(self) -> dict:
        h = {"Content-Type": "application/json"}
        t = asyncio.get_event_loop().time()
        if self.conn.exec_argv and self.conn._stale(t):
            # The exec plugin (e.g. gke-gcloud-auth-plugin) can take seconds —
            # refresh off-loop, one refresher at a time so a burst of requests
            # doesn't spawn a plugin per request.
            async with self._token_lock:
                if self.conn._stale(t):
                    tok = await asyncio.to_thread(self.conn.bearer, t)
                else:
                    tok = self.conn.bearer(t)
        else:
            tok = self.conn.bearer(t)
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        return h

    async def _req(self, verb: str, method: str, path: str,
                   opts: Optional[TransportOptions] = None,
                   **kw) -> httpx.Response:
        resp = await request_with_retries(
            self.http, method, path, opts=opts or self.topts,
            headers=await self._headers(), **kw)
        if resp.status_code >= 400:
            raise _error_for(resp, verb)
        return resp

    async def get(self, cls: type, name: str, namespace: str = "") -> Object:
        resp = await self._req("get", "GET",
                               resource_path(cls, namespace, name))
        return object_from_manifest(resp.json())

    # Server-side page size for every LIST: bounds apiserver + client memory
    # the way the reference's cached informer client bounds reads (options.go
    # QPS 200/burst 300 govern writes; pagination governs reads). GC over
    # thousands of Nodes no longer does one unbounded full list.
    LIST_PAGE_SIZE = 500

    async def list_pages(self, cls: type, params: Optional[dict] = None,
                         namespace: str = ""):
        """Async iterator over LIST page bodies (limit/continue chunking) —
        the one pagination walk, shared by list() and the watch re-list."""
        params = dict(params or {})
        cont = ""
        while True:
            page = dict(params, limit=str(self.LIST_PAGE_SIZE))
            if cont:
                page["continue"] = cont
            resp = await self._req("list", "GET",
                                   resource_path(cls, namespace), params=page)
            body = resp.json()
            for item in body.get("items", []):
                item.setdefault("kind", cls.KIND)
                item.setdefault("apiVersion", cls.API_VERSION)
            yield body
            cont = body.get("metadata", {}).get("continue", "")
            if not cont:
                return

    async def list(self, cls: type, labels: Optional[dict[str, str]] = None,
                   namespace: Optional[str] = None,
                   index: Optional[tuple[str, str]] = None) -> list[Object]:
        params: dict[str, str] = {}
        if labels:
            params["labelSelector"] = ",".join(f"{k}={v}" for k, v in labels.items())
        items = []
        async for body in self.list_pages(cls, params, namespace or ""):
            for item in body.get("items", []):
                items.append(cls.from_dict(item))
        if index is not None:
            name, value = index
            key_fn = self._indexes.get((cls, name))
            if key_fn is None:
                raise ClientError(f"no index {name!r} registered for {cls.KIND}")
            items = [o for o in items if value in (key_fn(o) or [])]
        return items

    async def create(self, obj: Object) -> Object:
        resp = await self._req("create", "POST",
                               resource_path(type(obj), obj.metadata.namespace),
                               json=obj.to_dict())
        return object_from_manifest(resp.json())

    async def update(self, obj: Object) -> Object:
        resp = await self._req(
            "update", "PUT",
            resource_path(type(obj), obj.metadata.namespace, obj.metadata.name),
            json=obj.to_dict())
        return object_from_manifest(resp.json())

    async def update_status(self, obj: Object) -> Object:
        resp = await self._req(
            "update", "PUT",
            resource_path(type(obj), obj.metadata.namespace,
                          obj.metadata.name) + "/status",
            json=obj.to_dict())
        return object_from_manifest(resp.json())

    async def delete(self, cls: type, name: str, namespace: str = "") -> None:
        await self._req("delete", "DELETE", resource_path(cls, namespace, name))

    async def evict(self, name: str, namespace: str = "",
                    uid: str = "") -> None:
        """POST the policy/v1 Eviction subresource — honors PodDisruptionBudgets
        server-side, which a bare pod DELETE would bypass (and the chart's RBAC
        grants pods/eviction create, not pods delete). A 429 here is a PDB
        verdict, not apiserver throttling — it bypasses the transport retry
        loop and surfaces as EvictionBlockedError so the eviction queue owns
        the backoff (terminator/eviction.go:199-209). ``uid`` becomes the
        delete precondition so a replacement pod reusing the name is never
        evicted by a stale queue entry (eviction.go:171-177)."""
        from ..apis.core import Pod
        body: dict = {"apiVersion": "policy/v1", "kind": "Eviction",
                      "metadata": {"name": name, "namespace": namespace}}
        if uid:
            body["deleteOptions"] = {"preconditions": {"uid": uid}}
        await self._req(
            "evict", "POST",
            resource_path(Pod, namespace, name) + "/eviction",
            opts=replace(self.topts,
                         retryable_status=self.topts.retryable_status - {429}),
            json=body)

    def watch(self, cls: type) -> "RestWatch":
        return RestWatch(self, cls)

    async def aclose(self) -> None:
        await self.http.aclose()


class RestWatch:
    """ListAndWatch with re-list on breakage. Same surface as runtime.Watch."""

    RECONNECT_BACKOFF = 1.0
    # Server-side watch window + a slightly longer client read timeout: a
    # half-open connection (LB blackhole, node power loss) then surfaces as
    # ReadTimeout → the normal re-list path, instead of hanging forever.
    WATCH_TIMEOUT_SECONDS = 300
    READ_TIMEOUT_SECONDS = 330.0

    def __init__(self, client: RestClient, cls: type):
        self.client = client
        self.cls = cls
        self._q: asyncio.Queue = asyncio.Queue()
        self._closed = False
        # keys this watch has told consumers exist — so a re-list after an
        # outage can synthesize DELETED tombstones for objects that vanished
        # while the stream was down (client-go reflector Replace() parity;
        # without it a cache layered on this watch holds deleted objects
        # until its own resync)
        self._known: set[tuple[str, str]] = set()
        self._task = asyncio.ensure_future(self._run())

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        if self._closed:
            raise StopAsyncIteration
        ev = await self._q.get()
        if ev is None or self._closed:
            raise StopAsyncIteration
        return ev

    def try_next(self) -> Optional[WatchEvent]:
        """Non-blocking pop, same contract as runtime.Watch.try_next: the
        informer pump drains bursts in one scheduling slot."""
        if self._closed:
            return None
        try:
            ev = self._q.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if ev is None:
            return None
        return ev

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._task.cancel()
        self._q.put_nowait(None)

    async def _run(self) -> None:
        rv = ""
        while not self._closed:
            try:
                if not rv:
                    # replay on EVERY (re-)list, not just the first: events
                    # that fired during a watch outage would otherwise be
                    # lost forever (no periodic resync downstream). Duplicate
                    # ADDED events are harmless — reconciles are
                    # level-triggered and the workqueue dedups by key.
                    rv = await self._list_into_queue()
                rv = await self._stream(rv)
            except asyncio.CancelledError:
                # close() cancelled the pump — propagate so the task ends
                # cancelled (a swallowed cancellation here would let a
                # mid-shutdown awaiter hang; PL002)
                raise
            except ResourceExpiredError as e:
                # 410 Gone / expired resourceVersion: the stream has a hole
                # no reconnect can fill. Gap-resync path — immediate
                # jittered re-list (which replays + synthesizes tombstones
                # above), NOT the generic reconnect backoff (PL015).
                log.info("watch %s expired: %s; re-listing", self.cls.KIND, e)
                apihealth.note_watch_gap()
                rv = ""
                await asyncio.sleep(
                    self.RECONNECT_BACKOFF * 0.1 * random.random())
            except Exception as e:
                log.warning("watch %s broken: %s; re-listing",
                            self.cls.KIND, e)
                rv = ""  # force re-list
                await asyncio.sleep(self.RECONNECT_BACKOFF)

    async def _list_into_queue(self) -> str:
        rv = ""
        fresh: set[tuple[str, str]] = set()
        async for body in self.client.list_pages(self.cls):
            for item in body.get("items", []):
                obj = self.cls.from_dict(item)
                fresh.add((obj.metadata.namespace, obj.metadata.name))
                self._q.put_nowait(WatchEvent(ADDED, obj))
            rv = body.get("metadata", {}).get("resourceVersion", "") or rv
        for ns, name in self._known - fresh:
            # tombstone: a metadata-only object — consumers key caches and
            # workqueues off (namespace, name), which is all it carries
            self._q.put_nowait(WatchEvent(DELETED, self.cls.from_dict(
                {"metadata": {"name": name, "namespace": ns}})))
        self._known = fresh
        return rv

    async def _stream(self, rv: str) -> str:
        params = {"watch": "true", "allowWatchBookmarks": "true",
                  "timeoutSeconds": str(self.WATCH_TIMEOUT_SECONDS)}
        if rv:
            params["resourceVersion"] = rv
        headers = await self.client._headers()
        timeout = httpx.Timeout(10.0, read=self.READ_TIMEOUT_SECONDS)
        async with self.client.http.stream(
                "GET", resource_path(self.cls), params=params,
                headers=headers, timeout=timeout) as resp:
            if resp.status_code == 410:
                raise ResourceExpiredError("watch: HTTP 410 Gone")
            if resp.status_code >= 400:
                raise ClientError(f"watch: HTTP {resp.status_code}")
            async for line in resp.aiter_lines():
                if self._closed:
                    return rv
                if not line.strip():
                    continue
                ev = json.loads(line)
                etype, raw = ev["type"], ev["object"]
                new_rv = raw.get("metadata", {}).get("resourceVersion", "")
                if etype == "BOOKMARK":
                    rv = new_rv or rv
                    continue
                if etype == "ERROR":
                    # a v1.Status payload: 410 Gone / "Expired" means the
                    # resourceVersion aged out of etcd's history — typed so
                    # _run takes the gap-resync path, not the backoff ladder
                    if (raw.get("code") == 410
                            or raw.get("reason") == "Expired"):
                        raise ResourceExpiredError(
                            f"watch expired: {raw}")
                    raise ClientError(f"watch error event: {raw}")
                raw.setdefault("kind", self.cls.KIND)
                raw.setdefault("apiVersion", self.cls.API_VERSION)
                if etype in (ADDED, MODIFIED, DELETED):
                    obj = self.cls.from_dict(raw)
                    key = (obj.metadata.namespace, obj.metadata.name)
                    if etype == DELETED:
                        self._known.discard(key)
                    else:
                        self._known.add(key)
                    self._q.put_nowait(WatchEvent(etype, obj))
                rv = new_rv or rv
        return rv
