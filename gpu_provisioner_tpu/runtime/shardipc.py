"""Cross-process shard transport: NDJSON frames over a unix socket.

The multi-process scale-out (operator/supervisor.py + operator/shardworker.py)
keeps ONE kube store and ONE fake cloud in the parent process and runs each
shard's controllers in its own OS process. This module is the wire between
them, and only the wire — it knows nothing about controllers or clouds:

- :class:`SocketClient` — the full runtime ``Client`` protocol proxied over
  the socket, so a worker's controllers/informers/lease table run unchanged
  against the parent's store. ``watch()`` returns a :class:`RemoteWatch`
  with the same ``try_next()``/idempotent-``close()`` contract the in-process
  ``Watch`` has, which is what the informer pump drains.
- :class:`ShardIPCServer` — the parent side: per-request task dispatch (one
  slow op never blocks the pipe), per-(conn, watch) store pumps, and the
  **shared-nothing relay filter**: watch events and full-scan lists of the
  claim-keyed kinds (NodeClaim, Node) are delivered to a worker only when
  the object's routing ranges intersect the worker's leased ranges, so each
  worker caches only its owned slice of the fleet. Label/index/namespace
  lists pass through unfiltered — cross-range reads (a slice group's member
  list) stay whole-fleet.
- **Wake frames** — the cross-process extension of the WakeHub seam. A
  worker that produces a wake for a claim it does not own posts a ``wake``
  frame; the server routes it to the owning worker's connection by
  ``range_of(name)`` (dropped when nothing owns the range — the lease-gain
  ADDED replay re-drives adoption anyway). Frames carry the existing
  sourced-wake vocabulary, so an LRO completion forwarded across processes
  still lands as ``source=lro`` in the receiving worker's ledger.

Relay ordering guarantee: per connection, events of one kind are written in
store-commit order (one pump task per watch, one reader per conn). A lease
handoff inserts a replay — ADDED for gained ranges, synthesized DELETED for
lost ones — which can interleave with live events; consumers absorb that
because informer upserts are idempotent and the dequeue-side ``owns`` fence
drops foreign keys.

Layering: runtime-only (provgraph PG001) — cloud proxies live with the
worker composition root (operator/shardworker.py), wired through the
server's ``extra_ops`` table here.

Frame shapes (one JSON object per line):

    {"id": 7, "op": "kube.get", "a": {...}}      request
    {"re": 7, "ok": ...} | {"re": 7, "err": {...}}  response
    {"push": "watch", "wid": 3, "t": "ADDED", "o": {...}}
    {"push": "wake", "name": "...", "source": "lro"}
    {"push": "ranges", "ranges": [0, 5, 9]}      worker → server
    {"push": "snap", "data": {...}}              worker → server
    {"push": "hello", "worker": "w0"}            worker → server
    {"push": "target", "workers": 4}             server → worker
    {"push": "stop"}                             server → worker
"""

from __future__ import annotations

import asyncio
import json
import logging
import weakref
from typing import Any, Callable, Optional

from ..apis import labels as wk
from ..apis.meta import Object, kind_for, object_from_manifest
from . import probes
from .client import (
    AlreadyExistsError, ClientError, ConflictError, EvictionBlockedError,
    NotFoundError, ResourceExpiredError, TooManyRequestsError,
)
from .shardlease import NUM_RANGES, range_of
from .store import WatchEvent

log = logging.getLogger("shardipc")

# Per-frame stream buffer ceiling, both directions. A frame is one JSON
# line; unfiltered full-scan lists (``kube.list`` of every NodeClaim at
# 10k claims) are the big ones — asyncio's 64 KiB readline default
# tears the connection down at a few hundred claims.
FRAME_LIMIT = 64 * 1024 * 1024

# Live servers, for the /metrics scrape fold (controllers/metrics.py walks
# this the way it walks operations.TRACKERS): worker snapshots hang off the
# server, and the weak set drops a supervisor's server with it.
SERVERS: "weakref.WeakSet[ShardIPCServer]" = weakref.WeakSet()

# Kinds the relay filters by claim-range ownership. Everything else
# (Pod, Lease, Event, PDB, ...) is delivered whole-fleet: those kinds are
# either coordination state every worker needs (Lease) or keyed by names
# that do not partition with claims.
FILTERED_KINDS = ("NodeClaim", "Node")

_ERROR_CLASSES = {c.__name__: c for c in (
    ClientError, NotFoundError, ConflictError, AlreadyExistsError,
    EvictionBlockedError, ResourceExpiredError, TooManyRequestsError,
)}


class RemoteError(ClientError):
    """A server-side error with no runtime-layer class (a cloud APIError,
    an unexpected crash). Carries the original class name and extras so the
    cloud proxies can re-raise their own taxonomy."""

    def __init__(self, cls_name: str, message: str,
                 extra: Optional[dict] = None):
        super().__init__(message)
        self.cls_name = cls_name
        self.extra = extra or {}


def wire_error(e: BaseException) -> dict:
    d: dict[str, Any] = {"cls": type(e).__name__, "msg": str(e)}
    code = getattr(e, "code", None)
    if isinstance(code, int):
        d["code"] = code
    retry_after = getattr(e, "retry_after", None)
    if isinstance(retry_after, (int, float)):
        d["retryAfter"] = retry_after
    return d


def unwire_error(d: dict) -> Exception:
    name, msg = d.get("cls", "ClientError"), d.get("msg", "")
    cls = _ERROR_CLASSES.get(name)
    if cls is TooManyRequestsError:
        return cls(msg, retry_after=d.get("retryAfter", 0.0))
    if cls is not None:
        return cls(msg)
    extra = {k: v for k, v in d.items() if k not in ("cls", "msg")}
    return RemoteError(name, msg, extra)


def routing_ranges(obj: Object, num_ranges: int = NUM_RANGES) -> set[int]:
    """The claim ranges an object belongs to. A NodeClaim routes by its own
    name (== pool name) and its slice group; a Node by the pool that owns it
    (slice-id/gke-nodepool label, falling back to its own name) and the
    group. Multi-key on purpose: the group's owning worker caches every
    member slice, so cross-slice group reads stay local to it."""
    labels = obj.metadata.labels
    if obj.KIND == "NodeClaim":
        keys = {obj.metadata.name}
    else:  # Node
        keys = {labels.get(wk.TPU_SLICE_ID_LABEL)
                or labels.get(wk.GKE_NODEPOOL_LABEL)
                or obj.metadata.name}
    group = labels.get(wk.TPU_SLICE_GROUP_LABEL)
    if group:
        keys.add(group)
    return {range_of(k, num_ranges) for k in keys}


def _dump(frame: dict) -> bytes:
    return json.dumps(frame, separators=(",", ":")).encode() + b"\n"


# --------------------------------------------------------------- client side

_W_CLOSED = object()


class RemoteWatch:
    """Client-side watch proxy: same surface as runtime.client.Watch
    (async iterator + ``try_next`` burst drain + idempotent ``close``),
    fed by the recv loop from ``watch`` push frames."""

    def __init__(self, client: "SocketClient", wid: int):
        self._client = client
        self._wid = wid
        self._q: asyncio.Queue = asyncio.Queue()
        self._closed = False

    def __aiter__(self):
        return self

    async def __anext__(self) -> WatchEvent:
        if self._closed:
            raise StopAsyncIteration
        ev = await self._q.get()
        if ev is _W_CLOSED or self._closed:
            raise StopAsyncIteration
        return ev

    def try_next(self) -> Optional[WatchEvent]:
        if self._closed:
            return None
        try:
            ev = self._q.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if ev is _W_CLOSED:
            return None
        return ev

    def _deliver(self, etype: str, manifest: dict) -> None:
        self._q.put_nowait(WatchEvent(etype, object_from_manifest(manifest)))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._client._watches.pop(self._wid, None)
        self._client._post({"push": "watch_close", "wid": self._wid})
        self._q.put_nowait(_W_CLOSED)


class SocketClient:
    """The runtime ``Client`` protocol over the shard socket, plus the
    worker-side push surface (wake out, ranges/snap out; wake/target/stop
    in via the ``on_*`` callbacks, all sync — schedule, don't await)."""

    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, identity: str = ""):
        self._reader = reader
        self._writer = writer
        self.identity = identity
        self._next_id = 0
        self._next_wid = 0
        self._pending: dict[int, asyncio.Future] = {}
        self._watches: dict[int, RemoteWatch] = {}
        self._task: Optional[asyncio.Task] = None
        self._closed = False
        # sync callbacks, wired by the worker composition root
        self.on_wake: Optional[Callable[[str, str], None]] = None
        self.on_target: Optional[Callable[[int], None]] = None
        self.on_stop: Optional[Callable[[], None]] = None

    @classmethod
    async def connect(cls, path: str, identity: str = "") -> "SocketClient":
        reader, writer = await asyncio.open_unix_connection(
            path, limit=FRAME_LIMIT)
        client = cls(reader, writer, identity=identity)
        client._post({"push": "hello", "worker": identity})
        client._task = asyncio.create_task(
            client._recv_loop(), name=f"shard-ipc-client/{identity}")
        return client

    # ------------------------------------------------------------ transport
    def _post(self, frame: dict) -> None:
        if self._closed:
            return
        self._writer.write(_dump(frame))

    async def call(self, op: str, **args) -> Any:
        if self._closed:
            raise ClientError("shard IPC connection closed")
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._pending[rid] = fut
        self._post({"id": rid, "op": op, "a": args})
        res = await fut
        if "err" in res:
            raise unwire_error(res["err"])
        return res.get("ok")

    async def _recv_loop(self) -> None:
        try:
            while True:
                line = await self._reader.readline()
                if not line:
                    break
                self._dispatch(json.loads(line))
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — transport death, not logic
            log.warning("shard IPC recv loop failed: %s", e)
        finally:
            self._fail_pending()

    def _dispatch(self, frame: dict) -> None:
        rid = frame.get("re")
        if rid is not None:
            fut = self._pending.pop(rid, None)
            if fut is not None and not fut.done():
                fut.set_result(frame)
            return
        push = frame.get("push")
        if push == "watch":
            w = self._watches.get(frame["wid"])
            if w is not None:
                w._deliver(frame["t"], frame["o"])
        elif push == "wake":
            if self.on_wake is not None:
                self.on_wake(frame["name"], frame["source"])
        elif push == "target":
            if self.on_target is not None:
                self.on_target(frame["workers"])
        elif push == "stop":
            if self.on_stop is not None:
                self.on_stop()

    def _fail_pending(self) -> None:
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_result(
                    {"err": {"cls": "ClientError",
                             "msg": "shard IPC connection closed"}})
        self._pending.clear()
        for w in list(self._watches.values()):
            w._q.put_nowait(_W_CLOSED)
            w._closed = True
        self._watches.clear()

    async def close(self) -> None:
        self._closed = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        self._fail_pending()
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except Exception:  # noqa: BLE001 — peer may already be gone
            pass

    # --------------------------------------------------------- push surface
    def send_wake(self, name: str, source: str) -> None:
        self._post({"push": "wake", "name": name, "source": source})

    def send_ranges(self, ranges: set[int]) -> None:
        self._post({"push": "ranges", "ranges": sorted(ranges)})

    def send_snap(self, data: dict) -> None:
        self._post({"push": "snap", "data": data})

    # ------------------------------------------------------ Client protocol
    async def get(self, cls: type, name: str, namespace: str = "") -> Object:
        res = await self.call("kube.get", kind=cls.KIND, name=name,
                              namespace=namespace)
        return object_from_manifest(res)

    async def list(self, cls: type, labels=None, namespace=None,
                   index=None) -> list[Object]:
        res = await self.call(
            "kube.list", kind=cls.KIND, labels=labels, namespace=namespace,
            index=list(index) if index is not None else None)
        return [object_from_manifest(m) for m in res]

    async def create(self, obj: Object) -> Object:
        return object_from_manifest(
            await self.call("kube.create", obj=obj.to_dict()))

    async def update(self, obj: Object) -> Object:
        return object_from_manifest(
            await self.call("kube.update", obj=obj.to_dict()))

    async def update_status(self, obj: Object) -> Object:
        return object_from_manifest(
            await self.call("kube.update_status", obj=obj.to_dict()))

    async def delete(self, cls: type, name: str, namespace: str = "") -> None:
        await self.call("kube.delete", kind=cls.KIND, name=name,
                        namespace=namespace)

    async def evict(self, name: str, namespace: str = "",
                    uid: str = "") -> None:
        await self.call("kube.evict", name=name, namespace=namespace, uid=uid)

    def watch(self, cls: type) -> RemoteWatch:
        self._next_wid += 1
        wid = self._next_wid
        w = RemoteWatch(self, wid)
        self._watches[wid] = w
        self._post({"push": "watch_open", "wid": wid, "kind": cls.KIND})
        return w


# --------------------------------------------------------------- server side

class _Conn:
    """One worker connection: its leased ranges, its open watch pumps, and
    the latest snapshot it pushed."""

    def __init__(self, writer: asyncio.StreamWriter):
        self.writer = writer
        self.worker = ""
        self.ranges: set[int] = set()
        # wid -> (cls, Watch, pump task)
        self.watches: dict[int, tuple] = {}
        self.tasks: set[asyncio.Task] = set()
        self.closed = False

    def post(self, frame: dict) -> None:
        if self.closed:
            return
        try:
            self.writer.write(_dump(frame))
        except Exception:  # noqa: BLE001 — dying conn; reader loop reaps it
            self.closed = True


class ShardIPCServer:
    """The parent-process end of the shard transport.

    ``client`` is the authoritative kube client (the parent's
    InMemoryClient). ``extra_ops`` extends the verb table — the supervisor
    registers the cloud proxies there (``cloud.np.*`` / ``cloud.qr.*``) so
    this module stays runtime-layer. Handlers are
    ``async fn(args: dict) -> jsonable``.
    """

    def __init__(self, client, num_ranges: int = NUM_RANGES,
                 extra_ops: Optional[dict[str, Callable]] = None):
        self.client = client
        self.num_ranges = num_ranges
        self.extra_ops = dict(extra_ops or {})
        self.conns: list[_Conn] = []
        # worker identity -> latest snap payload (wake ledger, queue depths,
        # digest states, ...), read by the supervisor's metrics fold.
        self.snapshots: dict[str, dict] = {}
        self.wakes_routed = 0
        self.wakes_dropped = 0
        # optional sync hook fired on every snapshot push: (worker, data).
        # The supervisor hangs its fleet-digest mirror refresh off it.
        self.on_snap: Optional[Callable[[str, dict], None]] = None
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set[asyncio.Task] = set()
        SERVERS.add(self)

    async def start(self, path: str) -> None:
        self._server = await asyncio.start_unix_server(
            self._serve, path=path, limit=FRAME_LIMIT)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for conn in list(self.conns):
            self._drop_conn(conn)
        for t in list(self._conn_tasks):
            t.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
        self._conn_tasks.clear()

    # ------------------------------------------------------------- topology
    def broadcast_target(self, workers: int) -> None:
        """Push the new worker-count target; each worker's lease table
        rebalances toward ceil(ranges/target) on its next tick."""
        for conn in self.conns:
            conn.post({"push": "target", "workers": workers})

    def broadcast_stop(self) -> None:
        for conn in self.conns:
            conn.post({"push": "stop"})

    def owner_of(self, name: str) -> Optional[_Conn]:
        k = range_of(name, self.num_ranges)
        for conn in self.conns:
            if k in conn.ranges:
                return conn
        return None

    def route_wake(self, name: str, source: str) -> bool:
        """Deliver a wake frame to the worker owning ``name``'s range.
        False (dropped) when no live worker owns it — safe: the range's
        next lessee replays ADDED for everything in it, which re-drives
        the reconcile the wake was for."""
        conn = self.owner_of(name)
        if conn is None:
            self.wakes_dropped += 1
            probes.emit("ipc-wake-dropped", name, source=source)
            return False
        conn.post({"push": "wake", "name": name, "source": source})
        self.wakes_routed += 1
        return True

    # ------------------------------------------------------------ conn loop
    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        conn = _Conn(writer)
        self.conns.append(conn)
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                try:
                    frame = json.loads(line)
                except ValueError:
                    log.warning("shard IPC: undecodable frame dropped")
                    continue
                self._dispatch(conn, frame)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — one conn's death is local
            log.warning("shard IPC conn %s failed: %s", conn.worker, e)
        finally:
            self._drop_conn(conn)

    def _drop_conn(self, conn: _Conn) -> None:
        if conn in self.conns:
            self.conns.remove(conn)
        conn.closed = True
        for cls, watch, task in conn.watches.values():
            watch.close()
            task.cancel()
        conn.watches.clear()
        for t in list(conn.tasks):
            t.cancel()
        try:
            conn.writer.close()
        except Exception:  # noqa: BLE001
            pass

    def _dispatch(self, conn: _Conn, frame: dict) -> None:
        """Push frames are handled inline (they are sync and ordering
        matters: a ranges frame must take effect before a later watch_open
        reads it); op requests fan out to per-request tasks so one slow op
        never blocks the pipe."""
        push = frame.get("push")
        if push is not None:
            handler = getattr(self, f"_push_{push}", None)
            if handler is None:
                log.warning("shard IPC: unknown push %r", push)
                return
            handler(conn, frame)
            return
        t = asyncio.create_task(self._handle_op(conn, frame))
        conn.tasks.add(t)
        t.add_done_callback(conn.tasks.discard)

    # ---------------------------------------------------------- push frames
    def _push_hello(self, conn: _Conn, frame: dict) -> None:
        conn.worker = frame.get("worker", "")

    def _push_ranges(self, conn: _Conn, frame: dict) -> None:
        new = set(frame.get("ranges", ()))
        gained, lost = new - conn.ranges, conn.ranges - new
        conn.ranges = new
        if gained or lost:
            t = asyncio.create_task(self._replay(conn, gained, lost))
            conn.tasks.add(t)
            t.add_done_callback(conn.tasks.discard)

    def _push_wake(self, conn: _Conn, frame: dict) -> None:
        self.route_wake(frame["name"], frame["source"])

    def _push_snap(self, conn: _Conn, frame: dict) -> None:
        if not conn.worker:
            return
        self.snapshots[conn.worker] = frame.get("data", {})
        if self.on_snap is not None:
            try:
                self.on_snap(conn.worker, self.snapshots[conn.worker])
            except Exception:  # noqa: BLE001 — observability-grade hook
                log.warning("on_snap hook failed", exc_info=True)

    def _push_watch_open(self, conn: _Conn, frame: dict) -> None:
        cls = kind_for(frame["kind"])
        wid = frame["wid"]
        watch = self.client.watch(cls)
        task = asyncio.create_task(
            self._pump(conn, wid, cls, watch),
            name=f"shard-ipc-pump/{conn.worker}/{cls.KIND}")
        conn.watches[wid] = (cls, watch, task)

    def _push_watch_close(self, conn: _Conn, frame: dict) -> None:
        entry = conn.watches.pop(frame["wid"], None)
        if entry is not None:
            cls, watch, task = entry
            watch.close()
            task.cancel()

    # --------------------------------------------------------- watch relay
    def _passes(self, conn: _Conn, obj: Object) -> bool:
        if obj.KIND not in FILTERED_KINDS:
            return True
        return bool(routing_ranges(obj, self.num_ranges) & conn.ranges)

    async def _pump(self, conn: _Conn, wid: int, cls: type, watch) -> None:
        try:
            async for ev in watch:
                if not self._passes(conn, ev.object):
                    continue
                conn.post({"push": "watch", "wid": wid, "t": ev.type,
                           "o": ev.object.to_dict()})
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — conn teardown races
            if not conn.closed:
                log.warning("shard IPC pump %s/%s failed: %s",
                            conn.worker, cls.KIND, e)

    async def _replay(self, conn: _Conn, gained: set[int],
                      lost: set[int]) -> None:
        """Lease-handoff resync over every open watch of a filtered kind:
        ADDED for objects entering the worker's view (the adoption
        re-drive), synthesized DELETED for objects leaving it (the worker's
        informer tombstones them; live events for those keys stop at the
        relay filter)."""
        for wid, (cls, watch, task) in list(conn.watches.items()):
            if cls.KIND not in FILTERED_KINDS:
                continue
            try:
                objs = await self.client.list(cls)
            except Exception as e:  # noqa: BLE001 — next tick re-replays
                log.warning("shard IPC replay list %s failed: %s",
                            cls.KIND, e)
                continue
            for obj in objs:
                rr = routing_ranges(obj, self.num_ranges)
                if rr & gained:
                    conn.post({"push": "watch", "wid": wid, "t": "ADDED",
                               "o": obj.to_dict()})
                elif rr & lost and not rr & conn.ranges:
                    conn.post({"push": "watch", "wid": wid, "t": "DELETED",
                               "o": obj.to_dict()})

    # ------------------------------------------------------------- requests
    async def _handle_op(self, conn: _Conn, frame: dict) -> None:
        rid, op, args = frame.get("id"), frame.get("op", ""), frame.get("a", {})
        try:
            fn = self.extra_ops.get(op)
            if fn is not None:
                result = await fn(args)
            else:
                handler = getattr(self, "_op_" + op.replace(".", "_"), None)
                if handler is None:
                    raise ClientError(f"unknown op {op!r}")
                result = await handler(conn, args)
            conn.post({"re": rid, "ok": result})
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — errors travel the wire
            conn.post({"re": rid, "err": wire_error(e)})

    async def _op_kube_get(self, conn, a):
        obj = await self.client.get(kind_for(a["kind"]), a["name"],
                                    a.get("namespace", ""))
        return obj.to_dict()

    async def _op_kube_list(self, conn, a):
        kind = a["kind"]
        labels, namespace, index = a.get("labels"), a.get("namespace"), \
            a.get("index")
        objs = await self.client.list(
            kind_for(kind), labels, namespace,
            tuple(index) if index is not None else None)
        # Range-filter ONLY the full scans of claim-keyed kinds (same filter
        # the watch relay applies, so a worker's informer initial list and
        # its watch stream agree). Label/index/namespace lists stay
        # whole-fleet: cross-range reads (slice-group membership, providerID
        # lookups) must see everything.
        if (kind in FILTERED_KINDS and labels is None and index is None
                and namespace is None):
            objs = [o for o in objs
                    if routing_ranges(o, self.num_ranges) & conn.ranges]
        return [o.to_dict() for o in objs]

    async def _op_kube_create(self, conn, a):
        return (await self.client.create(
            object_from_manifest(a["obj"]))).to_dict()

    async def _op_kube_update(self, conn, a):
        return (await self.client.update(
            object_from_manifest(a["obj"]))).to_dict()

    async def _op_kube_update_status(self, conn, a):
        return (await self.client.update_status(
            object_from_manifest(a["obj"]))).to_dict()

    async def _op_kube_delete(self, conn, a):
        await self.client.delete(kind_for(a["kind"]), a["name"],
                                 a.get("namespace", ""))
        return None

    async def _op_kube_evict(self, conn, a):
        await self.client.evict(a["name"], a.get("namespace", ""),
                                a.get("uid", ""))
        return None
