"""Lease-based claim-range ownership for multi-process shard workers.

The static crc32 partition (controllers/utils.shard_owns) pins claims to
shard INDEXES for a process's lifetime — changing the shard count means a
stop, and a dead shard's claims are orphaned until restart. This module
replaces the partition key's codomain with a fixed set of NUM_RANGES small
ranges, each owned through a coordination.k8s.io Lease object
(``shard-range-<k>``) renewed exactly like leader election
(runtime/leaderelection.py): ``range_of(name)`` is stable forever, but the
range→worker mapping is leases, so

- shard-count changes rebalance by lease handoff WITHOUT a stop: each
  worker targets ``ceil(live_ranges / target_workers)`` ranges, releasing
  excess leases for under-provisioned peers to pick up;
- a SIGKILLed worker's ranges expire (``lease_duration``) and are adopted
  by survivors — reclaimed, not orphaned;
- the handoff window is fenced at DEQUEUE (Controller.owns) and at the
  provider's mutation fence, so an in-flight enqueue from the losing
  worker drops instead of double-reconciling.

The table is deliberately client-agnostic: workers CRUD Lease objects over
the same (possibly remote — runtime/shardipc.SocketClient) kube client the
controllers use, so lease CAS safety is the store's resourceVersion
conflict detection end to end.
"""

from __future__ import annotations

import asyncio
import logging
import math
import zlib
from typing import Callable, Iterable, Optional

from ..apis.core import Lease, LeaseSpec
from ..apis.meta import ObjectMeta
from ..apis.serde import now
from .client import (
    AlreadyExistsError, Client, ConflictError, NotFoundError,
)
from .leaderelection import default_identity

log = logging.getLogger("shardlease")

# Fixed range count — the partition codomain. Small enough that the lease
# table is a handful of tiny objects, large enough that ceil-fair-share
# imbalance across any realistic worker count stays ≤ 2x (64 ranges / 8
# workers = 8 each, exactly).
NUM_RANGES = 64

LEASE_PREFIX = "shard-range-"
LEASE_NAMESPACE = "kube-system"

# envtest-scale defaults; production would use leaderelection's 15/10/2.
LEASE_DURATION = 2.0
RENEW_INTERVAL = 0.5


def range_of(name: str, num_ranges: int = NUM_RANGES) -> int:
    """The stable range a claim/pool/group name hashes into. Same crc32 the
    static partition used, different codomain — ownership moves by moving
    the range's lease, never by rehashing."""
    return zlib.crc32(name.encode()) % num_ranges


class ShardLeaseTable:
    """One worker's view of the range-lease table.

    ``start()`` runs the acquire/renew loop: renew held leases, release
    excess above the fair share, acquire free/expired leases up to it.
    ``owns(name)`` is the O(1) predicate handed to the registry;
    ``on_change(gained, lost)`` fires with range-id sets whenever holdings
    move — the shard worker uses it to update its relay subscription (which
    replays ADDED for gained ranges: the handoff resync that drives
    adoption reconciles).
    """

    def __init__(self, client: Client, identity: Optional[str] = None,
                 num_ranges: int = NUM_RANGES,
                 target_workers: int = 1,
                 lease_duration: float = LEASE_DURATION,
                 renew_interval: float = RENEW_INTERVAL,
                 namespace: str = LEASE_NAMESPACE,
                 on_change: Optional[
                     Callable[[set, set], None]] = None):
        self.client = client
        self.identity = identity or default_identity()
        self.num_ranges = num_ranges
        self.target_workers = max(1, target_workers)
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.namespace = namespace
        self.on_change = on_change
        self.ranges: set[int] = set()
        self._task: Optional[asyncio.Task] = None
        # (holder, renew_time) last observed per foreign range + local
        # monotonic observation time — leaderelection's clock-skew guard.
        self._observed: dict[int, tuple[tuple, float]] = {}
        self.acquired_total = 0
        self.released_total = 0
        self.adopted_total = 0  # acquired from an EXPIRED foreign holder

    # ------------------------------------------------------------ predicate
    def owns(self, name: str) -> bool:
        return range_of(name, self.num_ranges) in self.ranges

    def fair_share(self) -> int:
        return math.ceil(self.num_ranges / self.target_workers)

    def set_target_workers(self, n: int) -> None:
        """Topology push from the supervisor: the next tick rebalances
        toward the new fair share (release on shrink of share, acquire on
        growth) — no stop, no rehash."""
        self.target_workers = max(1, n)

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> None:
        if self._task is not None:
            return
        await self.tick()  # acquire synchronously so boot has a range set
        self._task = asyncio.create_task(self._loop(),
                                         name="shard-lease-table")

    async def stop(self, release: bool = True) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if release and self.ranges:
            for k in sorted(self.ranges):
                await self._release(k)
            self._apply_holdings(set())

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.renew_interval)
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001 — the table must keep
                log.warning("shard-lease tick failed: %s", e)  # renewing

    # ------------------------------------------------------------ mechanics
    def _lease_name(self, k: int) -> str:
        return f"{LEASE_PREFIX}{k}"

    def _expired(self, k: int, lease: Lease) -> bool:
        if lease.spec.renew_time is None:
            return True
        age = (now() - lease.spec.renew_time).total_seconds()
        if age > self.lease_duration:
            return True
        key = (lease.spec.holder_identity, lease.spec.renew_time)
        mono = asyncio.get_event_loop().time()
        seen = self._observed.get(k)
        if seen is None or seen[0] != key:
            self._observed[k] = (key, mono)
            return False
        return mono - seen[1] > self.lease_duration

    async def tick(self) -> None:
        """One renew/rebalance pass. Listing the whole table is one small
        LIST (NUM_RANGES tiny objects); every mutation is resourceVersion
        CAS, so two workers racing for the same range lose cleanly."""
        leases: dict[int, Lease] = {}
        for lease in await self.client.list(Lease,
                                            namespace=self.namespace):
            name = lease.metadata.name
            if not name.startswith(LEASE_PREFIX):
                continue
            try:
                leases[int(name[len(LEASE_PREFIX):])] = lease
            except ValueError:
                continue
        held = set(self.ranges)
        share = self.fair_share()

        # 1. renew what we hold (lost CAS = lost range, accept immediately)
        for k in sorted(held):
            lease = leases.get(k)
            if lease is None or lease.spec.holder_identity != self.identity:
                held.discard(k)
                continue
            lease.spec.renew_time = now()
            try:
                leases[k] = await self.client.update(lease)
            except (ConflictError, NotFoundError):
                held.discard(k)

        # 2. release excess above the fair share (shrink path of a
        # topology change): highest ranges first, deterministic, so two
        # over-provisioned workers don't thrash the same range.
        while len(held) > share:
            k = max(held)
            await self._release(k, leases.get(k))
            held.discard(k)

        # 3. acquire free/expired ranges up to the share
        if len(held) < share:
            for k in range(self.num_ranges):
                if len(held) >= share:
                    break
                if k in held:
                    continue
                lease = leases.get(k)
                if lease is None:
                    if await self._create(k):
                        held.add(k)
                        self.acquired_total += 1
                    continue
                if lease.spec.holder_identity == self.identity:
                    held.add(k)
                    continue
                released = not lease.spec.holder_identity
                if not released and not self._expired(k, lease):
                    continue
                lease.spec.holder_identity = self.identity
                lease.spec.acquire_time = now()
                lease.spec.renew_time = now()
                lease.spec.lease_transitions += 1
                try:
                    await self.client.update(lease)
                    held.add(k)
                    self.acquired_total += 1
                    if not released:
                        # taken from an expired HOLDER (worker death), not a
                        # graceful release — the crash-reclaim counter
                        self.adopted_total += 1
                        log.info("shard-lease: adopted expired range %d", k)
                except (ConflictError, NotFoundError):
                    continue  # a survivor beat us to the corpse

        self._apply_holdings(held)

    async def _create(self, k: int) -> bool:
        fresh = Lease(
            metadata=ObjectMeta(name=self._lease_name(k),
                                namespace=self.namespace),
            spec=LeaseSpec(
                holder_identity=self.identity,
                lease_duration_seconds=max(
                    1, math.ceil(self.lease_duration)),
                acquire_time=now(), renew_time=now()))
        try:
            await self.client.create(fresh)
            return True
        except AlreadyExistsError:
            return False

    async def _release(self, k: int, lease: Optional[Lease] = None) -> None:
        """Hand a range back (holder cleared, renew_time zeroed so the next
        claimant needn't wait out the duration)."""
        try:
            if lease is None:
                lease = await self.client.get(Lease, self._lease_name(k),
                                              self.namespace)
            if lease.spec.holder_identity != self.identity:
                return
            lease.spec.holder_identity = ""
            lease.spec.renew_time = None
            await self.client.update(lease)
            self.released_total += 1
        except (ConflictError, NotFoundError):
            pass

    def _apply_holdings(self, held: set[int]) -> None:
        gained = held - self.ranges
        lost = self.ranges - held
        if not gained and not lost:
            return
        self.ranges = held
        log.info("shard-lease %s: %d ranges held (+%d/-%d)", self.identity,
                 len(held), len(gained), len(lost))
        if self.on_change is not None:
            try:
                self.on_change(gained, lost)
            except Exception:  # noqa: BLE001 — subscription refresh is
                log.warning("shard-lease on_change failed",  # best-effort;
                            exc_info=True)  # the next tick retries nothing


def holders(leases: Iterable[Lease]) -> dict[str, set[int]]:
    """holder identity → owned range ids, from a raw Lease listing (test
    and supervisor-introspection helper)."""
    out: dict[str, set[int]] = {}
    for lease in leases:
        name = lease.metadata.name
        if not name.startswith(LEASE_PREFIX) or not lease.spec.holder_identity:
            continue
        try:
            k = int(name[len(LEASE_PREFIX):])
        except ValueError:
            continue
        out.setdefault(lease.spec.holder_identity, set()).add(k)
    return out
