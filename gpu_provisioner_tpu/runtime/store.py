"""In-memory API-server: the storage + watch half of the runtime.

Replicates the API-server behaviors the reference's controllers depend on
(they talk to a real apiserver through controller-runtime's cached client):

- monotonically increasing resourceVersion with optimistic-concurrency
  conflicts on update;
- watch streams delivering ADDED/MODIFIED/DELETED events per kind;
- finalizer semantics: delete of an object with finalizers only sets
  ``deletionTimestamp``; the object is actually removed when its finalizer
  list empties (this is what makes the termination flows in SURVEY.md §3.3
  work at all);
- ``generation`` bump on spec change, stable across status-only updates.

Used directly by envtest-style tests and ``fake``; production deployments
swap in the REST client behind the same ``Client`` seam.
"""

from __future__ import annotations

import asyncio
import itertools
import os
from dataclasses import dataclass
from typing import Optional

from ..apis.meta import Object
from ..apis.serde import now

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


@dataclass
class WatchEvent:
    """One watch-stream event. ``object`` is SHARED by every watcher of the
    kind and by the informer cache (client-go SharedInformer contract):
    consumers must treat it as READ-ONLY — deepcopy() before mutating.
    Predicates/map_fns/log taps all read; anything that normalizes or
    edits must copy first or it silently corrupts every other consumer."""
    type: str
    object: Object


class StoreError(Exception):
    pass


class StoreNotFound(StoreError):
    pass


class StoreConflict(StoreError):
    pass


class StoreAlreadyExists(StoreError):
    pass


def _new_uid() -> str:
    """UUID-shaped random uid without uuid.UUID's parse/format machinery —
    uid minting was 8% of a 2048-claim wave's CPU (one per object create);
    nothing parses uids, they are opaque identity/precondition tokens."""
    h = os.urandom(16).hex()
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


def _key(namespace: str, name: str) -> tuple[str, str]:
    return (namespace or "", name)


class Store:
    def __init__(self):
        self._objects: dict[type, dict[tuple[str, str], Object]] = {}
        self._rv = itertools.count(1)
        self._watchers: dict[type, list[asyncio.Queue]] = {}
        self._indexes: dict[tuple[type, str], object] = {}  # (cls, name) -> key_fn
        # Maintained inverted indexes — the informer-cache behavior that keeps
        # hot-path reads O(result) instead of O(bucket): registered field
        # indexes and every label key/value map to object keys, updated on
        # each mutation. Without these, per-claim node-wait polls and
        # per-reconcile providerID lookups scan the whole bucket, which is
        # O(claims²) during a provisioning wave (found: 64 claims fine,
        # 128 melted down).
        self._inverted: dict[tuple[type, str], dict[str, set]] = {}
        self._by_label: dict[type, dict[tuple[str, str], set]] = {}

    # -- watch ------------------------------------------------------------
    def watch(self, cls: type, initial_list: bool = True) -> asyncio.Queue:
        """Register a watch stream. ``initial_list`` replays existing objects
        as ADDED events first — informer ListAndWatch semantics, which the
        reference's controllers get from controller-runtime caches. Without
        it, objects created before a controller starts would never reconcile.

        Queues are unbounded: an in-process watcher that falls behind must
        still eventually see every event (there is no relist protocol like the
        real apiserver's 410 Gone → relist), and memory is bounded by event
        volume, which the workqueue dedups right behind the pump.

        Event objects are SHARED across watchers and READ-ONLY — see
        WatchEvent."""
        q: asyncio.Queue = asyncio.Queue()
        if initial_list:
            for obj in self._bucket(cls).values():
                q.put_nowait(WatchEvent(ADDED, obj.deepcopy()))
        self._watchers.setdefault(cls, []).append(q)
        return q

    def unwatch(self, cls: type, q: asyncio.Queue) -> None:
        ws = self._watchers.get(cls, [])
        if q in ws:
            ws.remove(q)

    def _notify(self, etype: str, obj: Object) -> None:
        # ONE clone per event, shared by every watcher — client-go
        # SharedInformer semantics: event objects are READ-ONLY for all
        # consumers (controllers map them to keys; the informer stores
        # them and clones on read). The clone still isolates consumers
        # from the store's own in-place mutations (delete() stamps
        # deletionTimestamp on the bucket object). Per-watcher clones
        # were ~the largest CPU cost of a 2048-claim wave.
        ws = self._watchers.get(type(obj))
        if not ws:
            return
        shared = obj.deepcopy()
        for q in ws:
            q.put_nowait(WatchEvent(etype, shared))

    # -- index ------------------------------------------------------------
    def add_index(self, cls: type, name: str, key_fn) -> None:
        """Field indexer analog (reference: operator.go:263-293 registers pod
        nodeName / node providerID / nodeclaim providerID indexes)."""
        self._indexes[(cls, name)] = key_fn
        inv: dict[str, set] = {}
        for k, obj in self._bucket(cls).items():
            for val in (key_fn(obj) or []):
                inv.setdefault(val, set()).add(k)
        self._inverted[(cls, name)] = inv

    def _index_add(self, obj: Object, k: tuple[str, str]) -> None:
        cls = type(obj)
        for (icls, name), fn in self._indexes.items():
            if icls is cls:
                inv = self._inverted[(icls, name)]
                for val in (fn(obj) or []):
                    inv.setdefault(val, set()).add(k)
        lab = self._by_label.setdefault(cls, {})
        for lk, lv in obj.metadata.labels.items():
            lab.setdefault((lk, lv), set()).add(k)

    def _index_remove(self, obj: Object, k: tuple[str, str]) -> None:
        cls = type(obj)
        for (icls, name), fn in self._indexes.items():
            if icls is cls:
                inv = self._inverted[(icls, name)]
                for val in (fn(obj) or []):
                    inv.get(val, set()).discard(k)
        lab = self._by_label.get(cls, {})
        for lk, lv in obj.metadata.labels.items():
            lab.get((lk, lv), set()).discard(k)

    # -- CRUD -------------------------------------------------------------
    def _bucket(self, cls: type) -> dict[tuple[str, str], Object]:
        return self._objects.setdefault(cls, {})

    def create(self, obj: Object) -> Object:
        b = self._bucket(type(obj))
        k = _key(obj.metadata.namespace, obj.metadata.name)
        if k in b:
            raise StoreAlreadyExists(f"{type(obj).__name__} {k} exists")
        stored = obj.deepcopy()
        stored.metadata.uid = stored.metadata.uid or _new_uid()
        stored.metadata.creation_timestamp = stored.metadata.creation_timestamp or now()
        stored.metadata.generation = 1
        stored.metadata.resource_version = str(next(self._rv))
        b[k] = stored
        self._index_add(stored, k)
        self._notify(ADDED, stored)
        return stored.deepcopy()

    def get(self, cls: type, name: str, namespace: str = "") -> Object:
        obj = self._bucket(cls).get(_key(namespace, name))
        if obj is None:
            raise StoreNotFound(f"{cls.__name__} {namespace}/{name} not found")
        return obj.deepcopy()

    def list(self, cls: type, labels: Optional[dict[str, str]] = None,
             namespace: Optional[str] = None,
             index: Optional[tuple[str, str]] = None) -> list[Object]:
        bucket = self._bucket(cls)
        # narrow to index candidates first — O(result), not O(bucket)
        if index:
            if (cls, index[0]) not in self._indexes:
                raise StoreError(f"no index {index[0]!r} registered for {cls.__name__}")
            keys = self._inverted[(cls, index[0])].get(index[1], set())
            candidates = [bucket[k] for k in keys if k in bucket]
        elif labels:
            lk, lv = next(iter(labels.items()))
            keys = self._by_label.get(cls, {}).get((lk, lv), set())
            candidates = [bucket[k] for k in keys if k in bucket]
        else:
            candidates = bucket.values()

        out = []
        for obj in candidates:
            if namespace is not None and obj.metadata.namespace != namespace:
                continue
            if labels and any(obj.metadata.labels.get(k) != v for k, v in labels.items()):
                continue
            out.append(obj.deepcopy())
        return out

    def _check_conflict(self, current: Object, incoming: Object) -> None:
        # The real apiserver rejects updates without a resourceVersion; allowing
        # them here would let lost-update bugs pass envtest and fail only in
        # production.
        if not incoming.metadata.resource_version:
            raise StoreConflict(
                f"{type(incoming).__name__} {incoming.metadata.name}: "
                "resourceVersion must be specified for an update")
        if incoming.metadata.resource_version != current.metadata.resource_version:
            raise StoreConflict(
                f"{type(incoming).__name__} {incoming.metadata.name}: resourceVersion "
                f"{incoming.metadata.resource_version} != {current.metadata.resource_version}")

    def update(self, obj: Object) -> Object:
        b = self._bucket(type(obj))
        k = _key(obj.metadata.namespace, obj.metadata.name)
        current = b.get(k)
        if current is None:
            raise StoreNotFound(f"{type(obj).__name__} {k} not found")
        self._check_conflict(current, obj)
        stored = obj.deepcopy()
        # Immutable server-side fields.
        stored.metadata.uid = current.metadata.uid
        stored.metadata.creation_timestamp = current.metadata.creation_timestamp
        # deletionTimestamp is server-owned: only delete() sets it.
        stored.metadata.deletion_timestamp = current.metadata.deletion_timestamp
        # dataclass == — same-class trees compare recursively without a
        # dict-serialization round trip (hot at fleet scale)
        if hasattr(current, "spec") and current.spec != stored.spec:
            stored.metadata.generation = current.metadata.generation + 1
        else:
            stored.metadata.generation = current.metadata.generation
        stored.metadata.resource_version = str(next(self._rv))
        self._index_remove(current, k)
        if stored.metadata.deletion_timestamp and not stored.metadata.finalizers:
            del b[k]
            self._notify(DELETED, stored)
            return stored.deepcopy()
        b[k] = stored
        self._index_add(stored, k)
        self._notify(MODIFIED, stored)
        return stored.deepcopy()

    def update_status(self, obj: Object) -> Object:
        """Status-subresource write: only .status changes, generation stable."""
        b = self._bucket(type(obj))
        k = _key(obj.metadata.namespace, obj.metadata.name)
        current = b.get(k)
        if current is None:
            raise StoreNotFound(f"{type(obj).__name__} {k} not found")
        self._check_conflict(current, obj)
        stored = current.deepcopy()
        from ..apis.meta import _fast_clone
        stored.status = _fast_clone(obj.status)   # status-subresource: only
        # .status crosses; cloning the whole incoming object threw away
        # everything but one field (profiled hot at 1024-claim waves)
        stored.metadata.resource_version = str(next(self._rv))
        b[k] = stored
        self._notify(MODIFIED, stored)
        return stored.deepcopy()

    def delete(self, cls: type, name: str, namespace: str = "") -> None:
        b = self._bucket(cls)
        k = _key(namespace, name)
        current = b.get(k)
        if current is None:
            raise StoreNotFound(f"{cls.__name__} {namespace}/{name} not found")
        if current.metadata.finalizers:
            if current.metadata.deletion_timestamp is None:
                current.metadata.deletion_timestamp = now()
                current.metadata.resource_version = str(next(self._rv))
                self._notify(MODIFIED, current)
            return
        del b[k]
        self._index_remove(current, k)
        self._notify(DELETED, current)
