"""WakeHub: the event-driven wake graph for requeued reconciles.

PR 9's critical-path attribution showed requeue-idle-gap at 57% of wave
wall: claims parked on ``Result(requeue_after=...)`` timers waiting for
state that had already changed. The tracker-completion ``Controller.inject``
seam proved the cure for ONE path (LRO completion); this module generalizes
it into a first-class hub every requeue-producing path registers against —
LRO completion, node registration/readiness watch events, placement
stockout-TTL expiry, status-flush completion — so ``requeue_after`` becomes
a safety-net deadline, never the primary wake-up.

Layering: this is runtime code, so it never imports prometheus. Wake counts
accumulate in the module-level ``WAKES`` registry (keyed by source) and are
exported counter-by-delta at scrape time by ``controllers/metrics.py`` as
``tpu_provisioner_requeue_wakes_total{source}`` — the STOCKOUTS_TOTAL idiom.
The workqueue calls :func:`note_wake` at the enqueue that actually lands
(dedup-dropped wakes are not counted), so hub-routed wakes, watch-borne
wakes and safety-net timer firings all share one ledger.
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable, Optional

from . import probes

# source -> cumulative wakes that landed an enqueue. Module-level like
# placement.STOCKOUTS: multiple hubs (multi-shard benches, test Envs in one
# process) accumulate into one ledger; the exporter tracks deltas.
WAKES: dict[str, int] = {}

# Well-known wake sources (the label vocabulary; free-form strings work too).
SOURCE_WATCH = "watch"            # primary object watch stream
SOURCE_NODE = "node"              # node registration/readiness events
SOURCE_LRO = "lro"                # tracked cloud operation completed
SOURCE_TIMER = "timer"            # requeue_after safety net actually fired
SOURCE_STOCKOUT = "stockout"      # placement stockout-TTL memo expired
SOURCE_STATUS_FLUSH = "status-flush"  # batched status write landed
SOURCE_INJECT = "inject"          # unattributed manual inject
SOURCE_REMOTE = "remote"          # wake delivered over the IPC transport

# Ledger key for safety-net timers that were never ARMED because an event
# wake source is registered for the park reason (the timer-diet
# optimization). Bookkeeping, not a delivered wake: timer_wake_share
# denominators must exclude it.
SKIPPED_TIMER_ARM = "timer-arm-skipped"


def note_wake(source: str) -> None:
    WAKES[source] = WAKES.get(source, 0) + 1


def note_skipped_arm() -> None:
    """Count a safety-net timer the controller declined to arm because the
    park's wake source is event-announced (see WakeHub.announce)."""
    WAKES[SKIPPED_TIMER_ARM] = WAKES.get(SKIPPED_TIMER_ARM, 0) + 1


WakeSink = Callable[..., Awaitable[None]]


class WakeHub:
    """Fan-out point for out-of-band wake producers.

    Sinks are async callables invoked as ``sink(name, source=source)`` —
    ``Controller.inject`` matches directly. Producers that know a future
    wake time (a stockout memo's TTL) use :meth:`wake_after`; the handle
    bookkeeping keeps the envtest leak gate able to enumerate everything
    the hub still owes the event loop.
    """

    def __init__(self) -> None:
        self._sinks: list[WakeSink] = []
        # Delivery tasks + delayed-wake handles are retained (provlint
        # PL007 bug class) and reaped in stop().
        self._tasks: set[asyncio.Task] = set()
        self._handles: set[asyncio.TimerHandle] = set()
        self._stopped = False
        self.delivered_total = 0
        # Event wake sources ANNOUNCED as live producers on this hub: a
        # controller park annotated with one of these can skip arming its
        # safety-net timer (the timer-diet) — the producer will wake it.
        self._announced: set[str] = set()
        # Cross-process transport hook (runtime/shardipc.py): a sync
        # callable ``route(name, source) -> bool``. Returning True claims
        # the wake — it was forwarded to the owning worker process and must
        # NOT deliver to local sinks (inject bypasses shard filters, so a
        # local delivery of a foreign claim would violate single-writer).
        self.route = None
        self.forwarded_total = 0

    def register(self, sink: WakeSink) -> None:
        self._sinks.append(sink)

    def announce(self, source: str) -> None:
        """Declare that a producer for ``source`` is wired into this hub
        (tracker completions for ``lro``, the Node watch for ``node``, the
        status batcher for ``status-flush``, ...). Announcements gate the
        safety-net timer diet — see ``Controller._worker``."""
        self._announced.add(source)

    def announced(self, source) -> bool:
        return source in self._announced

    async def wake(self, name: str, source: str) -> None:
        """Deliver a wake for ``name`` to every registered sink NOW.

        Dedup is the workqueue's: a wake for an item already enqueued (or
        dirty-while-processing) collapses there, so waking is always safe
        and never duplicates reconciles.
        """
        if self._stopped:
            return
        if self.route is not None:
            try:
                claimed = self.route(name, source)
            except Exception:  # noqa: BLE001 — transport loss ≠ lost wake:
                claimed = False  # deliver locally; dedup makes it safe
            if claimed:
                self.forwarded_total += 1
                probes.emit("hub-wake-forwarded", id(self), name=name,
                            source=source)
                return
        self.delivered_total += 1
        # schedfuzz stop-before-late-wake contract: emitted only for wakes
        # that actually deliver (a post-stop wake returns above, silently)
        probes.emit("hub-wake", id(self), name=name, source=source)
        for sink in list(self._sinks):
            await sink(name, source=source)

    def wake_after(self, name: str, delay: float, source: str) -> None:
        """Schedule a wake for ``name`` in ``delay`` seconds (loop clock).

        Fire-and-forget from sync code (the placement walk); the timer
        handle and the delivery task it spawns are both retained so stop()
        — and the leak gate — can account for them.
        """
        if self._stopped:
            return
        if delay <= 0:
            self._spawn(name, source)
            return
        loop = asyncio.get_event_loop()
        handle: asyncio.TimerHandle = loop.call_later(
            delay, self._fire, name, source)
        self._handles.add(handle)
        # call_later handles carry no completion callback; prune opportunistically
        self._handles = {h for h in self._handles if not h.cancelled()
                         and h.when() >= loop.time() - 1.0} | {handle}

    def _fire(self, name: str, source: str) -> None:
        self._spawn(name, source)

    def _spawn(self, name: str, source: str) -> None:
        if self._stopped:
            return
        task = asyncio.ensure_future(self.wake(name, source))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def pending(self) -> int:
        """Delayed wakes + in-flight deliveries the hub still owns."""
        live_handles = sum(1 for h in self._handles if not h.cancelled())
        return live_handles + len(self._tasks)

    async def stop(self) -> None:
        """Cancel delayed wakes and reap in-flight deliveries."""
        self._stopped = True
        probes.emit("hub-stop", id(self))
        for h in self._handles:
            h.cancel()
        self._handles.clear()
        tasks, self._tasks = set(self._tasks), set()
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
