"""Rate-limited dedup workqueue (client-go workqueue analog).

Semantics replicated from client-go, which every reference controller relies
on: an item present in the queue is not added twice; an item re-added while a
worker is processing it is re-queued after ``done``; ``add_rate_limited``
applies per-item backoff (5 ms → 1000 s window, client-go's default failure
rate limiter) cleared by ``forget``. The backoff uses decorrelated jitter
(delay ~ U(base, 3·previous), capped) rather than bare ``base·2**n``: a
fleet of items that failed together — one cloud outage fails every in-flight
create in the same second — must not come back as a synchronized retry wave
on every subsequent cycle.
"""

from __future__ import annotations

import asyncio
import heapq
import random
import time
from collections import deque
from typing import Any, Hashable, Optional

from . import probes
from .wakehub import SOURCE_TIMER, note_wake


class RateLimitingQueue:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0,
                 seed: Optional[int] = None):
        self.base_delay = base_delay
        self.max_delay = max_delay
        # Seedable for deterministic chaos/soak tests; None → os entropy.
        self._rng = random.Random(seed)
        self._last_delay: dict[Hashable, float] = {}
        self.requeues_total = 0
        # deque: get() pops from the FRONT — list.pop(0) is O(depth)
        # and a fleet wave holds thousands of ready items
        self._queue: deque[Hashable] = deque()
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        # Ready-queue residency stamps: set when an item lands in _queue,
        # consumed by get() into _waits, handed to the worker via
        # pop_wait() — the "queue-wait" phase of a claim's trace. Time
        # parked in the delayed heap is deliberately NOT counted: backoff
        # is the requeue-idle-gap phase, not queue congestion.
        self._enqueued: dict[Hashable, float] = {}
        self._waits: dict[Hashable, float] = {}
        # Wake-source stamps, parallel to the queue-wait stamps: what put
        # the item into the ready queue (watch/node/lro/timer/...), set at
        # the enqueue that landed (first cause wins — it ended the idle),
        # popped by the worker via pop_wake_source() and threaded into the
        # claimtrace queue-wait span so critical-path attribution can split
        # requeue-idle-gap into "woken early" vs "timer fired".
        self._wake_srcs: dict[Hashable, str] = {}
        self._woken_by: dict[Hashable, str] = {}
        self._failures: dict[Hashable, int] = {}
        # Delayed entries carry the item's wake epoch at push time: any
        # later enqueue (a watch event, a hub wake) bumps the epoch, so a
        # safety-net requeue_after timer whose item was already woken —
        # and reconciled, and possibly re-parked — is dropped as stale
        # instead of firing a spurious extra reconcile. The reconcile that
        # consumed the wake re-arms its own safety net if it still waits.
        self._delayed: list[tuple[float, int, Hashable, int]] = []
        self._epoch: dict[Hashable, int] = {}
        self.stale_timer_drops = 0
        self._seq = 0
        self._cond = asyncio.Condition()
        self._shutdown = False
        # ONE timer task owns the delayed heap's deadline; workers block on
        # the condition with no timeout. The previous design had every idle
        # worker wake on the next-due deadline — with ~1000 workers
        # (the reference's concurrency regime) that thundering herd of
        # wait_for timers + lock reacquisitions saturated the event loop
        # before any real work ran.
        self._timer: Optional[asyncio.Task] = None
        self._timer_wake = asyncio.Event()

    # -- core add/get/done ------------------------------------------------
    def _add_locked(self, item: Hashable,
                    source: Optional[str] = None) -> None:
        if self._shutdown or item in self._dirty:
            return
        self._dirty.add(item)
        self._epoch[item] = self._epoch.get(item, 0) + 1
        probes.emit("wq-enqueue", item, source=source)
        if source is not None:
            self._wake_srcs[item] = source
            note_wake(source)
        if item in self._processing:
            return  # will be re-queued on done()
        self._queue.append(item)
        self._enqueued[item] = time.monotonic()
        self._cond.notify()

    async def add(self, item: Hashable,
                  source: Optional[str] = None) -> None:
        async with self._cond:
            self._add_locked(item, source=source)

    async def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            await self.add(item, source=SOURCE_TIMER)
            return
        async with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed,
                           (time.monotonic() + delay, self._seq, item,
                            self._epoch.get(item, 0)))
            if self._timer is None or self._timer.done():
                self._timer = asyncio.create_task(self._timer_loop())
            else:
                self._timer_wake.set()  # new item may be due earlier

    async def _timer_loop(self) -> None:
        """Drain due delayed items into the ready queue, sleeping until the
        next deadline; exits when the heap empties (re-armed by add_after)."""
        while True:
            async with self._cond:
                if self._shutdown:
                    return
                nxt = self._drain_delayed_locked()
                if self._queue:
                    self._cond.notify(len(self._queue))
                if nxt is None:
                    self._timer = None
                    return
            self._timer_wake.clear()
            try:
                await asyncio.wait_for(self._timer_wake.wait(), timeout=nxt)
            except asyncio.TimeoutError:
                pass

    async def add_rate_limited(self, item: Hashable) -> None:
        async with self._cond:
            self._failures[item] = self._failures.get(item, 0) + 1
            # Decorrelated jitter (the AWS-architecture-blog variant):
            # sleep = min(cap, U(base, 3·prev)). Grows like the exponential
            # ladder in expectation but two items that failed in the same
            # instant immediately diverge instead of retrying in lockstep
            # forever.
            prev = self._last_delay.get(item, self.base_delay)
            delay = min(self.max_delay,
                        self._rng.uniform(self.base_delay,
                                          max(prev * 3, self.base_delay)))
            self._last_delay[item] = delay
            self.requeues_total += 1
        await self.add_after(item, delay)

    def num_requeues(self, item: Hashable) -> int:
        return self._failures.get(item, 0)

    async def forget(self, item: Hashable) -> None:
        async with self._cond:
            self._failures.pop(item, None)
            self._last_delay.pop(item, None)
            # _epoch is deliberately NOT popped here: a forget-then-re-arm
            # would reset the counter to 0, letting an older parked entry
            # (also pushed at 0, before an intervening wake) match again
            # and fire spuriously — the exact double-fire the epoch guard
            # exists to drop. The cost is one small int per distinct item
            # ever enqueued — noise next to the store's own object cache.

    async def reset_failures(self, item: Hashable) -> None:
        """Clear the failure COUNTER but keep the jitter memory: the next
        ``add_rate_limited`` continues at the current (capped) cadence
        instead of restarting the fast ladder. Used by the controller's
        retry-exhaustion degrade path — a full ``forget`` there would turn
        "degrade to slow retry" into a sawtooth retry storm."""
        async with self._cond:
            self._failures.pop(item, None)

    # -- observability (exported as gauges via controllers/metrics.py) ----
    def depth(self) -> int:
        """Items ready for a worker right now."""
        return len(self._queue)

    def delayed(self) -> int:
        """Items parked in backoff."""
        return len(self._delayed)

    def retrying(self) -> int:
        """Items with a live failure count (requeued at least once since
        their last forget)."""
        return len(self._failures)

    def _drain_delayed_locked(self) -> Optional[float]:
        """Move due delayed items into the queue; return seconds to next due."""
        nxt = None
        now = time.monotonic()
        while self._delayed:
            due, _, item, epoch = self._delayed[0]
            if due <= now:
                heapq.heappop(self._delayed)
                probes.emit("wq-timer-due", item,
                            stale=epoch != self._epoch.get(item, 0))
                if epoch != self._epoch.get(item, 0):
                    # superseded: the item was woken (and reconciled) after
                    # this safety net was armed — firing it now would only
                    # add a spurious reconcile
                    self.stale_timer_drops += 1
                    probes.emit("wq-stale-drop", item)
                    continue
                self._add_locked(item, source=SOURCE_TIMER)
            else:
                nxt = due - now
                break
        return nxt

    async def get(self) -> Any:
        async with self._cond:
            while True:
                self._drain_delayed_locked()  # cheap catch-up; timer notifies
                if self._queue:
                    item = self._queue.popleft()
                    self._dirty.discard(item)
                    self._processing.add(item)
                    stamped = self._enqueued.pop(item, None)
                    if stamped is not None:
                        self._waits[item] = time.monotonic() - stamped
                    src = self._wake_srcs.pop(item, None)
                    if src is not None:
                        self._woken_by[item] = src
                    return item
                if self._shutdown:
                    raise asyncio.CancelledError("workqueue shut down")
                await self._cond.wait()

    def pop_wait(self, item: Hashable) -> Optional[float]:
        """Seconds ``item`` sat ready before the ``get()`` that returned it;
        consumed exactly once (the worker pops it right after dequeue so
        the dict stays bounded by in-flight items)."""
        return self._waits.pop(item, None)

    def pop_wake_source(self, item: Hashable) -> Optional[str]:
        """What woke ``item`` for the ``get()`` that returned it (None when
        the enqueue carried no source); consumed exactly once, same
        bounded-by-in-flight contract as :meth:`pop_wait`."""
        return self._woken_by.pop(item, None)

    async def done(self, item: Hashable) -> None:
        async with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._enqueued[item] = time.monotonic()
                self._cond.notify()

    async def shutdown(self) -> None:
        async with self._cond:
            self._shutdown = True
            self._timer_wake.set()
            self._cond.notify_all()
            timer, self._timer = self._timer, None
        # Reap the delayed-heap timer task OUTSIDE the lock (its loop
        # re-acquires the condition). Without this, a queue stopped with
        # items still parked in backoff — max_delay is 1000s — left the
        # timer task sleeping long past its controller's teardown (found
        # by the envtest task-leak gate; provlint PL007 bug class).
        if timer is not None:
            timer.cancel()
            try:
                await timer
            except asyncio.CancelledError:
                pass

    def __len__(self) -> int:
        return len(self._queue)
