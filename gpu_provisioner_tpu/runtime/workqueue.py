"""Rate-limited dedup workqueue (client-go workqueue analog).

Semantics replicated from client-go, which every reference controller relies
on: an item present in the queue is not added twice; an item re-added while a
worker is processing it is re-queued after ``done``; ``add_rate_limited``
applies per-item exponential backoff (5 ms → 1000 s, client-go's default
failure rate limiter) cleared by ``forget``.
"""

from __future__ import annotations

import asyncio
import heapq
import time
from collections import deque
from typing import Any, Hashable, Optional


class RateLimitingQueue:
    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        # deque: get() pops from the FRONT — list.pop(0) is O(depth)
        # and a fleet wave holds thousands of ready items
        self._queue: deque[Hashable] = deque()
        self._dirty: set[Hashable] = set()
        self._processing: set[Hashable] = set()
        self._failures: dict[Hashable, int] = {}
        self._delayed: list[tuple[float, int, Hashable]] = []
        self._seq = 0
        self._cond = asyncio.Condition()
        self._shutdown = False
        # ONE timer task owns the delayed heap's deadline; workers block on
        # the condition with no timeout. The previous design had every idle
        # worker wake on the next-due deadline — with ~1000 workers
        # (the reference's concurrency regime) that thundering herd of
        # wait_for timers + lock reacquisitions saturated the event loop
        # before any real work ran.
        self._timer: Optional[asyncio.Task] = None
        self._timer_wake = asyncio.Event()

    # -- core add/get/done ------------------------------------------------
    def _add_locked(self, item: Hashable) -> None:
        if self._shutdown or item in self._dirty:
            return
        self._dirty.add(item)
        if item in self._processing:
            return  # will be re-queued on done()
        self._queue.append(item)
        self._cond.notify()

    async def add(self, item: Hashable) -> None:
        async with self._cond:
            self._add_locked(item)

    async def add_after(self, item: Hashable, delay: float) -> None:
        if delay <= 0:
            await self.add(item)
            return
        async with self._cond:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(self._delayed, (time.monotonic() + delay, self._seq, item))
            if self._timer is None or self._timer.done():
                self._timer = asyncio.create_task(self._timer_loop())
            else:
                self._timer_wake.set()  # new item may be due earlier

    async def _timer_loop(self) -> None:
        """Drain due delayed items into the ready queue, sleeping until the
        next deadline; exits when the heap empties (re-armed by add_after)."""
        while True:
            async with self._cond:
                if self._shutdown:
                    return
                nxt = self._drain_delayed_locked()
                if self._queue:
                    self._cond.notify(len(self._queue))
                if nxt is None:
                    self._timer = None
                    return
            self._timer_wake.clear()
            try:
                await asyncio.wait_for(self._timer_wake.wait(), timeout=nxt)
            except asyncio.TimeoutError:
                pass

    async def add_rate_limited(self, item: Hashable) -> None:
        async with self._cond:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        await self.add_after(item, min(self.base_delay * (2 ** n), self.max_delay))

    def num_requeues(self, item: Hashable) -> int:
        return self._failures.get(item, 0)

    async def forget(self, item: Hashable) -> None:
        async with self._cond:
            self._failures.pop(item, None)

    def _drain_delayed_locked(self) -> Optional[float]:
        """Move due delayed items into the queue; return seconds to next due."""
        nxt = None
        now = time.monotonic()
        while self._delayed:
            due, _, item = self._delayed[0]
            if due <= now:
                heapq.heappop(self._delayed)
                self._add_locked(item)
            else:
                nxt = due - now
                break
        return nxt

    async def get(self) -> Any:
        async with self._cond:
            while True:
                self._drain_delayed_locked()  # cheap catch-up; timer notifies
                if self._queue:
                    item = self._queue.popleft()
                    self._dirty.discard(item)
                    self._processing.add(item)
                    return item
                if self._shutdown:
                    raise asyncio.CancelledError("workqueue shut down")
                await self._cond.wait()

    async def done(self, item: Hashable) -> None:
        async with self._cond:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._cond.notify()

    async def shutdown(self) -> None:
        async with self._cond:
            self._shutdown = True
            self._timer_wake.set()
            self._cond.notify_all()

    def __len__(self) -> int:
        return len(self._queue)
