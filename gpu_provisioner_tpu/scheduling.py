"""Scheduling requirement/label/taint algebra.

Subset of the vendored karpenter scheduling library the reference leans on
(SURVEY.md §2b V14; used at pkg/providers/instance/instance.go:90-95 to resolve
the instance type and at registration.go:120-147 for taint/label sync). The
full Offerings engine is deliberately not built — the reference's
GetInstanceTypes returns an empty catalog (pkg/cloudprovider/cloudprovider.go:99-101)
and KAITO pins exact shapes via requirements.
"""

from __future__ import annotations

from typing import Iterable, Optional

from .apis import karpenter as kv1
from .apis.core import Taint


class Requirement:
    """The allowed value set for one label key."""

    def __init__(self, key: str, operator: str, values: Iterable[str] = (),
                 min_values: Optional[int] = None):
        self.key = key
        self.operator = operator
        self.raw_values = list(values)
        self.min_values = min_values

    def values(self) -> list[str]:
        """Allowed values, in declaration order (only meaningful for In)."""
        return list(self.raw_values) if self.operator == kv1.IN else []

    def any(self) -> str:
        vals = self.values()
        return vals[0] if vals else ""

    def matches(self, value: Optional[str]) -> bool:
        op = self.operator
        if op == kv1.IN:
            return value is not None and value in self.raw_values
        if op == kv1.NOT_IN:
            return value is None or value not in self.raw_values
        if op == kv1.EXISTS:
            return value is not None
        if op == kv1.DOES_NOT_EXIST:
            return value is None
        if op in (kv1.GT, kv1.LT):
            if not self.raw_values or not self.raw_values[0].lstrip("-").isdigit():
                return False
            if value is None or not value.lstrip("-").isdigit():
                return False
            bound = int(self.raw_values[0])
            return int(value) > bound if op == kv1.GT else int(value) < bound
        return False


class Requirements:
    """Keyed collection of Requirements built from a NodeClaim spec."""

    def __init__(self, reqs: Iterable[kv1.NodeSelectorRequirement] = ()):
        self._by_key: dict[str, Requirement] = {}
        for r in reqs:
            self.add(Requirement(r.key, r.operator, r.values, r.min_values))

    @classmethod
    def from_nodeclaim(cls, nc: kv1.NodeClaim) -> "Requirements":
        reqs = cls(nc.spec.requirements)
        # Labels act as implicit In-requirements (karpenter semantics).
        for k, v in nc.metadata.labels.items():
            if k not in reqs._by_key:
                reqs.add(Requirement(k, kv1.IN, [v]))
        return reqs

    def add(self, req: Requirement) -> None:
        existing = self._by_key.get(req.key)
        if existing is not None and existing.operator == kv1.IN and req.operator == kv1.IN:
            # Intersect allowed sets, preserving the established order.
            keep = [v for v in existing.raw_values if v in req.raw_values]
            existing.raw_values = keep
            return
        self._by_key[req.key] = req

    def get(self, key: str) -> Requirement:
        return self._by_key.get(key) or Requirement(key, kv1.DOES_NOT_EXIST)

    def has(self, key: str) -> bool:
        return key in self._by_key

    def keys(self) -> list[str]:
        return list(self._by_key)

    def preference(self, key: str, defaults: Iterable[str]) -> list[str]:
        """Preference-ordered allowed values for ``key``.

        An In-requirement pins the order to its declared values; any other
        requirement filters ``defaults`` through :meth:`Requirement.matches`
        (NotIn drops the excluded ones); no requirement at all returns
        ``defaults`` unchanged. This is the placement engine's candidate-axis
        expansion (zone / capacity-tier): declared values are a *ranking*,
        not just a set.
        """
        req = self._by_key.get(key)
        if req is None:
            return list(defaults)
        if req.operator == kv1.IN:
            return req.values()
        return [v for v in defaults if req.matches(v)]

    def compatible(self, labels: dict[str, str]) -> bool:
        return all(r.matches(labels.get(k)) for k, r in self._by_key.items())


def merge_taints(existing: list[Taint], desired: list[Taint]) -> list[Taint]:
    """Union by (key, effect), desired wins — the merge registration applies
    when syncing NodeClaim taints onto the Node (registration.go:120-147)."""
    out = list(desired)
    for t in existing:
        if not any(t.matches(d) for d in desired):
            out.append(t)
    return out


def remove_taint(taints: list[Taint], key: str) -> list[Taint]:
    return [t for t in taints if t.key != key]


def has_taint(taints: list[Taint], key: str) -> bool:
    return any(t.key == key for t in taints)
