"""Pooled, retrying HTTP transport shared by the kube and GCP REST clients.

The analog of the reference's ARM transport stack (pkg/utils/opts):
armbalancer pool of 100 connections (init_http_client.go:29-52) and a
20-retry / 5s-exponential-backoff policy (armopts.go:34-40). HTTP/1.1 here
(no h2 in this image); the pool limit is what matters for burst reconciles.
"""

from __future__ import annotations

import asyncio
import random
from dataclasses import dataclass

import httpx

RETRYABLE_STATUS = frozenset({408, 429, 500, 502, 503, 504})
# For cloud APIs 429 is a *semantic* answer (stockout/quota → the
# InsufficientCapacity lifecycle path), not throttling — never eat it in the
# transport; the kube apiserver's 429 IS throttling and stays retryable.
GCP_RETRYABLE_STATUS = RETRYABLE_STATUS - {429}


@dataclass
class TransportOptions:
    max_retries: int = 20          # armopts.go:36
    backoff_base: float = 5.0      # armopts.go:37 (exponential, seconds)
    backoff_cap: float = 60.0
    pool_connections: int = 100    # init_http_client.go:34
    timeout: float = 60.0
    user_agent: str = "tpu-provisioner"
    retryable_status: frozenset[int] = RETRYABLE_STATUS


def build_http_client(opts: TransportOptions | None = None,
                      verify=True, **kw) -> httpx.AsyncClient:
    opts = opts or TransportOptions()
    return httpx.AsyncClient(
        timeout=opts.timeout,
        limits=httpx.Limits(max_connections=opts.pool_connections,
                            max_keepalive_connections=opts.pool_connections),
        headers={"User-Agent": opts.user_agent},
        verify=verify, **kw)


async def request_with_retries(http: httpx.AsyncClient, method: str, url: str,
                               opts: TransportOptions | None = None,
                               **kw) -> httpx.Response:
    """Issue a request, retrying transient failures with capped exponential
    backoff. Any response that is not retryable — and the LAST response when
    the retry budget runs out — is returned as-is: the caller owns error
    taxonomy mapping (e.g. 429 → InsufficientCapacity must survive the
    transport). Only exhausted transport-level failures raise."""
    opts = opts or TransportOptions()
    last_exc: Exception | None = None
    last_resp: httpx.Response | None = None
    for attempt in range(opts.max_retries + 1):
        try:
            resp = await http.request(method, url, **kw)
        except (httpx.TransportError, httpx.TimeoutException) as e:
            last_exc, last_resp = e, None
        else:
            if resp.status_code not in opts.retryable_status:
                return resp
            last_resp = resp
        if attempt == opts.max_retries:
            break
        delay = min(opts.backoff_cap,
                    opts.backoff_base * (2 ** min(attempt, 6)))
        await asyncio.sleep(delay * (0.5 + random.random() / 2))
    if last_resp is not None:
        return last_resp
    raise last_exc  # type: ignore[misc]
