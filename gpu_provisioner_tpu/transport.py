"""Pooled, retrying HTTP transport shared by the kube and GCP REST clients.

The analog of the reference's ARM transport stack (pkg/utils/opts):
armbalancer pool of 100 connections (init_http_client.go:29-52) and a
20-retry / 5s-exponential-backoff policy (armopts.go:34-40). HTTP/1.1 here
(no h2 in this image); the pool limit is what matters for burst reconciles.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Optional

import httpx

RETRYABLE_STATUS = frozenset({408, 429, 500, 502, 503, 504})
# For cloud APIs 429 is a *semantic* answer (stockout/quota → the
# InsufficientCapacity lifecycle path), not throttling — never eat it in the
# transport; the kube apiserver's 429 IS throttling and stays retryable.
GCP_RETRYABLE_STATUS = RETRYABLE_STATUS - {429}

# Statuses that count against the circuit breaker: server-side failure, not
# semantic answers (4xx incl. 429 are the API *working* and saying no).
BREAKER_FAILURE_STATUS = frozenset({500, 502, 503, 504, 408})

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half-open"

# name → breaker, for metrics export (controllers/metrics.py reads this when
# /metrics is scraped). Re-creating a breaker under the same name replaces
# the entry — the newest client owns the gauge.
BREAKERS: dict[str, "CircuitBreaker"] = {}

# Open-transition listeners: ``fn(name, state=..., failures=...)`` fired
# whenever any breaker opens (fresh open or failed half-open probe). The
# flight recorder's breaker-trip trigger attaches here — transport sits
# BELOW runtime in the layering, so it cannot reach the runtime/probes
# seam; it carries its own tiny listener list instead, armed from outside
# (envtest / operator main) exactly like probes sinks. Listener errors are
# swallowed: observability must never fail a request path.
_breaker_listeners: list = []


def add_breaker_listener(fn) -> None:
    """Register ``fn(name, **info)`` for breaker open transitions
    (idempotent)."""
    if fn not in _breaker_listeners:
        _breaker_listeners.append(fn)


def remove_breaker_listener(fn) -> None:
    """Detach a listener; unknown listeners are a no-op."""
    try:
        _breaker_listeners.remove(fn)
    except ValueError:
        pass


def _notify_breaker_opened(breaker: "CircuitBreaker", state: str) -> None:
    for fn in list(_breaker_listeners):
        try:
            fn(breaker.name, state=state,
               failures=breaker.consecutive_failures,
               retry_after=round(breaker.retry_after(), 3))
        except Exception:  # noqa: BLE001 — listeners must not break I/O
            pass


# Throttle listeners: ``fn(name, retry_after=...)`` fired on every 429 the
# retry loop observes. Throttling is PACING, not failure — it never touches
# the breaker (see record_throttle) — but something above must slow down;
# the APIHealthGovernor's AIMD limit attaches here (armed from envtest /
# operator main, like the breaker-open seam).
_throttle_listeners: list = []


def add_throttle_listener(fn) -> None:
    """Register ``fn(name, retry_after=...)`` for 429 responses
    (idempotent)."""
    if fn not in _throttle_listeners:
        _throttle_listeners.append(fn)


def remove_throttle_listener(fn) -> None:
    try:
        _throttle_listeners.remove(fn)
    except ValueError:
        pass


def _notify_throttled(name: str, retry_after: float) -> None:
    for fn in list(_throttle_listeners):
        try:
            fn(name, retry_after=retry_after)
        except Exception:  # noqa: BLE001 — listeners must not break I/O
            pass


def parse_retry_after(resp) -> float:
    """Seconds from a Retry-After header; 0.0 when absent or unparseable
    (HTTP-date form included — honoring delta-seconds covers every real
    throttler we speak to, and a bad guess must never stall the loop)."""
    raw = resp.headers.get("Retry-After", "")
    try:
        return max(float(raw), 0.0)
    except ValueError:
        return 0.0


class BreakerOpenError(Exception):
    """The circuit breaker refused the call without touching the network.

    Carries ``retry_after`` (seconds until the next half-open probe) so
    callers can requeue with a sensible delay instead of busy-looping."""

    def __init__(self, name: str, retry_after: float):
        super().__init__(
            f"circuit breaker {name!r} is open; next probe in "
            f"{max(retry_after, 0):.1f}s")
        self.name = name
        self.retry_after = max(retry_after, 0.0)


class CircuitBreaker:
    """Consecutive-failure circuit breaker with half-open probing.

    Counts only server-side failures (5xx/408/transport errors). After
    ``failure_threshold`` consecutive failures the breaker opens: calls are
    rejected locally (``BreakerOpenError``) for ``reset_timeout`` seconds,
    then ONE probe is let through (half-open); its outcome closes or
    re-opens the breaker. Single-event-loop discipline: no awaits between
    check and mutate, so no lock is needed.
    """

    def __init__(self, name: str = "default", failure_threshold: int = 5,
                 reset_timeout: float = 30.0, clock=time.monotonic):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self._clock = clock
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probe_inflight = False
        self._probe_started = 0.0
        # observability (exported via controllers/metrics.py)
        self.rejected_total = 0
        self.opened_total = 0
        self.throttled_total = 0
        BREAKERS[name] = self

    @property
    def state(self) -> str:
        if self._opened_at is None:
            return BREAKER_CLOSED
        if self._clock() - self._opened_at >= self.reset_timeout:
            return BREAKER_HALF_OPEN
        return BREAKER_OPEN

    @property
    def consecutive_failures(self) -> int:
        return self._failures

    def retry_after(self) -> float:
        if self._opened_at is None:
            return 0.0
        return self.reset_timeout - (self._clock() - self._opened_at)

    def allow(self) -> bool:
        state = self.state
        if state == BREAKER_CLOSED:
            return True
        if state == BREAKER_HALF_OPEN:
            # One probe per window — but a probe whose outcome was never
            # recorded (caller cancelled mid-flight, process hiccup) must
            # not wedge the breaker half-open forever: after a full reset
            # window with no verdict, admit a fresh probe.
            stale = (self._probe_inflight
                     and self._clock() - self._probe_started >= self.reset_timeout)
            if not self._probe_inflight or stale:
                self._probe_inflight = True
                self._probe_started = self._clock()
                return True
        self.rejected_total += 1
        return False

    def release_probe(self) -> None:
        """The in-flight probe ended without an HTTP verdict (cancellation,
        unexpected exception): free the probe slot so the next caller can
        probe, without judging the endpoint either way."""
        self._probe_inflight = False

    def record_success(self) -> None:
        self._failures = 0
        self._opened_at = None
        self._probe_inflight = False

    def record_throttle(self) -> None:
        """A 429: the endpoint is alive and pacing us — NEUTRAL for the
        breaker. Before PR 16 throttled responses took the record_success
        path, which RESET the consecutive-failure count: a 5xx run
        interleaved with throttling could never open the breaker, masking
        a real outage behind the throttler. Now the count survives a 429
        untouched; only the half-open probe slot is released (a throttled
        probe proved the endpoint answers, but closing on it would slam a
        recovering server with the full call rate)."""
        self.throttled_total += 1
        self._probe_inflight = False

    def record_failure(self) -> None:
        self._failures += 1
        if self._probe_inflight:
            # failed probe: re-open for a fresh window
            self._opened_at = self._clock()
            self._probe_inflight = False
            _notify_breaker_opened(self, "reopened")
        elif (self._opened_at is None
                and self._failures >= self.failure_threshold):
            self._opened_at = self._clock()
            self.opened_total += 1
            _notify_breaker_opened(self, "opened")

    def unregister(self) -> None:
        """Drop this breaker from the metrics registry (client close): stale
        entries would keep exporting state no live client gates on."""
        if BREAKERS.get(self.name) is self:
            del BREAKERS[self.name]


@dataclass
class TransportOptions:
    max_retries: int = 20          # armopts.go:36
    backoff_base: float = 5.0      # armopts.go:37 (exponential, seconds)
    backoff_cap: float = 60.0
    pool_connections: int = 100    # init_http_client.go:34
    timeout: float = 60.0
    user_agent: str = "tpu-provisioner"
    retryable_status: frozenset[int] = RETRYABLE_STATUS
    breaker_threshold: int = 5     # consecutive 5xx/timeouts before opening
    breaker_reset: float = 30.0    # seconds open before a half-open probe


def build_http_client(opts: TransportOptions | None = None,
                      verify=True, **kw) -> httpx.AsyncClient:
    opts = opts or TransportOptions()
    return httpx.AsyncClient(
        timeout=opts.timeout,
        limits=httpx.Limits(max_connections=opts.pool_connections,
                            max_keepalive_connections=opts.pool_connections),
        headers={"User-Agent": opts.user_agent},
        verify=verify, **kw)


async def request_with_retries(http: httpx.AsyncClient, method: str, url: str,
                               opts: TransportOptions | None = None,
                               breaker: Optional[CircuitBreaker] = None,
                               **kw) -> httpx.Response:
    """Issue a request, retrying transient failures with capped exponential
    backoff. Any response that is not retryable — and the LAST response when
    the retry budget runs out — is returned as-is: the caller owns error
    taxonomy mapping (e.g. 429 → InsufficientCapacity must survive the
    transport). Only exhausted transport-level failures raise.

    With a ``breaker``, every attempt must pass it first: once consecutive
    5xx/timeouts open it, the retry loop stops hammering the endpoint and
    raises ``BreakerOpenError`` immediately — the caller requeues with
    backoff while the breaker's half-open probes watch for recovery. The
    breaker counts PER-ATTEMPT, so with a threshold below ``max_retries`` a
    sustained failure surfaces after ``breaker_threshold`` attempts rather
    than marathoning through the whole retry budget — deliberate: the
    workqueue's backoff owns the long wait, not a parked worker. Blips
    shorter than the threshold still heal in-loop (any success resets)."""
    opts = opts or TransportOptions()
    last_exc: Exception | None = None
    last_resp: httpx.Response | None = None
    for attempt in range(opts.max_retries + 1):
        if breaker is not None and not breaker.allow():
            raise BreakerOpenError(breaker.name, breaker.retry_after())
        try:
            resp = await http.request(method, url, **kw)
        except (httpx.TransportError, httpx.TimeoutException) as e:
            last_exc, last_resp = e, None
            if breaker is not None:
                breaker.record_failure()
        except BaseException:
            # No HTTP verdict (CancelledError from a reconcile deadline,
            # anything unexpected): don't judge the endpoint, but free the
            # half-open probe slot or the breaker wedges half-open forever.
            if breaker is not None:
                breaker.release_probe()
            raise
        else:
            retry_after = 0.0
            if resp.status_code == 429:
                # Throttling is pacing, not failure: neutral for the
                # breaker (consecutive 5xx counts survive), and the server
                # owns the delay via Retry-After. Fan out to the throttle
                # listeners so the APIHealthGovernor can shed load fleet-
                # wide instead of every caller rediscovering the limit.
                retry_after = parse_retry_after(resp)
                if breaker is not None:
                    breaker.record_throttle()
                if 429 in opts.retryable_status:
                    # only when this policy treats 429 AS throttling — for
                    # GCP clients (GCP_RETRYABLE_STATUS) a 429 is the
                    # semantic stockout answer and must not shed kube load
                    _notify_throttled(
                        breaker.name if breaker is not None else url,
                        retry_after)
            elif breaker is not None:
                if resp.status_code in BREAKER_FAILURE_STATUS:
                    breaker.record_failure()
                else:
                    breaker.record_success()
            if resp.status_code not in opts.retryable_status:
                return resp
            last_resp = resp
        if attempt == opts.max_retries:
            break
        delay = min(opts.backoff_cap,
                    opts.backoff_base * (2 ** min(attempt, 6)))
        delay *= 0.5 + random.random() / 2
        if last_resp is not None and last_resp.status_code == 429:
            # honor the server's Retry-After when it asks for MORE than our
            # backoff would wait; never less — pacing must not turn into
            # hammering just because the header was small
            delay = max(delay, parse_retry_after(last_resp))
        await asyncio.sleep(delay)
    if last_resp is not None:
        return last_resp
    raise last_exc  # type: ignore[misc]
