#!/bin/bash
# Round-5 capture watcher (committed for transparency — BENCH_NOTES_r05.md
# describes its role): probes the axon tunnel every ~3 min with a process
# that is NEVER timeout-killed (killing a backend-attached process is the
# documented remote-wedge trigger); on a successful probe it runs, from a
# snapshot of HEAD: hack/tpu_onchip_checks.py then the full bench.py,
# writing logs into the repo. A failed capture (nonzero rc) keeps the
# partial logs and loops back to probing — /tmp/capture_done marks only a
# FULLY-successful capture. Self-contained: the probe is written below.
cat > /tmp/tunnel_probe.py <<'PY'
import time
t0 = time.time()
print(f"probe start {t0}", flush=True)
import jax
devs = jax.devices()
print(f"probe ok {time.time()-t0:.1f}s devices={devs}", flush=True)
PY
while true; do
  python -u /tmp/tunnel_probe.py > /tmp/tunnel_probe_last.log 2>&1
  if grep -q "probe ok" /tmp/tunnel_probe_last.log; then
    echo "$(date -u +%H:%M:%S) tunnel ALIVE — capturing" >> /tmp/watcher.log
    rm -rf /tmp/capture_tree && mkdir -p /tmp/capture_tree
    git -C /root/repo archive HEAD | tar -x -C /tmp/capture_tree
    cd /tmp/capture_tree
    git -C /root/repo rev-parse HEAD > /root/repo/hack/capture_head_r05.txt
    python -u hack/tpu_onchip_checks.py > /root/repo/hack/tpu_onchip_checks_r05.log 2>&1
    rc1=$?   # capture BEFORE the $(date) substitution resets $?
    echo "$(date -u +%H:%M:%S) onchip checks rc=$rc1 done" >> /tmp/watcher.log
    python -u bench.py > /root/repo/bench_live_r05.log 2>&1
    rc2=$?
    echo "$(date -u +%H:%M:%S) bench rc=$rc2 done" >> /tmp/watcher.log
    cp bench_tpu_sections.jsonl.* /root/repo/hack/ 2>/dev/null
    if [ "$rc1" -eq 0 ] && [ "$rc2" -eq 0 ]; then
      touch /tmp/capture_done
      exit 0
    fi
    echo "$(date -u +%H:%M:%S) capture incomplete — partial logs kept, re-probing" >> /tmp/watcher.log
  else
    echo "$(date -u +%H:%M:%S) tunnel down ($(tail -1 /tmp/tunnel_probe_last.log | head -c 80))" >> /tmp/watcher.log
  fi
  sleep 180
done
