#!/usr/bin/env bash
# Render chart values from the active gcloud context — the analog of the
# reference's hack/deploy/configure-helm-values.sh, which envsubst-renders
# gpu-provisioner-values-template.yaml from `az` CLI output.
set -euo pipefail

PROJECT_ID="${PROJECT_ID:-$(gcloud config get-value project 2>/dev/null)}"
LOCATION="${LOCATION:-$(gcloud config get-value compute/zone 2>/dev/null)}"
CLUSTER_NAME="${CLUSTER_NAME:-$(gcloud config get-value container/cluster 2>/dev/null)}"
GSA_EMAIL="${GSA_EMAIL:-tpu-provisioner@${PROJECT_ID}.iam.gserviceaccount.com}"

for var in PROJECT_ID LOCATION CLUSTER_NAME; do
  if [ -z "${!var}" ]; then
    echo "error: $var is unset and not derivable from gcloud config" >&2
    exit 1
  fi
done

cat <<EOF
serviceAccount:
  annotations:
    iam.gke.io/gcp-service-account: ${GSA_EMAIL}
controller:
  env:
    - name: PROJECT_ID
      value: "${PROJECT_ID}"
    - name: LOCATION
      value: "${LOCATION}"
    - name: CLUSTER_NAME
      value: "${CLUSTER_NAME}"
    - name: DEPLOYMENT_MODE
      value: "managed"
    - name: LOG_LEVEL
      value: "info"
    - name: FEATURE_GATES
      value: "NodeRepair=true"
EOF
