"""On-chip value-level validation of every Pallas kernel path.

Interpret-mode tests (tests/test_ops.py) prove the algorithms on the CPU
mesh but CANNOT catch TPU lowering errors — the repo's documented gotcha
(ops/flash_attention.py: the rank-3 lse exists purely to satisfy a TPU
tiling rule that interpret mode never checks). This script runs the same
value comparisons as the interpret tests, but compiled for real TPU
silicon: resident/streaming/triangular forward + backward, the cache-aware
prefill kernel (fp and int8, static and traced start), and end-to-end
greedy generation flash-vs-dense.

Each check prints one JSON line {check, max_err, tol, ok}; the last line
is a summary {checks, passed, failed, platform}. Exit code 0 iff all pass.

Run: python hack/tpu_onchip_checks.py        (requires a live TPU)
Mirrors: tests/test_ops.py, tests/test_decode.py (interpret-mode twins).
"""

import dataclasses
import importlib
import json
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from gpu_provisioner_tpu.models.decode import (_cached_attention,
                                               _quantize_kv, generate)
from gpu_provisioner_tpu.models.llama import LlamaConfig, init_params
fa = importlib.import_module("gpu_provisioner_tpu.ops.flash_attention")
from gpu_provisioner_tpu.parallel.ring import dense_attention

# Both sides of every comparison run on the TPU, but the dense reference
# uses plain einsum (default precision → bf16 passes on the MXU) while the
# kernel accumulates fp32 via preferred_element_type; f32 tolerances are
# therefore MXU-pass-bounded, not interpret-mode 2e-5.
TOL_F32 = 2e-2
TOL_GRAD = 3e-2

RESULTS = []


def check(name, got, ref, tol):
    err = float(jnp.max(jnp.abs(jnp.asarray(got, jnp.float32)
                                - jnp.asarray(ref, jnp.float32))))
    ok = bool(err <= tol)
    RESULTS.append(ok)
    print(json.dumps({"check": name, "max_err": round(err, 6),
                      "tol": tol, "ok": ok}), flush=True)


def _qkv(B=2, S=512, Hq=4, Hkv=2, D=64, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    return (jax.random.normal(ks[0], (B, S, Hq, D)),
            jax.random.normal(ks[1], (B, S, Hkv, D)),
            jax.random.normal(ks[2], (B, S, Hkv, D)))


def run_forward_checks():
    for causal in (True, False):
        for Hkv in (4, 2, 1):
            q, k, v = _qkv(Hkv=Hkv)
            check(f"resident_fwd_causal={causal}_hkv={Hkv}",
                  fa.flash_attention(q, k, v, causal=causal),
                  dense_attention(q, k, v, causal=causal), TOL_F32)

    # windowed self-attention (training path): multi-block band grids
    q, k, v = _qkv(S=512)
    check("resident_fwd_window",
          fa.flash_attention(q, k, v, window=100, block_q=128,
                             block_k=128),
          dense_attention(q, k, v, window=100), TOL_F32)

    # streaming grid: force it by zeroing the residency budget
    saved = fa.RESIDENT_KV_BUDGET
    fa.RESIDENT_KV_BUDGET = 0
    try:
        for causal in (True, False):
            q, k, v = _qkv(S=1024)
            check(f"streaming_fwd_causal={causal}",
                  fa.flash_attention(q, k, v, causal=causal),
                  dense_attention(q, k, v, causal=causal), TOL_F32)
        q, k, v = _qkv(S=1024)
        check("triangular_fwd",
              fa.flash_attention(q, k, v, triangular=True),
              dense_attention(q, k, v), TOL_F32)
        check("streaming_fwd_window",
              fa.flash_attention(q, k, v, window=200, block_q=128,
                                 block_k=128),
              dense_attention(q, k, v, window=200), TOL_F32)
    finally:
        fa.RESIDENT_KV_BUDGET = saved


def run_backward_checks():
    def gpair(fn_a, fn_b, *args):
        ga = jax.grad(lambda *a: jnp.sum(fn_a(*a) ** 2),
                      argnums=(0, 1, 2))(*args)
        gb = jax.grad(lambda *a: jnp.sum(fn_b(*a) ** 2),
                      argnums=(0, 1, 2))(*args)
        return ga, gb

    for causal in (True, False):
        for Hkv in (2, 1):
            q, k, v = _qkv(B=1, S=256, Hq=2, Hkv=Hkv, D=64)
            ga, gb = gpair(
                lambda *a, c=causal: fa.flash_attention(*a, causal=c),
                lambda *a, c=causal: dense_attention(*a, causal=c), q, k, v)
            for nm, a, b in zip(("dq", "dk", "dv"), ga, gb):
                check(f"resident_bwd_{nm}_causal={causal}_hkv={Hkv}",
                      a, b, TOL_GRAD)

    # windowed backward (training path): band-pruned dQ/dKV kernels
    q, k, v = _qkv(B=1, S=512, Hq=2, Hkv=1, D=64)
    ga, gb = gpair(
        lambda *a: fa.flash_attention(*a, window=100, block_q=128,
                                      block_k=128),
        lambda *a: dense_attention(*a, window=100), q, k, v)
    for nm, a, b in zip(("dq", "dk", "dv"), ga, gb):
        check(f"windowed_bwd_{nm}", a, b, TOL_GRAD)

    saved = fa.RESIDENT_KV_BUDGET
    fa.RESIDENT_KV_BUDGET = 0
    try:
        q, k, v = _qkv(B=1, S=512, Hq=2, Hkv=1, D=64)
        ga, gb = gpair(fa.flash_attention, dense_attention, q, k, v)
        for nm, a, b in zip(("dq", "dk", "dv"), ga, gb):
            check(f"streaming_bwd_{nm}", a, b, TOL_GRAD)
        ga, gb = gpair(lambda *a: fa.flash_attention(*a, triangular=True),
                       dense_attention, q, k, v)
        for nm, a, b in zip(("dq", "dk", "dv"), ga, gb):
            check(f"triangular_bwd_{nm}", a, b, TOL_GRAD)
    finally:
        fa.RESIDENT_KV_BUDGET = saved


def run_cached_checks():
    B, S, ML, Hq, Hkv, D = 2, 128, 512, 4, 2, 64
    scale = D ** -0.5
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    for start in (0, 37, 384):
        s = jnp.asarray(start, jnp.int32)
        check(f"cached_fwd_start={start}",
              fa.flash_attention_cached(q, kc, vc, s, scale=scale),
              _cached_attention(q, kc, vc, s, scale), TOL_F32)
    # traced start under jit — the serving loop's shape
    f = jax.jit(lambda s: fa.flash_attention_cached(q, kc, vc, s,
                                                    scale=scale))
    s = jnp.asarray(65, jnp.int32)
    check("cached_fwd_traced_start",
          f(s), _cached_attention(q, kc, vc, s, scale), TOL_F32)

    # int8 mode: in-VMEM dequant vs the dense dequantizing sweep
    k_tm = jax.random.normal(ks[1], (B, ML, Hkv, D))
    v_tm = jax.random.normal(ks[2], (B, ML, Hkv, D))
    kq, kscl = _quantize_kv(k_tm)
    vq, vscl = _quantize_kv(v_tm)
    hm = lambda x: x.transpose(0, 2, 1, 3)
    s = jnp.asarray(130, jnp.int32)
    check("cached_fwd_int8",
          fa.flash_attention_cached(q, hm(kq), hm(vq), s, scale=scale,
                                    k_scale=hm(kscl), v_scale=hm(vscl)),
          _cached_attention(q, hm(kq), hm(vq), s, scale,
                            k_scale=hm(kscl), v_scale=hm(vscl)), TOL_F32)

    # padded prefill (ragged serving): real query rows only — pad-query
    # rows are unread garbage that differs between impls by design
    pad = jnp.asarray([0, 37], jnp.int32)
    s = jnp.asarray(256, jnp.int32)
    outp = fa.flash_attention_cached(q, kc, vc, s, scale=scale,
                                     pad_lens=pad)
    refp = _cached_attention(q, kc, vc, s, scale, pad_lens=pad)
    check("cached_fwd_padded", outp, refp, TOL_F32)   # all rows real @256

    # sliding-window serving (window masks + lower-bound DMA clamps)
    s = jnp.asarray(320, jnp.int32)
    check("cached_fwd_window",
          fa.flash_attention_cached(q, kc, vc, s, scale=scale, window=100),
          _cached_attention(q, kc, vc, s, scale, window=100), TOL_F32)
    check("cached_fwd_window_sinks",
          fa.flash_attention_cached(q, kc, vc, s, scale=scale, window=100,
                                    sinks=4),
          _cached_attention(q, kc, vc, s, scale, window=100, sinks=4),
          TOL_F32)
    padws = jnp.asarray([0, 17], jnp.int32)
    check("cached_fwd_window_sinks_padded",
          fa.flash_attention_cached(q, kc, vc, s, scale=scale, window=100,
                                    sinks=4, pad_lens=padws),
          _cached_attention(q, kc, vc, s, scale, window=100, sinks=4,
                            pad_lens=padws), TOL_F32)

    # decode-step kernel (S=1, per-kv-head grid, O(start) DMA)
    q1 = jax.random.normal(ks[0], (B, 1, Hq, D))
    for start in (0, 130, 384):
        s = jnp.asarray(start, jnp.int32)
        check(f"decode_fwd_start={start}",
              fa.flash_attention_decode(q1, kc, vc, s, scale=scale),
              _cached_attention(q1, kc, vc, s, scale), TOL_F32)
    pad = jnp.asarray([0, 37], jnp.int32)
    s = jnp.asarray(384, jnp.int32)
    check("decode_fwd_padded",
          fa.flash_attention_decode(q1, kc, vc, s, scale=scale,
                                    pad_lens=pad),
          _cached_attention(q1, kc, vc, s, scale, pad_lens=pad), TOL_F32)
    check("decode_fwd_int8",
          fa.flash_attention_decode(q1, hm(kq), hm(vq), s, scale=scale,
                                    k_scale=hm(kscl), v_scale=hm(vscl)),
          _cached_attention(q1, hm(kq), hm(vq), s, scale,
                            k_scale=hm(kscl), v_scale=hm(vscl)), TOL_F32)
    check("decode_fwd_window",
          fa.flash_attention_decode(q1, kc, vc, s, scale=scale, window=100),
          _cached_attention(q1, kc, vc, s, scale, window=100), TOL_F32)
    check("decode_fwd_window_sinks",
          fa.flash_attention_decode(q1, kc, vc, s, scale=scale, window=100,
                                    sinks=4),
          _cached_attention(q1, kc, vc, s, scale, window=100, sinks=4),
          TOL_F32)
    check("decode_fwd_window_sinks_padded",
          fa.flash_attention_decode(q1, kc, vc, s, scale=scale, window=100,
                                    sinks=4, pad_lens=pad),
          _cached_attention(q1, kc, vc, s, scale, window=100, sinks=4,
                            pad_lens=pad), TOL_F32)

    # per-row starts (batched speculative decoding): row b's DMA stops at
    # its OWN live prefix; reference = each row computed alone
    starts = jnp.asarray([37, 384], jnp.int32)
    ref = jnp.concatenate([
        _cached_attention(q1[b:b + 1], kc[b:b + 1], vc[b:b + 1],
                          starts[b], scale) for b in range(B)])
    check("decode_fwd_per_row_starts",
          fa.flash_attention_decode(q1, kc, vc, starts, scale=scale),
          ref, TOL_F32)

    # short query blocks S>1 (the speculative VERIFY kernel): per-query
    # causal frontier inside one cache fetch
    q4 = jax.random.normal(ks[0], (B, 4, Hq, D))
    s = jnp.asarray(300, jnp.int32)
    check("verify_fwd_s4",
          fa.flash_attention_decode(q4, kc, vc, s, scale=scale),
          _cached_attention(q4, kc, vc, s, scale), TOL_F32)
    ref = jnp.concatenate([
        _cached_attention(q4[b:b + 1], kc[b:b + 1], vc[b:b + 1],
                          starts[b], scale) for b in range(B)])
    check("verify_fwd_s4_per_row_starts",
          fa.flash_attention_decode(q4, kc, vc, starts, scale=scale),
          ref, TOL_F32)
    check("verify_fwd_s4_window_sinks_padded",
          fa.flash_attention_decode(q4, kc, vc, s, scale=scale, window=100,
                                    sinks=4, pad_lens=pad),
          _cached_attention(q4, kc, vc, s, scale, window=100, sinks=4,
                            pad_lens=pad), TOL_F32)


def run_generate_check():
    """End-to-end greedy generation: flash serving config must emit the
    exact token stream of the dense config on silicon."""
    cfg_d = LlamaConfig(vocab_size=256, dim=256, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=512, max_seq_len=1024,
                        dtype="float32", attn_impl="dense")
    cfg_f = dataclasses.replace(cfg_d, attn_impl="flash")
    params = init_params(jax.random.key(7), cfg_d)
    prompt = jax.random.randint(jax.random.key(8), (2, 128), 0, 256)
    toks_d = generate(params, prompt, cfg_d, max_new_tokens=16)
    toks_f = generate(params, prompt, cfg_f, max_new_tokens=16)
    same = bool(jnp.all(toks_d == toks_f))
    RESULTS.append(same)
    print(json.dumps({"check": "generate_greedy_flash_vs_dense",
                      "tokens_equal": same, "ok": same}), flush=True)

    # batched speculative decoding on silicon: per-row cache lengths +
    # per-row-start decode kernel + dropless verify — stream must equal
    # plain greedy's, row for row
    from gpu_provisioner_tpu.models.speculative import speculative_generate
    toks_s, _ = speculative_generate(params, params, prompt, cfg_f, cfg_f,
                                     max_new_tokens=16, spec_k=3,
                                     max_len=1024)
    same = bool(jnp.all(toks_s == toks_f))
    RESULTS.append(same)
    print(json.dumps({"check": "speculative_batched_greedy_vs_plain",
                      "tokens_equal": same, "ok": same}), flush=True)


def run_lowering_checks():
    """Production-shape bf16 lowering pass (moved from the staged pod suite
    — single-chip-runnable, VERDICT r4 item 7): every Pallas kernel variant
    at serving/training shapes (D=128, bf16), including the S=16384
    streaming grids, plus the triangular-grid VALUE sign-off against the
    rectangular grid (the gate for the keep/delete decision on the
    triangular variants)."""
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (1, 1024, 4, 128), jnp.bfloat16)
               for kk in ks)
    f32 = lambda x: jnp.asarray(x, jnp.float32)

    def finite(name, *xs):
        ok = all(bool(jnp.all(jnp.isfinite(f32(leaf))))
                 for x in xs for leaf in jax.tree.leaves(x))
        RESULTS.append(ok)
        print(json.dumps({"check": name, "finite": ok, "ok": ok}),
              flush=True)

    finite("lower_resident_fwd_bf16", fa.flash_attention(q, k, v))
    g = jax.grad(lambda *a: jnp.sum(fa.flash_attention(*a)
                                    .astype(jnp.float32) ** 2),
                 argnums=(0, 1, 2))(q, k, v)
    finite("lower_resident_bwd_bf16", g)
    kc = jax.random.normal(ks[1], (1, 2, 2048, 128), jnp.bfloat16)
    vc = jax.random.normal(ks[2], (1, 2, 2048, 128), jnp.bfloat16)
    finite("lower_cached_bf16",
           fa.flash_attention_cached(q[:, :128], kc, vc,
                                     jnp.asarray(17, jnp.int32)))
    kc8, vc8 = (kc * 31).astype(jnp.int8), (vc * 31).astype(jnp.int8)
    scl = jnp.full((1, 2, 2048, 1), 1 / 31.0, jnp.float32)
    finite("lower_cached_int8",
           fa.flash_attention_cached(q[:, :128], kc8, vc8,
                                     jnp.asarray(17, jnp.int32),
                                     k_scale=scl, v_scale=scl))
    # streaming S=16384 (exceeds the residency budget) — rectangular AND
    # triangular grids, forward and backward, then the value sign-off
    qs, ks_, vs = (jnp.tile(x, (1, 16, 1, 1)) for x in (q, k, v))
    stream = fa.flash_attention(qs, ks_, vs)
    tri = fa.flash_attention(qs, ks_, vs, triangular=True)
    finite("lower_streaming_16k_bf16", stream)
    finite("lower_streaming_tri_16k_bf16", tri)
    check("tri_vs_rect_fwd_16k", tri, stream, 2e-2)
    g_rect = jax.grad(lambda *a: jnp.sum(fa.flash_attention(*a)
                                         .astype(jnp.float32) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
    g_tri = jax.grad(lambda *a: jnp.sum(
        fa.flash_attention(*a, triangular=True).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for nm, a, b in zip(("dq", "dk", "dv"), g_tri, g_rect):
        check(f"tri_vs_rect_bwd_{nm}", a, b, 2e-2)


def main():
    platform = jax.devices()[0].platform
    print(json.dumps({"platform": platform,
                      "device": str(jax.devices()[0])}), flush=True)
    run_forward_checks()
    run_backward_checks()
    run_cached_checks()
    run_generate_check()
    run_lowering_checks()
    summary = {"checks": len(RESULTS), "passed": sum(RESULTS),
               "failed": len(RESULTS) - sum(RESULTS), "platform": platform}
    print(json.dumps(summary), flush=True)
    return 0 if all(RESULTS) else 1


if __name__ == "__main__":
    sys.exit(main())
