"""PL001 true positives: blocking calls inside async defs."""
import time
import urllib.request


async def reconcile():
    time.sleep(1)                                  # BAD: blocks the loop


async def fetch():
    return urllib.request.urlopen("http://x")      # BAD: sync HTTP


async def read_config():
    with open("/etc/config") as f:                 # BAD: sync file I/O
        return f.read()
