"""PL001 true negatives: async seams, and blocking calls in sync defs."""
import asyncio
import time


async def reconcile():
    await asyncio.sleep(1)


async def read_config():
    return await asyncio.to_thread(_read)


def _read():
    time.sleep(0.01)        # sync helper: out of PL001's async-body scope
    with open("/etc/config") as f:
        return f.read()
