"""PL002 true positives: swallowed cancellation / crash injection."""
import asyncio


async def swallow_cancel():
    try:
        await asyncio.sleep(1)
    except asyncio.CancelledError:      # BAD: eats the shutdown signal
        return None


def swallow_everything():
    try:
        return 1
    except BaseException:               # BAD: eats SimulatedCrash too
        return None


def swallow_crash(chaos):
    try:
        chaos.hit("point")
    except (ValueError, SystemExit):    # BAD: SystemExit never re-raised
        pass
