"""PL002 true negatives: re-raise, the task-reap idiom, narrow excepts."""
import asyncio


async def isolate_and_reraise():
    try:
        await asyncio.sleep(1)
    except asyncio.CancelledError:
        raise                               # propagates
    except Exception:                       # cannot catch CancelledError
        return None


async def reap_cancelled_task(task):
    task.cancel()
    try:
        await task                          # the TASK's own cancellation
    except asyncio.CancelledError:
        pass


def reraise_base():
    try:
        return 1
    except BaseException:
        raise
