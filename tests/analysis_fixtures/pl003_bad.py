"""PL003 true positives: cloud mutations with no preceding fence check."""


class Provider:
    async def create(self, pool):
        return await self.nodepools.begin_create(pool)      # BAD: unfenced

    async def delete(self, name):
        await self.queued.delete(name)                      # BAD: unfenced
        return await self.nodepools.begin_delete(name)      # BAD: unfenced
