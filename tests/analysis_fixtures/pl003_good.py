"""PL003 true negatives: fence check precedes every cloud mutation."""


class Provider:
    def _fence_check(self):
        if self.fence is not None:
            self.fence.check()

    async def create(self, pool):
        self._fence_check()
        return await self.nodepools.begin_create(pool)

    async def delete(self, name):
        self.fence.check()
        await self.queued.delete(name)
        return await self.nodepools.begin_delete(name)

    async def read_only(self, name):
        return await self.nodepools.get(name)   # reads need no fence
