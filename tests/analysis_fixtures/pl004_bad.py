"""PL004 true positives: naked wall clocks in a controller."""
import time
from dataclasses import dataclass, field
from datetime import datetime, timezone


async def reconcile():
    started = time.monotonic()                  # BAD
    stamp = datetime.now(timezone.utc)          # BAD
    return started, stamp, time.time()          # BAD


@dataclass
class Entry:
    at: float = field(default_factory=time.monotonic)   # BAD: bare reference
