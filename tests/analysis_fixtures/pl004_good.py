"""PL004 true negatives: the injected clock seams."""
import asyncio


async def reconcile(serde_now, loop_now):
    mono = asyncio.get_event_loop().time()
    wall = serde_now()
    return mono, wall, loop_now()
