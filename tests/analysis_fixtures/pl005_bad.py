"""PL005 true positives: metric registration inside functions."""
from prometheus_client import Counter, Gauge


def register_counter():
    return Counter("x_total", "doc", ["label"])     # BAD


async def reconcile():
    Gauge("depth", "doc", []).set(1)                # BAD: per-reconcile
