"""PL005 true negatives: module-scope registration; mutation in functions."""
from prometheus_client import Counter

REQUESTS = Counter("x_total", "doc", ["label"])


async def reconcile():
    REQUESTS.labels("a").inc()      # mutating an existing collector is fine
