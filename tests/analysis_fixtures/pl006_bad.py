"""PL006 true positive: await while holding a non-async lock."""
import asyncio
import threading

_lock = threading.Lock()


async def critical():
    with _lock:                     # sync lock …
        await asyncio.sleep(0.1)    # BAD: … held across a suspension point
