"""PL006 true negatives: async lock, or sync lock with no await inside."""
import asyncio
import threading

_alock = asyncio.Lock()
_slock = threading.Lock()


async def critical():
    async with _alock:
        await asyncio.sleep(0.1)


def sync_critical(shared):
    with _slock:
        shared.append(1)
