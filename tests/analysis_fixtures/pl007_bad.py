"""PL007 true positives: fire-and-forget background tasks."""
import asyncio


async def fire_and_forget(work):
    asyncio.ensure_future(work())           # BAD: handle discarded


async def assign_and_drop(work):
    t = asyncio.create_task(work())         # BAD: never referenced again
    return None
