"""PL007 true negatives: retained / tracked / reaped task handles."""
import asyncio


class Component:
    def start(self, work):
        self._task = asyncio.create_task(work())    # retained on self


async def tracked(work, registry: set):
    t = asyncio.create_task(work())
    registry.add(t)
    t.add_done_callback(registry.discard)


async def awaited(work):
    t = asyncio.ensure_future(work())
    return await t
