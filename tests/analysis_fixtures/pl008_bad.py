"""PL008 true positives: mutable default arguments."""


def build(labels={}, taints=[]):            # BAD ×2
    return labels, taints


async def reconcile(*, seen=set(), extra=dict()):   # BAD ×2
    return seen, extra
