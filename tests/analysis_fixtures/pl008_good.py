"""PL008 true negatives: None defaults materialized inside."""


def build(labels=None, taints=None):
    return dict(labels or {}), list(taints or [])


async def reconcile(*, seen=None, retries=3, name=""):
    return seen if seen is not None else set(), retries, name
