"""PL009 true positives: ungated crash seams in control-plane layers."""
from ..chaos.crash import SimulatedCrash            # BAD in this layer


class Provider:
    async def create(self, pool):
        self.crashes.hit("after_begin_create", pool.name)   # BAD: no gate
        return pool
