"""PL009 true negative: the None-gated _crash helper idiom."""


class Provider:
    def __init__(self, crashes=None):
        self.crashes = crashes      # chaos.CrashPoints; None in production

    def _crash(self, point, key):
        if self.crashes is not None:
            self.crashes.hit(point, key)

    async def create(self, pool):
        self._crash("after_begin_create", pool.name)
        return pool
