"""PL010 true positive: deadline-free sleep polling in a test."""
import asyncio


async def test_converges(env):
    while True:                         # BAD: no deadline anywhere
        if env.done:
            break
        await asyncio.sleep(0.01)
