"""PL010 true negatives: deadline-bounded polls."""
import asyncio


async def test_converges(env):
    deadline = asyncio.get_event_loop().time() + 10.0
    while True:
        if env.done:
            break
        assert asyncio.get_event_loop().time() < deadline, "never converged"
        await asyncio.sleep(0.01)


async def test_bounded_laps(env):
    for _ in range(100):
        await asyncio.sleep(0.01)
        if env.done:
            break
