"""PL011 true positive: marker not registered in pyproject.toml."""
import pytest


@pytest.mark.totally_unregistered_marker        # BAD
def test_something():
    assert True
