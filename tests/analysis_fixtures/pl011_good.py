"""PL011 true negatives: registered and builtin markers."""
import pytest


@pytest.mark.chaos                      # registered in pyproject.toml
@pytest.mark.parametrize("x", [1, 2])   # pytest builtin
def test_something(x):
    assert x


@pytest.mark.skipif(True, reason="builtin")
def test_skipped():
    assert True
