"""PL012 true positives: span_begin with no finally-guaranteed span_end."""


async def reconcile_bare(tracer, name):
    token = tracer.span_begin(name, "reconcile")   # BAD: nothing closes it
    result = await do_work(name)
    tracer.span_end(token)                         # skipped if do_work raises
    return result


async def reconcile_except_only(tracer, name):
    token = tracer.span_begin(name, "reconcile")   # BAD: except is not finally
    try:
        return await do_work(name)
    except Exception:
        tracer.span_end(token)
        raise


async def do_work(name):
    return name
