"""PL012 true negatives: context-manager and try/finally span closure."""

import contextlib


async def reconcile_with_cm(tracer, name):
    # the shape real code uses: tracer.span() closes in its own finally
    with tracer.span(name, "reconcile"):
        return await do_work(name)


async def reconcile_manual_pair(tracer, name):
    token = tracer.span_begin(name, "reconcile")
    try:
        return await do_work(name)
    finally:
        tracer.span_end(token)


@contextlib.contextmanager
def span(tracer, name):
    # the tracer's own context-manager shape: begin BEFORE the try,
    # end in the finally — function-scoped guarantee
    token = tracer.span_begin(name, "reconcile")
    try:
        yield token
    finally:
        tracer.span_end(token)


async def do_work(name):
    return name
