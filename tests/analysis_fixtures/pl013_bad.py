"""PL013 true positives: CreateError reasons spelled as string literals."""

from gpu_provisioner_tpu.errors import CreateError


def launch(pool):
    if pool is None:
        raise CreateError("pool vanished mid-create", "CreateInProgress")
    if pool.status == "ERROR":
        raise CreateError("pool landed ERROR", reason="DegradedPool")
    return pool


def classify(e):
    if e.reason == "Stockout":
        return "terminal"
    return "retry"
