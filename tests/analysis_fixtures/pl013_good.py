"""PL013 true negatives: reasons come from the central enum; reason-ish
strings OUTSIDE the CreateError vocabulary stay legal."""

from gpu_provisioner_tpu.errors import (
    CreateError, REASON_DEGRADED_POOL, REASON_STOCKOUT, reason_is_terminal,
)


def launch(pool):
    if pool is None:
        raise CreateError("capacity exhausted", reason=REASON_STOCKOUT)
    if pool.status == "ERROR":
        raise CreateError("pool landed ERROR", REASON_DEGRADED_POOL)
    return pool


def classify(e, diag):
    if reason_is_terminal(e.reason):
        return "terminal"
    # a repair diagnosis reason is a node condition TYPE, not a CreateError
    # reason — comparing it to a non-enum literal is not a finding
    if diag.reason == "SpotPreempted":
        return "repair"
    return "retry"
