"""PL014 true positives: requeue_after waits with no declared wake source."""

from gpu_provisioner_tpu.runtime.controller import Result


class Reconciler:
    async def reconcile(self, req):
        if self.launching(req):
            # an in-progress wait parked on a bare timer: nothing says what
            # event is supposed to arrive before the deadline fires
            return Result(requeue_after=5.0)
        return Result()

    async def drain(self, node):
        if not node.drained:
            return Result(requeue_after=self.opts.requeue)
        return Result()
