"""PL014 true negatives: annotated waits, in-function WakeHub arming, and
the requeue_after=None / plain-Result shapes that are not waits at all."""

from gpu_provisioner_tpu.runtime.controller import Result


class Reconciler:
    async def reconcile(self, req):
        if self.launching(req):
            # wakes: lro — tracker completion via the WakeHub
            return Result(requeue_after=5.0)
        return Result()

    async def parked(self, req, remaining):
        # the function itself arms the hub: the timer is the safety net
        self.wakehub.wake_after(req.name, remaining, "stockout")
        return Result(requeue_after=remaining * 2)

    async def aggregate(self, requeues):
        # wakes: aggregate — min of the sub-reconcilers' annotated waits
        return Result(requeue_after=min(requeues) if requeues else None)

    async def done(self, req):
        return Result(requeue_after=None)
