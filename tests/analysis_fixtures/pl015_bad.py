"""PL015 true positives: watch/list pumps whose broad error handlers never
classify expired-resourceVersion — a 410 Gone falls into the generic retry
path and the pump reconnects forever against compacted history."""

import asyncio
import logging

log = logging.getLogger("fixture")


class Pump:
    async def _run(self):
        while True:
            watch = self.client.watch(self.cls)
            try:
                while True:
                    event = await watch.__anext__()
                    self._apply(event)
            except Exception:
                # swallows 410 Gone into the same one-second reconnect as
                # any transient error: the cache silently diverges
                log.warning("watch failed, reconnecting")
                await asyncio.sleep(1.0)

    async def relist_loop(self):
        while True:
            try:
                objs = await self.client.list(self.cls)
                self._replace(objs)
            except ClientError:
                # a stale-resourceVersion list error needs a fresh relist
                # from "" — retrying the same RV can never succeed
                continue


class ClientError(Exception):
    pass
