"""PL015 abstentions: pumps that classify 410 distinctly, pumps with no
broad handler, and watch-shaped names that never touch a watch surface."""

import asyncio
import logging

log = logging.getLogger("fixture")


class ResourceExpiredError(Exception):
    pass


class Pump:
    async def _run(self):
        # classifies the gap: typed except arm ahead of the broad one
        while True:
            watch = self.client.watch(self.cls)
            try:
                while True:
                    event = await watch.__anext__()
                    self._apply(event)
            except ResourceExpiredError:
                await self._resync()
            except Exception:
                log.warning("watch failed, reconnecting")
                await asyncio.sleep(1.0)

    async def provider_pump(self):
        # classifies via the provider errors' typed predicate
        while True:
            try:
                pages = await self.api.list_pages()
                self._replace(pages)
            except Exception as e:
                if getattr(e, "expired", False):
                    self._page_token = None
                continue

    async def _resync(self):
        # touches list but has NO except handler: the caller owns the
        # retry ladder (the informer _resync shape) — nothing to classify
        objs = await self.client.list(self.cls)
        self._replace(objs)

    async def _run_ticker(self):
        # pump-shaped name, but never touches a watch/list surface
        # (providers/operations.py `_run` shape)
        while True:
            try:
                self._tick()
            except Exception:
                log.warning("tick failed")
            await asyncio.sleep(0.05)
