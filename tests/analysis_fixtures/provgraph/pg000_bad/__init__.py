"""Fixture package."""
