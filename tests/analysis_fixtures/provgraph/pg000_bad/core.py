"""TP: malformed waivers — missing reason, unknown rule."""
A = 1  # provgraph: disable=PG001
B = 2  # provgraph: disable=PG999 — no such rule
