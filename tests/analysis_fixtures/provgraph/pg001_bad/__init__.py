"""Fixture package."""
