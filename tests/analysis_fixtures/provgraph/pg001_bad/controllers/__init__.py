"""Fixture subpackage."""
