"""Import target for the runtime-layer violation."""
