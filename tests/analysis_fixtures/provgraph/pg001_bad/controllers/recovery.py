"""TP: a controller importing a cloud-specific provider module."""
from ..providers.gcp import NP_ERROR  # noqa: F401  (PG001: cloud-specific)
