"""Fixture subpackage."""
