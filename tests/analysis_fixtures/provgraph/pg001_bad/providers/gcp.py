"""Cloud-specific module (import target)."""
NP_ERROR = "ERROR"
