"""TP: the provider layer importing the control loops above it."""
from ..controllers import loops  # noqa: F401  (PG001: providers -> controllers)
