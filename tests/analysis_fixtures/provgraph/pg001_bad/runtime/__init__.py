"""Fixture subpackage."""
