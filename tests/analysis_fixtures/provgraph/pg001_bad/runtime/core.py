"""TP: runtime importing a layer above itself."""
from ..controllers import loops  # noqa: F401  (PG001: runtime -> controllers)
