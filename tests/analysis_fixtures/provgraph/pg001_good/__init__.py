"""Fixture package."""
