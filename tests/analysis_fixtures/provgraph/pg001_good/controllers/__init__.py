"""Fixture subpackage."""
