"""TN: controllers import the cloud-NEUTRAL provider seam."""
from ..providers import instance  # noqa: F401
