"""Fixture subpackage."""
