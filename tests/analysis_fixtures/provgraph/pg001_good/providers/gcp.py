"""Cloud-specific module nobody above the seam imports."""
