"""TN: providers import runtime (downward edge)."""
from ..runtime import client  # noqa: F401
