"""Fixture subpackage."""
