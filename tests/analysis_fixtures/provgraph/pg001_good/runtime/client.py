"""Runtime-internal import target."""
