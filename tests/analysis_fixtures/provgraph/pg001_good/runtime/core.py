"""TN: runtime imports only its own layer."""
from . import client  # noqa: F401
