"""Fixture package."""
