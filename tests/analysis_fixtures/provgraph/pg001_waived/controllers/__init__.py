"""Fixture subpackage."""
