"""Same violation as pg001_bad, carrying a reasoned waiver."""
# provgraph: disable=PG001 — fixture mirror of the real recovery scan:
# seam extraction is the ROADMAP item-4 refactor, tracked there
from ..providers.gcp import NP_ERROR  # noqa: F401
