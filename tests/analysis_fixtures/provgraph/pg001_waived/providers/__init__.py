"""Fixture subpackage."""
