"""Fixture subpackage."""
