"""Fixture package."""
