"""Fixture subpackage."""
