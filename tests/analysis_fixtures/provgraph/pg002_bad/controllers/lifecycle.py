"""TP: a declared wake edge no producer in the package ever fires."""


async def reconcile(result):
    return result(requeue_after=5.0)  # wakes: lro
