"""Fixture package."""
