"""Fixture subpackage."""
