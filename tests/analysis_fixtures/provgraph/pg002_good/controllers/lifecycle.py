"""TN: both declared wake edges have producers elsewhere in the package."""


async def reconcile(result):
    return result(requeue_after=5.0)  # wakes: lro


async def registration(result):
    return result(requeue_after=1.0)  # wakes: node
