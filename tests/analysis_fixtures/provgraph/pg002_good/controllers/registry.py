"""Producers: a literal-source hub wake and a SOURCE_* watch."""
from ..runtime.wakehub import SOURCE_NODE


async def on_complete(hub, name):
    await hub.wake(name, "lro")


def build(mgr, node_claim_map):
    mgr.watches(object, map_fn=node_claim_map, wake_source=SOURCE_NODE)
