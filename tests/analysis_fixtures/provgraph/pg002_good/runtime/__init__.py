"""Fixture subpackage."""
