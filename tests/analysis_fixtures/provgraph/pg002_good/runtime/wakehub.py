"""Source-constant vocabulary for the fixture."""
SOURCE_NODE = "node"
