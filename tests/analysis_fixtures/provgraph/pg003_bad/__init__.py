"""Fixture package."""
