"""Fixture subpackage."""
