"""TP: a caller reaches a cloud mutation through an unfenced helper.

The helper's own (direct, unfenced) mutation is PL003's jurisdiction; the
PG003 finding is the CALL in launch(), which holds no fence either."""


class Provider:
    async def _do_create(self, pool):
        await self.api.begin_create(pool)

    async def launch(self, pool):
        await self._do_create(pool)
