"""Fixture package."""
