"""Fixture subpackage."""
