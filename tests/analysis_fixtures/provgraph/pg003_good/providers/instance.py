"""TN: both fence disciplines — helper self-fenced, caller-held fence."""


class Provider:
    async def _do_create(self, pool):
        self._fence_check()
        await self.api.begin_create(pool)

    async def launch(self, pool):
        await self._do_create(pool)


class Queued:
    async def _submit(self, qr):
        await self.queued.create(qr)

    async def ensure(self, qr):
        self._fence_check()
        await self._submit(qr)
