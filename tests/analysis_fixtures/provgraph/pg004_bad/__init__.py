"""Fixture package."""
