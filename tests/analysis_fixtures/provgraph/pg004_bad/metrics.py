"""TP both directions: registered-but-undocumented + documented ghost."""
GHOST = "tpu_provisioner_ghost_total"
