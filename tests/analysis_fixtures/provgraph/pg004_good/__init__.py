"""Fixture package."""
