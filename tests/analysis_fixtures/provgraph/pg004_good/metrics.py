"""TN: every registered family documented, brace shorthands included."""
HITS = "tpu_provisioner_cache_hits"
MISSES = "tpu_provisioner_cache_misses"
WAKES = "tpu_provisioner_wakes_total"
