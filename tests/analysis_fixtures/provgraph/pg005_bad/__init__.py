"""Fixture package: PG005 shard-isolation violation."""
