"""Fixture subpackage."""
