"""TP: a controller reaching into the shard IPC seam for live state."""
from ..runtime import shardipc  # noqa: F401  (PG005: outside the seam)
