"""Fixture subpackage."""
