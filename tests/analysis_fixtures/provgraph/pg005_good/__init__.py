"""Fixture package: legal shard-seam wiring only."""
