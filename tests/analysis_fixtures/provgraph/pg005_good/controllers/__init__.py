"""Fixture subpackage."""
