"""TN: the /metrics scrape is a sanctioned read-only snapshot consumer."""
from ..runtime import shardipc  # noqa: F401  (allowed: seam reader)
