"""Fixture subpackage."""
