"""TN: the composition root wires the seam together (seam member)."""
from ..runtime import shardipc  # noqa: F401  (allowed: inside the seam)
