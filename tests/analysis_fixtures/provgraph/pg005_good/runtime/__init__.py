"""Fixture subpackage."""
