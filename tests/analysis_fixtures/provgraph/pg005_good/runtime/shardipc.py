"""Stand-in for the shard IPC transport (seam member)."""

SERVERS = set()
