"""Waiver-syntax corpus: valid waivers suppress, malformed ones are
themselves findings (PL000)."""


def trailing_waiver(items=[]):  # provlint: disable=mutable-default — fixture: shared sentinel is intended here
    return items


# provlint: disable=mutable-default — fixture: comment-only waiver covers
# the next code line, wrapped reason and all
def comment_waiver(items=[]):
    return items


def missing_reason(items=[]):  # provlint: disable=mutable-default
    return items


def unknown_rule(items=[]):  # provlint: disable=no-such-rule — some reason
    return items
