"""Test bootstrap.

JAX-touching tests run on a virtual 8-device CPU mesh (the reference tests
distributed behavior without a cluster via fakes — SURVEY.md §4.2; here the
sharding path additionally gets real multi-device execution on host CPU).
The env vars must be set before the first ``import jax`` anywhere.
"""

import os
import sys

# TPU_POD_TESTS=1 opts out of the CPU forcing so tests/test_tpu_pod.py can
# drive real multi-chip hardware (staged — no such hardware in this env).
ON_TPU_POD = os.environ.get("TPU_POD_TESTS") == "1"

if not ON_TPU_POD:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Site hooks (axon register) may override jax_platforms at interpreter start,
# which silently ignores the env var above — force the config directly.
# The axon wrapper also initializes EVERY registered backend on first
# jax.devices() call even under jax_platforms=cpu, so a wedged TPU tunnel
# would hang the whole suite — drop the non-CPU factories outright; these
# tests only ever use the forced-host CPU mesh.
if not ON_TPU_POD:
    try:
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
        from gpu_provisioner_tpu.parallel.topology import (
            drop_foreign_backend_factories as _drop)
        _drop()
    except ImportError:
        pass

import asyncio
import functools

import pytest


def pytest_collection_modifyitems(config, items):
    """TPU_POD_TESTS=1 disables the CPU-platform forcing above, which would
    strip the wedged-tunnel hang protection from every other test — so in
    that mode ONLY the pod file runs; everything else is deselected."""
    if not ON_TPU_POD:
        return
    keep = [i for i in items if "test_tpu_pod" in str(i.fspath)]
    drop = [i for i in items if "test_tpu_pod" not in str(i.fspath)]
    if drop:
        config.hook.pytest_deselected(items=drop)
        items[:] = keep


def async_test(fn, timeout: float = 60):
    """Run an async test function to completion (no pytest-asyncio here)."""

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        return asyncio.run(asyncio.wait_for(fn(*args, **kwargs), timeout=timeout))

    return wrapper


def async_test_long(fn):
    """e2e wrapper: subprocess + HTTP + generous Eventually timeouts."""
    return async_test(fn, timeout=300)


@pytest.fixture
def anyio_backend():
    return "asyncio"


def pytest_configure(config):
    config.addinivalue_line("markers", "e2e: end-to-end specs (operator subprocess)")
