"""HTTP fakes for the e2e suite: a kube apiserver and a GCP endpoint.

The reference's e2e tier runs against a real AKS cluster (SURVEY.md §4.3);
this harness gets the same black-box property on a laptop: the REAL operator
process speaks REAL HTTP to (a) an apiserver facade over runtime.Store —
which already implements resourceVersion conflicts, finalizer-gated deletes
and watch streams — and (b) a GCP facade over fake.FakeCloud, which
materializes Node objects into that same store when node pools come up,
exactly as GKE's kubelets would. Against a live GKE cluster the same specs
run by pointing Environment at the production endpoints instead.
"""

from __future__ import annotations

import asyncio
import itertools
import json
from typing import Optional

from aiohttp import web

from gpu_provisioner_tpu.apis.meta import Object, kind_for
from gpu_provisioner_tpu.providers.gcp import APIError, NodePool, QueuedResource
from gpu_provisioner_tpu.fake.cloud import FakeCloud
from gpu_provisioner_tpu.runtime import InMemoryClient
from gpu_provisioner_tpu.runtime.store import (StoreAlreadyExists,
                                               StoreConflict, StoreNotFound)

# plural → Kind for every kind the controllers touch; reverse of
# runtime.rest.resource_path's pluralization.
def _cls_for(plural: str) -> type:
    return kind_for({
        "nodeclaims": "NodeClaim", "nodes": "Node", "pods": "Pod",
        "volumeattachments": "VolumeAttachment", "events": "Event",
        "kaitonodeclasses": "KaitoNodeClass", "leases": "Lease",
        "poddisruptionbudgets": "PodDisruptionBudget",
    }[plural])


class FakeKubeAPIServer:
    """Apiserver facade over runtime.Store (shared with the fake cloud)."""

    def __init__(self, client: Optional[InMemoryClient] = None):
        self.client = client or InMemoryClient()
        self.store = self.client.store
        self.app = web.Application()
        for base in ("/api/v1", "/apis/{group}/{version}"):
            self.app.router.add_route("*", base + "/{plural}", self._collection)
            self.app.router.add_route("*", base + "/{plural}/{name}", self._item)
            self.app.router.add_route(
                "PUT", base + "/{plural}/{name}/status", self._status)
            self.app.router.add_route(
                "*", base + "/namespaces/{ns}/{plural}", self._collection)
            self.app.router.add_route(
                "*", base + "/namespaces/{ns}/{plural}/{name}", self._item)
            self.app.router.add_route(
                "PUT", base + "/namespaces/{ns}/{plural}/{name}/status",
                self._status)
            self.app.router.add_route(
                "POST", base + "/namespaces/{ns}/{plural}/{name}/eviction",
                self._evict)
        self.runner: Optional[web.AppRunner] = None
        self.port = 0
        self.list_counts: dict[str, int] = {}

    async def start(self) -> str:
        self.runner = web.AppRunner(self.app, shutdown_timeout=1.0)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    async def stop(self) -> None:
        if self.runner:
            await self.runner.cleanup()

    # --- helpers -----------------------------------------------------------

    def _parse(self, req: web.Request) -> tuple[type, str, str]:
        try:
            cls = _cls_for(req.match_info["plural"])
        except KeyError:
            raise web.HTTPNotFound(text=f"unknown resource "
                                        f"{req.match_info['plural']!r}"
                                   ) from None
        return (cls, req.match_info.get("ns", ""),
                req.match_info.get("name", ""))

    @staticmethod
    def _json(obj: Object, status: int = 200) -> web.Response:
        return web.json_response(obj.to_dict(), status=status)

    # --- routes ------------------------------------------------------------

    async def _collection(self, req: web.Request) -> web.StreamResponse:
        cls, ns, _ = self._parse(req)
        if req.method == "POST":
            obj = cls.from_dict(await req.json())
            try:
                created = self.store.create(obj)
            except StoreAlreadyExists as e:
                return web.Response(status=409, text=str(e))
            return self._json(created, 201)
        if req.method != "GET":
            return web.Response(status=405)
        if req.query.get("watch") == "true":
            return await self._watch(req, cls)
        # LIST-load accounting: e2e asserts informer-backed reads keep the
        # steady-state full-list rate near zero (one count per page walk,
        # not per page, so pagination doesn't inflate it)
        if "continue" not in req.query:
            self.list_counts[cls.KIND] = self.list_counts.get(cls.KIND, 0) + 1
        labels = None
        sel = req.query.get("labelSelector", "")
        if sel:
            labels = dict(p.split("=", 1) for p in sel.split(","))
        items = self.store.list(cls, labels, ns or None)
        # limit/continue pagination with a KEYSET cursor (last ns/name seen),
        # not a positional index: concurrent deletes shift positions and a
        # positional cursor silently skips survivors — fatal when the skipped
        # object's ADDED event is the only thing that would ever reconcile it.
        items.sort(key=lambda o: (o.metadata.namespace, o.metadata.name))
        meta = {"resourceVersion": str(self.store.current_rv())
                if hasattr(self.store, "current_rv") else "0"}
        limit = int(req.query.get("limit", "0") or 0)
        cont = req.query.get("continue", "")
        if cont:
            cns, _, cname = cont.partition("\x00")
            items = [o for o in items
                     if (o.metadata.namespace, o.metadata.name) > (cns, cname)]
        if limit and len(items) > limit:
            last = items[limit - 1]
            meta["continue"] = f"{last.metadata.namespace}\x00{last.metadata.name}"
            items = items[:limit]
        return web.json_response({
            "kind": f"{cls.KIND}List",
            "items": [o.to_dict() for o in items],
            "metadata": meta})

    async def _watch(self, req: web.Request, cls: type) -> web.StreamResponse:
        resp = web.StreamResponse()
        resp.content_type = "application/json"
        await resp.prepare(req)
        # Register the live queue FIRST, then snapshot the backlog — both
        # before any await — so an object created between the client's LIST
        # (which handed it this resourceVersion) and this registration is
        # replayed rather than lost. Ignoring the rv here was a real found
        # bug: a claim created in that gap never reconciled (ListAndWatch
        # has no periodic resync to recover it).
        q = self.store.watch(cls, initial_list=False)
        backlog = []
        rv_param = req.query.get("resourceVersion", "")
        if rv_param:
            try:
                since = int(rv_param)
            except ValueError:
                since = 0
            for o in self.store.list(cls):
                try:
                    orv = int(o.metadata.resource_version or "0")
                except ValueError:
                    orv = 0
                if orv > since:
                    backlog.append(o)
        try:
            for o in backlog:  # duplicates are fine — level-triggered clients
                line = json.dumps({"type": "ADDED",
                                   "object": o.to_dict()}) + "\n"
                await resp.write(line.encode())
            while True:
                try:
                    ev = await asyncio.wait_for(q.get(), timeout=0.5)
                except asyncio.TimeoutError:
                    # q.get() would otherwise block past a silent peer
                    # disconnect and hang server shutdown for its full grace
                    if req.transport is None or req.transport.is_closing():
                        break
                    continue
                line = json.dumps({"type": ev.type,
                                   "object": ev.object.to_dict()}) + "\n"
                await resp.write(line.encode())
        # provlint: disable=cancellation-swallow — peer disconnect mid-write
        # is this streaming handler's normal exit; aiohttp owns the handler
        # task and reaps it — finishing the response beats re-raising here
        except (ConnectionResetError, asyncio.CancelledError):
            pass
        finally:
            self.store.unwatch(cls, q)
        return resp

    async def _item(self, req: web.Request) -> web.Response:
        cls, ns, name = self._parse(req)
        try:
            if req.method == "GET":
                return self._json(self.store.get(cls, name, ns))
            if req.method == "PUT":
                return self._json(self.store.update(cls.from_dict(await req.json())))
            if req.method == "DELETE":
                self.store.delete(cls, name, ns)
                return web.json_response({"status": "Success"})
        except StoreNotFound as e:
            return web.Response(status=404, text=str(e))
        except StoreConflict as e:
            return web.Response(status=409, text=str(e))
        return web.Response(status=405)

    async def _evict(self, req: web.Request) -> web.Response:
        """Eviction subresource with real apiserver semantics: 429 when a
        matching PodDisruptionBudget has no disruptions left, 409 on a uid
        precondition mismatch, 404 when the pod is gone, 201 on success."""
        from gpu_provisioner_tpu.apis.core import Pod, PodDisruptionBudget
        cls, ns, name = self._parse(req)
        try:
            body = await req.json()
        except Exception:  # noqa: BLE001 — empty body is legal
            body = {}
        want_uid = (body.get("deleteOptions") or {}).get(
            "preconditions", {}).get("uid", "")
        try:
            pod = self.store.get(Pod, name, ns)
            if want_uid and pod.metadata.uid != want_uid:
                return web.Response(
                    status=409,
                    text=f"precondition failed: uid {want_uid} != "
                         f"{pod.metadata.uid}")
            pods = self.store.list(Pod, namespace=ns)
            for pdb in self.store.list(PodDisruptionBudget, namespace=ns):
                if (pdb.spec.selector.matches(pod.metadata.labels)
                        and pdb.disruptions_allowed(pods) <= 0):
                    return web.Response(
                        status=429,
                        text=f"Cannot evict pod as it would violate the pod's "
                             f"disruption budget {pdb.metadata.name}")
            self.store.delete(cls, name, ns)
        except StoreNotFound as e:
            return web.Response(status=404, text=str(e))
        return web.json_response({"status": "Success"}, status=201)

    async def _status(self, req: web.Request) -> web.Response:
        cls, ns, name = self._parse(req)
        try:
            return self._json(self.store.update_status(cls.from_dict(await req.json())))
        except StoreNotFound as e:
            return web.Response(status=404, text=str(e))
        except StoreConflict as e:
            return web.Response(status=409, text=str(e))


class FakeGCPServer:
    """GKE + Cloud TPU facade over fake.FakeCloud (container/v1 + tpu/v2
    wire shapes, matching providers/rest.py's translation)."""

    def __init__(self, cloud: FakeCloud):
        self.cloud = cloud
        self.ops: dict[str, object] = {}
        self._op_ids = itertools.count(1)
        self.app = web.Application()
        r = self.app.router
        npp = "/v1/projects/{p}/locations/{l}/clusters/{c}/nodePools"
        r.add_route("POST", npp, self._np_create)
        r.add_route("GET", npp, self._np_list)
        r.add_route("GET", npp + "/{name}", self._np_get)
        r.add_route("DELETE", npp + "/{name}", self._np_delete)
        r.add_route("GET", "/v1/projects/{p}/locations/{l}/operations/{op}",
                    self._op_get)
        qrp = "/v2/projects/{p}/locations/{l}/queuedResources"
        r.add_route("POST", qrp, self._qr_create)
        r.add_route("GET", qrp, self._qr_list)
        r.add_route("GET", qrp + "/{name}", self._qr_get)
        r.add_route("DELETE", qrp + "/{name}", self._qr_delete)
        self.runner: Optional[web.AppRunner] = None
        self.port = 0

    async def start(self) -> str:
        self.runner = web.AppRunner(self.app, shutdown_timeout=1.0)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"

    async def stop(self) -> None:
        if self.runner:
            await self.runner.cleanup()

    # --- node pools --------------------------------------------------------

    @staticmethod
    def _api_error(e: APIError) -> web.Response:
        return web.Response(status=e.code, text=str(e))

    def _track(self, op) -> dict:
        op_id = f"operation-{next(self._op_ids)}"
        self.ops[op_id] = op
        return {"name": op_id, "status": "RUNNING"}

    async def _np_create(self, req: web.Request) -> web.Response:
        wire = (await req.json())["nodePool"]
        cfg = wire.get("config", {})
        ra = cfg.get("reservationAffinity", {})
        pool = NodePool.from_dict({
            "name": wire["name"],
            "initialNodeCount": wire.get("initialNodeCount", 1),
            "config": {
                "machineType": cfg.get("machineType", ""),
                "diskSizeGb": cfg.get("diskSizeGb", 0),
                "labels": cfg.get("labels", {}),
                "taints": cfg.get("taints", []),
                "spot": cfg.get("spot", False),
                "imageType": cfg.get("imageType", ""),
                "reservation": (ra.get("values") or [""])[0]},
            "placementPolicy": (
                {"type": wire["placementPolicy"].get("type", "COMPACT"),
                 "tpuTopology": wire["placementPolicy"].get("tpuTopology", "")}
                if "placementPolicy" in wire else None)})
        try:
            op = await self.cloud.nodepools.begin_create(pool)
        except APIError as e:
            return self._api_error(e)
        return web.json_response(self._track(op))

    def _np_wire(self, p: NodePool) -> dict:
        d = {"name": p.name, "status": p.status,
             "statusMessage": p.status_message,
             "initialNodeCount": p.initial_node_count,
             "config": {"machineType": p.config.machine_type,
                        "diskSizeGb": p.config.disk_size_gb,
                        "labels": p.config.labels,
                        "taints": p.config.taints,
                        "spot": p.config.spot,
                        "imageType": p.config.image_type}}
        if p.config.reservation:
            d["config"]["reservationAffinity"] = {
                "consumeReservationType": "SPECIFIC_RESERVATION",
                "key": "compute.googleapis.com/reservation-name",
                "values": [p.config.reservation]}
        if p.placement_policy:
            d["placementPolicy"] = {"type": p.placement_policy.type,
                                    "tpuTopology": p.placement_policy.tpu_topology}
        return d

    async def _np_get(self, req: web.Request) -> web.Response:
        try:
            pool = await self.cloud.nodepools.get(req.match_info["name"])
        except APIError as e:
            return self._api_error(e)
        return web.json_response(self._np_wire(pool))

    async def _np_delete(self, req: web.Request) -> web.Response:
        try:
            op = await self.cloud.nodepools.begin_delete(req.match_info["name"])
        except APIError as e:
            return self._api_error(e)
        return web.json_response(self._track(op))

    async def _np_list(self, req: web.Request) -> web.Response:
        pools = await self.cloud.nodepools.list()
        return web.json_response({"nodePools": [self._np_wire(p) for p in pools]})

    async def _op_get(self, req: web.Request) -> web.Response:
        op = self.ops.get(req.match_info["op"])
        if op is None:
            return web.Response(status=404, text="operation not found")
        if not await op.done():
            return web.json_response({"name": req.match_info["op"],
                                      "status": "RUNNING"})
        body = {"name": req.match_info["op"], "status": "DONE"}
        try:
            await op.result()
        except APIError as e:
            # real container/v1 Operation.error is a google.rpc.Status
            body["error"] = {"code": {429: 8, 404: 5, 409: 6}.get(e.code, 13),
                             "message": str(e)}
        return web.json_response(body)

    # --- queued resources --------------------------------------------------

    def _qr_wire(self, qr: QueuedResource) -> dict:
        node = {"acceleratorType": qr.accelerator_type,
                "runtimeVersion": qr.runtime_version}
        if qr.spot:
            node["schedulingConfig"] = {"spot": True}
        wire = {"name": f"queuedResources/{qr.name}",
                "tpu": {"nodeSpec": [{"nodeId": qr.node_pool, "node": node}]},
                "state": {"state": qr.state}}
        if qr.reservation:
            wire["reservationName"] = qr.reservation
        return wire

    async def _qr_create(self, req: web.Request) -> web.Response:
        wire = await req.json()
        spec = (wire.get("tpu", {}).get("nodeSpec") or [{}])[0]
        node = spec.get("node", {})
        qr = QueuedResource(
            name=req.query["queuedResourceId"],
            accelerator_type=node.get("acceleratorType", ""),
            runtime_version=node.get("runtimeVersion", ""),
            node_pool=spec.get("nodeId", ""),
            reservation=wire.get("reservationName", ""),
            spot=bool(node.get("schedulingConfig", {}).get("spot", False)))
        try:
            await self.cloud.queuedresources.create(qr)
        except APIError as e:
            return self._api_error(e)
        return web.json_response({"name": "operations/qr-create"})

    async def _qr_get(self, req: web.Request) -> web.Response:
        try:
            qr = await self.cloud.queuedresources.get(req.match_info["name"])
        except APIError as e:
            return self._api_error(e)
        return web.json_response(self._qr_wire(qr))

    async def _qr_delete(self, req: web.Request) -> web.Response:
        try:
            await self.cloud.queuedresources.delete(req.match_info["name"])
        except APIError as e:
            return self._api_error(e)
        return web.json_response({})

    async def _qr_list(self, req: web.Request) -> web.Response:
        qrs = await self.cloud.queuedresources.list()
        return web.json_response({"queuedResources": [self._qr_wire(q) for q in qrs]})
