"""e2e Environment: the same specs against HTTP fakes or a real cluster.

The analog of the reference harness's Environment + Monitor + expectations
(test/e2e/pkg/environment/common/environment.go:56-88, monitor.go:32-100,
expectation.go:45-415): spins up the apiserver/GCP facades, launches the
operator as a SUBPROCESS (black box — real flags, env, HTTP, signals), and
exposes an expectation surface with Eventually semantics plus controller log
dump on failure (expectation.go:375's printControllerLogs analog).

``E2E_TARGET=real`` retargets the suite at a live cluster, mirroring the
reference's real-AKS mode (suite_test.go:34-45): the kube client comes from
``KUBECONFIG`` (token, client-cert, or exec-plugin auth — a stock
``gcloud container clusters get-credentials`` kubeconfig works), node-pool
assertions go through the production GKE client (PROJECT_ID / LOCATION /
CLUSTER_NAME env, ADC credentials), the operator is expected to already be
deployed (helm chart), and teardown deletes every NodeClaim carrying the
test DISCOVERY_LABEL in parallel (setup.go:58-89 analog). Specs that poke
fake-cloud seams (fault injection, direct store access) are marked
``fake_only`` and skip on the real target.
"""

from __future__ import annotations

import asyncio
import os
import socket
import sys
import time
from typing import Optional

import httpx
import pytest
import yaml

from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.core import Node
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import CONDITION_READY
from gpu_provisioner_tpu.fake.cloud import FakeCloud
from gpu_provisioner_tpu.runtime import InMemoryClient
from gpu_provisioner_tpu.runtime.client import NotFoundError
from gpu_provisioner_tpu.runtime.rest import KubeConnection, RestClient
from gpu_provisioner_tpu.transport import TransportOptions

from .backends import FakeGCPServer, FakeKubeAPIServer

E2E_TARGET = os.environ.get("E2E_TARGET", "fake")
IS_REAL = E2E_TARGET == "real"

fake_only = pytest.mark.skipif(
    IS_REAL, reason="drives fake-cloud seams (fault injection, direct store "
                    "access, operator subprocess) with no real-cluster analog")

# The reference defaults Eventually to 10 min on real AKS
# (environment.go:67); the fake cloud answers in ms, but specs share a loaded
# CI box with JAX compiles — generous timeouts keep them deterministic.
DEFAULT_TIMEOUT = float(os.environ.get("E2E_TIMEOUT_SECONDS",
                                       "600" if IS_REAL else "90"))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def connect_real(env: Optional[dict] = None,
                       kubeconfig: Optional[str] = None):
    """The real-target connection path: kube client from a kubeconfig
    (token, client-cert, or exec-plugin auth), production GKE client from
    PROJECT_ID/LOCATION/CLUSTER_NAME (+ optional endpoint override), and
    the CRD-served readiness gate. Shared by E2E_TARGET=real and the local
    conformance suite (test_real_conformance.py), which points it at the
    fake apiserver/GCP facade so these branches run on every push instead
    of staying dead until someone has GKE credentials."""
    from gpu_provisioner_tpu.auth.config import build_config
    from gpu_provisioner_tpu.auth.credentials import new_credential
    from gpu_provisioner_tpu.providers import rest as gcprest

    client = RestClient(KubeConnection.from_kubeconfig(kubeconfig))
    cfg = build_config(env)
    nodepools = gcprest.GKENodePoolsClient(
        new_credential(cfg), cfg.project_id, cfg.location, cfg.cluster_name,
        endpoint=cfg.gke_api_endpoint or gcprest.GKE_ENDPOINT)
    # readiness gate: apiserver reachable + NodeClaim CRD served (the
    # reference's readyz checks CRD presence, operator.go:207-224)
    await client.list(NodeClaim)
    return client, nodepools


async def discovery_teardown(client, eventually,
                             timeout: float = DEFAULT_TIMEOUT) -> None:
    """Delete every test-labeled object in parallel and wait for the
    controllers to unwind the claims (setup.go:58-89's 50-worker cleanup)."""
    from gpu_provisioner_tpu.apis.kaito import KaitoNodeClass

    selector = {wk.DISCOVERY_LABEL: wk.DISCOVERY_VALUE}

    async def _delete(cls: type, name: str) -> None:
        try:
            await client.delete(cls, name)
        except NotFoundError:
            pass

    deletes = [(NodeClaim, c.metadata.name)
               for c in await client.list(NodeClaim, labels=selector)]
    deletes += [(KaitoNodeClass, k.metadata.name)
                for k in await client.list(KaitoNodeClass, labels=selector)]
    await asyncio.gather(*(_delete(cls, name) for cls, name in deletes))

    async def all_gone():
        left = await client.list(NodeClaim, labels=selector)
        return not left or None
    await eventually(all_gone, timeout=timeout, what="e2e NodeClaims cleaned up")


class Environment:
    def __init__(self, tmp_path, *, gc_interval: float = 1.0,
                 leak_grace: float = 1.0, extra_env: Optional[dict] = None,
                 cloud_kwargs: Optional[dict] = None):
        self.tmp_path = tmp_path
        self.gc_interval = gc_interval
        self.leak_grace = leak_grace
        self.extra_env = extra_env or {}
        self.cloud_kwargs = cloud_kwargs or {}
        self.real = IS_REAL
        self.proc: Optional[asyncio.subprocess.Process] = None
        self.client: Optional[RestClient] = None
        self.nodepools = None  # node-pool assertion seam, both targets
        self._log_task = None
        self.logs: list[str] = []
        self._extra: list[tuple] = []   # (proc, pump) of extra replicas
        if self.real:
            return
        self.backing = InMemoryClient()
        self.cloud = FakeCloud(self.backing, create_latency=0.1,
                               delete_latency=0.05, node_ready_delay=0.05,
                               **self.cloud_kwargs)
        self.kube_server = FakeKubeAPIServer(self.backing)
        self.gcp_server = FakeGCPServer(self.cloud)
        self.health_port = _free_port()
        self.metrics_port = _free_port()

    async def __aenter__(self) -> "Environment":
        if self.real:
            return await self._enter_real()
        kube_url = await self.kube_server.start()
        gcp_url = await self.gcp_server.start()
        self.kube_url, self.gcp_url = kube_url, gcp_url

        kubeconfig = self.tmp_path / "kubeconfig"
        kubeconfig.write_text(yaml.safe_dump({
            "current-context": "e2e",
            "contexts": [{"name": "e2e",
                          "context": {"cluster": "e2e", "user": "e2e"}}],
            "clusters": [{"name": "e2e", "cluster": {"server": kube_url}}],
            "users": [{"name": "e2e", "user": {"token": "e2e-token"}}],
        }))

        self.proc = await self.spawn_operator()
        self._log_task = asyncio.create_task(
            self._pump_logs(self.proc))

        self.client = RestClient(
            KubeConnection(server=kube_url, token="e2e-token"),
            transport=TransportOptions(max_retries=3, backoff_base=0.05,
                                       backoff_cap=0.2))
        self.nodepools = self.cloud.nodepools
        await self._await_ready()
        return self

    def subprocess_env(self, *, metrics_port: Optional[int] = None,
                       health_port: Optional[int] = None,
                       extra: Optional[dict] = None) -> dict:
        """The operator-subprocess environment — ONE home for every
        setting so the primary operator and any extra replica a spec
        launches (e.g. shard peers) can never drift onto different
        timing configs."""
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        return {**os.environ,
                # The operator is control-plane only — never imports jax.
                # Site hooks (axon sitecustomize) preload jax + a PJRT
                # plugin into every interpreter when this var is set,
                # which added seconds of startup and caused
                # readiness-timeout flakes when specs shared the box with
                # JAX-compiling tests.
                "PALLAS_AXON_POOL_IPS": "",
                "PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
                "KUBECONFIG": str(self.tmp_path / "kubeconfig"),
                "KUBERNETES_SERVICE_HOST": "",   # force kubeconfig path
                "PROJECT_ID": "test-project", "LOCATION": "us-central2-b",
                "CLUSTER_NAME": "kaito",
                "E2E_TEST_MODE": "true", "E2E_STATIC_TOKEN": "e2e-token",
                "GKE_API_ENDPOINT": f"{self.gcp_url}/v1",
                "TPU_API_ENDPOINT": f"{self.gcp_url}/v2",
                "METRICS_PORT": str(metrics_port or self.metrics_port),
                "HEALTH_PROBE_PORT": str(health_port or self.health_port),
                "GC_INTERVAL_SECONDS": str(self.gc_interval),
                "GC_LEAK_GRACE_SECONDS": str(self.leak_grace),
                "TERMINATION_REQUEUE_SECONDS": "0.2",
                "INSTANCE_REQUEUE_SECONDS": "0.2",
                "LOG_LEVEL": "debug",
                **self.extra_env,
                **(extra or {})}

    async def spawn_operator(self, extra: Optional[dict] = None):
        """Launch an operator subprocess against this Environment's fakes.
        With ``extra`` (e.g. a shard peer's SHARD_INDEX) the replica gets
        its own ports and its logs pump into self.logs tagged by index —
        an undrained debug-level pipe would otherwise fill and block the
        child. Extra replicas are torn down in __aexit__."""
        if extra is None:
            env = self.subprocess_env()
        else:
            env = self.subprocess_env(metrics_port=_free_port(),
                                      health_port=_free_port(),
                                      extra=extra)
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "gpu_provisioner_tpu.operator", env=env,
            stdout=asyncio.subprocess.PIPE, stderr=asyncio.subprocess.STDOUT)
        if extra is not None:
            tag = f"[replica{len(self._extra)}] "
            self._extra.append(
                (proc, asyncio.create_task(self._pump_logs(proc, tag))))
        return proc

    async def _enter_real(self) -> "Environment":
        """Target a live cluster: kubeconfig client + production GKE client;
        the operator must already be running in-cluster (helm chart)."""
        self.client, self.nodepools = await connect_real()
        return self

    async def _cleanup_real(self) -> None:
        await discovery_teardown(self.client, self.eventually,
                                 DEFAULT_TIMEOUT)

    async def _pump_logs(self, proc, tag: str = "") -> None:
        assert proc and proc.stdout
        async for line in proc.stdout:
            self.logs.append(tag + line.decode(errors="replace").rstrip())

    async def _await_ready(self) -> None:
        async with httpx.AsyncClient() as http:
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                if self.proc.returncode is not None:
                    self.dump_logs()
                    raise RuntimeError(
                        f"operator exited rc={self.proc.returncode}")
                try:
                    r = await http.get(
                        f"http://127.0.0.1:{self.health_port}/readyz")
                    if r.status_code == 200:
                        return
                except httpx.TransportError:
                    pass
                await asyncio.sleep(0.1)
        self.dump_logs()
        raise TimeoutError("operator /readyz never became 200")

    async def __aexit__(self, *exc) -> None:
        if self.real:
            try:
                await self._cleanup_real()
            finally:
                if self.client:
                    await self.client.aclose()
                if self.nodepools is not None:
                    await self.nodepools.aclose()
            return
        procs = [p for p, _ in [(self.proc, self._log_task)] + self._extra
                 if p and p.returncode is None]
        for proc in procs:          # signal everyone first, then reap
            proc.terminate()        # concurrently (10s total, not per proc)

        async def _reap(proc):
            try:
                await asyncio.wait_for(proc.wait(), 10)
            except asyncio.TimeoutError:
                proc.kill()
                await proc.wait()
        await asyncio.gather(*(_reap(p) for p in procs))
        for _proc, pump in self._extra:
            pump.cancel()
        if self._log_task:
            self._log_task.cancel()
        if self.client:
            await self.client.aclose()
        await self.gcp_server.stop()
        await self.kube_server.stop()
        if exc and exc[0] is not None:
            self.dump_logs()

    def dump_logs(self) -> None:
        if self.real:
            return  # operator logs live in the cluster (kubectl logs)
        print("\n--- operator logs " + "-" * 50)
        for line in self.logs[-200:]:
            print(line)
        print("--- end operator logs " + "-" * 46)

    # --- expectations ------------------------------------------------------

    async def eventually(self, predicate, timeout: float = DEFAULT_TIMEOUT,
                         what: str = "condition"):
        """Poll an async predicate until truthy (Gomega Eventually analog).
        Returns the predicate's value."""
        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            last = await predicate()
            if last:
                return last
            await asyncio.sleep(0.1)
        self.dump_logs()
        raise TimeoutError(f"{what} not met within {timeout}s (last={last!r})")

    async def expect_nodeclaim_ready(self, name: str,
                                     timeout: float = DEFAULT_TIMEOUT) -> NodeClaim:
        async def check():
            try:
                nc = await self.client.get(NodeClaim, name)
            except NotFoundError:
                return None
            return nc if nc.status_conditions.is_true(CONDITION_READY) else None

        return await self.eventually(check, timeout,
                                     f"NodeClaim {name} Ready")

    async def kaito_pools(self) -> list:
        """Kaito-owned node pools only (agentPoolIsOwnedByKaito analog,
        reference instance.go:387-400) — a real cluster also has system
        pools."""
        return [p for p in await self.nodepools.list()
                if (p.config.labels or {}).get(wk.NODEPOOL_LABEL)
                == wk.KAITO_NODEPOOL_NAME]

    async def _managed_nodes(self) -> list[Node]:
        """Provisioner-managed nodes only — a real cluster also has system
        pools the specs must not count (the reference scopes its Monitor the
        same way via its nodepool labels)."""
        return [n for n in await self.client.list(Node)
                if wk.TPU_SLICE_ID_LABEL in n.metadata.labels]

    async def expect_node_count(self, n: int,
                                timeout: float = DEFAULT_TIMEOUT) -> list[Node]:
        async def check():
            nodes = await self._managed_nodes()
            # `or True` so expecting zero nodes doesn't return a falsy []
            return (nodes or True) if len(nodes) == n else None

        result = await self.eventually(check, timeout, f"{n} nodes")
        return result if result is not True else []

    async def expect_gone(self, cls: type, name: str, namespace: str = "",
                          timeout: float = DEFAULT_TIMEOUT) -> None:
        async def check():
            try:
                await self.client.get(cls, name, namespace)
                return None
            except NotFoundError:
                return True

        await self.eventually(check, timeout, f"{cls.KIND} {name} gone")


class Monitor:
    """Counts created/deleted nodes vs a reset point (monitor.go:32-100)."""

    def __init__(self, env: Environment):
        self.env = env
        self._baseline: set[str] = set()
        self._seen: set[str] = set()

    async def reset(self) -> None:
        self._baseline = {n.metadata.name
                          for n in await self.env._managed_nodes()}
        self._seen = set(self._baseline)

    async def _observe(self) -> set[str]:
        names = {n.metadata.name
                 for n in await self.env._managed_nodes()}
        self._seen |= names
        return names

    async def created_count(self) -> int:
        await self._observe()
        return len(self._seen - self._baseline)

    async def deleted_count(self) -> int:
        """Nodes observed since reset() that are now gone — counting requires
        having polled (e.g. via created_count) while they existed."""
        current = await self._observe()
        return len(self._seen - current)
