"""e2e smoke: the real operator process provisions and tears down a slice.

First two of the reference suite's specs (suite_test.go:49 provision via
workspace label, :183 teardown via NodeClaim delete) run against the HTTP
fakes; the full 8-spec suite lives in test_suite.py. Marked e2e — slower
than unit tests (subprocess + HTTP + real timers).
"""

import pytest

from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.core import Node
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.fake import make_nodeclaim

from ..conftest import async_test_long as async_test
from .env import Environment, Monitor

pytestmark = pytest.mark.e2e


@async_test
async def test_provision_and_teardown_multihost(tmp_path):
    async with Environment(tmp_path) as env:
        mon = Monitor(env)
        await mon.reset()

        # multi-host: v5p-32 = 4 hosts (BASELINE.json north star shape)
        await env.client.create(make_nodeclaim("ws0", "tpu-v5p-32"))
        nc = await env.expect_nodeclaim_ready("ws0")
        assert nc.status.provider_id
        assert nc.metadata.labels[wk.TPU_TOPOLOGY_LABEL] == "2x2x4"

        nodes = await env.expect_node_count(4)
        indices = sorted(n.metadata.labels[wk.TPU_WORKER_INDEX_LABEL]
                         for n in nodes)
        assert indices == ["0", "1", "2", "3"]
        assert await mon.created_count() == 4

        # teardown via NodeClaim delete (suite_test.go:183): finalizer drains
        # nodes, deletes the node pool, then the claim disappears
        await env.client.delete(NodeClaim, "ws0")
        await env.expect_gone(NodeClaim, "ws0")
        await env.expect_node_count(0)
        assert await mon.deleted_count() == 4
        assert not await env.kaito_pools()
