"""Real-mode branch conformance against local fakes (VERDICT r3 item 4).

``E2E_TARGET=real``'s code paths — kubeconfig parsing with token AND
exec-plugin auth, the production GKE REST client with endpoint override,
the CRD-served readiness gate, and the discovery-label teardown — run here
against the local fake apiserver + GCP facade on every push, instead of
staying dead until someone has GKE credentials. The real-target analog is
the reference's live suite bootstrap (suite_test.go:34-45 + setup.go:58-89);
this file is the conformance harness that keeps those branches honest
without a cluster.
"""

import json
import sys

import pytest
import yaml

from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import CONDITION_READY
from gpu_provisioner_tpu.fake import make_nodeclaim

from ..conftest import async_test_long as async_test
from .env import Environment, connect_real, discovery_teardown, fake_only

pytestmark = pytest.mark.e2e


def _exec_kubeconfig(tmp_path, server: str) -> str:
    """Kubeconfig whose user authenticates via a client-go exec credential
    plugin (the shape `gcloud container clusters get-credentials` writes) —
    the plugin is a tiny script printing an ExecCredential with the fake
    apiserver's static token, plus an env-passthrough assertion."""
    plugin = tmp_path / "fake-auth-plugin.py"
    plugin.write_text(
        "import json, os, sys\n"
        "assert os.environ.get('CONFORMANCE_MARK') == '1', 'exec env lost'\n"
        "json.dump({'apiVersion': 'client.authentication.k8s.io/v1',\n"
        "           'kind': 'ExecCredential',\n"
        "           'status': {'token': 'e2e-token'}}, sys.stdout)\n")
    kc = tmp_path / "kubeconfig-exec"
    kc.write_text(yaml.safe_dump({
        "current-context": "e2e",
        "contexts": [{"name": "e2e",
                      "context": {"cluster": "e2e", "user": "e2e"}}],
        "clusters": [{"name": "e2e", "cluster": {"server": server}}],
        "users": [{"name": "e2e", "user": {"exec": {
            "apiVersion": "client.authentication.k8s.io/v1",
            "command": sys.executable,
            "args": [str(plugin)],
            "env": [{"name": "CONFORMANCE_MARK", "value": "1"}],
        }}}],
    }))
    return str(kc)


@fake_only
@pytest.mark.parametrize("auth", ["token", "exec"])
@async_test
async def test_real_mode_branches_against_local_fakes(tmp_path, auth):
    """Drive the exact clients _enter_real/_cleanup_real build — kubeconfig
    kube client, production GKE REST client — against the fake backends,
    through a full provision → assert-pool → discovery-teardown cycle."""
    async with Environment(tmp_path) as env:   # fakes + operator subprocess
        genv = {"PROJECT_ID": "test-project", "LOCATION": "us-central2-b",
                "CLUSTER_NAME": "kaito",
                "E2E_TEST_MODE": "true", "E2E_STATIC_TOKEN": "e2e-token",
                "GKE_API_ENDPOINT": f"{env.gcp_url}/v1",
                "TPU_API_ENDPOINT": f"{env.gcp_url}/v2"}
        kubeconfig = (str(tmp_path / "kubeconfig") if auth == "token"
                      else _exec_kubeconfig(tmp_path, env.kube_url))
        client, nodepools = await connect_real(genv, kubeconfig)
        try:
            await client.create(make_nodeclaim("conf0", "tpu-v5e-8"))

            async def ready():
                nc = await client.get(NodeClaim, "conf0")
                return (nc if nc.status_conditions.is_true(CONDITION_READY)
                        else None)
            await env.eventually(ready, what="conf0 Ready (real-mode client)")

            # node-pool assertion through the PRODUCTION GKE REST client
            pool = await nodepools.get("conf0")
            assert pool.name == "conf0"
            assert pool.config.labels[wk.NODEPOOL_LABEL] \
                == wk.KAITO_NODEPOOL_NAME

            # the real-mode teardown: discovery-label sweep + unwind wait
            await discovery_teardown(client, env.eventually, timeout=60)

            async def pool_gone():
                from gpu_provisioner_tpu.providers.gcp import APIError
                try:
                    await nodepools.get("conf0")
                    return None
                except APIError as e:
                    return e.not_found or None
            await env.eventually(pool_gone, what="conf0 pool deleted")
        finally:
            await client.aclose()
            await nodepools.aclose()
