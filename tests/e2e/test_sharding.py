"""e2e: TWO sharded operator processes cover the fleet together.

The single asyncio event loop is the control plane's documented throughput
ceiling above ~2048 concurrent claims; claim-shard scaling (SHARDS /
SHARD_INDEX, controllers/registry.py) runs N operator replicas that
partition per-claim work by name hash with no coordination. This spec
boots shard 0 through the standard Environment and shard 1 as a second
REAL operator subprocess against the same apiserver/GCP fakes, then
provisions claims landing on BOTH shards — everything must go Ready, and
the partition must be real (each claim hashes to exactly one shard).
"""

import asyncio
import os
import sys

import pytest

from gpu_provisioner_tpu.controllers.utils import shard_owns
from gpu_provisioner_tpu.fake import make_nodeclaim

from ..conftest import async_test_long as async_test
from .env import Environment, _free_port, fake_only

pytestmark = pytest.mark.e2e


@fake_only
@async_test
async def test_two_shards_cover_the_fleet(tmp_path):
    async with Environment(tmp_path,
                           extra_env={"SHARDS": "2",
                                      "SHARD_INDEX": "0"}) as env:
        # shard 1: a second operator process, same fakes, own ports
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env2 = {**os.environ,
                "PALLAS_AXON_POOL_IPS": "",
                "PYTHONPATH": repo_root + os.pathsep
                + os.environ.get("PYTHONPATH", ""),
                "KUBECONFIG": str(tmp_path / "kubeconfig"),
                "KUBERNETES_SERVICE_HOST": "",
                "PROJECT_ID": "test-project",
                "LOCATION": "us-central2-b", "CLUSTER_NAME": "kaito",
                "E2E_TEST_MODE": "true", "E2E_STATIC_TOKEN": "e2e-token",
                "GKE_API_ENDPOINT": f"{env.gcp_url}/v1",
                "TPU_API_ENDPOINT": f"{env.gcp_url}/v2",
                "METRICS_PORT": str(_free_port()),
                "HEALTH_PROBE_PORT": str(_free_port()),
                "SHARDS": "2", "SHARD_INDEX": "1",
                "LOG_LEVEL": "debug"}
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "gpu_provisioner_tpu.operator", env=env2,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.STDOUT)
        try:
            # claims spanning both shards, found deterministically
            names = []
            for idx in (0, 0, 1, 1):
                names.append(next(
                    f"cl{i}" for i in range(100)
                    if shard_owns(f"cl{i}", 2, idx)
                    and f"cl{i}" not in names))
            assert {shard_owns(n, 2, 0) for n in names} == {True, False}
            for n in names:
                await env.client.create(make_nodeclaim(n))
            for n in names:
                await env.expect_nodeclaim_ready(n)
            # the partition was load-bearing: every pool exists exactly
            # once (no double-create from overlapping reconciles)
            pools = [p.name for p in await env.kaito_pools()]
            assert sorted(pools) == sorted(names)
        finally:
            if proc.returncode is None:
                proc.terminate()
                try:
                    await asyncio.wait_for(proc.wait(), 10)
                except asyncio.TimeoutError:
                    proc.kill()
                    await proc.wait()
