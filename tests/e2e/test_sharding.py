"""e2e: TWO sharded operator processes cover the fleet together.

The single asyncio event loop is the control plane's documented throughput
ceiling above ~2048 concurrent claims; claim-shard scaling (SHARDS /
SHARD_INDEX, controllers/registry.py) runs N operator replicas that
partition per-claim work by name hash with no coordination. This spec
boots shard 0 through the standard Environment and shard 1 as a second
REAL operator replica (same fakes, same timing config — spawn_operator
shares the env construction), then provisions claims landing on BOTH
shards — everything must go Ready, and the partition must be real (each
claim hashes to exactly one shard, each pool created exactly once).
"""

import pytest

from gpu_provisioner_tpu.controllers.utils import shard_owns
from gpu_provisioner_tpu.fake import make_nodeclaim

from ..conftest import async_test_long as async_test
from .env import Environment, fake_only

pytestmark = pytest.mark.e2e


@fake_only
@async_test
async def test_two_shards_cover_the_fleet(tmp_path):
    async with Environment(tmp_path,
                           extra_env={"SHARDS": "2",
                                      "SHARD_INDEX": "0"}) as env:
        await env.spawn_operator({"SHARD_INDEX": "1"})
        # claims spanning both shards, found deterministically
        names = []
        for idx in (0, 0, 1, 1):
            names.append(next(
                f"cl{i}" for i in range(100)
                if shard_owns(f"cl{i}", 2, idx)
                and f"cl{i}" not in names))
        assert {shard_owns(n, 2, 0) for n in names} == {True, False}
        for n in names:
            await env.client.create(make_nodeclaim(n))
        for n in names:
            await env.expect_nodeclaim_ready(n)
        # the partition was load-bearing: every pool exists exactly once
        # (no double-create from overlapping reconciles)
        pools = [p.name for p in await env.kaito_pools()]
        assert sorted(pools) == sorted(names)
