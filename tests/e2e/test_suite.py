"""The full e2e suite: 8 reference specs + TPU-specific extensions.

Mirrors test/e2e/suites/suite_test.go (:49 workspace provision, :117
ragengine provision, :183 teardown via NodeClaim delete — covered by
test_provisioning.py, :252/:529 teardown via Node delete, :321 nodeclass
provisioning, :387 negative foreign-nodeclass, :452 image family via
annotation) plus specs the reference cannot have: stockout →
InsufficientCapacity claim deletion, leaked-instance GC, node auto-repair,
and multi-slice DCN groups. Each spec runs the REAL operator subprocess
against the HTTP fakes (env.Environment).
"""

import asyncio

import pytest

from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.karpenter import (LAUNCHED, NodeClaim,
                                                NodeClassRef)
from gpu_provisioner_tpu.apis.kaito import KaitoNodeClass
from gpu_provisioner_tpu.apis.meta import ObjectMeta
from gpu_provisioner_tpu.apis.serde import now
from gpu_provisioner_tpu.catalog import lookup as catalog_lookup
from gpu_provisioner_tpu.fake import make_nodeclaim
from gpu_provisioner_tpu.providers.gcp import APIError, NodePool, NodePoolConfig
from gpu_provisioner_tpu.providers.instance import ts_label
from gpu_provisioner_tpu.apis.core import Node

from ..conftest import async_test_long as async_test
from .env import Environment, fake_only

pytestmark = pytest.mark.e2e


@async_test
async def test_provision_via_workspace_label_single_host(tmp_path):
    """suite_test.go:49 — plus TPU capacity/topology assertions."""
    async with Environment(tmp_path) as env:
        await env.client.create(make_nodeclaim("ws1", "tpu-v5e-8",
                                               workspace="myws"))
        nc = await env.expect_nodeclaim_ready("ws1")
        (node,) = await env.expect_node_count(1)
        assert node.status.capacity[wk.TPU_RESOURCE_NAME] == "8"
        assert node.metadata.labels[wk.GKE_TPU_ACCELERATOR_LABEL] == \
            "tpu-v5-lite-podslice"
        assert node.metadata.labels[wk.KAITO_WORKSPACE_LABEL] == "myws"
        assert nc.status.node_name == node.metadata.name


@async_test
async def test_provision_via_ragengine_label(tmp_path):
    """suite_test.go:117 — ragengine ownership path."""
    async with Environment(tmp_path) as env:
        nc = make_nodeclaim("rag0", "tpu-v5e-8")
        del nc.metadata.labels[wk.KAITO_WORKSPACE_LABEL]
        nc.metadata.labels[wk.KAITO_RAGENGINE_LABEL] = "rag"
        nc.spec.node_class_ref = None  # ragengine label alone must qualify
        await env.client.create(nc)
        await env.expect_nodeclaim_ready("rag0")
        (node,) = await env.expect_node_count(1)
        assert node.metadata.labels[wk.KAITO_RAGENGINE_LABEL] == "rag"


@async_test
async def test_teardown_via_node_delete(tmp_path):
    """suite_test.go:252,529 — deleting the Node unwinds claim + pool."""
    async with Environment(tmp_path) as env:
        await env.client.create(make_nodeclaim("wsn", "tpu-v5e-8"))
        await env.expect_nodeclaim_ready("wsn")
        (node,) = await env.expect_node_count(1)

        await env.client.delete(Node, node.metadata.name)
        await env.expect_gone(NodeClaim, "wsn")
        await env.expect_node_count(0)

        async def pools_gone():
            return not await env.kaito_pools() or None
        await env.eventually(pools_gone, what="node pools cleaned up")


@async_test
async def test_nodeclass_provisioning(tmp_path):
    """suite_test.go:321 — NodeClassRef alone (no kaito labels) qualifies."""
    from gpu_provisioner_tpu.runtime import AlreadyExistsError
    async with Environment(tmp_path) as env:
        try:
            await env.client.create(KaitoNodeClass(metadata=ObjectMeta(
                name="default",
                labels={wk.DISCOVERY_LABEL: wk.DISCOVERY_VALUE})))
        except AlreadyExistsError:
            pass  # left by a previous real-target run mid-teardown
        nc = make_nodeclaim("klass0", "tpu-v5e-8")
        del nc.metadata.labels[wk.KAITO_WORKSPACE_LABEL]
        assert nc.spec.node_class_ref.kind == "KaitoNodeClass"
        await env.client.create(nc)
        await env.expect_nodeclaim_ready("klass0")


@async_test
async def test_foreign_nodeclass_is_ignored(tmp_path):
    """suite_test.go:387 — a non-kaito NodeClaim must NOT provision."""
    async with Environment(tmp_path) as env:
        nc = make_nodeclaim("foreign0", "tpu-v5e-8")
        del nc.metadata.labels[wk.KAITO_WORKSPACE_LABEL]
        nc.spec.node_class_ref = NodeClassRef(
            group="karpenter.azure.com", kind="AKSNodeClass", name="default")
        await env.client.create(nc)

        await asyncio.sleep(3)  # several reconcile periods
        fresh = await env.client.get(NodeClaim, "foreign0")
        assert not fresh.status_conditions.is_true(LAUNCHED)
        assert not await env.kaito_pools()
        assert await env._managed_nodes() == []


@async_test
async def test_image_family_annotation(tmp_path):
    """suite_test.go:452 — AzureLinux-annotation analog: node image family
    → GKE imageType (determineOSSKU, instance.go:416-441)."""
    async with Environment(tmp_path) as env:
        await env.client.create(make_nodeclaim(
            "img0", "tpu-v5e-8",
            annotations={wk.KAITO_NODE_IMAGE_FAMILY_ANNOTATION: "ubuntu"}))
        await env.expect_nodeclaim_ready("img0")
        pool = await env.nodepools.get("img0")
        assert pool.config.image_type == "UBUNTU_CONTAINERD"


@fake_only
@async_test
async def test_stockout_deletes_claim(tmp_path):
    """No reference analog on AKS; BASELINE hard part 2 — RESOURCE_EXHAUSTED
    must surface as InsufficientCapacity and delete the claim
    (launch.go:84-109 behavior), never retry-loop."""
    async with Environment(tmp_path) as env:
        env.cloud.nodepools.fail(
            "begin_create", APIError("no v5e capacity in zone", code=429),
            times=100)
        await env.client.create(make_nodeclaim("stock0", "tpu-v5e-8"))
        await env.expect_gone(NodeClaim, "stock0")
        assert not await env.cloud.nodepools.list()


@fake_only
@async_test
async def test_gc_deletes_leaked_instance(tmp_path):
    """pkg/controllers/instance/garbagecollection readme scenario: a slice
    whose NodeClaim no longer exists is deleted after the leak grace."""
    async with Environment(tmp_path) as env:
        shape = catalog_lookup("tpu-v5e-8")
        leaked = NodePool(
            name="leaked0",
            config=NodePoolConfig(
                machine_type=shape.machine_type,
                labels={wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME,
                        wk.KAITO_CREATION_TIMESTAMP_LABEL: ts_label(now()),
                        **shape.node_labels(slice_id="leaked0")}),
            initial_node_count=1)
        op = await env.cloud.nodepools.begin_create(leaked)
        await op.result()

        async def gone():
            names = [p.name for p in await env.cloud.nodepools.list()]
            return "leaked0" not in names or None
        await env.eventually(gone, what="leaked pool collected")
        # its orphan nodes are reaped too (controller.go:99-120)
        await env.expect_node_count(0)


@fake_only
@async_test
async def test_node_repair_replaces_unhealthy(tmp_path):
    """§3.5 — NodeReady=False past toleration deletes the NodeClaim."""
    async with Environment(
            tmp_path, extra_env={"REPAIR_TOLERATION_SECONDS": "1"}) as env:
        await env.client.create(make_nodeclaim("sick0", "tpu-v5e-8"))
        await env.expect_nodeclaim_ready("sick0")
        (node,) = await env.expect_node_count(1)

        # the "kubelet" reports NotReady
        for c in node.status.conditions:
            if c.type == "Ready":
                c.status = "False"
                c.reason = "KubeletNotReady"
                c.last_transition_time = now()
        await env.client.update_status(node)

        await env.expect_gone(NodeClaim, "sick0")


@fake_only
@async_test
async def test_operator_with_leader_election(tmp_path):
    """Multi-replica readiness: election ON (reference defaults it off,
    options.go:117, but implements it) — the operator must acquire the
    coordination.k8s.io Lease before reconciling, then work normally."""
    from gpu_provisioner_tpu.apis.core import Lease
    async with Environment(
            tmp_path,
            extra_env={"DISABLE_LEADER_ELECTION": "false"}) as env:
        lease = await env.eventually(
            lambda: env.client.get(Lease, "tpu-provisioner", "default"),
            what="lease acquired")
        assert lease.spec.holder_identity
        await env.client.create(make_nodeclaim("led0", "tpu-v5e-8"))
        await env.expect_nodeclaim_ready("led0")


@fake_only
@async_test
async def test_multislice_group_provisions_n_slices(tmp_path):
    """BASELINE config 5: 4× v5e-16 NodeClaims in one DCN slice group.

    Beyond pool count, asserts the full bootstrap loop the provisioner must
    close with NO manual env: distinct ordered slice indices on every pool's
    nodes, one agreed coordinator, and SliceTopology.from_node_labels
    yielding globally-unique jax.distributed process ids for every worker.
    """
    from gpu_provisioner_tpu.parallel.topology import SliceTopology

    async with Environment(tmp_path) as env:
        for i in range(4):
            nc = make_nodeclaim(f"slice{i}", "tpu-v5e-16",
                                labels={wk.TPU_SLICE_GROUP_LABEL: "dpgroup"})
            await env.client.create(nc)
        for i in range(4):
            # suite default (90s fake / E2E_TIMEOUT_SECONDS): the 4-slice
            # wave flaked once at 60s under heavy CPU contention
            await env.expect_nodeclaim_ready(f"slice{i}")
        nodes = await env.expect_node_count(8)  # 4 slices × 2 hosts
        groups = {n.metadata.labels.get(wk.TPU_SLICE_GROUP_LABEL)
                  for n in nodes}
        assert groups == {"dpgroup"}
        pools = await env.cloud.nodepools.list()
        assert len(pools) == 4

        # distinct ordered slice indices, stamped on every member's nodes.
        # Polled: the SliceGroupController stamps identity asynchronously
        # after nodes register — node count reaching 8 does not imply the
        # labels have converged yet.
        async def indices_converged():
            ns = await env._managed_nodes()
            got = {}
            for n in ns:
                idx = n.metadata.labels.get(wk.TPU_SLICE_INDEX_LABEL)
                if idx is None:
                    return None
                got.setdefault(idx, set()).add(
                    n.metadata.labels[wk.GKE_NODEPOOL_LABEL])
            ok = (sorted(got) == ["0", "1", "2", "3"]
                  and all(len(p) == 1 for p in got.values()))
            return (ns, got) if ok else None
        nodes, by_index = await env.eventually(
            indices_converged, what="slice indices stamped on all nodes")

        # one agreed coordinator: worker 0 of slice 0 (stamped by the same
        # controller pass; polled for the same reason as the indices)
        (pool0,) = by_index["0"]

        async def coordinator_agreed():
            ns = await env._managed_nodes()
            coords = {n.metadata.labels.get(wk.TPU_COORDINATOR_LABEL)
                      for n in ns}
            return ns if coords == {f"gke-kaito-{pool0}-w0"} else None
        nodes = await env.eventually(coordinator_agreed,
                                     what="coordinator agreed on all nodes")

        # every worker bootstraps jax.distributed args from labels alone.
        # Polled for the same reason as the indices/coordinator: a pool
        # created off a momentarily-incomplete group view can be stamped a
        # low num-slices, and the SliceGroupController repairs that label on
        # the nodes a pass later — with non-blocking creates all four pools
        # materialize at once, so the repair races this read.
        async def bootstrap_args_converged():
            args_seen = []
            for n in await env._managed_nodes():
                topo = SliceTopology.from_node_labels(n.metadata.labels,
                                                      environ={})
                args = topo.distributed_init_args()
                if (args["num_processes"] != 8 or args["coordinator_address"]
                        != f"gke-kaito-{pool0}-w0:8476"):
                    return None
                args_seen.append(args["process_id"])
            return args_seen if sorted(args_seen) == list(range(8)) else None
        await env.eventually(bootstrap_args_converged,
                             what="jax.distributed bootstrap args converged")


@fake_only
@async_test
async def test_pdb_blocked_drain_warns_then_completes(tmp_path):
    """TPU extension: a PDB-blocked drain goes through the REAL eviction
    subresource (fake apiserver answers 429), the operator surfaces a
    Warning event, and teardown completes once the budget is lifted —
    black-box coverage of terminator/eviction.go:199-209 semantics."""
    from gpu_provisioner_tpu.apis.core import (Event, LabelSelector, Pod,
                                               PodDisruptionBudget,
                                               PodDisruptionBudgetSpec,
                                               PodSpec)
    async with Environment(tmp_path) as env:
        await env.client.create(make_nodeclaim("wsp", "tpu-v5e-8"))
        await env.expect_nodeclaim_ready("wsp")
        (node,) = await env.expect_node_count(1)

        await env.client.create(Pod(
            metadata=ObjectMeta(name="served", namespace="default",
                                labels={"app": "served"}),
            spec=PodSpec(node_name=node.metadata.name)))
        await env.client.create(PodDisruptionBudget(
            metadata=ObjectMeta(name="served-pdb", namespace="default"),
            spec=PodDisruptionBudgetSpec(
                selector=LabelSelector(match_labels={"app": "served"}),
                min_available=1)))

        await env.client.delete(NodeClaim, "wsp")

        async def warned():
            evs = await env.client.list(Event, namespace="default")
            hits = [e for e in evs if e.type == "Warning"
                    and e.reason == "FailedDraining"
                    and e.involved_object.name == "served"]
            return hits or None
        await env.eventually(warned, what="FailedDraining warning event")

        await env.client.delete(PodDisruptionBudget, "served-pdb", "default")
        await env.expect_gone(NodeClaim, "wsp")
        await env.expect_gone(Pod, "served", "default")


@async_test
async def test_real_mode_plumbing_against_stand_in_cluster(tmp_path, monkeypatch):
    """E2E_TARGET=real wiring, proven without a live cluster: Environment
    builds its client from KUBECONFIG (token auth here; exec-plugin covered
    in test_rest), reaches node pools through the production GKE client, and
    cleanup deletes discovery-labeled NodeClaims in parallel. The fakes stand
    in for the cluster; on a real one the same code path runs unchanged."""
    import yaml as _yaml

    from gpu_provisioner_tpu.fake.cloud import FakeCloud
    from gpu_provisioner_tpu.runtime import InMemoryClient

    from . import env as env_module
    from .backends import FakeGCPServer, FakeKubeAPIServer

    backing = InMemoryClient()
    cloud = FakeCloud(backing)
    kube_server = FakeKubeAPIServer(backing)
    gcp_server = FakeGCPServer(cloud)
    kube_url = await kube_server.start()
    gcp_url = await gcp_server.start()
    try:
        kubeconfig = tmp_path / "kubeconfig"
        kubeconfig.write_text(_yaml.safe_dump({
            "current-context": "real",
            "contexts": [{"name": "real",
                          "context": {"cluster": "c", "user": "u"}}],
            "clusters": [{"name": "c", "cluster": {"server": kube_url}}],
            "users": [{"name": "u", "user": {"token": "real-token"}}],
        }))
        for k, v in {"KUBECONFIG": str(kubeconfig),
                     "PROJECT_ID": "real-proj", "LOCATION": "us-central2-b",
                     "CLUSTER_NAME": "kaito",
                     "E2E_TEST_MODE": "true", "E2E_STATIC_TOKEN": "real-token",
                     "GKE_API_ENDPOINT": f"{gcp_url}/v1",
                     "TPU_API_ENDPOINT": f"{gcp_url}/v2"}.items():
            monkeypatch.setenv(k, v)
        monkeypatch.setattr(env_module, "IS_REAL", True)

        async with Environment(tmp_path) as env:
            assert env.real and env.proc is None  # no subprocess in real mode
            assert await env.kaito_pools() == []
            # a discovery-labeled claim left behind by a spec...
            await env.client.create(make_nodeclaim("straggler", "tpu-v5e-8"))
            assert len(await env.client.list(NodeClaim)) == 1
        # ...is swept by the exit cleanup (setup.go:58-89 analog)
        assert await backing.list(NodeClaim) == []
    finally:
        await gcp_server.stop()
        await kube_server.stop()


@fake_only
@async_test
async def test_steady_state_list_load_is_flat(tmp_path):
    """Informer-backed reads: with claims settled, the GC loops must ride
    the watch cache instead of re-LISTing Nodes/NodeClaims every cycle
    (reference reads through controller-runtime's cached client). Allows a
    tiny allowance for the eviction/termination paths that read directly."""
    async with Environment(tmp_path, gc_interval=0.5) as env:
        await env.client.create(make_nodeclaim("calm", "tpu-v5e-8"))
        await env.expect_nodeclaim_ready("calm")
        await asyncio.sleep(1.0)  # settle in-flight reconciles

        before = dict(env.kube_server.list_counts)
        await asyncio.sleep(3.0)  # ~6 GC cycles
        after = dict(env.kube_server.list_counts)

        for kind in ("Node", "NodeClaim"):
            grew = after.get(kind, 0) - before.get(kind, 0)
            assert grew <= 2, (
                f"{kind} full-LIST count grew by {grew} across ~6 GC cycles "
                f"— informer cache is not serving steady-state reads "
                f"(before={before}, after={after})")
