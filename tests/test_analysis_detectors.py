"""Runtime-detector tests: the event-loop stall detector, the generalized
task/thread leak gate, and the regression tests for the two leak classes
provlint's dynamic side shook out (workqueue delayed-heap timers surviving
Manager.stop; tracker notify tasks surviving tracker.stop)."""

import asyncio
import threading
import time

import pytest

from gpu_provisioner_tpu.analysis.detectors import (
    EventLoopStallError, StallDetector, TaskLeakError, ThreadLeakError,
    check_no_leaked_tasks, check_no_leaked_threads, thread_snapshot,
)
from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
from gpu_provisioner_tpu.providers.operations import (
    OperationTracker, PHASE_SUCCEEDED,
)
from gpu_provisioner_tpu.runtime.controller import Manager

from .conftest import async_test

# Fast envtest config for detector tests: no claims are created, so only
# the singleton cadences matter.
FAST = dict(gc_interval=0.1, leak_grace=0.1, recovery_interval=600.0)


# ----------------------------------------------------------- stall detector

@async_test
async def test_stall_detector_catches_blocking_sleep():
    det = StallDetector(budget=0.1, interval=0.02)
    det.start()
    await asyncio.sleep(0.05)       # let the sentinel anchor itself
    time.sleep(0.35)                # block the loop — the sin under test
    await asyncio.sleep(0.05)       # sentinel wakes, observes the stall
    await det.stop()
    assert det.worst >= 0.2
    assert det.stalls
    with pytest.raises(EventLoopStallError):
        det.check()


@async_test
async def test_stall_detector_quiet_on_healthy_loop():
    det = StallDetector(budget=0.5, interval=0.02)
    det.start()
    for _ in range(10):
        await asyncio.sleep(0.01)
    await det.stop()
    det.check()                     # no stall, no raise
    assert det.stalls == []


@async_test
async def test_envtest_fails_a_test_that_blocks_the_loop():
    opts = EnvtestOptions(stall_budget=0.15, stall_interval=0.02, **FAST)
    with pytest.raises(EventLoopStallError):
        async with Env(opts):
            await asyncio.sleep(0.05)
            time.sleep(0.4)         # blocking work on the shared loop
            await asyncio.sleep(0.05)


@async_test
async def test_envtest_stall_gate_never_masks_a_test_failure():
    opts = EnvtestOptions(stall_budget=0.15, stall_interval=0.02, **FAST)
    with pytest.raises(AssertionError, match="the real failure"):
        async with Env(opts):
            time.sleep(0.4)
            await asyncio.sleep(0.05)
            raise AssertionError("the real failure")


# ----------------------------------------------------------------- leak gate

@async_test
async def test_envtest_clean_teardown_has_no_leaks():
    async with Env(EnvtestOptions(**FAST)) as env:
        await asyncio.sleep(0.05)
    assert not any(t is not None and not t.done()
                   for _, t in env._component_tasks())


@async_test
async def test_envtest_leak_gate_catches_a_component_that_forgot_to_stop():
    env = Env(EnvtestOptions(**FAST))
    entered = await env.__aenter__()
    assert entered is env

    async def parked():
        await asyncio.sleep(300)

    t = asyncio.create_task(parked(), name="forgotten-timer")
    env.eviction._timers.add(t)
    real_stop = env.eviction.stop

    async def broken_stop():     # a teardown path that forgot its timers
        env.eviction._timers.discard(t)  # hide from stop…
        await real_stop()
        env.eviction._timers.add(t)      # …but the task still exists

    env.eviction.stop = broken_stop
    try:
        with pytest.raises(TaskLeakError, match="forgotten-timer"):
            await env.__aexit__(None, None, None)
    finally:
        t.cancel()


@async_test
async def test_leak_helpers_render_survivors():
    async def parked():
        await asyncio.sleep(60)

    t = asyncio.create_task(parked(), name="leaky")
    try:
        with pytest.raises(TaskLeakError, match="leaky"):
            check_no_leaked_tasks([("component", t)])
    finally:
        t.cancel()
    check_no_leaked_tasks([("component", None)])    # absent task is fine


def test_thread_leak_check():
    before = thread_snapshot()
    stop = threading.Event()
    th = threading.Thread(target=stop.wait, name="leaky-thread")
    th.start()
    try:
        with pytest.raises(ThreadLeakError, match="leaky-thread"):
            check_no_leaked_threads(before)
    finally:
        stop.set()
        th.join()
    check_no_leaked_threads(before)


@async_test
async def test_env_startup_failure_unwinds_started_components():
    """Review-pass regression: a failed Env startup never reaches
    __aexit__ — components started before the failure (tracker, eviction,
    stall sentinel) must be unwound, not leaked into later tests."""
    env = Env(EnvtestOptions(**FAST))

    async def boom():
        raise RuntimeError("manager refused to start")

    env.manager.start = boom
    with pytest.raises(RuntimeError, match="refused to start"):
        await env.__aenter__()
    assert env.tracker is None or not env.tracker.task_alive()
    assert env.eviction._task is None
    assert env.stall is None or env.stall._task is None
    check_no_leaked_tasks(env._component_tasks())


@async_test
async def test_env_teardown_is_exception_safe():
    """Review-pass regression: one failing stop must not strand the
    components after it — every stop runs, then the FIRST failure
    re-raises."""
    env = Env(EnvtestOptions(**FAST))
    await env.__aenter__()

    async def broken_stop():
        raise RuntimeError("manager stop exploded")

    env.manager.stop = broken_stop
    with pytest.raises(RuntimeError, match="stop exploded"):
        await env.__aexit__(None, None, None)
    # everything AFTER the failing stop still tore down
    assert env.tracker is None or not env.tracker.task_alive()
    assert env.eviction._task is None
    assert env.stall is None or env.stall._task is None
    # the real manager never stopped — reap it so this test doesn't leak
    await Manager.stop(env.manager)


def test_stall_budget_env_override(monkeypatch):
    monkeypatch.setenv("PROVLINT_STALL_BUDGET", "0")

    async def run():
        async with Env(EnvtestOptions(**FAST)) as env:
            assert env.stall is None   # disabled by the env var
    asyncio.run(run())


# ------------------------------------------------- regression: timer leak

@async_test
async def test_workqueue_timer_does_not_outlive_manager_stop():
    """PR 7 defect fix: an item parked in rate-limit backoff (max_delay is
    1000s in production) left the queue's delayed-heap timer task sleeping
    long after Manager.stop() — found by the generalized leak gate."""
    async with Env(EnvtestOptions(**FAST)) as env:
        lifecycle = env.manager.controllers[0]
        await lifecycle.queue.add_after("parked-item", 120.0)
        await asyncio.sleep(0.02)
        assert lifecycle.queue._timer is not None
        assert not lifecycle.queue._timer.done()
    # Env.__aexit__ ran the leak gate: reaching here at all proves the
    # timer was reaped; assert directly for the message's sake.
    assert lifecycle.queue._timer is None


# ------------------------------------------- regression: notify-task leak

@async_test
async def test_tracker_stop_reaps_inflight_notify_tasks():
    """PR 7 defect fix: subscriber-notification tasks were fired with
    asyncio.ensure_future and dropped — a slow subscriber's task outlived
    tracker.stop() and kept injecting into a dead incarnation's queues."""
    tracker = OperationTracker(None, None, interval=0.05)
    entered = asyncio.Event()

    async def slow_subscriber(op):
        entered.set()
        await asyncio.sleep(300)

    tracker.subscribe(slow_subscriber)
    op = tracker.track_create("claim0", 1, budget=10.0)
    tracker._complete(op, PHASE_SUCCEEDED, "Created", "done")
    await asyncio.wait_for(entered.wait(), timeout=5)
    assert tracker._notify_tasks
    await tracker.stop()
    assert not tracker._notify_tasks
    check_no_leaked_tasks([("notify", t) for t in tracker._notify_tasks])
