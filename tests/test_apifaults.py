"""PR 16: degraded-mode control plane — apiserver brownout/partition chaos,
watch-gap resync, and adaptive overload shedding.

Layers under test:

- ``transport.py``: 429-with-Retry-After is pacing, not failure — neutral
  for the breaker (consecutive-5xx counts survive), fanned out to the
  throttle listeners, honored as a backoff floor.
- ``runtime/apihealth.py``: the AIMD governor and its
  HEALTHY→BROWNOUT→PARTITIONED→CATCHUP mode machine.
- ``runtime/informer.py``: 410 Gone → jittered relist → diff-synthesized
  ADDED/MODIFIED/DELETED through the relay (client-go Replace parity).
- ``chaos/apifaults.py`` profiles driven through the whole envtest stack,
  ending in the 200-claim / 30s-partition acceptance soak (slow-marked).

Seeded like the rest of the chaos suite: ``CHAOS_SEED=<n> make brownout``
reproduces a failure exactly.
"""

import asyncio
import os

import httpx
import pytest

from gpu_provisioner_tpu.analysis.schedfuzz import (
    FuzzEvent, TraceRecorder, check_partition_fenced_mutate,
)
from gpu_provisioner_tpu.apis.core import Node, NodeSpec
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import CONDITION_READY, ObjectMeta
from gpu_provisioner_tpu.chaos import (
    ApiFaultClient, ApiFaultInjector, api_fault_profile,
)
from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
from gpu_provisioner_tpu.fake import make_nodeclaim
from gpu_provisioner_tpu.observability.flightrecorder import FlightRecorder
from gpu_provisioner_tpu.runtime import probes
from gpu_provisioner_tpu.runtime.apihealth import (
    APIHEALTH, BROWNOUT, CATCHUP, HEALTHY, PARTITIONED, APIHealthGovernor,
    GovernedClient, PartitionFencedError,
)
from gpu_provisioner_tpu.runtime.client import (
    ClientError, InMemoryClient, NotFoundError, TooManyRequestsError,
)
from gpu_provisioner_tpu.runtime.informer import CachedListClient
from gpu_provisioner_tpu.runtime.store import ADDED, DELETED
from gpu_provisioner_tpu.runtime.wakehub import SOURCE_TIMER, WAKES
from gpu_provisioner_tpu.transport import (
    BREAKER_CLOSED, BREAKER_OPEN, GCP_RETRYABLE_STATUS, CircuitBreaker,
    TransportOptions, add_throttle_listener, parse_retry_after,
    remove_throttle_listener, request_with_retries,
)

from .conftest import async_test, async_test_long

pytestmark = pytest.mark.chaos

SEED = int(os.environ.get("CHAOS_SEED", "7"))


# ------------------------------------------------------------------ helpers

def fault_env(faults, launch_timeout: float = 20.0, **opt_kw) -> Env:
    """Envtest under apiserver weather: informer on (the 410 path belongs
    to the informer pump — raw manager watches must never see it) and the
    workqueue backoff left at its production-like defaults, so convergence
    after a heal PROVES the watch-source wake path instead of leaning on a
    shortened timer safety net."""
    opts = EnvtestOptions(api_faults=faults, use_informer=True,
                          gc_interval=0.25, leak_grace=0.25, **opt_kw)
    opts.lifecycle.launch_timeout = launch_timeout
    opts.lifecycle.registration_timeout = launch_timeout
    return Env(opts)


async def wait_for(pred, what: str, timeout: float = 10.0,
                   tick: float = 0.02) -> None:
    deadline = asyncio.get_event_loop().time() + timeout
    while not pred():
        assert asyncio.get_event_loop().time() < deadline, \
            f"timed out waiting for {what}"
        await asyncio.sleep(tick)


async def converge(env: Env, names: list[str], timeout: float = 30.0
                   ) -> set[str]:
    """Wait until every claim is Ready (reads ride the RAW client)."""
    deadline = asyncio.get_event_loop().time() + timeout
    ready: set[str] = set()
    while True:
        for name in set(names) - ready:
            try:
                nc = await env.client.get(NodeClaim, name)
            except NotFoundError:
                raise AssertionError(f"claim {name} was LOST") from None
            if nc.status_conditions.is_true(CONDITION_READY):
                ready.add(name)
        if ready == set(names):
            return ready
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(
                f"claims did not converge: {len(ready)}/{len(names)} ready; "
                f"missing={sorted(set(names) - ready)[:8]}")
        await asyncio.sleep(0.05)


def begin_creates(env: Env) -> int:
    """ADMITTED pool creates (the zone-keyed counters). Post-heal re-walks
    that 409 against a live pool are adoption — the safe at-least-once
    answer — and must not count as duplicates; a pool actually admitted
    twice (carcass replace aside) would."""
    return sum(v for k, v in env.cloud.nodepools.calls.items()
               if k.startswith("begin_create:"))


def degraded_bundle_keys(rec: FlightRecorder) -> set[str]:
    return {b["trigger"]["key"].split(":", 1)[1] for b in rec.bundles()
            if b["trigger"]["kind"] == "degraded-mode"}


# ----------------------------------------- transport: 429 is pacing (PR 16a)

@async_test
async def test_429_preserves_breaker_failure_count():
    """The regression this PR fixes: the old 429 path called
    record_success(), RESETTING the consecutive-5xx count — a real outage
    interleaved with throttling could never open the breaker. 429 must be
    neutral: no failure, no reset."""
    script = [503, 503, 429, 503]

    def handler(req: httpx.Request) -> httpx.Response:
        code = script.pop(0)
        if code == 429:
            return httpx.Response(429, headers={"Retry-After": "0.01"})
        return httpx.Response(code, text="boom")

    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    b = CircuitBreaker("pace", failure_threshold=3, reset_timeout=60.0)
    opts = TransportOptions(max_retries=0, backoff_base=0.001,
                            backoff_cap=0.002)
    for _ in range(2):                       # two real failures
        await request_with_retries(http, "GET", "https://x.test/a",
                                   opts=opts, breaker=b)
    assert b.consecutive_failures == 2 and b.state == BREAKER_CLOSED
    await request_with_retries(http, "GET", "https://x.test/a",
                               opts=opts, breaker=b)   # throttled
    assert b.throttled_total == 1
    assert b.consecutive_failures == 2, \
        "429 reset the consecutive-failure count — outage masked by throttle"
    assert b.state == BREAKER_CLOSED, "429 must never count toward opening"
    await request_with_retries(http, "GET", "https://x.test/a",
                               opts=opts, breaker=b)   # third real failure
    assert b.state == BREAKER_OPEN
    await http.aclose()


@async_test
async def test_sustained_429_never_opens_breaker():
    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(429, headers={"Retry-After": "0.001"})

    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    b = CircuitBreaker("throttle-only", failure_threshold=3,
                       reset_timeout=60.0)
    opts = TransportOptions(max_retries=0, backoff_base=0.001,
                            backoff_cap=0.002)
    for _ in range(20):
        resp = await request_with_retries(http, "GET", "https://x.test/a",
                                          opts=opts, breaker=b)
        assert resp.status_code == 429
    assert b.state == BREAKER_CLOSED and b.throttled_total == 20
    assert b.consecutive_failures == 0
    await http.aclose()


@async_test
async def test_429_feeds_throttle_listeners_except_gcp_policy():
    """Kube-policy 429s fan out Retry-After to the throttle listeners (the
    governor's transport seam); GCP-policy clients treat 429 as the
    semantic stockout answer and must NOT shed kube load."""
    got: list[tuple[str, float]] = []

    def listener(name: str, retry_after: float) -> None:
        got.append((name, retry_after))

    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(429, headers={"Retry-After": "0.3"})

    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    add_throttle_listener(listener)
    try:
        await request_with_retries(
            http, "GET", "https://x.test/a",
            opts=TransportOptions(max_retries=0))
        assert got == [("https://x.test/a", 0.3)]
        await request_with_retries(
            http, "GET", "https://x.test/a",
            opts=TransportOptions(max_retries=0,
                                  retryable_status=GCP_RETRYABLE_STATUS))
        assert len(got) == 1, "GCP-policy 429 must not notify kube shedding"
    finally:
        remove_throttle_listener(listener)
        await http.aclose()


@async_test
async def test_retry_after_is_honored_as_delay_floor():
    calls = {"n": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        calls["n"] += 1
        if calls["n"] == 1:
            return httpx.Response(429, headers={"Retry-After": "0.25"})
        return httpx.Response(200, json={})

    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    t0 = asyncio.get_event_loop().time()
    resp = await request_with_retries(
        http, "GET", "https://x.test/a",
        opts=TransportOptions(max_retries=1, backoff_base=0.001,
                              backoff_cap=0.002))
    elapsed = asyncio.get_event_loop().time() - t0
    assert resp.status_code == 200
    assert elapsed >= 0.2, \
        f"Retry-After floor not honored: retried after {elapsed:.3f}s"
    await http.aclose()


def test_parse_retry_after():
    mk = lambda headers: httpx.Response(429, headers=headers)  # noqa: E731
    assert parse_retry_after(mk({"Retry-After": "1.5"})) == 1.5
    assert parse_retry_after(mk({})) == 0.0
    assert parse_retry_after(mk({"Retry-After": "soon"})) == 0.0
    assert parse_retry_after(mk({"Retry-After": "-5"})) == 0.0


# ------------------------------------------------- governor mode machine

def test_governor_mode_machine_and_aimd():
    t = {"now": 0.0}
    g = APIHealthGovernor(clock=lambda: t["now"], partition_threshold=3,
                          brownout_hold=1.0, catchup_hold=1.0,
                          rate_max=256.0)
    entered: list[str] = []
    g.add_degraded_listener(lambda mode, **info: entered.append(mode))
    assert g.mode() == HEALTHY and g.healthz_line() == "ok"

    g.note_throttle(retry_after=0.5)
    assert g.mode() == BROWNOUT and g._rate == 128.0
    assert g.status_window_factor() == 4.0
    assert "degraded mode=BROWNOUT" in g.healthz_line()

    for _ in range(3):
        g.note_failure()
    assert g.mode() == PARTITIONED and g.partition_fenced()
    assert g.status_window_factor() == 8.0
    assert g.mode_value() == 3 - 1  # PARTITIONED ordinal

    g.note_success()
    assert g.mode() == CATCHUP and not g.partition_fenced()
    rate_in_catchup = g._rate
    g.note_success()
    assert g._rate == rate_in_catchup + g.increase, "additive increase"

    t["now"] = 5.0              # past both holds
    assert g.mode() == HEALTHY
    assert g._rate == g.rate_max, "HEALTHY re-entry restores full pace"
    assert g.status_window_factor() == 1.0
    assert entered == [BROWNOUT, PARTITIONED, CATCHUP]
    assert g.entries_total[PARTITIONED] == 1
    assert g.entries_total[HEALTHY] == 1


def test_governor_brownout_decays_and_throttle_resets_failures():
    t = {"now": 0.0}
    g = APIHealthGovernor(clock=lambda: t["now"], partition_threshold=3,
                          brownout_hold=0.5)
    g.note_failure()
    g.note_failure()
    g.note_throttle()            # an ANSWER: consecutive outage count resets
    assert g._consec_failures == 0
    g.note_failure()
    assert g.mode() == BROWNOUT, "throttle must have reset the outage count"
    t["now"] = 1.0
    assert g.mode() == HEALTHY


@async_test
async def test_governor_pace_noop_healthy_sheds_degraded():
    g = APIHealthGovernor(rate_max=8.0, brownout_hold=60.0)
    shed_before = APIHEALTH["shed"]
    t0 = asyncio.get_event_loop().time()
    for _ in range(50):
        await g.pace()
    assert asyncio.get_event_loop().time() - t0 < 0.1, \
        "HEALTHY pace() must be a no-op fast path"
    assert APIHEALTH["shed"] == shed_before

    g.note_failure()             # BROWNOUT: rate 8 -> 4, tokens clamp to 4
    t0 = asyncio.get_event_loop().time()
    for _ in range(6):
        await g.pace()
    assert asyncio.get_event_loop().time() - t0 >= 0.2, \
        "degraded pace() must actually shed"
    assert APIHEALTH["shed"] > shed_before


def test_governor_emits_api_mode_probes():
    rec = TraceRecorder()
    probes.add_sink(rec)
    try:
        t = {"now": 0.0}
        g = APIHealthGovernor(clock=lambda: t["now"], partition_threshold=1)
        g.note_failure()         # straight to PARTITIONED (threshold 1)
        g.note_success()         # CATCHUP
    finally:
        probes.remove_sink(rec)
    modes = [e.key for e in rec.events if e.name == "api-mode"]
    assert modes == [PARTITIONED, CATCHUP]


@async_test
async def test_governed_client_classifies_outcomes():
    class StubInner:
        def __init__(self):
            self.exc = None
            self.store = None

        async def get(self, cls, name, namespace=""):
            if self.exc is not None:
                raise self.exc
            return object()

    t = {"now": 0.0}
    g = APIHealthGovernor(clock=lambda: t["now"], partition_threshold=2,
                          brownout_hold=60.0)
    c = GovernedClient(StubInner(), g)

    c.inner.exc = TooManyRequestsError("429", retry_after=0.2)
    with pytest.raises(TooManyRequestsError):
        await c.get(Node, "x")
    assert g.mode() == BROWNOUT and g.throttles_total == 1

    c.inner.exc = NotFoundError("404")      # semantic answer == success
    with pytest.raises(NotFoundError):
        await c.get(Node, "x")
    assert g.failures_total == 0

    c.inner.exc = ClientError("503")
    for _ in range(2):
        with pytest.raises(ClientError):
            await c.get(Node, "x")
    assert g.mode() == PARTITIONED

    c.inner.exc = None
    await c.get(Node, "x")
    assert g.mode() == CATCHUP


# ------------------------------------------------ informer gap resync matrix

def _node(name: str) -> Node:
    return Node(metadata=ObjectMeta(name=name), spec=NodeSpec())


@async_test
async def test_informer_gap_synthesizes_add_and_delete():
    """Gap matrix rows 1+2: an ADDED dropped during the gap and a DELETED
    swallowed during the gap both reach relay subscribers as synthesized
    events after the 410-triggered relist-and-diff."""
    inner = InMemoryClient()
    await inner.create(_node("a"))
    await inner.create(_node("b"))
    faults = ApiFaultInjector(seed=SEED, gap_start=0.05, gap_duration=0.3)
    client = CachedListClient(ApiFaultClient(inner, faults), (Node,))
    await client.start()        # anchors the fault clock
    try:
        w = client.watch(Node)
        replay = sorted([(await asyncio.wait_for(w.__anext__(), 2.0))
                         .object.metadata.name for _ in range(2)])
        assert replay == ["a", "b"]

        await asyncio.sleep(0.1)            # into the gap window
        assert faults.gap_active()
        await inner.create(_node("c"))      # ADDED — dropped by the stream
        await inner.delete(Node, "a")       # DELETED — swallowed

        want = {(ADDED, "c"), (DELETED, "a")}
        seen: set = set()
        deadline = asyncio.get_event_loop().time() + 5.0
        while not want <= seen:
            assert asyncio.get_event_loop().time() < deadline, \
                f"synthesized events missing: {want - seen}"
            ev = await asyncio.wait_for(w.__anext__(), 5.0)
            seen.add((ev.type, ev.object.metadata.name))

        inf = client._informers[Node]
        assert inf.watch_gaps >= 1, "410 was not classified as a gap"
        assert inf.relists >= 2, "boot sync + gap resync expected"
        assert sum(faults.dropped.values()) >= 2
        names = sorted(n.metadata.name for n in await client.list(Node))
        assert names == ["b", "c"], "cache did not heal to the true state"
    finally:
        await client.stop()


@async_test
async def test_informer_gap_reports_to_governor_and_ledger():
    inner = InMemoryClient()
    await inner.create(_node("a"))
    faults = ApiFaultInjector(seed=SEED, gap_start=0.02, gap_duration=0.15)
    client = CachedListClient(ApiFaultClient(inner, faults), (Node,))
    t = {"now": 0.0}
    g = APIHealthGovernor(clock=lambda: t["now"])
    gaps_before = APIHEALTH["watch_gaps"]
    await client.start()
    for inf in client._informers.values():
        inf.governor = g
    try:
        await asyncio.sleep(0.05)
        await inner.create(_node("dropped"))    # force a lost event
        deadline = asyncio.get_event_loop().time() + 5.0
        while APIHEALTH["watch_gaps"] == gaps_before:
            assert asyncio.get_event_loop().time() < deadline
            await asyncio.sleep(0.02)
        assert g.mode() == BROWNOUT, "watch gap must be brownout evidence"
    finally:
        await client.stop()


@async_test
async def test_soak_watch_gap_relist_races_live_reconciles():
    """Gap matrix row 3: the relist-and-diff lands while reconciles are
    live and the status batcher holds pending overlays — everything still
    converges, with no stale-store spurious status-write storm (the PR 11
    bug class; bounded by the PR 11 patches-per-claim gate)."""
    faults = api_fault_profile("watch_gap", seed=SEED,
                               gap_start=0.15, gap_duration=0.4)
    names = [f"wg{i}" for i in range(8)]
    async with fault_env(faults) as env:
        for n in names[:5]:
            await env.client.create(make_nodeclaim(n))
        await wait_for(faults.gap_active, "the watch gap to open")
        for n in names[5:]:                 # ADDED events land in the gap
            await env.client.create(make_nodeclaim(n))
        await converge(env, names)
        assert set(env.cloud.nodepools.pools) == set(names)
        assert begin_creates(env) == len(names), "duplicate pool creates"
        # stale-cache reconciles during the gap re-derive conditions; the
        # no-op suppression (transition times bump only on flips) must eat
        # them — count WRITES, not flush attempts
        writes = env.status_batcher.writes
        assert writes / len(names) <= 3.0, \
            f"spurious status writes after relist: {writes}/{len(names)}"
        gaps = sum(i.watch_gaps
                   for i in env.informers._informers.values())
        assert gaps >= 1, "profile never forced a watch gap"


# ----------------------------------------------------- profile soaks (fast)

@async_test
async def test_soak_apiserver_brownout_sheds_and_converges():
    faults = api_fault_profile("apiserver_brownout", seed=SEED,
                               brownout_duration=1.5)
    names = [f"bo{i}" for i in range(8)]
    shed_before = APIHEALTH["shed"]
    async with fault_env(faults) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        await converge(env, names)
        gov = env.governor
        assert gov.throttles_total + gov.failures_total > 0, \
            "brownout never reached the governor"
        assert gov.entries_total.get(BROWNOUT, 0) >= 1
        assert set(env.cloud.nodepools.pools) == set(names)
        assert begin_creates(env) == len(names)
        # one bundle per distinct degraded mode entered; flap re-entries
        # are suppressed, not duplicated
        assert degraded_bundle_keys(env.flight_recorder) == \
            set(gov.entries_total) - {HEALTHY}
    assert APIHEALTH["shed"] >= shed_before


@async_test
async def test_soak_apiserver_partition_fences_and_converges():
    faults = api_fault_profile("apiserver_partition", seed=SEED,
                               partition_start=0.3, partition_duration=1.0)
    names = [f"pt{i}" for i in range(8)]
    rec = TraceRecorder()
    probes.add_sink(rec)
    try:
        # slow node readiness so the wave is still mid-lifecycle when the
        # cut lands — an idle fleet sees no verbs fail and proves nothing
        async with fault_env(faults, node_ready_delay=0.5,
                             node_join_delay=0.2) as env:
            for n in names[:5]:
                await env.client.create(make_nodeclaim(n))
            await wait_for(faults.partition_active, "the partition to cut")
            for n in names[5:]:     # born into the outage: ADDEDs drop on
                await env.client.create(make_nodeclaim(n))  # the dead watch
            await converge(env, names)
            gov = env.governor
            assert gov.entries_total.get(PARTITIONED, 0) >= 1, \
                "partition never tripped the mode machine"
            assert gov.entries_total.get(CATCHUP, 0) >= 1
            assert set(env.cloud.nodepools.pools) == set(names)
            assert begin_creates(env) == len(names), "duplicate pool creates"
            assert degraded_bundle_keys(env.flight_recorder) == \
                set(gov.entries_total) - {HEALTHY}
    finally:
        probes.remove_sink(rec)
    assert check_partition_fenced_mutate(rec.events) == [], \
        "a cloud mutation landed inside the PARTITIONED window"


@async_test
async def test_soak_catchup_storm_stays_paced():
    faults = api_fault_profile("catchup_storm", seed=SEED)
    names = [f"cs{i}" for i in range(10)]
    rec = TraceRecorder()
    probes.add_sink(rec)
    try:
        async with fault_env(faults, launch_timeout=30.0,
                             node_ready_delay=0.5,
                             node_join_delay=0.2) as env:
            for n in names:
                await env.client.create(make_nodeclaim(n))
            await converge(env, names, timeout=40.0)
            gov = env.governor
            assert gov.entries_total.get(PARTITIONED, 0) >= 1
            assert gov.entries_total.get(CATCHUP, 0) >= 1
            assert set(env.cloud.nodepools.pools) == set(names)
            assert begin_creates(env) == len(names)
            relists = sum(i.relists
                          for i in env.informers._informers.values())
            assert relists > len(env.informers._informers), \
                "heal_410 must force a full-fleet relist beyond boot syncs"
    finally:
        probes.remove_sink(rec)
    assert check_partition_fenced_mutate(rec.events) == []


# -------------------------------------------- provider fence + healthz/metrics

@async_test
async def test_provider_refuses_cloud_mutation_while_partitioned():
    """The fence raises BEFORE the fence-check probe: a refused mutation
    must leave neither a fence-check nor a cloud-mutate event behind."""
    env = Env(EnvtestOptions(api_governor=False))   # un-started: direct call
    t = {"now": 0.0}
    g = APIHealthGovernor(clock=lambda: t["now"], partition_threshold=1)
    g.note_failure()
    assert g.partition_fenced()
    env.provider.api_governor = g
    rec = TraceRecorder()
    probes.add_sink(rec)
    try:
        with pytest.raises(PartitionFencedError):
            await env.provider.create(make_nodeclaim("fenced"))
    finally:
        probes.remove_sink(rec)
    assert begin_creates(env) == 0, "mutation escaped the partition fence"
    assert not [e for e in rec.events
                if e.name in ("fence-check", "cloud-mutate")]


@async_test
async def test_healthz_and_metrics_report_degraded_mode():
    from aiohttp.test_utils import TestClient, TestServer

    from gpu_provisioner_tpu.controllers.metrics import (
        DEGRADED_MODE, update_runtime_gauges,
    )
    from gpu_provisioner_tpu.operator.server import build_apps
    from gpu_provisioner_tpu.runtime import Manager

    mgr = Manager(InMemoryClient())
    t = {"now": 0.0}
    g = APIHealthGovernor(clock=lambda: t["now"], partition_threshold=1,
                          catchup_hold=3600.0)
    metrics_app, health_app = build_apps(mgr)
    async with TestClient(TestServer(health_app)) as hc:
        r = await hc.get("/healthz")
        assert r.status == 200 and await r.text() == "ok"
        g.note_failure()
        g.note_success()        # CATCHUP — worst (and sticky: huge hold)
        r = await hc.get("/healthz")
        assert r.status == 200, "liveness stays 200: a restart can't help"
        assert "degraded mode=CATCHUP" in await r.text()
    update_runtime_gauges(object())
    assert DEGRADED_MODE._value.get() == 3.0
    del g                       # drop from GOVERNORS before other tests


def test_metrics_ledger_deltas():
    from gpu_provisioner_tpu.controllers.metrics import (
        API_SHED_TOTAL, RELISTS_TOTAL, WATCH_GAPS_TOTAL,
        update_runtime_gauges,
    )
    from gpu_provisioner_tpu.runtime import apihealth

    update_runtime_gauges(object())     # flush any prior deltas
    before = (WATCH_GAPS_TOTAL._value.get(), RELISTS_TOTAL._value.get(),
              API_SHED_TOTAL._value.get())
    apihealth.note_watch_gap()
    apihealth.note_relist()
    apihealth.note_relist()
    apihealth.note_shed()
    update_runtime_gauges(object())
    assert WATCH_GAPS_TOTAL._value.get() == before[0] + 1
    assert RELISTS_TOTAL._value.get() == before[1] + 2
    assert API_SHED_TOTAL._value.get() == before[2] + 1


def test_flight_recorder_one_bundle_per_degraded_mode():
    rec = FlightRecorder()
    rec.degraded_entered(BROWNOUT, reason="throttled")
    rec.degraded_entered(BROWNOUT, reason="flap re-entry")
    rec.degraded_entered(PARTITIONED, reason="outage")
    assert degraded_bundle_keys(rec) == {BROWNOUT, PARTITIONED}
    assert rec.triggers_suppressed == 1


def test_schedfuzz_partition_fenced_mutate_checker():
    def ev(i, name, key):
        return FuzzEvent(i, name, key, "Task-1#abc", {})

    events = [ev(0, "cloud-mutate", "create:p0"),       # HEALTHY: fine
              ev(1, "api-mode", PARTITIONED),
              ev(2, "cloud-mutate", "create:p1"),       # violation
              ev(3, "api-mode", CATCHUP),
              ev(4, "cloud-mutate", "create:p2")]       # healed: fine
    out = check_partition_fenced_mutate(events)
    assert len(out) == 1 and out[0].seq == 2
    assert "PARTITIONED" in out[0].message


def test_api_fault_profiles_are_deterministic():
    a = api_fault_profile("apiserver_brownout", seed=11)
    b = api_fault_profile("apiserver_brownout", seed=11)
    c = api_fault_profile("apiserver_brownout", seed=12)
    draws_a = [a._draw("throttle", "get", n) for n in range(32)]
    assert draws_a == [b._draw("throttle", "get", n) for n in range(32)]
    assert draws_a != [c._draw("throttle", "get", n) for n in range(32)]
    with pytest.raises(ValueError, match="unknown API fault profile"):
        api_fault_profile("nope")


# ------------------------------------------------- acceptance soak (PR 16)

@pytest.mark.slow
@async_test_long
async def test_soak_200_claims_survive_30s_partition():
    """The PR 16 acceptance bar: a 200-claim wave with a 30-second total
    apiserver partition dropped mid-wave converges 100%, with zero
    duplicate pool creates, zero claims lost, exactly one flight-recorder
    bundle per degraded-mode entered, and a heal-time catch-up that stays
    inside the PR 11/12 gates (status patches/claim and timer-wake share).
    The schedfuzz checker replays the probe stream to prove no cloud
    mutation landed while partition-fenced."""
    faults = api_fault_profile("apiserver_partition", seed=SEED,
                               partition_start=0.6,
                               partition_duration=30.0)
    names = [f"ap{i:03d}" for i in range(200)]
    rec = TraceRecorder()
    probes.add_sink(rec)
    try:
        async with fault_env(faults, launch_timeout=90.0,
                             node_ready_delay=0.3, node_join_delay=0.1,
                             create_latency=0.05) as env:
            for n in names[:100]:           # first half: mid-wave cut
                await env.client.create(make_nodeclaim(n))
            await wait_for(faults.partition_active, "the partition to cut",
                           tick=0.05)
            # second half arrives DURING the outage: their ADDED events die
            # on the dead watch stream — only the gap resync can find them
            for n in names[100:]:
                await env.client.create(make_nodeclaim(n))
            await wait_for(lambda: not faults.partition_active(),
                           "the partition to heal", timeout=45.0, tick=0.25)
            wakes_at_heal = dict(WAKES)
            await converge(env, names, timeout=90.0)

            # -- zero duplicates, zero losses ----------------------------
            assert set(env.cloud.nodepools.pools) == set(names)
            assert begin_creates(env) == len(names), \
                "duplicate pool creates after the heal"

            # -- mode machine + flight recorder --------------------------
            gov = env.governor
            assert gov.entries_total.get(PARTITIONED, 0) >= 1
            assert gov.entries_total.get(CATCHUP, 0) >= 1
            assert degraded_bundle_keys(env.flight_recorder) == \
                set(gov.entries_total) - {HEALTHY}

            # -- catch-up storm inside the PR 11/12 gates ----------------
            writes = env.status_batcher.writes
            assert writes / len(names) <= 3.0, \
                f"status-write storm: {writes / len(names):.2f}/claim"
            delta = {k: WAKES.get(k, 0) - wakes_at_heal.get(k, 0)
                     for k in WAKES}
            wakes = sum(delta.values())
            timer_share = delta.get(SOURCE_TIMER, 0) / max(wakes, 1)
            # Catch-up is NOT steady state: 100 claims born during the
            # outage run their whole lifecycle post-heal, and their
            # in-progress/registration safety requeues race event
            # delivery while the CATCHUP pace throttles the backlog —
            # legitimate timer wakes (measured 0.1-0.2 across runs and
            # scales; bench_apifaults shares the bound). The gate is for
            # the real failure: a resync that stops carrying the wake
            # load pushes the share toward 1.0, not for the PR 12
            # steady-state 0.05.
            assert timer_share <= 0.3, (
                f"catch-up leaned on the timer safety net: "
                f"{timer_share:.3f} of {wakes} wakes {delta}")
            assert delta.get("watch", 0) > delta.get(SOURCE_TIMER, 0), \
                f"watch wakes did not dominate the catch-up: {delta}"
    finally:
        probes.remove_sink(rec)
    assert check_partition_fenced_mutate(rec.events) == [], \
        "cloud mutation landed while the incarnation was partition-fenced"
