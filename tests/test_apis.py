"""API machinery: serde round-trips, conditions, manifest loading."""

import yaml

from gpu_provisioner_tpu.apis import karpenter as kv1
from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.core import Node, NodeSpec, Taint
from gpu_provisioner_tpu.apis.meta import (
    FALSE, TRUE, UNKNOWN, CONDITION_READY, ObjectMeta, object_from_manifest,
)
from gpu_provisioner_tpu.apis.serde import now, parse_time


def make_nodeclaim(name="ws0", shape="tpu-v5e-8"):
    return kv1.NodeClaim(
        metadata=ObjectMeta(name=name, labels={
            wk.KAITO_WORKSPACE_LABEL: "ws",
            wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME,
        }),
        spec=kv1.NodeClaimSpec(
            requirements=[kv1.NodeSelectorRequirement(
                key=wk.INSTANCE_TYPE_LABEL, operator=kv1.IN, values=[shape])],
            resources=kv1.ResourceRequirements(requests={"storage": "50Gi"}),
            node_class_ref=kv1.NodeClassRef(group="kaito.sh", kind="KaitoNodeClass", name="default"),
        ),
    )


def test_serde_roundtrip_camelcase():
    nc = make_nodeclaim()
    nc.status.provider_id = "gce://p/us-central2-b/pool-0"
    d = nc.to_dict()
    assert d["apiVersion"] == "karpenter.sh/v1"
    assert d["kind"] == "NodeClaim"
    assert d["spec"]["nodeClassRef"]["kind"] == "KaitoNodeClass"
    assert d["status"]["providerID"].startswith("gce://")
    back = kv1.NodeClaim.from_dict(d)
    assert back.spec.requirements[0].key == wk.INSTANCE_TYPE_LABEL
    assert back.status.provider_id == nc.status.provider_id
    assert back.metadata.labels == nc.metadata.labels


def test_time_roundtrip():
    t = now()
    assert parse_time(t.strftime("%Y-%m-%dT%H:%M:%SZ")) == t


def test_conditions_ready_ladder():
    nc = make_nodeclaim()
    cs = nc.status_conditions
    cs.initialize()
    assert cs.get(CONDITION_READY).status == UNKNOWN
    cs.set_true(kv1.LAUNCHED)
    cs.set_true(kv1.REGISTERED)
    assert cs.get(CONDITION_READY).status == UNKNOWN  # Initialized still unknown
    cs.set_true(kv1.INITIALIZED)
    assert cs.get(CONDITION_READY).status == TRUE
    cs.set_false(kv1.REGISTERED, "NodeGone")
    assert cs.get(CONDITION_READY).status == FALSE
    assert cs.get(CONDITION_READY).reason == "NodeGone"


def test_condition_transition_time_stable():
    nc = make_nodeclaim()
    cs = nc.status_conditions
    cs.set_true(kv1.LAUNCHED, "r1")
    t1 = cs.get(kv1.LAUNCHED).last_transition_time
    cs.set_true(kv1.LAUNCHED, "r2")  # same status → transition time unchanged
    assert cs.get(kv1.LAUNCHED).last_transition_time == t1


def test_manifest_loading_and_deepcopy():
    y = """
apiVersion: karpenter.sh/v1
kind: NodeClaim
metadata:
  name: ws-tpu
  labels:
    kaito.sh/workspace: ws
spec:
  requirements:
    - key: node.kubernetes.io/instance-type
      operator: In
      values: ["tpu-v5p-32"]
"""
    obj = object_from_manifest(yaml.safe_load(y))
    assert isinstance(obj, kv1.NodeClaim)
    cp = obj.deepcopy()
    cp.metadata.labels["x"] = "y"
    assert "x" not in obj.metadata.labels


def test_node_ready_and_taints():
    n = Node(metadata=ObjectMeta(name="n0"),
             spec=NodeSpec(provider_id="gce://p/z/i",
                           taints=[Taint(key=wk.UNREGISTERED_TAINT)]))
    assert not n.is_ready()
    from gpu_provisioner_tpu.apis.meta import Condition
    n.status.conditions.append(Condition(type="Ready", status=TRUE))
    assert n.is_ready()
