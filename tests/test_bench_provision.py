"""Bench-harness smoke tests (marked ``bench`` + ``slow``: excluded from the
tier-1 gate, run via ``make bench`` / ``pytest -m bench``)."""

import pytest

from bench.bench_megawave import check_timer_share
from bench.bench_provision import (
    bench_constrained_wave, bench_gc_pass, check_budget, check_pr04_budget,
    check_pr09, make_budget, make_pr04_budget,
)

from .conftest import async_test

pytestmark = [pytest.mark.bench, pytest.mark.slow]


@async_test
async def test_gc_pass_fast_path_beats_legacy():
    """The PR's headline claim at smoke scale: the fast list path does ONE
    bulk Node list per GC pass (legacy did one per pool) and wins wall
    clock under a modeled apiserver RTT."""
    before = await bench_gc_pass(20, legacy=True)
    after = await bench_gc_pass(20, legacy=False)
    # fast path: 1 bulk Node list (cp.list) + 1 orphan-node list + 1 claim list
    assert after["kube_lists_total"] == 3, after
    assert after["kube_node_lists"] == 2, after
    assert before["kube_node_lists"] >= 20, before
    assert before["list_path_calls"] / after["list_path_calls"] >= 5
    assert before["wall_s"] > after["wall_s"]


@async_test
async def test_gc_pass_reaps_nothing_during_measurement():
    out = await bench_gc_pass(5, legacy=False)
    assert out["pools"] == 5  # asserted inside the harness too


@async_test
async def test_constrained_wave_tracker_beats_blocking():
    """PR 4's headline at smoke scale: with workers squeezed, the tracker
    wave wins wall clock, pins far fewer worker-seconds, and issues ZERO
    client-side LRO polls (the blocking baseline polls per operation)."""
    before = await bench_constrained_wave(12, workers=4, blocking=True,
                                          create_latency=0.2)
    after = await bench_constrained_wave(12, workers=4, blocking=False,
                                         create_latency=0.2)
    assert before["poll_calls"]["operation_poll"] > 0
    assert after["poll_calls"]["operation_poll"] == 0
    assert before["ready_wall_s"] > after["ready_wall_s"]
    assert (before["pinned_worker_seconds_total"]
            > after["pinned_worker_seconds_total"])
    assert before["leaked_pools"] == after["leaked_pools"] == 0


def test_pr04_budget_check_flags_regression_and_passes_clean():
    recorded = {"budget": {"constrained_wave_poll_calls": 600,
                           "constrained_wave_pinned_worker_seconds": 6.0}}
    bad = {"after": {"poll_calls_total": 4000,
                     "pinned_worker_seconds_total": 90.0}}
    violations = check_pr04_budget(bad, recorded)
    assert any("poll calls" in v for v in violations)
    assert any("pinned-worker-seconds" in v for v in violations)

    good = {"after": {"poll_calls_total": 200,
                      "pinned_worker_seconds_total": 2.0}}
    assert check_pr04_budget(good, recorded) == []
    derived = make_pr04_budget(good)
    assert derived["constrained_wave_poll_calls"] == 600
    assert derived["constrained_wave_pinned_worker_seconds"] == 6.0


def test_budget_check_flags_regression_and_passes_clean():
    recorded = {"budget": {"gc_pass_kube_lists": 3,
                           "gc_pass_cloud_calls": 2,
                           "wave_cloud_calls_per_claim": 10.0}}
    bad = {"gc_pass": {"after": {"kube_lists_total": 23,
                                 "cloud_calls": {"list": 1}}},
           "wave": {"claims": 10, "cloud_calls_total": 500}}
    violations = check_budget(bad, recorded)
    assert any("kube lists" in v for v in violations)
    assert any("wave cloud calls" in v for v in violations)

    good = {"gc_pass": {"after": {"kube_lists_total": 3,
                                  "cloud_calls": {"list": 1}}},
            "wave": {"claims": 10, "cloud_calls_total": 80}}
    assert check_budget(good, recorded) == []
    derived = make_budget(good)
    assert derived["gc_pass_kube_lists"] == 3
    assert derived["wave_cloud_calls_per_claim"] == 24.0  # 3× headroom


def test_timer_wake_share_gate_flags_fallback_storm():
    healthy = {"timer_wake_share": 0.001,
               "wakes_by_source": {"watch": 999, "timer": 1}}
    assert check_timer_share(healthy, "reference") == []
    storm = {"timer_wake_share": 0.62,
             "wakes_by_source": {"timer": 620, "watch": 380}}
    (violation,) = check_timer_share(storm, "reference")
    assert "62.0%" in violation and "safety-net" in violation


def test_pr09_gate_flags_overhead_and_low_attribution():
    good = {"attribution": {"attributed_fraction": 0.99},
            "tracing_overhead_fraction": 0.03}
    assert check_pr09(good) == []
    bad = {"attribution": {"attributed_fraction": 0.5},
           "tracing_overhead_fraction": 0.4}
    violations = check_pr09(bad)
    assert any("attribution too low" in v for v in violations)
    assert any("overhead regressed" in v for v in violations)
