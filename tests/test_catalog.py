"""Catalog + scheduling: requirements → slice shape resolution."""

import pytest

from gpu_provisioner_tpu import catalog
from gpu_provisioner_tpu.apis import karpenter as kv1
from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.scheduling import Requirements

from .test_apis import make_nodeclaim


def reqs(*pairs, labels=None):
    nc = make_nodeclaim()
    nc.spec.requirements = [
        kv1.NodeSelectorRequirement(key=k, operator=op, values=list(vals))
        for (k, op, vals) in pairs
    ]
    nc.metadata.labels = labels or {}
    return Requirements.from_nodeclaim(nc)


def test_instance_type_first_value_wins():
    r = reqs((wk.INSTANCE_TYPE_LABEL, kv1.IN, ["tpu-v5e-8", "tpu-v5p-32"]))
    s = catalog.resolve(r)
    assert s.generation == "v5e" and s.chips == 8 and s.hosts == 1
    assert s.topology == "2x4" and s.machine_type == "ct5lp-hightpu-8t"


def test_v5p_32_is_four_hosts():
    # v5p-32 counts TensorCores: 16 chips, 4 hosts on a 2x2x4 ICI torus
    # (BASELINE.json multi-host config; SURVEY.md §2c).
    s = catalog.lookup("v5p-32")
    assert s.chips == 16 and s.hosts == 4 and s.topology == "2x2x4"
    assert s.multi_host and s.ici_dims == (2, 2, 4)


def test_aliases():
    assert catalog.lookup("v5litepod-8") is catalog.lookup("tpu-v5e-8")
    assert catalog.lookup("V5E-8") is catalog.lookup("tpu-v5e-8")
    assert catalog.lookup("v5p/2x2x4") is catalog.lookup("v5p-32")


def test_accelerator_topology_resolution():
    r = reqs((wk.TPU_ACCELERATOR_LABEL, kv1.IN, ["v5e"]),
             (wk.TPU_TOPOLOGY_LABEL, kv1.IN, ["4x8"]))
    s = catalog.resolve(r)
    assert s.chips == 32 and s.hosts == 4


def test_chip_count_resource_request():
    r = reqs((wk.TPU_ACCELERATOR_LABEL, kv1.IN, ["v6e"]))
    s = catalog.resolve(r, resources={wk.TPU_RESOURCE_NAME: "5"})
    assert s.generation == "v6e" and s.chips == 8  # smallest fitting


def test_unknown_shape_raises():
    r = reqs((wk.INSTANCE_TYPE_LABEL, kv1.IN, ["Standard_NC12s_v3"]))
    with pytest.raises(catalog.UnknownShapeError):
        catalog.resolve(r)


def test_labels_act_as_requirements():
    r = reqs(labels={wk.INSTANCE_TYPE_LABEL: "tpu-v4-32"})
    s = catalog.resolve(r)
    assert s.generation == "v4" and s.chips == 16 and s.hosts == 4


def test_node_labels_and_capacity():
    s = catalog.lookup("tpu-v5e-16")
    labels = s.node_labels(slice_id="pool-abc")
    assert labels[wk.GKE_TPU_TOPOLOGY_LABEL] == "4x4"
    assert labels[wk.TPU_HOSTS_LABEL] == "2"
    assert labels[wk.TPU_SLICE_ID_LABEL] == "pool-abc"
    assert labels[wk.KAITO_MACHINE_TYPE_LABEL] == "tpu"
    cap = s.per_host_capacity()
    assert cap[wk.TPU_RESOURCE_NAME] == "8"


def test_node_labels_record_placement_verdict():
    """Zone/tier parity: the placement walk's verdict rides the pool labels
    onto every node of the slice — and stays absent for direct callers that
    never made a placement decision."""
    s = catalog.lookup("tpu-v5e-16")
    bare = s.node_labels(slice_id="pool-abc")
    assert wk.ZONE_LABEL not in bare
    assert wk.TPU_CAPACITY_TIER_LABEL not in bare
    placed = s.node_labels(slice_id="pool-abc", zone="us-central2-c",
                           capacity_tier="spot")
    assert placed[wk.ZONE_LABEL] == "us-central2-c"
    assert placed[wk.TPU_CAPACITY_TIER_LABEL] == "spot"
    # the placement labels ride along without disturbing the slice identity
    assert placed[wk.TPU_SLICE_ID_LABEL] == "pool-abc"


def test_requirements_algebra():
    r = reqs((wk.TPU_ACCELERATOR_LABEL, kv1.IN, ["v5e", "v5p"]),
             (wk.TPU_ACCELERATOR_LABEL, kv1.IN, ["v5p"]))
    assert r.get(wk.TPU_ACCELERATOR_LABEL).values() == ["v5p"]
    assert r.compatible({wk.TPU_ACCELERATOR_LABEL: "v5p"})
    assert not r.compatible({wk.TPU_ACCELERATOR_LABEL: "v5e"})
    r2 = reqs((wk.ZONE_LABEL, kv1.NOT_IN, ["us-east1-a"]))
    assert r2.compatible({})
    assert not r2.compatible({wk.ZONE_LABEL: "us-east1-a"})


def test_every_catalog_entry_consistent():
    for s in catalog.CATALOG:
        import math
        assert math.prod(s.ici_dims) == s.chips, s.name
        assert s.chips == s.hosts * s.chips_per_host or s.hosts == 1, s.name
        assert catalog.lookup(s.name) is not None
