"""Chaos soak suite: convergence invariants under named fault profiles.

Every test runs the WHOLE provisioner (envtest) under a seeded
``chaos.ChaosPolicy`` and asserts the three robustness invariants the fleet
depends on:

1. every NodeClaim converges — Ready, or correctly terminally deleted;
2. zero leaked or duplicate cloud resources — node pools and queued
   resources in the fake cloud exactly match the surviving claims;
3. zero wedged workqueue items — after convergence no controller queue
   holds a ready item or a live failure counter.

Profiles are deterministic for a fixed seed (keyed hash draws, not a shared
RNG stream), so a failure here reproduces with ``CHAOS_SEED=<n> make chaos``.
"""

import asyncio
import os

import httpx
import pytest

from gpu_provisioner_tpu import chaos
from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import CONDITION_READY
from gpu_provisioner_tpu.auth.credentials import StaticTokenCredential
from gpu_provisioner_tpu.apis.core import Node
from gpu_provisioner_tpu.controllers.metrics import (
    BREAKER_STATE, WORKQUEUE_RETRYING, update_runtime_gauges,
)
from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
from gpu_provisioner_tpu.fake import make_nodeclaim
from gpu_provisioner_tpu.providers.gcp import APIError
from gpu_provisioner_tpu.providers.instance import PROVISIONING_MODE_ANNOTATION
from gpu_provisioner_tpu.providers.rest import GKENodePoolsClient
from gpu_provisioner_tpu.runtime.client import NotFoundError
from gpu_provisioner_tpu.runtime.workqueue import RateLimitingQueue
from gpu_provisioner_tpu.transport import (
    BREAKER_CLOSED, BREAKER_OPEN, BREAKERS, BreakerOpenError, CircuitBreaker,
    TransportOptions, request_with_retries,
)

from .conftest import async_test

pytestmark = pytest.mark.chaos

SEED = int(os.environ.get("CHAOS_SEED", "7"))


def chaos_env(policy, launch_timeout: float = 2.0, **opt_kw) -> Env:
    """Envtest tuned for soak: fast GC, short liveness budget, and a small
    queue max_delay so the post-exhaustion slow-retry cadence fits test
    time (production keeps client-go's 1000s)."""
    opts = EnvtestOptions(chaos=policy, gc_interval=0.1, leak_grace=0.1,
                          **opt_kw)
    opts.lifecycle.launch_timeout = launch_timeout
    opts.lifecycle.registration_timeout = launch_timeout
    env = Env(opts)
    for i, c in enumerate(env.manager.controllers):
        c.queue.max_delay = 0.5
        c.queue._rng.seed((SEED << 8) | i)  # reproducible jitter draws
    return env


async def converge(env: Env, names: list[str], timeout: float = 20.0
                   ) -> tuple[set[str], set[str]]:
    """Wait until every claim is Ready or gone; returns (ready, gone)."""
    deadline = asyncio.get_event_loop().time() + timeout
    ready: set[str] = set()
    gone: set[str] = set()
    while True:
        for name in set(names) - ready - gone:
            try:
                nc = await env.client.get(NodeClaim, name)
            except NotFoundError:
                gone.add(name)
                continue
            if nc.status_conditions.is_true(CONDITION_READY):
                ready.add(name)
        if ready | gone == set(names):
            return ready, gone
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(
                f"claims did not converge: ready={sorted(ready)} "
                f"gone={sorted(gone)} of {sorted(names)}")
        await asyncio.sleep(0.05)


async def assert_no_leaks_and_drained(env: Env, ready: set[str],
                                      timeout: float = 10.0) -> None:
    """The leak + wedge invariants, with a settle loop: deletes/GC for the
    terminal claims may still be in flight when convergence is observed."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        pools = set(env.cloud.nodepools.pools)
        qrs = set(env.cloud.queuedresources.resources)
        queues_ok = all(
            c.queue.depth() == 0 and c.queue.retrying() == 0
            for c in env.manager.controllers if not c.singleton)
        nodes = await env.client.list(Node)
        node_pools = {n.metadata.labels.get(wk.GKE_NODEPOOL_LABEL)
                      for n in nodes}
        if (pools == ready and not qrs and queues_ok
                and node_pools <= ready | {None}):
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(
                f"leak/wedge invariant violated: pools={sorted(pools)} "
                f"(want {sorted(ready)}), queued={sorted(qrs)} (want none), "
                f"orphan-node-pools={sorted((node_pools - ready) - {None}, key=str)}, "
                f"queues_drained={queues_ok}")
        await asyncio.sleep(0.05)


# ------------------------------------------------------------ soak profiles

@async_test
async def test_soak_flaky_cloud_converges():
    """20% transient 5xx on every cloud call: everything still reaches
    Ready, nothing leaks, no queue wedges."""
    policy = chaos.profile("flaky-cloud", seed=SEED)
    names = [f"fl{i}" for i in range(6)]
    async with chaos_env(policy, launch_timeout=10.0) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        ready, gone = await converge(env, names, timeout=30.0)
        assert ready == set(names), f"terminal deletions under flake: {gone}"
        assert policy.injected_total("error:") > 0, "profile injected nothing"
        await assert_no_leaks_and_drained(env, ready)


@async_test
async def test_soak_stockout_bursts_terminate_cleanly():
    """First creates hit RESOURCE_EXHAUSTED: exactly those claims are
    terminally deleted (KAITO's re-shape contract), the rest reach Ready,
    and the stockout victims leave nothing behind.

    The memo TTL is zeroed: this soak pins the PRE-memo burst contract
    (exactly the probed claims die); the memo's N-claims-one-probe behavior
    has its own soak in tests/test_placement.py."""
    policy = chaos.profile("stockout-flaky", seed=SEED)
    names = [f"so{i}" for i in range(5)]
    async with chaos_env(policy, launch_timeout=10.0,
                         stockout_memo_ttl=0.0) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        ready, gone = await converge(env, names, timeout=30.0)
        # the profile injects 429 on exactly the first two begin_create calls
        assert len(gone) == 2, f"want 2 stockout deletions, got {sorted(gone)}"
        assert policy.injected["error:nodepools.begin_create"] >= 2
        await assert_no_leaks_and_drained(env, ready)


@async_test
async def test_soak_stockout_window_terminates_inside_claims():
    """The capacity-model ``stockout`` profile dries EVERY zone for its
    first second: claims whose placement walk runs inside the window are
    terminally deleted (single-candidate legacy contract — the claim can
    never launch as specified) and leave nothing behind."""
    policy = chaos.profile("stockout", seed=SEED)
    names = [f"sw{i}" for i in range(2)]
    async with chaos_env(policy, launch_timeout=10.0) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        ready, gone = await converge(env, names, timeout=30.0)
        assert gone == set(names), \
            f"dry-window claims must terminate, got ready={sorted(ready)}"
        assert policy.injected_total("stockout:") >= 1, \
            "the dry window never fired"
        await assert_no_leaks_and_drained(env, set())


@async_test
async def test_soak_partial_provision_reaps_doomed_pools():
    """Pools report RUNNING but kubelets never join for ~half the claims:
    launch liveness must reap the claims and GC the half-created pools —
    the dominant orphaned-capacity failure mode."""
    policy = chaos.profile("partial-provision", seed=SEED)
    names = [f"pp{i}" for i in range(6)]
    doomed = {n for n in names if policy._draw("no_join", n) < 0.5}
    assert 0 < len(doomed) < len(names), \
        f"seed {SEED} gives a degenerate split; pick another"
    async with chaos_env(policy, launch_timeout=1.5) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        ready, gone = await converge(env, names, timeout=30.0)
        assert gone == doomed
        assert ready == set(names) - doomed
        await assert_no_leaks_and_drained(env, ready)


@async_test
async def test_soak_stuck_queued_resource_does_not_leak_qr():
    """Queued capacity wedges mid-ladder (stuck CREATING): liveness reaps
    the claims and — the leak the chaos suite found — the queued resources
    must be cleaned up even though no pool ever existed."""
    policy = chaos.profile("stuck-queue", seed=SEED)
    names = [f"sq{i}" for i in range(3)]
    async with chaos_env(policy, launch_timeout=1.0) as env:
        for n in names:
            await env.client.create(make_nodeclaim(
                n, annotations={PROVISIONING_MODE_ANNOTATION: "queued"}))
        ready, gone = await converge(env, names, timeout=20.0)
        assert gone == set(names), "stuck queued claims must be reaped"
        await assert_no_leaks_and_drained(env, set())


@async_test
async def test_soak_stuck_queue_cached_provider_no_qr_leak():
    """PR 2 composition check: the read-through instance cache + informer
    layering must preserve PR 1's stuck-queue invariant — delete() still
    performs queued-resource cleanup FIRST, and no cached (or negative)
    entry lets a retried delete skip it. Zero leaked queued resources."""
    from gpu_provisioner_tpu.providers.instance import has_index

    policy = chaos.profile("stuck-queue", seed=SEED)
    names = [f"cq{i}" for i in range(3)]
    async with chaos_env(policy, launch_timeout=1.0,
                         use_informer=True) as env:
        assert env.provider.cfg.cache_ttl > 0, "cache must actually be on"
        assert has_index(env.provider.kube), "index wiring must survive"
        for n in names:
            await env.client.create(make_nodeclaim(
                n, annotations={PROVISIONING_MODE_ANNOTATION: "queued"}))
        ready, gone = await converge(env, names, timeout=20.0)
        assert gone == set(names), "stuck queued claims must be reaped"
        await assert_no_leaks_and_drained(env, set())
        assert env.provider.queued.calls["delete"] >= len(names), \
            "queued cleanup must have run through the counted seam"


@async_test
async def test_soak_operation_result_error_no_duplicate_pools():
    """LRO done()→result() raises and leaves an ERROR pool carcass: retries
    must replace the carcass in place — never duplicate, never wedge."""
    policy = chaos.profile("op-error", seed=SEED)
    names = [f"oe{i}" for i in range(5)]
    async with chaos_env(policy, launch_timeout=15.0) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        ready, gone = await converge(env, names, timeout=30.0)
        assert ready == set(names), f"op-error must be retried through: {gone}"
        assert policy.injected_total("op_error:") > 0
        await assert_no_leaks_and_drained(env, ready)


@async_test
async def test_soak_outage_backoff_bounds_call_rate():
    """Sustained 100% outage of the node-pool API: claims cannot converge —
    the invariant is COST. Decorrelated-jitter backoff must keep the cloud
    call rate O(log) per claim, not a hot loop, and the failure counters
    must be visible on the workqueue gauges."""
    policy = chaos.profile("outage", seed=SEED)
    names = [f"ou{i}" for i in range(4)]
    async with chaos_env(policy, launch_timeout=60.0) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        await asyncio.sleep(2.0)
        calls = env.cloud.nodepools.calls["begin_create"]
        # hot-looping 4 claims for 2s would be thousands of calls; the
        # jittered ladder (~1.5× growth per retry, then the 0.5s cap this
        # suite sets) averages ~17 per claim with a heavy tail — bound at
        # the tail's ceiling, still an order of magnitude under a storm
        assert calls <= 40 * len(names), f"retry storm: {calls} creates in 2s"
        # nothing terminally deleted — 503 is weather, not an answer
        for n in names:
            await env.client.get(NodeClaim, n)
        lifecycle = next(c for c in env.manager.controllers
                         if c.name == "nodeclaim.lifecycle")
        assert lifecycle.queue.retrying() > 0, "claims should be in backoff"
        update_runtime_gauges(env.manager)
        assert (WORKQUEUE_RETRYING.labels("nodeclaim.lifecycle")._value.get()
                > 0), "backoff state must be visible on the exported gauge"


@async_test
async def test_soak_hang_injection_trips_reconcile_deadline():
    """Hung cloud calls are cancelled at the per-reconcile deadline, counted,
    and retried to convergence — a wedged API call must never park a worker
    forever."""
    policy = chaos.ChaosPolicy(SEED, rules=[
        chaos.FaultRule(match="nodepools.begin_create", hang=30.0,
                        hang_rate=1.0, until=2),
    ])
    names = [f"hg{i}" for i in range(3)]
    async with chaos_env(policy, launch_timeout=20.0,
                         reconcile_timeout=2.0) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        ready, gone = await converge(env, names, timeout=30.0)
        assert ready == set(names)
        lifecycle = next(c for c in env.manager.controllers
                         if c.name == "nodeclaim.lifecycle")
        assert lifecycle.timeouts_total >= 1, "deadline never fired"
        await assert_no_leaks_and_drained(env, ready)


@async_test
async def test_soak_flaky_apiserver_converges():
    """kube.* chaos: a flaky apiserver (10% transient errors on reads and
    writes) must also be retried through to full convergence."""
    policy = chaos.ChaosPolicy(SEED, rules=[
        chaos.FaultRule(match="kube.*", rate=0.1,
                        error=chaos.transient_kube()),
    ])
    names = [f"ka{i}" for i in range(4)]
    async with chaos_env(policy, launch_timeout=10.0) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        ready, gone = await converge(env, names, timeout=30.0)
        assert ready == set(names)
        assert policy.injected_total("error:kube") > 0
        await assert_no_leaks_and_drained(env, ready)


# ------------------------------------------------------- retry exhaustion

@async_test
async def test_retry_exhaustion_emits_warning_and_degrades():
    """A persistently-failing item stops climbing the backoff ladder after
    max_retries: warning event + metric, counter forgotten, slow retry
    cadence — and once the fault clears, the claim still converges."""
    policy = chaos.ChaosPolicy(SEED, rules=[
        chaos.FaultRule(match="nodepools.begin_create", rate=1.0, until=6,
                        error=chaos.transient(503)),
    ])
    async with chaos_env(policy, launch_timeout=30.0) as env:
        lifecycle = next(c for c in env.manager.controllers
                         if c.name == "nodeclaim.lifecycle")
        lifecycle.max_retries = 3  # exhaust quickly: 6 hard failures ahead
        await env.client.create(make_nodeclaim("ex0"))
        nc = await env.wait_ready("ex0", timeout=20)
        assert nc.status_conditions.is_true(CONDITION_READY)
        assert lifecycle.retries_exhausted_total >= 1
        from gpu_provisioner_tpu.apis.core import Event
        events = await env.client.list(Event)
        assert any(e.reason == "ReconcileRetriesExhausted" for e in events), \
            [e.reason for e in events]


# ------------------------------------------------------ workqueue jitter

@async_test
async def test_decorrelated_jitter_desynchronizes_retry_wave():
    """Items that failed together must not retry in lockstep: with
    decorrelated jitter the per-item delays diverge; with base*2**n they
    would be byte-identical."""
    q = RateLimitingQueue(base_delay=0.01, max_delay=10.0, seed=SEED)
    items = [f"item{i}" for i in range(8)]
    for _ in range(4):  # four synchronized failure rounds
        for it in items:
            await q.add_rate_limited(it)
        while q.delayed() or len(q):
            try:
                got = await asyncio.wait_for(q.get(), 5)
            except asyncio.TimeoutError:
                break
            await q.done(got)
    delays = {round(q._last_delay[it], 6) for it in items}
    assert len(delays) > len(items) // 2, \
        f"retry wave stayed synchronized: {delays}"
    assert all(q._last_delay[it] <= 10.0 for it in items)
    assert q.requeues_total == 4 * len(items)
    await q.forget("item0")
    assert "item0" not in q._last_delay and q.num_requeues("item0") == 0


# ------------------------------------------------------- circuit breaker

def test_circuit_breaker_state_machine():
    t = {"now": 0.0}
    b = CircuitBreaker("t", failure_threshold=3, reset_timeout=10.0,
                       clock=lambda: t["now"])
    assert b.state == BREAKER_CLOSED and b.allow()
    for _ in range(3):
        b.record_failure()
    assert b.state == BREAKER_OPEN
    assert not b.allow() and b.rejected_total == 1
    t["now"] = 10.1                       # half-open: exactly one probe
    assert b.allow()
    assert not b.allow(), "second probe must be rejected"
    b.record_failure()                    # probe failed → re-open
    assert b.state == BREAKER_OPEN and not b.allow()
    t["now"] = 20.3
    assert b.allow()
    b.record_success()                    # probe succeeded → closed
    assert b.state == BREAKER_CLOSED and b.consecutive_failures == 0
    # a probe that leaks (caller died, no verdict ever recorded) must not
    # wedge the breaker half-open: after a full reset window with no
    # answer, a fresh probe is admitted
    for _ in range(3):
        b.record_failure()                # open again at t=20.3
    t["now"] = 30.4
    assert b.allow()                      # probe admitted, never resolved
    assert not b.allow()
    t["now"] = 40.5
    assert b.allow(), "stale unresolved probe must be superseded"
    # and an explicitly released probe frees the slot immediately
    b.release_probe()
    assert b.allow()


@async_test
async def test_breaker_prevents_hot_loop_and_recovers():
    """Sustained outage at the REST layer: once the breaker opens, reconcile
    attempts cost zero HTTP calls until the reset window; after recovery the
    half-open probe closes it and traffic resumes."""
    hits = {"n": 0}
    healthy = {"v": False}

    def handler(req: httpx.Request) -> httpx.Response:
        hits["n"] += 1
        if healthy["v"]:
            return httpx.Response(200, json={"name": "p1", "config": {},
                                             "initialNodeCount": 1})
        return httpx.Response(503, text="backend down")

    topts = TransportOptions(max_retries=2, backoff_base=0.001,
                             backoff_cap=0.002, breaker_threshold=5,
                             breaker_reset=0.2)
    gke = GKENodePoolsClient(
        StaticTokenCredential("tok"), "p", "l", "c",
        transport=topts,
        http=httpx.AsyncClient(transport=httpx.MockTransport(handler)))
    # outage: hammer get() the way a naive controller would
    for _ in range(30):
        with pytest.raises(APIError):
            await gke.get("p1")
    # 30 calls × 3 attempts = 90 without a breaker; it opens after 5
    assert hits["n"] <= 6, f"breaker did not bound outage traffic: {hits}"
    assert gke.breaker.state == BREAKER_OPEN
    assert gke.breaker.rejected_total > 0
    # the open-breaker error surfaces as a retryable 503, NOT a 4xx —
    # controllers requeue with backoff instead of failing terminally
    try:
        await gke.get("p1")
    except APIError as e:
        assert e.code == 503 and not e.exhausted and not e.not_found
    # recovery: after the reset window one probe goes through and closes it
    healthy["v"] = True
    await asyncio.sleep(0.25)
    pool = await gke.get("p1")
    assert pool.name == "p1"
    assert gke.breaker.state == BREAKER_CLOSED
    update_runtime_gauges(object())  # no manager: breaker gauges only
    assert BREAKER_STATE.labels(gke.breaker.name)._value.get() == 0.0
    assert BREAKERS.get(gke.breaker.name) is gke.breaker
    await gke.aclose()
    assert gke.breaker.name not in BREAKERS, "closed client must unregister"


@async_test
async def test_cancelled_probe_releases_breaker_slot():
    """A reconcile-deadline cancellation mid-probe leaves no HTTP verdict;
    the transport must free the probe slot instead of blackholing the
    endpoint until restart."""
    b = CircuitBreaker("probe-leak", failure_threshold=1, reset_timeout=0.01)

    def handler(req: httpx.Request) -> httpx.Response:
        raise asyncio.CancelledError()

    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    b.record_failure()                    # open
    await asyncio.sleep(0.02)             # into the half-open window
    with pytest.raises(asyncio.CancelledError):
        await request_with_retries(http, "GET", "https://x.test/a",
                                   opts=TransportOptions(max_retries=0),
                                   breaker=b)
    assert b.allow(), "cancelled probe must not wedge the breaker"
    await http.aclose()


@async_test
async def test_request_with_retries_raises_breaker_open_immediately():
    async def handler(req):
        return httpx.Response(500, text="boom")

    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    b = CircuitBreaker("rwr", failure_threshold=2, reset_timeout=60.0)
    opts = TransportOptions(max_retries=5, backoff_base=0.001,
                            backoff_cap=0.002)
    with pytest.raises(BreakerOpenError) as ei:
        await request_with_retries(http, "GET", "https://x.test/a",
                                   opts=opts, breaker=b)
    assert ei.value.retry_after > 0
    await http.aclose()


# ---------------------------------------------------------- policy basics

@async_test
async def test_chaos_policy_is_deterministic_and_windowed():
    async def collect(policy):
        out = []
        for _ in range(40):
            try:
                await policy.before_call("nodepools", "get")
                out.append("ok")
            except APIError as e:
                out.append(e.code)
        return out

    rules = [chaos.FaultRule(match="nodepools.*", rate=0.3,
                             error=chaos.transient(503), after=5, until=30)]
    a = await collect(chaos.ChaosPolicy(11, rules=rules))
    b = await collect(chaos.ChaosPolicy(11, rules=rules))
    c = await collect(chaos.ChaosPolicy(12, rules=rules))
    assert a == b, "same seed must inject identically"
    assert a != c, "different seed should differ"
    assert all(x == "ok" for x in a[:5]), "window: no faults before `after`"
    assert all(x == "ok" for x in a[30:]), "window: no faults past `until`"
    assert any(x == 503 for x in a[5:30])


def test_unknown_profile_is_an_error():
    with pytest.raises(ValueError):
        chaos.profile("no-such-profile")
