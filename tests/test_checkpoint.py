"""Sharded checkpoint save/restore incl. cross-mesh resharding restore."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from gpu_provisioner_tpu.models.checkpoint import (restore_train_state,
                                                   save_train_state)
from gpu_provisioner_tpu.models.llama import PRESETS
from gpu_provisioner_tpu.models.train import (BATCH_SPEC, default_optimizer,
                                              make_train_state,
                                              make_train_step)
from gpu_provisioner_tpu.parallel import make_mesh

CFG = PRESETS["tiny"]


def _one_step(mesh, params, opt_state, opt):
    step = make_train_step(mesh, CFG, opt)
    toks = jax.random.randint(jax.random.key(1), (8, 33), 0, CFG.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    return step(params, opt_state, put(toks[:, :-1]), put(toks[:, 1:]))


def test_checkpoint_roundtrip_and_cross_mesh_restore(tmp_path):
    opt = default_optimizer()
    mesh_dp = make_mesh(8)                      # dp8
    params, opt_state, _ = make_train_state(jax.random.key(0), CFG, mesh_dp,
                                            optimizer=opt)
    params, opt_state, _ = _one_step(mesh_dp, params, opt_state, opt)
    save_train_state(tmp_path / "ckpt", params, opt_state, step=1)

    # restore onto a DIFFERENT topology: tp2 × sp2 × dp2 — orbax reshards
    mesh_tp = make_mesh(8, tp=2, sp=2)
    r_params, r_opt, step = restore_train_state(tmp_path / "ckpt", mesh_tp,
                                                CFG, opt)
    assert step == 1
    assert jax.tree.structure(params) == jax.tree.structure(r_params)
    assert jax.tree.structure(opt_state) == jax.tree.structure(r_opt)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(r_params),
                    strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(opt_state), jax.tree.leaves(r_opt),
                    strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the restored state trains on the new mesh and matches the old mesh's
    # next-step loss (same data, same math, different sharding)
    _, _, loss_new = _one_step(mesh_tp, r_params, r_opt, opt)
    _, _, loss_old = _one_step(mesh_dp, params, opt_state, opt)
    np.testing.assert_allclose(float(loss_new), float(loss_old),
                               atol=2e-2, rtol=2e-3)  # bf16 reduction order


def test_checkpoint_refuses_pipeline_layout_mismatch(tmp_path):
    """A checkpoint stamped with an interleaved pipeline layout must not
    restore through a logical-order (or different-geometry) target — that
    would silently permute layers (ADVICE r3)."""
    import pytest

    opt = default_optimizer()
    mesh = make_mesh(8)
    params, opt_state, _ = make_train_state(jax.random.key(0), CFG, mesh,
                                            optimizer=opt)
    save_train_state(tmp_path / "ckpt", params, opt_state, step=3,
                     n_stages=2, n_chunks=2)
    with pytest.raises(ValueError, match="pipeline layout"):
        restore_train_state(tmp_path / "ckpt", mesh, CFG, opt)
    _, _, step = restore_train_state(tmp_path / "ckpt", mesh, CFG, opt,
                                     n_stages=2, n_chunks=2)
    assert step == 3


def test_checkpoint_restores_pre_layout_format(tmp_path):
    """A checkpoint written before layout stamping (no 'layout' entry, e.g.
    round-3 artifacts) must still restore, defaulting to logical order."""
    import orbax.checkpoint as ocp

    opt = default_optimizer()
    mesh = make_mesh(8)
    params, opt_state, _ = make_train_state(jax.random.key(0), CFG, mesh,
                                            optimizer=opt)
    with ocp.StandardCheckpointer() as ckptr:       # legacy save format
        ckptr.save(str(tmp_path / "old"), {"params": params,
                                           "opt_state": opt_state, "step": 5})
    r_params, _, step = restore_train_state(tmp_path / "old", mesh, CFG, opt)
    assert step == 5
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(r_params),
                    strict=True):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_manager_rotates_and_resumes(tmp_path):
    opt = default_optimizer()
    mesh = make_mesh(8)
    params, opt_state, _ = make_train_state(jax.random.key(0), CFG, mesh,
                                            optimizer=opt)
    from gpu_provisioner_tpu.models.checkpoint import TrainCheckpointManager
    mgr = TrainCheckpointManager(tmp_path / "ckpts", mesh, CFG, opt,
                                 max_to_keep=2, save_interval_steps=2)
    try:
        saved = [s for s in range(1, 7) if mgr.maybe_save(s, params, opt_state)]
        # orbax always saves the first step it sees, then every interval
        assert saved == [1, 2, 4, 6]
        mgr.wait_until_finished()
        assert mgr.latest_step() == 6
        assert sorted(int(p.name) for p in (tmp_path / "ckpts").iterdir()
                      if p.name.isdigit()) == [4, 6]   # rotation
        r_params, r_opt, step = mgr.restore_latest()
        assert step == 6
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(r_params),
                        strict=True):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        mgr.close()
