"""CloudProvider shim + metrics decorator — mirrors
pkg/cloudprovider/cloudprovider_test.go (Create/List/Get/Delete through
mocked cloud + k8s)."""

import pytest

from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.cloudprovider import (
    MetricsDecorator, NodeClaimNotFoundError, TPUCloudProvider,
)
from gpu_provisioner_tpu.cloudprovider.metrics import METHOD_ERRORS, current_controller
from gpu_provisioner_tpu.fake import FakeCloud, make_nodeclaim
from gpu_provisioner_tpu.providers.instance import InstanceProvider, ProviderConfig
from gpu_provisioner_tpu.runtime import InMemoryClient

from .conftest import async_test


def setup():
    kube = InMemoryClient()
    cloud = FakeCloud(kube, create_latency=0.01, delete_latency=0.01)
    provider = InstanceProvider(cloud.nodepools, kube,
                                ProviderConfig(node_wait_interval=0.01))
    return kube, cloud, TPUCloudProvider(provider)


@async_test
async def test_create_returns_nodeclaim_view():
    _, _, cp = setup()
    out = await cp.create(make_nodeclaim("ws0", "tpu-v5e-16"))
    assert out.status.provider_id.startswith("gce://")
    assert out.metadata.labels[wk.CAPACITY_TYPE_LABEL] == wk.CAPACITY_TYPE_ON_DEMAND
    assert out.metadata.labels[wk.INSTANCE_TYPE_LABEL] == "tpu-v5e-16"
    assert out.metadata.labels[wk.TPU_TOPOLOGY_LABEL] == "4x4"
    assert out.metadata.labels[wk.TPU_HOSTS_LABEL] == "2"
    assert out.metadata.creation_timestamp is not None
    assert out.status.capacity[wk.TPU_RESOURCE_NAME] == "16"


@async_test
async def test_get_list_delete_roundtrip():
    _, _, cp = setup()
    created = await cp.create(make_nodeclaim("ws0"))
    got = await cp.get(created.status.provider_id)
    assert got.metadata.name == "ws0"
    listed = await cp.list()
    assert [n.metadata.name for n in listed] == ["ws0"]
    await cp.delete(created)
    with pytest.raises(NodeClaimNotFoundError):
        await cp.get(created.status.provider_id)
    with pytest.raises(NodeClaimNotFoundError):
        await cp.get("")


@async_test
async def test_instance_types_catalog_exposed():
    _, _, cp = setup()
    types = await cp.get_instance_types()
    assert any(t.name == "tpu-v5p-32" and t.hosts == 4 for t in types)


@async_test
async def test_repair_policies_and_drift():
    _, _, cp = setup()
    policies = cp.repair_policies()
    assert any(p.condition_type == "Ready" and p.condition_status == "Unknown"
               and p.toleration_duration == 600 for p in policies)
    assert await cp.is_drifted(make_nodeclaim()) == ""


@async_test
async def test_metrics_decorator_counts_errors():
    _, _, cp = setup()
    decorated = MetricsDecorator(cp)
    current_controller.set("test.controller")
    before = METHOD_ERRORS.labels("test.controller", "get", "gcp",
                                  "NodeClaimNotFoundError")._value.get()
    with pytest.raises(NodeClaimNotFoundError):
        await decorated.get("gce://p/z/missing-w0")
    after = METHOD_ERRORS.labels("test.controller", "get", "gcp",
                                 "NodeClaimNotFoundError")._value.get()
    assert after == before + 1
    assert decorated.name() == "gcp"
    assert decorated.repair_policies()
