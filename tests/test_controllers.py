"""Controller behavior through the envtest harness: the provisioning ladder
(§3.2), deprovision flow (§3.3), both GC loops (§3.4), auto-repair (§3.5)."""

import asyncio

import pytest

from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.core import (
    Event, LabelSelector, Node, Pod, PodDisruptionBudget,
    PodDisruptionBudgetSpec, PodSpec,
)
from gpu_provisioner_tpu.apis.karpenter import (
    DRAINED, INITIALIZED, LAUNCHED, NodeClaim, REGISTERED,
)
from gpu_provisioner_tpu.apis.meta import ObjectMeta
from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
from gpu_provisioner_tpu.fake import make_nodeclaim
from gpu_provisioner_tpu.providers.gcp import APIError
from gpu_provisioner_tpu.runtime import (
    EvictionBlockedError, InMemoryClient, NotFoundError,
)

from .conftest import async_test


@async_test
async def test_provision_ladder_single_host():
    async with Env() as env:
        await env.client.create(make_nodeclaim("ws0", "tpu-v5e-8"))
        nc = await env.wait_ready("ws0")
        cs = nc.status_conditions
        assert cs.is_true(LAUNCHED) and cs.is_true(REGISTERED) and cs.is_true(INITIALIZED)
        assert nc.status.provider_id.startswith("gce://")
        assert nc.status.node_name == "gke-kaito-ws0-w0"
        assert wk.TERMINATION_FINALIZER in nc.metadata.finalizers
        # topology labels propagated onto the CR (instanceToNodeClaim analog)
        assert nc.metadata.labels[wk.TPU_TOPOLOGY_LABEL] == "2x4"
        # and synced onto the node (registration)
        node = await env.client.get(Node, "gke-kaito-ws0-w0")
        assert node.metadata.labels[wk.KAITO_WORKSPACE_LABEL] == "ws"
        assert wk.TERMINATION_FINALIZER in node.metadata.finalizers
        assert any(o.kind == "NodeClaim" for o in node.metadata.owner_references)


@async_test
async def test_steady_state_has_no_write_churn():
    # Regression: a no-op status flush must not bump resourceVersion, or the
    # controller's own watch feeds it forever (reconcile hot loop).
    async with Env() as env:
        await env.client.create(make_nodeclaim("calm"))
        await env.wait_ready("calm")
        await asyncio.sleep(0.2)  # let in-flight reconciles settle
        rv1 = (await env.client.get(NodeClaim, "calm")).metadata.resource_version
        await asyncio.sleep(0.5)
        rv2 = (await env.client.get(NodeClaim, "calm")).metadata.resource_version
        assert rv1 == rv2, "steady-state NodeClaim is being rewritten every reconcile"


@async_test
async def test_provision_multi_host_v5p_32():
    opts = EnvtestOptions(node_join_delay=0.02, node_ready_delay=0.05)
    async with Env(opts) as env:
        await env.client.create(make_nodeclaim("big", "tpu-v5p-32"))
        nc = await env.wait_ready("big")
        nodes = await env.client.list(Node, labels={wk.GKE_NODEPOOL_LABEL: "big"})
        assert len(nodes) == 4
        assert nc.status.node_name == "gke-kaito-big-w0"
        idx = sorted(n.metadata.labels[wk.TPU_WORKER_INDEX_LABEL] for n in nodes)
        assert idx == list("0123")


@async_test
async def test_unmanaged_nodeclaim_ignored():
    async with Env() as env:
        nc = make_nodeclaim("rogue")
        nc.metadata.labels = {}  # no kaito labels
        nc.spec.node_class_ref = None
        await env.client.create(nc)
        await asyncio.sleep(0.3)
        got = await env.client.get(NodeClaim, "rogue")
        assert got.status.conditions == [] and got.metadata.finalizers == []
        assert env.cloud.nodepools.pools == {}


@async_test
async def test_insufficient_capacity_deletes_nodeclaim():
    async with Env() as env:
        env.cloud.nodepools.fail("begin_create", APIError("stockout", code=429))
        await env.client.create(make_nodeclaim("oom"))
        await env.wait_gone("oom", timeout=5)


@async_test
async def test_transient_create_error_retries_to_ready():
    async with Env() as env:
        env.cloud.nodepools.fail("begin_create", APIError("flake", code=500), times=2)
        await env.client.create(make_nodeclaim("flaky"))
        nc = await env.wait_ready("flaky")
        assert nc.status_conditions.is_true(LAUNCHED)
        assert env.cloud.nodepools.calls["begin_create"] >= 3


@async_test
async def test_deprovision_flow_drains_and_deletes_pool():
    async with Env() as env:
        await env.client.create(make_nodeclaim("ws0"))
        await env.wait_ready("ws0")
        # park a workload pod on the node
        await env.client.create(Pod(
            metadata=ObjectMeta(name="inference", namespace="default"),
            spec=PodSpec(node_name="gke-kaito-ws0-w0")))
        await env.client.delete(NodeClaim, "ws0")
        await env.wait_gone("ws0")
        assert env.cloud.nodepools.pools == {}
        assert await env.client.list(Node) == []
        with pytest.raises(NotFoundError):
            await env.client.get(Pod, "inference", "default")  # evicted


@async_test
async def test_node_delete_triggers_drain_condition():
    async with Env() as env:
        await env.client.create(make_nodeclaim("ws0"))
        await env.wait_ready("ws0")
        await env.client.create(Pod(
            metadata=ObjectMeta(name="p0", namespace="default"),
            spec=PodSpec(node_name="gke-kaito-ws0-w0")))
        await env.client.delete(NodeClaim, "ws0")
        await env.wait_gone("ws0")
        # Drained condition was surfaced during teardown (best-effort check on
        # the CR having been deleted; pod must be gone)
        with pytest.raises(NotFoundError):
            await env.client.get(Pod, "p0", "default")


@async_test
async def test_instance_gc_reaps_leaked_pool():
    async with Env() as env:
        # create through the provider directly — no NodeClaim backs the
        # pool (create_and_wait: the blocking driver over the resumable
        # create state machine, for direct use with no reconciler)
        await env.provider.create_and_wait(make_nodeclaim("leak"))
        assert "leak" in env.cloud.nodepools.pools
        deadline = asyncio.get_event_loop().time() + 5
        while "leak" in env.cloud.nodepools.pools:
            assert asyncio.get_event_loop().time() < deadline, "GC never reaped pool"
            await asyncio.sleep(0.05)
        # orphan nodes reaped too
        deadline = asyncio.get_event_loop().time() + 5
        while await env.client.list(Node):
            assert asyncio.get_event_loop().time() < deadline, "GC never reaped nodes"
            await asyncio.sleep(0.05)


@async_test
async def test_gc_holds_off_on_stale_informer_cache():
    """Watch-age liveness bound (VERDICT r4 item 9): when the informer
    cache stops observing the apiserver (wedged watch AND failing
    re-lists), GC must refuse to act on the stale view instead of reaping
    a 'leak' it can no longer verify — then resume once the cache is
    fresh again."""
    # leak_grace longer than the time to wedge: the pool is created while
    # the informers are LIVE (the provider's node-wait reads the cache),
    # becomes GC-eligible only after the wedge is in place
    opts = EnvtestOptions(gc_interval=0.05, leak_grace=0.3,
                          use_informer=True)
    async with Env(opts) as env:
        loop = asyncio.get_event_loop()
        await env.provider.create_and_wait(make_nodeclaim("leak"))
        # wedge: stop the pumps (no events, no re-lists) but keep serving
        # the cache, and stamp it ancient
        for inf in env.informers._informers.values():
            await inf.stop()
            inf.synced = True
            inf.last_sync = loop.time() - 1e6
        await asyncio.sleep(0.6)             # grace + several GC intervals
        assert "leak" in env.cloud.nodepools.pools, \
            "GC acted on a cache older than the liveness bound"
        # un-wedge: a fresh observation lets the pass run again
        for inf in env.informers._informers.values():
            inf.last_sync = loop.time()
        deadline = loop.time() + 5
        while "leak" in env.cloud.nodepools.pools:
            assert loop.time() < deadline, "GC never resumed after unwedge"
            await asyncio.sleep(0.05)


@async_test
async def test_shard_partition_only_reconciles_owned_claims():
    """Claim-shard scaling (registry.py shards/shard_index): a shard's
    controllers reconcile ONLY claims whose name hashes to it — foreign
    claims never enqueue, so N processes partition the fleet without
    coordination. GC singletons run on shard 0 only."""
    from gpu_provisioner_tpu.controllers.utils import shard_owns

    # find names deterministically on each side of a 2-way split
    mine = [f"sh{i}" for i in range(40) if shard_owns(f"sh{i}", 2, 0)][:2]
    theirs = [f"sh{i}" for i in range(40)
              if not shard_owns(f"sh{i}", 2, 0)][:2]
    assert len(mine) == 2 and len(theirs) == 2

    async with Env(EnvtestOptions(shards=2, shard_index=0)) as env:
        for n in mine + theirs:
            await env.client.create(make_nodeclaim(n))
        for n in mine:
            await env.wait_ready(n)
        # foreign claims: untouched — no Launched condition, no pool
        for n in theirs:
            nc = await env.client.get(NodeClaim, n)
            assert nc.status_conditions.get("Launched") is None, n
            assert n not in env.cloud.nodepools.pools
    # the complementary shard picks up exactly the other half
    async with Env(EnvtestOptions(shards=2, shard_index=1)) as env:
        for n in mine + theirs:
            await env.client.create(make_nodeclaim(n))
        for n in theirs:
            await env.wait_ready(n)
        for n in mine:
            nc = await env.client.get(NodeClaim, n)
            assert nc.status_conditions.get("Launched") is None, n


def test_health_refuses_repair_on_stale_cache_unit():
    from gpu_provisioner_tpu.controllers.health import (HealthOptions,
                                                        NodeHealthController)

    class Stale:
        def cache_age(self, cls):
            return 1e9

    assert NodeHealthController(Stale(), None)._cache_too_stale()
    assert not NodeHealthController(
        Stale(), None, options=HealthOptions(max_cache_age=0))._cache_too_stale()

    class Fresh:
        def cache_age(self, cls):
            return 1.0

    assert not NodeHealthController(Fresh(), None)._cache_too_stale()


@async_test
async def test_nodeclaim_gc_reaps_vanished_instance():
    async with Env() as env:
        await env.client.create(make_nodeclaim("ws0"))
        await env.wait_ready("ws0")
        # instance vanishes out from under the claim; kubelet goes dark
        env.cloud.nodepools.pools.clear()
        node = await env.client.get(Node, "gke-kaito-ws0-w0")
        for c in node.status.conditions:
            if c.type == "Ready":
                c.status = "False"
        await env.client.update_status(node)
        await env.wait_gone("ws0", timeout=5)


@async_test
async def test_repair_unhealthy_node_replaces_nodeclaim():
    async with Env(EnvtestOptions(repair_toleration=0.1)) as env:
        await env.client.create(make_nodeclaim("sick"))
        await env.wait_ready("sick")
        node = await env.client.get(Node, "gke-kaito-sick-w0")
        for c in node.status.conditions:
            if c.type == "Ready":
                c.status = "False"
                c.reason = "KubeletDead"
        await env.client.update_status(node)
        await env.wait_gone("sick", timeout=5)  # repair deletes the claim


@async_test
async def test_repair_circuit_breaker_halts_mass_repair():
    """Cluster breaker (health/controller.go:130-151's disabled breaker,
    enabled here behind an option): when most managed nodes are unhealthy —
    the signature of a bad rollout, not N independent hardware faults —
    auto-repair must NOT mass-delete expensive slices."""
    opts = EnvtestOptions(repair_toleration=0.1,
                          repair_max_unhealthy_fraction=0.5)
    async with Env(opts) as env:
        for name in ("ca", "cb", "cc"):
            await env.client.create(make_nodeclaim(name))
        for name in ("ca", "cb", "cc"):
            await env.wait_ready(name)
        # 3/3 unhealthy > 0.5 → breaker trips, nothing is reaped. Each flip
        # restarts the toleration clock (fresh last_transition_time), so the
        # first node cannot be repaired in the window before the other two
        # flips land.
        from gpu_provisioner_tpu.apis.serde import now as _now
        for name in ("ca", "cb", "cc"):
            node = await env.client.get(Node, f"gke-kaito-{name}-w0")
            for c in node.status.conditions:
                if c.type == "Ready":
                    c.status = "False"
                    c.reason = "BadRollout"
                    c.last_transition_time = _now()
            await env.client.update_status(node)
        await asyncio.sleep(1.0)  # several tolerations + reconciles
        for name in ("ca", "cb", "cc"):
            assert (await env.client.get(NodeClaim, name)).metadata.name == name

        # recovery drops the fraction under the limit → repair resumes on
        # the one still-unhealthy node
        for name in ("cb", "cc"):
            node = await env.client.get(Node, f"gke-kaito-{name}-w0")
            for c in node.status.conditions:
                if c.type == "Ready":
                    c.status = "True"
            await env.client.update_status(node)
        await env.wait_gone("ca", timeout=10)


@async_test
async def test_liveness_timeout_deletes_stuck_claim():
    opts = EnvtestOptions()
    opts.lifecycle.launch_timeout = 0.2
    async with Env(opts) as env:
        env.cloud.nodepools.fail("begin_create", APIError("down", code=500), times=10**6)
        await env.client.create(make_nodeclaim("stuck"))
        await env.wait_gone("stuck", timeout=5)


@async_test
async def test_queued_provisioning_end_to_end():
    opts = EnvtestOptions(qr_step_latency=0.05)
    async with Env(opts) as env:
        from gpu_provisioner_tpu.providers.instance import PROVISIONING_MODE_ANNOTATION
        await env.client.create(make_nodeclaim(
            "qr0", "tpu-v5e-16",
            annotations={PROVISIONING_MODE_ANNOTATION: "queued"}))
        nc = await env.wait_ready("qr0", timeout=10)
        assert nc.status_conditions.is_true(INITIALIZED)
        assert env.cloud.queuedresources.resources["qr0"].state == "ACTIVE"


# --- slice-group identity convergence (controllers/slicegroup.py) ----------

async def _poll(fn, timeout=10.0, what="condition"):
    import time as _t
    deadline = _t.monotonic() + timeout
    while _t.monotonic() < deadline:
        got = await fn()
        if got:
            return got
        await asyncio.sleep(0.05)
    raise TimeoutError(f"{what} not met within {timeout}s")


def _group_nodes(env, group):
    async def get():
        return await env.client.list(
            Node, labels={wk.TPU_SLICE_GROUP_LABEL: group})
    return get


@async_test
async def test_slicegroup_identity_converges_on_incremental_join():
    """A member joining an existing group re-stamps num-slices on every
    node, not just the new member's (identity labels would otherwise be
    frozen at each pool's create time)."""
    async with Env() as env:
        await env.client.create(make_nodeclaim(
            "aa", "tpu-v5e-16", labels={wk.TPU_SLICE_GROUP_LABEL: "g"}))
        await env.wait_ready("aa")

        async def aa_stamped():
            nodes = await _group_nodes(env, "g")()
            return nodes if all(
                n.metadata.labels.get(wk.TPU_NUM_SLICES_LABEL) == "1"
                for n in nodes) and nodes else None
        await _poll(aa_stamped, what="aa num-slices=1")

        await env.client.create(make_nodeclaim(
            "bb", "tpu-v5e-16", labels={wk.TPU_SLICE_GROUP_LABEL: "g"}))
        await env.wait_ready("bb")

        async def converged():
            nodes = await _group_nodes(env, "g")()
            ok = len(nodes) == 4 and all(
                n.metadata.labels.get(wk.TPU_NUM_SLICES_LABEL) == "2"
                and n.metadata.labels.get(wk.TPU_COORDINATOR_LABEL)
                == "gke-kaito-aa-w0" for n in nodes)
            return nodes if ok else None
        await _poll(converged, what="group converged to num-slices=2")


@async_test
async def test_slicegroup_coordinator_repaired_after_slice0_replacement():
    """Slice 0's pool deleted and replaced under a new claim name: the new
    claim takes the free index 0 and survivors' nodes are re-pointed at the
    new coordinator."""
    async with Env() as env:
        for name in ("aa", "bb"):
            await env.client.create(make_nodeclaim(
                name, "tpu-v5e-16", labels={wk.TPU_SLICE_GROUP_LABEL: "g"}))
        for name in ("aa", "bb"):
            await env.wait_ready(name)

        await env.client.delete(NodeClaim, "aa")

        async def aa_gone():
            nodes = await _group_nodes(env, "g")()
            mine = [n for n in nodes if "aa" in n.metadata.name]
            return not mine or None
        await _poll(aa_gone, what="aa nodes removed")

        await env.client.create(make_nodeclaim(
            "cc", "tpu-v5e-16", labels={wk.TPU_SLICE_GROUP_LABEL: "g"}))
        await env.wait_ready("cc")

        async def repaired():
            nodes = await _group_nodes(env, "g")()
            cc = [n for n in nodes if "cc" in n.metadata.name]
            ok = (cc and all(
                n.metadata.labels.get(wk.TPU_SLICE_INDEX_LABEL) == "0"
                for n in cc) and all(
                n.metadata.labels.get(wk.TPU_COORDINATOR_LABEL)
                == "gke-kaito-cc-w0" for n in nodes))
            return nodes if ok else None
        await _poll(repaired, what="coordinator repointed to cc")


# ---------------------------------------------------------------- eviction

def _pdb(name="inf-pdb", app="inf", min_available=1):
    return PodDisruptionBudget(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=PodDisruptionBudgetSpec(
            selector=LabelSelector(match_labels={"app": app}),
            min_available=min_available))


def _workload_pod(name="inference", node="gke-kaito-ws0-w0", app="inf"):
    return Pod(metadata=ObjectMeta(name=name, namespace="default",
                                   labels={"app": app}),
               spec=PodSpec(node_name=node))


@async_test
async def test_in_memory_evict_honors_pdb():
    from gpu_provisioner_tpu.runtime import ConflictError, InMemoryClient
    client = InMemoryClient()
    await client.create(_workload_pod())
    await client.create(_pdb())
    with pytest.raises(EvictionBlockedError):
        await client.evict("inference", "default")
    # a stale uid precondition (pod replaced under the same name) is a 409,
    # not an eviction — the queue drops such entries
    await client.delete(PodDisruptionBudget, "inf-pdb", "default")
    with pytest.raises(ConflictError):
        await client.evict("inference", "default", uid="stale-uid")
    # lifting the budget unblocks the same call
    await client.evict("inference", "default")
    with pytest.raises(NotFoundError):
        await client.get(Pod, "inference", "default")


@async_test
async def test_blocked_eviction_warning_throttles_to_doubling_schedule():
    """After WARN_AFTER the Warning repeats only on a doubling schedule
    (3, 6, 12, 24 attempts) — not on every ~10s capped-delay retry, which
    would cost the recorder an apiserver round-trip each time (ADVICE r3)."""
    from gpu_provisioner_tpu.controllers.termination import EvictionQueue

    published = []

    class Rec:
        async def publish(self, obj, type_, reason, msg):
            published.append(msg)

    q = EvictionQueue(client=None, recorder=Rec())
    pod = _workload_pod()
    for fails in range(1, 25):
        await q._warn_blocked(pod, RuntimeError("pdb"), fails)
    assert len(published) == 4 and "after 3 attempts" in published[0]
    assert [int(m.split("after ")[1].split(" ")[0]) for m in published] \
        == [3, 6, 12, 24]


@async_test
async def test_blocked_eviction_warns_then_drains_when_pdb_lifted():
    """A PDB-blocked drain retries with backoff, surfaces a Warning event on
    the pod once the blockage persists (eviction.go:199-207 analog), and
    completes as soon as the budget allows."""
    async with Env() as env:
        await env.client.create(make_nodeclaim("ws0"))
        await env.wait_ready("ws0")
        await env.client.create(_workload_pod())
        await env.client.create(_pdb())
        await env.client.delete(NodeClaim, "ws0")

        async def warned():
            evs = await env.client.list(Event, namespace="default")
            hits = [e for e in evs if e.type == "Warning"
                    and e.reason == "FailedDraining"
                    and e.involved_object.name == "inference"]
            return hits or None
        await _poll(warned, timeout=15.0, what="FailedDraining warning")

        await env.client.delete(PodDisruptionBudget, "inf-pdb", "default")
        await env.wait_gone("ws0", timeout=15.0)

        # the unblocked eviction lands on the queue's next backoff retry —
        # poll rather than racing the retry ladder's phase
        async def evicted():
            try:
                await env.client.get(Pod, "inference", "default")
                return None
            except NotFoundError:
                return True
        await _poll(evicted, timeout=15.0, what="pod evicted after PDB lift")
        assert env.cloud.nodepools.pools == {}


@async_test
async def test_grace_deadline_escalates_past_blocked_eviction():
    """A permanently PDB-blocked pod cannot hold the node hostage: once the
    NodeClaim's termination-grace deadline passes, drain is abandoned and the
    instance is torn down anyway (terminator grace escalation)."""
    async with Env() as env:
        nc = make_nodeclaim("ws0")
        nc.spec.termination_grace_period = "0s"
        await env.client.create(nc)
        await env.wait_ready("ws0")
        await env.client.create(_workload_pod())
        await env.client.create(_pdb())
        await env.client.delete(NodeClaim, "ws0")
        await env.wait_gone("ws0", timeout=15.0)
        # instance + claim gone; the blocked pod survives (it was never
        # evictable) — K8s pod GC owns it once its node is gone
        assert env.cloud.nodepools.pools == {}
        assert await env.client.list(Node) == []
        got = await env.client.get(Pod, "inference", "default")
        assert got.metadata.name == "inference"


@async_test
async def test_eviction_queue_stop_clears_parked_state_no_timer_leak():
    """Crash-restart satellite: stop() while pods are parked in backoff must
    cancel every timer task, clear the dedup/failure maps (no ghost entries
    blocking a future enqueue), and leave nothing that can resurrect keys
    into a later queue."""
    from gpu_provisioner_tpu.controllers.termination import EvictionQueue

    client = InMemoryClient()
    pod = _workload_pod()
    await client.create(pod)
    await client.create(_pdb())  # blocks eviction → backoff timers
    q = EvictionQueue(client, qps=100)
    q.start()
    stored = await client.get(Pod, "inference", "default")
    q.enqueue(stored)
    key = (stored.metadata.namespace, stored.metadata.name,
           stored.metadata.uid)
    deadline = asyncio.get_event_loop().time() + 5
    while q._failures.get(key, 0) < 2:  # parked in a backoff timer
        assert asyncio.get_event_loop().time() < deadline, "never blocked"
        await asyncio.sleep(0.02)

    await q.stop()
    assert not q._timers, "backoff timer task leaked past stop()"
    assert not q._pods and not q._failures, "dedup/failure ghosts survived"
    assert q._q.empty()
    # a cancelled timer firing late must not resurrect the key
    await asyncio.sleep(0.5)
    assert q._q.empty() and not q._pods


@async_test
async def test_eviction_queue_restart_redrains_parked_pods():
    """A restarted queue re-discovers and drains pods the dead incarnation
    had parked in backoff — stop() left no dedup entry to swallow the
    re-enqueue."""
    from gpu_provisioner_tpu.controllers.termination import EvictionQueue

    client = InMemoryClient()
    await client.create(_workload_pod())
    await client.create(_pdb())
    q = EvictionQueue(client, qps=100)
    q.start()
    stored = await client.get(Pod, "inference", "default")
    q.enqueue(stored)
    key = (stored.metadata.namespace, stored.metadata.name,
           stored.metadata.uid)
    deadline = asyncio.get_event_loop().time() + 5
    while q._failures.get(key, 0) < 1:
        assert asyncio.get_event_loop().time() < deadline
        await asyncio.sleep(0.02)
    await q.stop()

    # restart: the blockage lifts, a fresh drain pass re-enqueues the pod
    await client.delete(PodDisruptionBudget, "inf-pdb", "default")
    q.start()
    q.enqueue(stored)
    assert key in q._pods, "stale dedup entry swallowed the re-enqueue"
    deadline = asyncio.get_event_loop().time() + 5
    while True:
        try:
            await client.get(Pod, "inference", "default")
        except NotFoundError:
            break  # evicted by the restarted queue
        assert asyncio.get_event_loop().time() < deadline, \
            "restarted queue never drained the parked pod"
        await asyncio.sleep(0.02)
    await q.stop()
