"""KV-cache inference: decode equivalence with the full forward."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from gpu_provisioner_tpu.models.decode import (cached_forward, generate,
                                               init_kv_cache, kv_cache_specs,
                                               prefill)
from gpu_provisioner_tpu.models.llama import PRESETS, forward, init_params
from gpu_provisioner_tpu.models.train import shard_params
from gpu_provisioner_tpu.parallel import make_mesh

CFG = PRESETS["tiny"]


def test_prefill_matches_full_forward():
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 12), 0, CFG.vocab_size)
    cache = init_kv_cache(CFG, 2, 32)
    logits, cache = jax.jit(cached_forward, static_argnums=3)(
        params, prompt, cache, CFG)
    ref = forward(params, prompt, CFG)
    assert int(cache.length) == 12
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)  # bf16 activations


def test_incremental_decode_matches_teacher_forcing():
    """Decode step t's logits must equal the full forward's last position on
    the same prefix — the cache IS the prefix."""
    params = init_params(jax.random.key(0), CFG)
    toks = jax.random.randint(jax.random.key(1), (1, 10), 0, CFG.vocab_size)
    cache = init_kv_cache(CFG, 1, 16)
    _, cache = prefill(params, toks[:, :4], cache, CFG)
    for t in range(4, 10):
        logits, cache = cached_forward(params, toks[:, t:t + 1], cache, CFG)
        ref = forward(params, toks[:, :t + 1], CFG)[:, -1]
        np.testing.assert_allclose(np.asarray(logits[:, 0]), np.asarray(ref),
                                   atol=3e-2, rtol=3e-2)


def test_generate_greedy_matches_stepwise_argmax():
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    out = jax.jit(
        lambda p, t: generate(p, t, CFG, max_new_tokens=5))(params, prompt)
    assert out.shape == (2, 5)

    # reference: greedy via repeated full forwards
    seq = prompt
    want = []
    for _ in range(5):
        nxt = jnp.argmax(forward(params, seq, CFG)[:, -1], axis=-1)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(jnp.stack(want, axis=1)))


def test_generate_tensor_parallel_on_mesh():
    """The decode path shards: params tp over ``model``, cache heads too —
    same greedy tokens as the single-device run."""
    mesh = make_mesh(8, tp=2)
    host = init_params(jax.random.key(0), CFG)
    params = shard_params(host, mesh, CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    out = jax.jit(
        lambda p, t: generate(p, t, CFG, max_new_tokens=4))(params, prompt)
    ref = jax.jit(
        lambda p, t: generate(p, t, CFG, max_new_tokens=4))(host, prompt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert kv_cache_specs(CFG).k == P(None, None, None, "model", None)


def test_fresh_prefill_fast_path_matches_general():
    """fresh=True prefill (S x S causal + one cache write) must agree with
    the general cached forward on logits, cache contents, and length."""
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 12), 0, CFG.vocab_size)
    fast_logits, fast_cache = prefill(params, prompt,
                                      init_kv_cache(CFG, 2, 32), CFG,
                                      fresh=True)
    gen_logits, gen_cache = prefill(params, prompt,
                                    init_kv_cache(CFG, 2, 32), CFG)
    np.testing.assert_allclose(np.asarray(fast_logits),
                               np.asarray(gen_logits), atol=3e-2, rtol=3e-2)
    assert int(fast_cache.length) == int(gen_cache.length) == 12
    np.testing.assert_allclose(
        np.asarray(fast_cache.k.astype(jnp.float32)),
        np.asarray(gen_cache.k.astype(jnp.float32)), atol=3e-2, rtol=3e-2)
    # and decode continues identically from either cache
    nxt = jax.random.randint(jax.random.key(2), (2, 1), 0, CFG.vocab_size)
    a, _ = cached_forward(params, nxt, fast_cache, CFG)
    b, _ = cached_forward(params, nxt, gen_cache, CFG)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=3e-2, rtol=3e-2)


def test_generate_sampling_reproducible_and_in_vocab():
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    out1 = generate(params, prompt, CFG, max_new_tokens=4, temperature=0.8,
                    key=jax.random.key(7))
    out2 = generate(params, prompt, CFG, max_new_tokens=4, temperature=0.8,
                    key=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert out1.shape == (2, 4)
    assert int(out1.min()) >= 0 and int(out1.max()) < CFG.vocab_size
