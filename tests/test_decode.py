"""KV-cache inference: decode equivalence with the full forward."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from gpu_provisioner_tpu.models.decode import (cached_forward, generate,
                                               init_kv_cache, kv_cache_specs,
                                               prefill)
from gpu_provisioner_tpu.models.llama import PRESETS, forward, init_params
from gpu_provisioner_tpu.models.train import shard_params
from gpu_provisioner_tpu.parallel import make_mesh

CFG = PRESETS["tiny"]


def test_prefill_matches_full_forward():
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 12), 0, CFG.vocab_size)
    cache = init_kv_cache(CFG, 2, 32)
    logits, cache = jax.jit(cached_forward, static_argnums=3)(
        params, prompt, cache, CFG)
    ref = forward(params, prompt, CFG)
    assert int(cache.length) == 12
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref),
                               atol=3e-2, rtol=3e-2)  # bf16 activations


def test_incremental_decode_matches_teacher_forcing():
    """Decode step t's logits must equal the full forward's last position on
    the same prefix — the cache IS the prefix."""
    params = init_params(jax.random.key(0), CFG)
    toks = jax.random.randint(jax.random.key(1), (1, 10), 0, CFG.vocab_size)
    cache = init_kv_cache(CFG, 1, 16)
    _, cache = prefill(params, toks[:, :4], cache, CFG)
    for t in range(4, 10):
        logits, cache = cached_forward(params, toks[:, t:t + 1], cache, CFG)
        ref = forward(params, toks[:, :t + 1], CFG)[:, -1]
        np.testing.assert_allclose(np.asarray(logits[:, 0]), np.asarray(ref),
                                   atol=3e-2, rtol=3e-2)


def test_generate_greedy_matches_stepwise_argmax():
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    out = jax.jit(
        lambda p, t: generate(p, t, CFG, max_new_tokens=5))(params, prompt)
    assert out.shape == (2, 5)

    # reference: greedy via repeated full forwards
    seq = prompt
    want = []
    for _ in range(5):
        nxt = jnp.argmax(forward(params, seq, CFG)[:, -1], axis=-1)
        want.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None].astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(jnp.stack(want, axis=1)))


def test_generate_tensor_parallel_on_mesh():
    """The decode path shards: params tp over ``model``, cache heads too —
    same greedy tokens as the single-device run."""
    mesh = make_mesh(8, tp=2)
    host = init_params(jax.random.key(0), CFG)
    params = shard_params(host, mesh, CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    out = jax.jit(
        lambda p, t: generate(p, t, CFG, max_new_tokens=4))(params, prompt)
    ref = jax.jit(
        lambda p, t: generate(p, t, CFG, max_new_tokens=4))(host, prompt)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert kv_cache_specs(CFG).k == P(None, None, "model", None, None)


def test_fresh_prefill_fast_path_matches_general():
    """fresh=True prefill (S x S causal + one cache write) must agree with
    the general cached forward on logits, cache contents, and length."""
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 12), 0, CFG.vocab_size)
    fast_logits, fast_cache = prefill(params, prompt,
                                      init_kv_cache(CFG, 2, 32), CFG,
                                      fresh=True)
    gen_logits, gen_cache = prefill(params, prompt,
                                    init_kv_cache(CFG, 2, 32), CFG)
    np.testing.assert_allclose(np.asarray(fast_logits),
                               np.asarray(gen_logits), atol=3e-2, rtol=3e-2)
    assert int(fast_cache.length) == int(gen_cache.length) == 12
    np.testing.assert_allclose(
        np.asarray(fast_cache.k.astype(jnp.float32)),
        np.asarray(gen_cache.k.astype(jnp.float32)), atol=3e-2, rtol=3e-2)
    # and decode continues identically from either cache
    nxt = jax.random.randint(jax.random.key(2), (2, 1), 0, CFG.vocab_size)
    a, _ = cached_forward(params, nxt, fast_cache, CFG)
    b, _ = cached_forward(params, nxt, gen_cache, CFG)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               atol=3e-2, rtol=3e-2)


import pytest


@pytest.mark.parametrize("kv_dtype", ["auto", "int8"])
def test_multiturn_flash_prefill_matches_dense(kv_dtype):
    """Multi-turn serving: prefill a block-sized prompt, decode a few, then
    prefill a second turn — attn_impl="flash" (cache-aware Pallas kernel on
    the S≥128 turns, dense on S=1 steps) must match attn_impl="dense"
    end-to-end on logits, cache contents, and length. Parametrized over the
    fp and int8 cache modes (the kernel dequantizes in VMEM for the
    latter)."""
    import dataclasses

    cfg_d = dataclasses.replace(CFG, max_seq_len=512, kv_cache_dtype=kv_dtype)
    cfg_f = dataclasses.replace(cfg_d, attn_impl="flash")
    params = init_params(jax.random.key(0), cfg_d)
    turn1 = jax.random.randint(jax.random.key(1), (2, 128), 0,
                               cfg_d.vocab_size)
    turn2 = jax.random.randint(jax.random.key(2), (2, 128), 0,
                               cfg_d.vocab_size)

    def serve(cfg):
        cache = init_kv_cache(cfg, 2, 384)
        l1, cache = cached_forward(params, turn1, cache, cfg)   # start=0
        tok = jnp.argmax(l1[:, -1:], axis=-1).astype(jnp.int32)
        for _ in range(2):                                      # S=1 decode
            ld, cache = cached_forward(params, tok, cache, cfg)
            tok = jnp.argmax(ld[:, -1:], axis=-1).astype(jnp.int32)
        l2, cache = cached_forward(params, turn2, cache, cfg)   # start=130
        return l1, l2, cache

    l1d, l2d, cd = serve(cfg_d)
    l1f, l2f, cf = serve(cfg_f)
    np.testing.assert_allclose(np.asarray(l1f), np.asarray(l1d),
                               atol=3e-2, rtol=3e-2)
    np.testing.assert_allclose(np.asarray(l2f), np.asarray(l2d),
                               atol=3e-2, rtol=3e-2)
    assert int(cf.length) == int(cd.length) == 258
    # compare caches in VALUE space: int8 mode stores quanta, and upstream
    # bf16 noise can flip a rounding boundary by one unit — dequantized
    # values are what attention consumes. Both halves: k and v travel
    # separate quantize/write/dequant paths.
    def deq(buf, scl):
        return (np.asarray(buf.astype(jnp.float32))
                * (np.asarray(scl) if scl is not None else 1.0))
    np.testing.assert_allclose(deq(cf.k, cf.k_scale), deq(cd.k, cd.k_scale),
                               atol=3e-2, rtol=3e-2)
    np.testing.assert_allclose(deq(cf.v, cf.v_scale), deq(cd.v, cd.v_scale),
                               atol=3e-2, rtol=3e-2)


def test_flash_prefill_on_tp_mesh_matches_dense():
    """attn_impl="flash" serving on a tensor-parallel mesh (kv-head-sharded
    cache): GSPMD gathers around the pallas_call — results must match the
    dense impl under the SAME sharding (isolates the kernel from tp's own
    bf16 reduction-order noise)."""
    import dataclasses

    cfg_d = dataclasses.replace(CFG, max_seq_len=512)
    cfg_f = dataclasses.replace(cfg_d, attn_impl="flash")
    mesh = make_mesh(8, tp=2)
    params = shard_params(init_params(jax.random.key(0), cfg_d), mesh, cfg_d)
    prompt = jax.random.randint(jax.random.key(1), (2, 128), 0,
                                cfg_d.vocab_size)
    outs = {}
    for cfg in (cfg_d, cfg_f):
        cache = init_kv_cache(cfg, 2, 256)
        logits, cache = jax.jit(cached_forward, static_argnums=3)(
            params, prompt, cache, cfg)
        outs[cfg.attn_impl] = (logits, cache)
    np.testing.assert_allclose(np.asarray(outs["flash"][0]),
                               np.asarray(outs["dense"][0]),
                               atol=3e-2, rtol=3e-2)
    assert int(outs["flash"][1].length) == 128


def test_topk_topp_filters():
    from gpu_provisioner_tpu.models.decode import (_filter_top_k,
                                                   _filter_top_p)

    logits = jnp.log(jnp.array([[0.5, 0.25, 0.125, 0.125]]))
    k2 = np.asarray(_filter_top_k(logits, 2))
    assert np.isfinite(k2[0, :2]).all() and (k2[0, 2:] < -1e20).all()
    # top_p=0.6: exclusive mass 0 and 0.5 are < 0.6 → keep exactly {0, 1}
    p6 = np.asarray(_filter_top_p(logits, 0.6))
    assert np.isfinite(p6[0, :2]).all() and (p6[0, 2:] < -1e20).all()
    # top_p smaller than the top token's own mass still keeps that token
    p1 = np.asarray(_filter_top_p(logits, 0.1))
    assert np.isfinite(p1[0, 0]) and (p1[0, 1:] < -1e20).all()


def test_generate_sampling_requires_key():
    import pytest

    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (1, 4), 0, CFG.vocab_size)
    with pytest.raises(ValueError, match="requires an explicit PRNG key"):
        generate(params, prompt, CFG, max_new_tokens=2, temperature=0.8)
    with pytest.raises(ValueError, match="top_k"):
        generate(params, prompt, CFG, max_new_tokens=2, temperature=0.8,
                 top_k=0, key=jax.random.key(0))
    with pytest.raises(ValueError, match="top_p"):
        generate(params, prompt, CFG, max_new_tokens=2, temperature=0.8,
                 top_p=1.5, key=jax.random.key(0))


def test_generate_topk1_equals_greedy():
    """top_k=1 collapses sampling to argmax regardless of temperature/key."""
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    greedy = generate(params, prompt, CFG, max_new_tokens=4)
    sampled = generate(params, prompt, CFG, max_new_tokens=4,
                       temperature=1.3, top_k=1, key=jax.random.key(9))
    np.testing.assert_array_equal(np.asarray(greedy), np.asarray(sampled))


def test_generate_topk_topp_reproducible_and_in_vocab():
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    kw = dict(max_new_tokens=4, temperature=0.8, top_k=16, top_p=0.9)
    out1 = generate(params, prompt, CFG, **kw, key=jax.random.key(7))
    out2 = generate(params, prompt, CFG, **kw, key=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert int(out1.min()) >= 0 and int(out1.max()) < CFG.vocab_size
    # a different key must be allowed to differ (not a hard guarantee per
    # position, but across 8 draws identical output means a wiring bug)
    out3 = generate(params, prompt, CFG, **kw, key=jax.random.key(8))
    assert out3.shape == out1.shape


def test_int8_kv_cache_tracks_fp_and_serves():
    """cfg.kv_cache_dtype="int8": the cache stores int8 + per-token scales
    (half the HBM), logits track the fp cache closely, prefill/decode
    agree on the next token, and generate runs end-to-end."""
    import dataclasses

    cfg8 = dataclasses.replace(CFG, kv_cache_dtype="int8")
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 12), 0,
                                CFG.vocab_size)
    lf, cf = jax.jit(cached_forward, static_argnums=3)(
        params, prompt, init_kv_cache(CFG, 2, 32), CFG)
    l8, c8 = jax.jit(cached_forward, static_argnums=3)(
        params, prompt, init_kv_cache(cfg8, 2, 32), cfg8)
    assert c8.k.dtype == jnp.int8 and c8.k_scale is not None
    assert c8.k_scale.shape == (CFG.n_layers, 2, CFG.n_kv_heads, 32, 1)
    # int8 cache ≈ fp cache on logits (measured max diff ~0.1 on ~4.0
    # logits for this seed), and they agree on the next token
    np.testing.assert_allclose(np.asarray(l8), np.asarray(lf),
                               atol=0.2, rtol=0.2)
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(l8[:, -1], -1)),
        np.asarray(jnp.argmax(lf[:, -1], -1)))
    # decode continues against the quantized buffers
    nxt = jnp.argmax(l8[:, -1:], axis=-1).astype(jnp.int32)
    ld, c8 = cached_forward(params, nxt, c8, cfg8)
    assert int(c8.length) == 13 and bool(jnp.all(jnp.isfinite(ld)))
    # the whole generate loop (fresh prefill + scan) under int8
    out = generate(params, prompt, cfg8, max_new_tokens=4)
    assert out.shape == (2, 4) and int(out.max()) < CFG.vocab_size


def test_chunked_prefill_matches_single_shot():
    """prefill_chunked == one cached_forward over the whole prompt, on
    logits, cache contents and length — incl. a ragged final chunk."""
    from gpu_provisioner_tpu.models.decode import prefill_chunked

    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 22), 0,
                                CFG.vocab_size)
    full, full_cache = cached_forward(params, prompt,
                                      init_kv_cache(CFG, 2, 32), CFG)
    last, ck_cache = prefill_chunked(params, prompt,
                                     init_kv_cache(CFG, 2, 32), CFG,
                                     chunk=8)   # 8+8+6: ragged tail
    np.testing.assert_allclose(np.asarray(last), np.asarray(full[:, -1]),
                               atol=3e-2, rtol=3e-2)
    assert int(ck_cache.length) == int(full_cache.length) == 22
    np.testing.assert_allclose(
        np.asarray(ck_cache.k.astype(jnp.float32)),
        np.asarray(full_cache.k.astype(jnp.float32)), atol=3e-2, rtol=3e-2)
    np.testing.assert_allclose(
        np.asarray(ck_cache.v.astype(jnp.float32)),
        np.asarray(full_cache.v.astype(jnp.float32)), atol=3e-2, rtol=3e-2)


def test_generate_eos_finishes_rows_independently():
    """Once a row emits eos_id every later position is eos_id (the HF
    unfinished_sequences convention); other rows keep generating."""
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    base = np.asarray(generate(params, prompt, CFG, max_new_tokens=5))
    # choose row 0's second token as the eos — row 0 then finishes at
    # position 1; precondition: it must not occur in row 1's output
    eos = int(base[0, 1])
    assert eos not in base[1], "pick a different seed"
    out = np.asarray(generate(params, prompt, CFG, max_new_tokens=5,
                              eos_id=eos))
    np.testing.assert_array_equal(out[0, :2], base[0, :2])  # up to + incl eos
    assert (out[0, 1:] == eos).all()                        # finished
    np.testing.assert_array_equal(out[1], base[1])          # unaffected


def test_left_padded_ragged_batch_matches_unpadded():
    """The standard serving layout for ragged prompts: left-pad to a common
    width. Each padded row must generate EXACTLY what it generates alone —
    pad keys masked out of attention, RoPE counting from the first real
    token, prefill and every decode step."""
    params = init_params(jax.random.key(0), CFG)
    # real tokens in [1, vocab): 0 is the pad id and must not occur
    p_short = jax.random.randint(jax.random.key(1), (1, 5), 1,
                                 CFG.vocab_size)
    p_long = jax.random.randint(jax.random.key(2), (1, 8), 1,
                                CFG.vocab_size)
    solo_short = generate(params, p_short, CFG, max_new_tokens=4)
    solo_long = generate(params, p_long, CFG, max_new_tokens=4)

    padded = jnp.concatenate(
        [jnp.zeros((1, 3), p_short.dtype), p_short], axis=1)
    batch = jnp.concatenate([padded, p_long], axis=0)          # [2, 8]
    out = jax.jit(lambda pr, t: generate(pr, t, CFG, max_new_tokens=4,
                                         pad_id=0))(params, batch)
    np.testing.assert_array_equal(np.asarray(out[0]),
                                  np.asarray(solo_short[0]))
    np.testing.assert_array_equal(np.asarray(out[1]),
                                  np.asarray(solo_long[0]))


def test_generate_returns_logprobs():
    """return_logprobs: greedy logprobs equal log_softmax at the argmax of
    a stepwise reference; tokens unchanged vs the plain call; sampled-mode
    logprobs are finite, ≤ 0, and keyed reproducibly."""
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    toks_plain = generate(params, prompt, CFG, max_new_tokens=3)
    toks, lps = generate(params, prompt, CFG, max_new_tokens=3,
                         return_logprobs=True)
    np.testing.assert_array_equal(np.asarray(toks), np.asarray(toks_plain))
    assert lps.shape == (2, 3) and bool(jnp.all(lps <= 0))
    # reference: stepwise full-forward greedy logprob of the first token
    ref_logits = forward(params, prompt, CFG)[:, -1]
    ref_lp = jax.nn.log_softmax(ref_logits, -1)[
        jnp.arange(2), jnp.argmax(ref_logits, -1)]
    np.testing.assert_allclose(np.asarray(lps[:, 0]), np.asarray(ref_lp),
                               atol=3e-2, rtol=3e-2)

    kw = dict(max_new_tokens=3, temperature=0.8, top_k=16,
              return_logprobs=True)
    t1, l1 = generate(params, prompt, CFG, **kw, key=jax.random.key(7))
    t2, l2 = generate(params, prompt, CFG, **kw, key=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    assert bool(jnp.all(jnp.isfinite(l1))) and bool(jnp.all(l1 <= 0))


def test_generate_sampling_reproducible_and_in_vocab():
    params = init_params(jax.random.key(0), CFG)
    prompt = jax.random.randint(jax.random.key(1), (2, 6), 0, CFG.vocab_size)
    out1 = generate(params, prompt, CFG, max_new_tokens=4, temperature=0.8,
                    key=jax.random.key(7))
    out2 = generate(params, prompt, CFG, max_new_tokens=4, temperature=0.8,
                    key=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert out1.shape == (2, 4)
    assert int(out1.min()) >= 0 and int(out1.max()) < CFG.vocab_size


def test_sliding_window_generate_flash_matches_dense():
    """cfg.sliding_window: flash serving (windowed kernels) and dense
    serving (windowed sweep) must emit identical greedy tokens, and both
    must differ from full-causal generation once the context exceeds the
    window (proving the window actually bites)."""
    import dataclasses

    from gpu_provisioner_tpu.models.llama import LlamaConfig

    cfg_d = LlamaConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                        dtype="float32", attn_impl="dense",
                        sliding_window=32)
    cfg_f = dataclasses.replace(cfg_d, attn_impl="flash")
    cfg_full = dataclasses.replace(cfg_d, sliding_window=None)
    params = init_params(jax.random.key(30), cfg_d)
    prompt = jax.random.randint(jax.random.key(31), (2, 128), 0, 128)
    td = generate(params, prompt, cfg_d, max_new_tokens=8, max_len=256)
    tf = generate(params, prompt, cfg_f, max_new_tokens=8, max_len=256)
    tfull = generate(params, prompt, cfg_full, max_new_tokens=8, max_len=256)
    assert (td == tf).all()
    assert not (td == tfull).all()


def test_sliding_window_teacher_forcing_matches_full_forward():
    """Windowed cached forward vs the windowed full forward — the cached
    path and forward() must agree on every position (cfg.sliding_window
    respected by BOTH)."""
    from gpu_provisioner_tpu.models.decode import cached_forward, init_kv_cache
    from gpu_provisioner_tpu.models.llama import LlamaConfig, forward

    cfg = LlamaConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                      dtype="float32", sliding_window=16)
    params = init_params(jax.random.key(32), cfg)
    toks = jax.random.randint(jax.random.key(33), (1, 48), 0, 128)
    full = forward(params, toks, cfg)
    cache = init_kv_cache(cfg, 1, 64)
    logits, cache = cached_forward(params, toks[:, :24], cache, cfg)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :24]),
                               atol=1e-4, rtol=1e-4)
    for i in range(24, 48):
        logits, cache = cached_forward(params, toks[:, i:i + 1], cache, cfg)
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full[:, i]),
                                   atol=1e-4, rtol=1e-4)


def test_sliding_window_ring_raises():
    import pytest

    from gpu_provisioner_tpu.models.train import make_attn_fn
    from gpu_provisioner_tpu.parallel import make_mesh

    mesh = make_mesh(8, sp=2, tp=1)
    with pytest.raises(NotImplementedError):
        make_attn_fn(mesh, impl="dense", window=8)


def test_attention_sinks_generate_flash_matches_dense():
    """cfg.attn_sinks: flash and dense serving agree; sinks change the
    output once generation runs past the window; ragged row == solo."""
    import dataclasses

    from gpu_provisioner_tpu.models.llama import LlamaConfig

    cfg_d = LlamaConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                        dtype="float32", attn_impl="dense",
                        sliding_window=24, attn_sinks=4)
    cfg_f = dataclasses.replace(cfg_d, attn_impl="flash")
    cfg_nosink = dataclasses.replace(cfg_d, attn_sinks=0)
    params = init_params(jax.random.key(40), cfg_d)
    prompt = jax.random.randint(jax.random.key(41), (2, 128), 1, 128)
    td = generate(params, prompt, cfg_d, max_new_tokens=8, max_len=256)
    tf = generate(params, prompt, cfg_f, max_new_tokens=8, max_len=256)
    tn = generate(params, prompt, cfg_nosink, max_new_tokens=8, max_len=256)
    assert (td == tf).all()
    assert not (td == tn).all()

    # ragged: sinks anchor at each row's first REAL token
    PAD = 0
    p1 = prompt[1:, :96]
    batch = jnp.concatenate(
        [prompt[:1],
         jnp.concatenate([jnp.full((1, 32), PAD, jnp.int32), p1], 1)], 0)
    got = generate(params, batch, cfg_d, max_new_tokens=6, max_len=256,
                   pad_id=PAD)
    solo1 = generate(params, p1, cfg_d, max_new_tokens=6, max_len=256)
    assert (got[1] == solo1[0]).all()
