"""Continuous-batching engine specs: slotting must never change tokens.

The invariant throughout: a request served through the engine — whatever
slot it lands in, whoever its neighbours are, however it was bucketed —
emits EXACTLY the stream plain generate() produces for it alone. That is
the contract that makes continuous batching a scheduling optimization
rather than a semantics change.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from gpu_provisioner_tpu.models.decode import generate
from gpu_provisioner_tpu.models.engine import ServeEngine
from gpu_provisioner_tpu.models.llama import LlamaConfig, init_params

CFG = LlamaConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                  n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                  dtype="float32")
PARAMS = init_params(jax.random.key(0), CFG)


def _prompt(seed, n):
    return list(jax.random.randint(jax.random.key(seed), (n,), 1, 128)
                .tolist())


def _solo(prompt, new, **kw):
    toks = generate(PARAMS, jnp.asarray([prompt], jnp.int32), CFG,
                    max_new_tokens=new, max_len=256, **kw)
    return [int(t) for t in toks[0]]


def test_engine_matches_generate_per_request():
    eng = ServeEngine(PARAMS, CFG, slots=2, max_len=64,
                      prefill_buckets=(16, 32))
    r1 = eng.submit(_prompt(1, 10), 8)
    r2 = eng.submit(_prompt(2, 20), 12)
    out = eng.run()
    assert out[r1] == _solo(_prompt(1, 10), 8)
    assert out[r2] == _solo(_prompt(2, 20), 12)


def test_engine_staggered_arrival_and_slot_reuse():
    """More requests than slots, submitted mid-flight: finished slots are
    reused and late arrivals still match their solo stream."""
    eng = ServeEngine(PARAMS, CFG, slots=2, max_len=64,
                      prefill_buckets=(16,))
    rids = [eng.submit(_prompt(s, 8 + s), 4 + s) for s in range(3)]
    for _ in range(3):                      # partial progress
        eng.step()
    rids.append(eng.submit(_prompt(9, 12), 6))   # arrives mid-flight
    out = eng.run()
    for i, rid in enumerate(rids[:3]):
        assert out[rid] == _solo(_prompt(i, 8 + i), 4 + i), f"req {i}"
    assert out[rids[3]] == _solo(_prompt(9, 12), 6)


def test_engine_eos_frees_slot_early():
    free = _solo(_prompt(4, 10), 12)
    eos = free[2]                            # appears early in the stream
    want = _solo(_prompt(4, 10), 12, eos_id=eos)
    eng = ServeEngine(PARAMS, CFG, slots=1, max_len=64,
                      prefill_buckets=(16,))
    r1 = eng.submit(_prompt(4, 10), 12, eos_id=eos)
    r2 = eng.submit(_prompt(5, 10), 4)       # queued behind r1's slot
    out = eng.run()
    # engine stops AT the first eos (the slot frees) — generate() keeps
    # emitting forced eos padding; the engine's stream is the truncation
    n = out[r1].index(eos) + 1 if eos in out[r1] else len(out[r1])
    assert out[r1] == want[:n]
    assert eos in out[r1]
    assert len(out[r1]) < 12                 # finished early, slot reused
    assert out[r2] == _solo(_prompt(5, 10), 4)


def test_engine_flash_kernels_and_moe():
    # reference runs the SAME attn impl: the engine invariant is that
    # slotting/bucketing never changes tokens (flash-vs-dense equality has
    # its own tests; accumulation-order ties are out of scope here)
    cfg_f = dataclasses.replace(CFG, attn_impl="flash")
    eng = ServeEngine(PARAMS, cfg_f, slots=2, max_len=256,
                      prefill_buckets=(16,))
    r1 = eng.submit(_prompt(6, 9), 6)
    out = eng.run()
    want = generate(PARAMS, jnp.asarray([_prompt(6, 9)], jnp.int32), cfg_f,
                    max_new_tokens=6, max_len=256)
    assert out[r1] == [int(t) for t in want[0]]

    from gpu_provisioner_tpu.models.moe import MoEConfig, init_moe_model
    mcfg = MoEConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                     n_experts=4, experts_per_token=2, dtype="float32")
    mp = init_moe_model(jax.random.key(7), mcfg)
    meng = ServeEngine(mp, mcfg, slots=2, max_len=64,
                       prefill_buckets=(16,))
    pr = _prompt(8, 11)
    rid = meng.submit(pr, 6)
    mout = meng.run()
    # MoE reference: generate() on the BUCKET-padded prompt — expert
    # capacity is computed from the padded prefill length (the engine's
    # documented bucketing semantic, same class as chunked prefill's
    # per-chunk capacity), so the solo run must be padded identically
    padded = jnp.asarray([[0] * (16 - len(pr)) + pr], jnp.int32)
    want = generate(mp, padded, mcfg, max_new_tokens=6, max_len=256,
                    pad_id=0)
    assert mout[rid] == [int(t) for t in want[0]]


def test_engine_sliding_window_serving():
    """SWA serving through the engine: the window mask + per-slot pads
    compose in the decode path — streams equal generate() with the same
    window config."""
    cfg_w = dataclasses.replace(CFG, sliding_window=12)
    eng = ServeEngine(PARAMS, cfg_w, slots=2, max_len=64,
                      prefill_buckets=(16,))
    p = _prompt(35, 9)
    rid = eng.submit(p, 8)
    out = eng.run()
    padded = jnp.asarray([[0] * 7 + p], jnp.int32)   # bucket 16, 7 pads
    want = generate(PARAMS, padded, cfg_w, max_new_tokens=8, max_len=64,
                    pad_id=0)
    assert out[rid] == [int(t) for t in want[0]]


def test_engine_int8_cache():
    """The memory-constrained serving configuration: int8 KV cache rides
    the same insert/step machinery (scales inserted alongside values)."""
    cfg8 = dataclasses.replace(CFG, kv_cache_dtype="int8")
    eng = ServeEngine(PARAMS, cfg8, slots=2, max_len=64,
                      prefill_buckets=(16,))
    p = _prompt(30, 9)
    rid = eng.submit(p, 6)
    out = eng.run()
    want = generate(PARAMS, jnp.asarray([p], jnp.int32), cfg8,
                    max_new_tokens=6, max_len=64)
    assert out[rid] == [int(t) for t in want[0]]


def test_engine_sampled_mode_in_vocab():
    eng = ServeEngine(PARAMS, CFG, slots=2, max_len=64,
                      prefill_buckets=(16,), temperature=0.9, top_k=40,
                      key=jax.random.key(11))
    r1 = eng.submit(_prompt(10, 8), 6)
    r2 = eng.submit(_prompt(11, 8), 6)
    out = eng.run()
    for rid in (r1, r2):
        assert len(out[rid]) == 6
        assert all(0 <= t < 128 for t in out[rid])


def test_engine_streaming_step_contract():
    """step() surfaces EVERY emitted token: the admission token (from
    prefill logits), same-step decode tokens, and requests that finish
    during admission (max_new_tokens=1) — concatenated step outputs
    reconstruct each request's full stream."""
    eng = ServeEngine(PARAMS, CFG, slots=2, max_len=64,
                      prefill_buckets=(16,))
    r1 = eng.submit(_prompt(20, 8), 5)
    r2 = eng.submit(_prompt(21, 8), 1)       # finishes AT admission
    streams: dict[int, list[int]] = {}
    while eng.pending:
        for rid, toks in eng.step().items():
            streams.setdefault(rid, []).extend(toks)
    assert streams[r2] == eng.finished[r2] == _solo(_prompt(21, 8), 1)
    assert streams[r1] == eng.finished[r1] == _solo(_prompt(20, 8), 5)


def test_engine_speculative_matches_plain_streams():
    """Speculative engine slots (draft per round, wide verify, per-slot
    acceptance) emit exactly the plain greedy streams — including slot
    reuse, staggered arrival, quota truncation of the last window, and
    per-request eos."""
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft = init_params(jax.random.key(3), draft_cfg)
    eng = ServeEngine(PARAMS, CFG, slots=2, max_len=64,
                      prefill_buckets=(16,), draft_params=draft,
                      draft_cfg=draft_cfg, spec_k=3)
    rids = {eng.submit(_prompt(40 + i, 8 + i), 5 + i): (40 + i, 8 + i,
                                                       5 + i)
            for i in range(3)}
    eng.step()
    rids[eng.submit(_prompt(44, 12), 7)] = (44, 12, 7)   # mid-flight
    out = eng.run()
    for rid, (seed, n, new) in rids.items():
        assert out[rid] == _solo(_prompt(seed, n), new), f"req {rid}"

    # self-draft: full acceptance — finishes in ~ceil(new/k+1) steps/slot
    eng2 = ServeEngine(PARAMS, CFG, slots=1, max_len=64,
                       prefill_buckets=(16,), draft_params=PARAMS,
                       draft_cfg=CFG, spec_k=3)
    r = eng2.submit(_prompt(45, 8), 8)
    steps = 0
    while eng2.pending:
        eng2.step()
        steps += 1
    assert eng2.finished[r] == _solo(_prompt(45, 8), 8)
    assert steps <= 3                      # 1 admit-token + 2 full rounds

    # eos inside an accepted window truncates and frees the slot
    free = _solo(_prompt(46, 10), 12)
    eos = free[3]
    want = _solo(_prompt(46, 10), 12, eos_id=eos)
    eng3 = ServeEngine(PARAMS, CFG, slots=1, max_len=64,
                       prefill_buckets=(16,), draft_params=PARAMS,
                       draft_cfg=CFG, spec_k=3)
    r = eng3.submit(_prompt(46, 10), 12, eos_id=eos)
    out3 = eng3.run()
    k = out3[r].index(eos) + 1
    assert out3[r] == want[:k] and eos in out3[r]


def test_engine_speculative_moe_target():
    """Speculative engine with a Mixtral-capacity MoE target: drop-free
    verify keeps slot streams equal to the plain engine's."""
    from gpu_provisioner_tpu.models.moe import MoEConfig, init_moe_model

    mcfg = MoEConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                     n_experts=8, experts_per_token=2,
                     capacity_factor=1.25, dtype="float32")
    mp = init_moe_model(jax.random.key(9), mcfg)
    draft_cfg = dataclasses.replace(CFG)
    draft = init_params(jax.random.key(3), draft_cfg)
    plain = ServeEngine(mp, mcfg, slots=2, max_len=64,
                        prefill_buckets=(16,))
    spec = ServeEngine(mp, mcfg, slots=2, max_len=64,
                       prefill_buckets=(16,), draft_params=draft,
                       draft_cfg=draft_cfg, spec_k=2)
    p = _prompt(47, 9)
    rp = plain.submit(p, 8)
    rs = spec.submit(p, 8)
    assert spec.run()[rs] == plain.run()[rp]


def test_engine_prefix_caching_exact_and_lru():
    """Shared-prefix requests: the prefix prefills ONCE (LRU), each
    request's suffix continues it right-padded — streams equal solo
    generate() on prefix+prompt exactly; eviction works."""
    prefix = _prompt(50, 11)
    eng = ServeEngine(PARAMS, CFG, slots=2, max_len=96,
                      prefill_buckets=(16,), prefix_cache_size=2)
    reqs = {}
    for i in range(4):                       # 4 requests, one prefix
        p = _prompt(51 + i, 7 + i)
        reqs[eng.submit(p, 6, prefix=prefix)] = p
    out = eng.run()
    assert eng.prefix_misses == 1            # prefilled once, reused 3×
    assert eng.prefix_hits == 3
    assert eng.stats()["prefix_cache_hits"] == 3
    for rid, p in reqs.items():
        assert out[rid] == _solo(prefix + p, 6), f"req {rid}"
    # a second prefix shares the cache; a third evicts the LRU entry
    for j, extra in enumerate((_prompt(60, 9), _prompt(61, 13))):
        eng.submit(_prompt(62 + j, 7), 4, prefix=extra)
    eng.run()
    assert eng.prefix_misses == 3
    assert len(eng._prefix_lru) == 2         # size bound enforced
    # the evicted first prefix re-prefills on next use
    r = eng.submit(_prompt(64, 7), 4, prefix=prefix)
    out2 = eng.run()
    assert eng.prefix_misses == 4
    assert out2[r] == _solo(prefix + _prompt(64, 7), 4)


def test_engine_prefix_with_int8_cache():
    """Prefix rows quantize too: the int8 prefix cache row carries its
    scales through insert/suffix/decode — stream equals solo int8."""
    cfg8 = dataclasses.replace(CFG, kv_cache_dtype="int8")
    prefix = _prompt(55, 11)
    eng = ServeEngine(PARAMS, cfg8, slots=1, max_len=96,
                      prefill_buckets=(16,))
    p = _prompt(56, 8)
    rid = eng.submit(p, 6, prefix=prefix)
    out = eng.run()
    # solo reference at the SAME padding (prefix buckets to 16 with 5
    # left pads → int8 scales quantize identical values either way, but
    # keep the reference shape-identical for strictness)
    padded = jnp.asarray([[0] * 5 + prefix + p], jnp.int32)
    want = generate(PARAMS, padded, cfg8, max_new_tokens=6, max_len=96,
                    pad_id=0)
    assert out[rid] == [int(t) for t in want[0]]


def test_engine_prefix_with_speculation():
    """Prefix caching composes with the speculative engine: both caches
    carry the prefix row and the streams stay exactly plain greedy's."""
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft = init_params(jax.random.key(3), draft_cfg)
    prefix = _prompt(70, 10)
    eng = ServeEngine(PARAMS, CFG, slots=2, max_len=96,
                      prefill_buckets=(16,), draft_params=draft,
                      draft_cfg=draft_cfg, spec_k=3)
    reqs = {eng.submit(_prompt(71 + i, 8), 6, prefix=prefix): i
            for i in range(3)}
    out = eng.run()
    assert eng.prefix_misses == 1
    for rid, i in reqs.items():
        assert out[rid] == _solo(prefix + _prompt(71 + i, 8), 6)


def test_engine_prefix_validation():
    from gpu_provisioner_tpu.models.moe import MoEConfig, init_moe_model

    eng = ServeEngine(PARAMS, CFG, slots=1, max_len=64,
                      prefill_buckets=(16,))
    with pytest.raises(ValueError, match="empty prefix"):
        eng.submit(_prompt(80, 8), 4, prefix=[])
    with pytest.raises(ValueError, match="prefix 16"):
        # prefix buckets to 16: 16 + 16 + 40 > 64
        eng.submit(_prompt(80, 8), 40, prefix=_prompt(81, 10))
    with pytest.raises(ValueError, match="bucket"):
        eng.submit(_prompt(80, 8), 4, prefix=_prompt(81, 40))  # no bucket
    mcfg = MoEConfig(vocab_size=128, dim=64, n_layers=1, n_heads=4,
                     n_kv_heads=2, hidden_dim=128, max_seq_len=256,
                     n_experts=4, experts_per_token=2, dtype="float32")
    meng = ServeEngine(init_moe_model(jax.random.key(1), mcfg), mcfg,
                       slots=1, max_len=64, prefill_buckets=(16,))
    with pytest.raises(ValueError, match="dense family"):
        meng.submit(_prompt(82, 8), 4, prefix=_prompt(83, 8))


def test_engine_logprobs_match_generate():
    """return_logprobs: per-token logprobs equal generate()'s for the
    same stream — plain AND speculative engines (the speculative path
    scores under the verify distribution, speculative_generate's
    convention, which provably equals plain greedy's)."""
    import numpy as np

    p = _prompt(95, 9)
    want_t, want_lp = generate(PARAMS, jnp.asarray([p], jnp.int32), CFG,
                               max_new_tokens=6, max_len=256,
                               return_logprobs=True)
    eng = ServeEngine(PARAMS, CFG, slots=2, max_len=64,
                      prefill_buckets=(16,), return_logprobs=True)
    rid = eng.submit(p, 6)
    out = eng.run()
    assert out[rid] == [int(t) for t in want_t[0]]
    np.testing.assert_allclose(eng.finished_logprobs[rid],
                               np.asarray(want_lp[0]), atol=1e-5)

    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft = init_params(jax.random.key(3), draft_cfg)
    seng = ServeEngine(PARAMS, CFG, slots=2, max_len=64,
                       prefill_buckets=(16,), draft_params=draft,
                       draft_cfg=draft_cfg, spec_k=3,
                       return_logprobs=True)
    rid = seng.submit(p, 6)
    sout = seng.run()
    assert sout[rid] == [int(t) for t in want_t[0]]
    assert len(seng.finished_logprobs[rid]) == 6
    np.testing.assert_allclose(seng.finished_logprobs[rid],
                               np.asarray(want_lp[0]), atol=1e-5)


def test_engine_stats_counters():
    eng = ServeEngine(PARAMS, CFG, slots=2, max_len=64,
                      prefill_buckets=(16,))
    assert eng.stats()["slots_active"] == 0
    r1 = eng.submit(_prompt(90, 8), 4)
    eng.submit(_prompt(91, 8), 4)
    eng.submit(_prompt(92, 8), 4)        # queues behind 2 slots
    eng.step()
    s = eng.stats()
    assert s["slots_active"] == 2 and s["queue_depth"] == 1
    assert s["requests_submitted"] == 3
    eng.run()
    s = eng.stats()
    assert s["requests_finished"] == 3 and s["slots_active"] == 0
    assert s["tokens_emitted"] == 12
    assert len(eng.finished[r1]) == 4


def test_engine_validation():
    with pytest.raises(ValueError, match="slot"):
        ServeEngine(PARAMS, CFG, slots=0)
    with pytest.raises(ValueError, match="PRNG"):
        ServeEngine(PARAMS, CFG, temperature=0.5)
    eng = ServeEngine(PARAMS, CFG, slots=1, max_len=32,
                      prefill_buckets=(16,))
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(_prompt(12, 10), 32)      # 16 + 32 > 32
    with pytest.raises(ValueError, match="bucket"):
        eng.submit(_prompt(13, 20), 4)       # no bucket >= 20
    with pytest.raises(ValueError, match="empty"):
        eng.submit([], 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit(_prompt(14, 8), 0)
    with pytest.raises(ValueError, match="spec_k"):
        ServeEngine(PARAMS, CFG, draft_params=PARAMS, draft_cfg=CFG,
                    spec_k=0)
    with pytest.raises(ValueError, match="together"):
        ServeEngine(PARAMS, CFG, draft_params=PARAMS)
