"""examples/*.yaml must parse and provision through envtest — the parity
check for the reference's examples/v1-nodeclaim-gpu.yaml reconciled in
BASELINE.json's envtest config."""

import pytest
import glob
import os

import yaml

from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import object_from_manifest
from gpu_provisioner_tpu.envtest import Env

from .conftest import async_test

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def load_all() -> list:
    objs = []
    for path in sorted(glob.glob(os.path.join(EXAMPLES, "*.yaml"))):
        with open(path) as f:
            for doc in yaml.safe_load_all(f):
                if doc:
                    objs.append((os.path.basename(path), object_from_manifest(doc)))
    return objs


def test_examples_parse_to_registered_kinds():
    objs = load_all()
    assert len(objs) >= 7  # single, multihost, 4× multislice, queued
    assert all(o.metadata.name for _, o in objs)


@async_test
async def test_examples_provision_in_envtest():
    async with Env() as env:
        for fname, obj in load_all():
            if isinstance(obj, NodeClaim):
                await env.client.create(obj)
        for fname, obj in load_all():
            if isinstance(obj, NodeClaim):
                nc = await env.wait_ready(obj.metadata.name, timeout=30)
                assert nc.status.provider_id, fname


def _run_workload_example(script: str) -> "subprocess.CompletedProcess":
    """Run an examples/workloads script on the 8-way CPU mesh as its
    docstring documents. PALLAS_AXON_POOL_IPS="" keeps the axon site hook
    out of the subprocess: with the TPU tunnel absent/wedged its PJRT
    probe can hang jax init for the full timeout."""
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ,
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
           "JAX_PLATFORMS": "cpu",
           "PALLAS_AXON_POOL_IPS": "",
           "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", "")}
    return subprocess.run(
        [sys.executable, os.path.join(repo, "examples", "workloads", script)],
        env=env, capture_output=True, text=True, timeout=600)


@pytest.mark.e2e
def test_serve_example_runs():
    """The documented serving example (tp mesh, sampled generation,
    multi-turn cache continuation) runs end to end on the CPU mesh."""
    r = _run_workload_example("serve.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "sampled:" in r.stdout and "done" in r.stdout
    assert "multi-turn cache length: 34" in r.stdout


@pytest.mark.e2e
def test_train_resume_example_runs():
    """The documented workload example (train → checkpoint → resume on a
    different mesh layout) runs end to end on the CPU mesh."""
    r = _run_workload_example("train_resume.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "resuming on mesh" in r.stdout and "done" in r.stdout
