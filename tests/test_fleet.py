"""fleetscope tests: SLO digests/burn-rate units, probe fan-out structure,
flight-recorder ring/trigger semantics, and the chaos-triggered bundle
round-trips (trigger → ring → disk → HTTP → parse).

The chaos soaks reuse test_chaos's seeded env builder; bundle triggers are
forced deterministically — a microsecond SLO target makes every envtest
claim a violation (fast-burn), and a near-zero mass-repair fraction makes
the first preempted spot node trip the repair breaker."""

import asyncio
import gc
import json

import pytest

from gpu_provisioner_tpu import chaos
from gpu_provisioner_tpu.controllers.metrics import (
    SLO_BURN_RATE, SLO_CLAIMS_OBSERVED, SLO_OBJECTIVE_TARGET,
    SLO_VIOLATIONS_TOTAL, TIMER_WAKE_SHARE, update_runtime_gauges,
)
from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
from gpu_provisioner_tpu.fake import make_nodeclaim
from gpu_provisioner_tpu.observability import Tracer, TraceStore
from gpu_provisioner_tpu.observability.fleet import (
    BUCKET_BOUNDS, ENGINES, BurnWindow, FleetAggregator, LatencyDigest,
    SLOObjective, SLOTracker, engine_stats, register_engine,
)
from gpu_provisioner_tpu.observability.flightrecorder import (
    RECORDED_EVENTS, FlightRecorder,
)
from gpu_provisioner_tpu.runtime import probes

from .conftest import async_test
from .test_chaos import SEED, chaos_env, converge
from .test_placement import ZONE_A, ZONE_B, ZONE_C, spot_claim

# ------------------------------------------------------------- digest units


def test_latency_digest_quantiles_and_flat_memory():
    d = LatencyDigest()
    for i in range(1, 101):
        d.record(i / 100.0)           # 0.01 .. 1.00
    assert d.count == 100
    assert d.min == 0.01 and d.max == 1.0
    # the geometric ladder guarantees ~11% relative error per bucket
    assert abs(d.quantile(0.50) - 0.50) <= 0.50 * 0.15
    assert abs(d.quantile(0.95) - 0.95) <= 0.95 * 0.15
    assert d.quantile(1.0) == 1.0
    assert abs(d.mean - 0.505) < 1e-9
    # memory is O(buckets), not O(observations): 100× more samples, same
    # structure — the BENCH_pr14 flatness property at unit scale
    big = LatencyDigest()
    for i in range(10_000):
        big.record((i % 100 + 1) / 100.0)
    assert len(big.counts) == len(d.counts) == len(BUCKET_BOUNDS) + 1
    # single-sample digest reports the sample itself (min/max clamp)
    one = LatencyDigest()
    one.record(0.5)
    assert one.quantile(0.5) == one.quantile(0.99) == 0.5
    assert LatencyDigest().quantile(0.95) == 0.0
    s = d.summary()
    assert s["count"] == 100 and s["max"] == 1.0


def test_burn_window_slides_and_expires():
    t = {"now": 0.0}
    w = BurnWindow(10.0, clock=lambda: t["now"])
    for _ in range(4):
        w.note(ok=False)
    w.note(ok=True)
    assert w.counts() == (1, 4)
    assert w.bad_fraction() == pytest.approx(0.8)
    # everything ages out once the window has fully slid past
    t["now"] = 11.0
    assert w.counts() == (0, 0)
    assert w.bad_fraction() == 0.0


def test_slo_tracker_multi_window_alert_and_rearm():
    t = {"now": 0.0}
    obj = SLOObjective(target=1.0, percentile=0.95, fast_window=10.0,
                       slow_window=100.0, burn_threshold=1.0, min_samples=3)
    trk = SLOTracker(obj, clock=lambda: t["now"])
    trk.note(5.0)
    trk.note(5.0)
    # two violations are under min_samples — burn ∞ into an empty window
    # is noise, not an incident
    assert not trk.fast_burning()
    trk.note(5.0)
    assert trk.fast_burning()
    burn = trk.burn_rates()
    assert burn["fast"] >= 1.0 and burn["slow"] >= 1.0
    assert trk.bad == 3 and trk.good == 0
    # the fast window slides clean; a healthy stretch clears the alert
    # even though the slow window still remembers the incident
    t["now"] = 12.0
    for _ in range(5):
        trk.note(0.1)
    assert not trk.fast_burning()
    d = trk.to_dict()
    assert d["violations"] == 3 and d["good"] == 5


@async_test
async def test_fleet_aggregator_keys_fast_burn_fires_once():
    store = TraceStore()
    tracer = Tracer(store)
    agg = FleetAggregator(objectives=(SLOObjective(
        target=1e-9, fast_window=30.0, slow_window=60.0,
        burn_threshold=0.1, min_samples=1),))
    tracer.add_listener(agg.on_trace_event)
    fired = []
    agg.on_fast_burn = fired.append

    for claim in ("fa0", "fa1"):
        with tracer.span(claim, "reconcile"):
            await asyncio.sleep(0.002)
        tracer.set_trace_attrs(claim, zone="z1", generation="v5e",
                               tier="spot")
        tracer.annotate(claim, "ready")
    assert agg.claims_observed == 2
    assert ("z1", "v5e", "spot", "0") in agg.digests
    # the alert fires on the TRANSITION into burn — the second violating
    # claim arrives already-burning and must not re-trigger
    assert len(fired) == 1 and fired[0].objective.name == "time-to-ready"
    snap = agg.snapshot()
    assert snap["keys"][0]["zone"] == "z1"
    assert snap["objectives"][0]["violations"] == 2
    assert snap["objectives"][0]["fast_burning"]
    # a trace that never reached ready (or has no analyzable window)
    # counts as unattributed, not a crash
    tracer.annotate("fa-empty", "ready")
    assert agg.unattributed == 1


# ------------------------------------------------------- flight recorder units


def test_recorder_ring_bounds_and_event_filter():
    rec = FlightRecorder(capacity=4)
    rec.probe("wq-enqueue", "hot", n=1)       # hot-path event: not recorded
    assert rec.events_recorded == 0
    for i in range(10):
        rec.probe("hub-wake", f"w{i}", source="watch")
    assert rec.events_recorded == 10
    assert len(rec.events()) == 4, "ring must stay bounded"
    assert [e["key"] for e in rec.events()] == ["w6", "w7", "w8", "w9"]
    assert "hub-wake" in RECORDED_EVENTS and "wq-enqueue" not in RECORDED_EVENTS


def test_recorder_trigger_dedupe_and_sources():
    rec = FlightRecorder(capacity=16)
    rec.add_source("ok", lambda: {"depth": 3})
    rec.add_source("broken", lambda: 1 / 0)
    rec.probe("hub-wake", "w0", source="timer")
    b = rec.trigger("breaker-trip", key="gke-nodepools")
    assert b is not None
    assert b["sources"]["ok"] == {"depth": 3}
    assert "error" in b["sources"]["broken"], \
        "a failing source must degrade, not fail the snapshot"
    assert b["events"][0]["event"] == "hub-wake"
    # exactly one bundle per distinct (kind, key): repeats are counted
    assert rec.trigger("breaker-trip", key="gke-nodepools") is None
    assert rec.triggers_suppressed == 1
    assert rec.trigger("breaker-trip", key="cloudtpu") is not None
    assert len(rec.bundles()) == 2
    assert rec.bundle("breaker-trip:gke-nodepools") is b
    assert rec.bundle() is rec.bundle("breaker-trip:cloudtpu")
    assert rec.bundle("no-such") is None
    # non-JSON info values are coerced, never poison serialization
    rec.probe("fence-drop", object(), controller=object())
    json.dumps(rec.events())
    stats = rec.stats()
    assert stats["bundles"] == 2 and stats["triggers_suppressed"] == 1


def test_probe_fanout_structure_single_none_check():
    """The disabled fast path must stay ONE module-global None check; a fuzz
    probe and a recorder sink must coexist and detach independently."""
    assert probes._active is None, "a prior test leaked a probe/sink"
    seen_probe, seen_sink = [], []

    def fuzz(event, key, **info):
        seen_probe.append(event)

    def sink_fn(event, key, **info):
        seen_sink.append(event)

    probes.add_sink(sink_fn)
    probes.add_sink(sink_fn)                       # idempotent
    assert probes._active == (sink_fn,)
    prev = probes.arm(fuzz)
    probes.emit("x", "k")
    assert seen_probe == ["x"] and seen_sink == ["x"]
    probes.disarm(prev)
    probes.emit("y", "k")
    assert seen_probe == ["x"] and seen_sink == ["x", "y"]
    probes.remove_sink(sink_fn)
    probes.remove_sink(sink_fn)                    # unknown: no-op
    assert probes._active is None


@async_test
async def test_disabled_recorder_and_fleet_leave_seams_dark():
    """fleet=False/flight_recorder=False: no aggregator, no sink — the
    probe seam reads None for the whole run and /slo, /debugz/* are not
    routed."""
    from aiohttp.test_utils import TestClient, TestServer
    from gpu_provisioner_tpu.operator.server import build_apps

    async with Env(EnvtestOptions(fleet=False, flight_recorder=False)) as env:
        assert env.fleet is None and env.flight_recorder is None
        assert probes._active is None, \
            "disabled observability must cost exactly the None check"
        await env.client.create(make_nodeclaim("dk0"))
        await env.wait_ready("dk0")
        assert probes._active is None
        metrics_app, _ = build_apps(env.manager,
                                    trace_store=env.trace_store)
        async with TestClient(TestServer(metrics_app)) as mc:
            assert (await mc.get("/slo")).status == 404
            assert (await mc.get("/debugz/bundle")).status == 404
    assert probes._active is None


# ------------------------------------------------------ engine-stats bridge


def test_engine_registry_weak_and_gauges():
    class FakeEngine:
        def stats(self):
            return {"slots": 8, "slots_active": 3, "queue_depth": 5,
                    "requests_submitted": 40, "requests_finished": 37,
                    "tokens_emitted": 1234, "prefix_cache_entries": 7,
                    "prefix_cache_hits": 20, "prefix_cache_misses": 4}

    eng = FakeEngine()
    name = register_engine(eng, name="unit-engine")
    assert name == "unit-engine"
    assert engine_stats()["unit-engine"]["queue_depth"] == 5
    from gpu_provisioner_tpu.controllers.metrics import (
        ENGINE_PREFIX_CACHE, ENGINE_QUEUE_DEPTH, ENGINE_SLOTS,
    )
    update_runtime_gauges(object())    # no manager: registry sampling only
    assert ENGINE_QUEUE_DEPTH.labels("unit-engine")._value.get() == 5
    assert ENGINE_SLOTS.labels("unit-engine", "active")._value.get() == 3
    assert ENGINE_PREFIX_CACHE.labels(
        "unit-engine", "hits")._value.get() == 20
    # weak registry: a collected engine drops out of the scrape instead of
    # freezing its last values behind a dead name
    del eng
    gc.collect()
    assert "unit-engine" not in engine_stats()
    assert "unit-engine" not in ENGINES


# ------------------------------------------------------------- chaos soaks

WAVE = 10


@pytest.mark.chaos
@pytest.mark.capacity
@async_test
async def test_zonal_stockout_fast_burn_bundle_round_trip(tmp_path):
    """The acceptance round-trip under seeded zonal_stockout: a microsecond
    SLO target turns every ready claim into a violation, the fast-burn
    trigger snapshots exactly one bundle, and the bundle round-trips
    trigger → disk → HTTP → parse byte-identically."""
    from aiohttp.test_utils import TestClient, TestServer
    from gpu_provisioner_tpu.operator.server import build_apps

    policy = chaos.profile("zonal_stockout", seed=SEED)
    zones = {
        ZONE_A: {"v5e": 8},          # room for exactly one slice
        ZONE_B: {"v5e": 10_000},     # ample chips — but chaos-dry
        ZONE_C: {"v5e": 10_000},
    }
    objective = SLOObjective(target=1e-6, fast_window=30.0, slow_window=60.0,
                             burn_threshold=1.0, min_samples=3)
    violations0 = SLO_VIOLATIONS_TOTAL.labels("time-to-ready")._value.get()
    names = [f"fb{i}" for i in range(WAVE)]
    async with chaos_env(policy, launch_timeout=30.0, zones=zones,
                         stockout_memo_ttl=30.0,
                         slo_objectives=(objective,),
                         bundle_dir=str(tmp_path)) as env:
        for n in names:
            await env.client.create(make_nodeclaim(n))
        ready, gone = await converge(env, names, timeout=45.0)
        assert ready == set(names), f"wave lost claims: {sorted(gone)}"

        snap = env.fleet.snapshot()
        assert snap["claims_observed"] == WAVE
        assert snap["objectives"][0]["violations"] == WAVE
        landed = {k["zone"] for k in snap["keys"]}
        assert landed <= {ZONE_A, ZONE_C}, f"digest keys: {landed}"
        assert snap["phases"], "phase attribution never populated"

        rec = env.flight_recorder
        burn_bundles = [b for b in rec.bundles()
                        if b["trigger"]["kind"] == "slo-fast-burn"]
        assert len(burn_bundles) == 1, \
            f"want exactly one fast-burn bundle, got {len(burn_bundles)}"
        bundle = burn_bundles[0]
        assert bundle["trigger"]["key"] == "slo-fast-burn:time-to-ready"
        kinds = {e["event"] for e in bundle["events"]}
        assert "placement-verdict" in kinds, sorted(kinds)
        for section in ("queue_depths", "inflight_ops", "placement_memos",
                        "recent_traces"):
            assert section in bundle["sources"], bundle["sources"].keys()
        assert ZONE_B in bundle["sources"]["placement_memos"]["stockouts"]

        # disk leg: the trigger wrote exactly this bundle
        files = sorted(tmp_path.glob("bundle-*-slo-fast-burn*.json"))
        assert len(files) == 1, [f.name for f in files]
        assert json.loads(files[0].read_text()) == bundle
        assert rec.bundles_written >= 1

        # HTTP leg: /slo and /debugz/bundle serve the same objects
        metrics_app, _ = build_apps(env.manager, trace_store=env.trace_store,
                                    fleet=env.fleet, recorder=rec)
        async with TestClient(TestServer(metrics_app)) as mc:
            slo = await (await mc.get("/slo")).json()
            assert slo["claims_observed"] == WAVE
            assert slo["objectives"][0]["target_s"] == pytest.approx(1e-6)
            r = await mc.get("/debugz/bundle?trigger=slo-fast-burn:time-to-ready")
            assert r.status == 200
            assert await r.json() == bundle
            listing = await (await mc.get("/debugz/bundle?list=1")).json()
            assert listing["stats"]["bundles"] == len(rec.bundles())
            assert (await mc.get("/debugz/bundle?trigger=nope")).status == 404
            # /traces pagination satellite: ?limit= bounds, ?since= filters
            page = await (await mc.get("/traces?limit=3")).json()
            assert len(page["traces"]) == 3
            cursor = max(t["last_at"] for t in page["traces"])
            newer = await (await mc.get(
                f"/traces?limit=50&since={cursor + 1e9}")).json()
            assert newer["traces"] == []
            assert (await mc.get("/traces?since=bogus")).status == 400

        # scrape satellites: timer-wake share + SLO families go live
        update_runtime_gauges(env.manager)
        assert 0.0 <= TIMER_WAKE_SHARE._value.get() <= 1.0
        assert SLO_CLAIMS_OBSERVED._value.get() >= WAVE
        assert SLO_OBJECTIVE_TARGET.labels(
            "time-to-ready")._value.get() == pytest.approx(1e-6)
        assert SLO_BURN_RATE.labels(
            "time-to-ready", "fast")._value.get() >= 0.0
        assert (SLO_VIOLATIONS_TOTAL.labels("time-to-ready")._value.get()
                >= violations0 + WAVE)


@pytest.mark.chaos
@pytest.mark.capacity
@async_test
async def test_spot_reclaim_repair_breaker_trip_bundles_once():
    """spot_reclaim preempts every spot slice; with the mass-repair breaker
    tuned to trip on the first unhealthy node, the trip must snapshot
    exactly one bundle whose ring already holds the wave's placement
    verdicts — and repeats of the same trigger are suppressed, not
    re-bundled."""
    policy = chaos.profile("spot_reclaim", seed=SEED)
    names = ["sb0", "sb1"]
    async with chaos_env(policy, launch_timeout=20.0,
                         repair_toleration=0.2,
                         spot_reclaim_grace=1.0,
                         repair_max_unhealthy_fraction=0.01,
                         repair_breaker_min_unhealthy=1) as env:
        for n in names:
            await env.client.create(spot_claim(n))
        ready, _ = await converge(env, names, timeout=20.0)
        assert ready == set(names)
        rec = env.flight_recorder
        deadline = asyncio.get_event_loop().time() + 15.0
        while rec.bundle("repair-breaker-trip:cluster") is None:
            assert asyncio.get_event_loop().time() < deadline, \
                f"repair breaker never tripped: {rec.stats()}"
            await asyncio.sleep(0.05)
        trips = [b for b in rec.bundles()
                 if b["trigger"]["kind"] == "repair-breaker-trip"]
        assert len(trips) == 1, "one distinct trigger, one bundle"
        bundle = trips[0]
        verdicts = [e for e in bundle["events"]
                    if e["event"] == "placement-verdict"]
        assert {v["key"] for v in verdicts} >= set(names), \
            "the bundle must carry the wave's placement verdicts"
        assert "queue_depths" in bundle["sources"]
        # a second trip of the SAME (kind, key) is deduped and counted
        suppressed0 = rec.triggers_suppressed
        assert rec.trigger("repair-breaker-trip", key="cluster") is None
        assert rec.triggers_suppressed == suppressed0 + 1
        assert len([b for b in rec.bundles()
                    if b["trigger"]["kind"] == "repair-breaker-trip"]) == 1
