"""Node-fault soak matrix + repair regression suite (controllers/health.py).

Every soak runs the WHOLE provisioner (envtest) with a seeded
``chaos.NodeFaultInjector`` playing the kubelet fleet, a KAITO-simulating
replacer recreating claims the repair loop deletes, and asserts the repair
invariants:

1. every workload converges back to Ready once the fault window closes;
2. zero orphaned pools / queued resources — the fake cloud exactly matches
   the surviving claims;
3. total repairs never exceed the configured RepairBudget;
4. the ``maintenance_wave`` + fraction-breaker case performs ZERO
   force-deletes while the breaker is tripped.

The full profile × workload matrix is marked ``slow`` (run via
``make repair``); the regression tests (flap bug pin, observed-staleness
anchoring, truncation robustness, budget/breaker units, mid-repair crash ×
recovery) stay in tier-1.
"""

import asyncio
import os
from collections import defaultdict

import pytest

from gpu_provisioner_tpu import chaos
from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.core import Node, Pod, PodSpec
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import CONDITION_READY, ObjectMeta
from gpu_provisioner_tpu.controllers.health import (
    REPAIR_STATS, HealthOptions, NodeHealthController, RepairBudget,
)
from gpu_provisioner_tpu.envtest import Env, EnvtestOptions, RestartableEnv
from gpu_provisioner_tpu.fake import make_nodeclaim
from gpu_provisioner_tpu.fake.builders import set_node_condition, set_node_ready
from gpu_provisioner_tpu.runtime import NotFoundError

from .conftest import async_test

pytestmark = [pytest.mark.chaos, pytest.mark.repair]

SEED = int(os.environ.get("CHAOS_SEED", "7"))


def repair_env(injector=None, **kw) -> Env:
    """Envtest tuned for repair soaks: fast GC, short toleration, hysteresis
    at test timescale, breaker off unless the scenario turns it on."""
    kw.setdefault("gc_interval", 0.1)
    kw.setdefault("leak_grace", 0.1)
    kw.setdefault("repair_toleration", 0.4)
    kw.setdefault("repair_flap_threshold", 3)
    kw.setdefault("repair_flap_window", 6.0)
    kw.setdefault("repair_drain_deadline", 0.6)
    kw.setdefault("repair_drain_requeue", 0.05)
    kw.setdefault("repair_throttle_requeue", 0.1)
    kw.setdefault("repair_max_unhealthy_fraction", 0.0)
    opts = EnvtestOptions(node_faults=injector, **kw)
    opts.lifecycle.launch_timeout = 20.0
    opts.lifecycle.registration_timeout = 20.0
    return Env(opts)


# (claim name, shape, slice-group) per workload case of the matrix.
SHAPES = {
    "single-host": [("h0", "tpu-v5e-8", None)],
    "multi-host": [("mh0", "tpu-v5p-32", None)],
    "multi-slice-group": [("g0", "tpu-v5e-16", "g"),
                          ("g1", "tpu-v5e-16", "g")],
}


def _claim(name, shape, group):
    labels = {wk.TPU_SLICE_GROUP_LABEL: group} if group else None
    return make_nodeclaim(name, shape, labels=labels)


def start_replacer(env: Env, specs):
    """KAITO simulation: repair deletes a NodeClaim; the workspace
    controller would recreate it. Returns (task, per-claim recreate counts)."""
    counts = defaultdict(int)

    async def run():
        # provlint: disable=unbounded-sleep-poll — not a poll-until: this
        # simulator runs until the test cancels the returned task
        while True:
            for name, shape, group in specs:
                try:
                    await env.client.get(NodeClaim, name)
                except NotFoundError:
                    try:
                        await env.client.create(_claim(name, shape, group))
                        counts[name] += 1
                    except Exception:  # noqa: BLE001 — create race; next lap
                        pass
                except Exception:  # noqa: BLE001 — transient read error
                    pass
            await asyncio.sleep(0.05)

    return asyncio.create_task(run()), counts


async def wait_repaired_and_converged(env: Env, names, timeout=20.0):
    """All claims Ready AND no managed node matches any repair policy."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        ok = True
        for name in names:
            try:
                nc = await env.client.get(NodeClaim, name)
            except NotFoundError:
                ok = False
                break
            if not nc.status_conditions.is_true(CONDITION_READY):
                ok = False
                break
        if ok:
            nodes = await env.client.list(
                Node, labels={wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME})
            hc = _health_controller(env)
            if any(hc._match_policy(n) is not None for n in nodes):
                ok = False
        if ok:
            return
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(f"repair never converged {sorted(names)}")
        await asyncio.sleep(0.05)


def _health_controller(env: Env) -> NodeHealthController:
    c = next(c for c in env.manager.controllers if c.name == "node.health")
    return c.reconciler


async def assert_no_leaks(env: Env, names: set, timeout=10.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        pools = set(env.cloud.nodepools.pools)
        qrs = set(env.cloud.queuedresources.resources)
        nodes = await env.client.list(Node)
        node_pools = {n.metadata.labels.get(wk.GKE_NODEPOOL_LABEL)
                      for n in nodes}
        if pools == names and not qrs and node_pools <= names | {None}:
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(
                f"leak invariant violated: pools={sorted(pools)} (want "
                f"{sorted(names)}), qrs={sorted(qrs)}, orphan-node-pools="
                f"{sorted((node_pools - names) - {None}, key=str)}")
        await asyncio.sleep(0.05)


def _stats():
    return {k: REPAIR_STATS[k] for k in
            ("started", "succeeded", "throttled", "flap_detections")}


# ------------------------------------------------------- the soak matrix

MATRIX = [(p, s) for p in ("flapping_node", "degraded_slice", "silent_death",
                           "maintenance_wave")
          for s in ("single-host", "multi-host", "multi-slice-group")]


@pytest.mark.slow
@pytest.mark.parametrize("profile,shape", MATRIX)
@async_test
async def test_repair_soak_matrix(profile, shape):
    specs = SHAPES[shape]
    names = {n for n, _, _ in specs}
    # windows sized so the repair reliably lands INSIDE the fault (stale-
    # heartbeat detection alone costs bound + truncation slack), while a
    # replacement node re-entering the window still converges once it closes
    overrides = {"degraded_slice": dict(duration=1.5),
                 "flapping_node": dict(duration=4.0),
                 "silent_death": dict(duration=6.0)}.get(profile, {})
    inj = chaos.node_fault_profile(profile, seed=SEED, **overrides)
    env_kw = dict(repair_rate=6.0, repair_rate_interval=60.0, repair_burst=6,
                  repair_max_concurrent=4)
    wave = profile == "maintenance_wave"
    if wave:
        # the correlated-wave case: breaker ON, trippable at any fleet size
        env_kw.update(repair_max_unhealthy_fraction=0.5,
                      repair_breaker_min_unhealthy=1,
                      repair_breaker_ttl=0.2)
    if profile == "silent_death":
        env_kw.update(repair_heartbeat_bound=0.5)
    else:
        inj.heartbeat = False  # cut heartbeat write churn where irrelevant

    before = _stats()
    deletes0 = None
    async with repair_env(inj, **env_kw) as env:
        deletes0 = env.cloud.nodepools.calls["begin_delete"]
        for name, shp, group in specs:
            await env.client.create(_claim(name, shp, group))
        for name, _, _ in specs:
            await env.wait_ready(name, timeout=15)
        replacer, counts = start_replacer(env, specs)
        t0 = asyncio.get_event_loop().time()
        try:
            if wave:
                # breaker holds everything back: just outlive the wave
                await asyncio.sleep(1.0)
            else:
                # flap and silent-death are invisible to a point-in-time
                # _match_policy scan (Ready reads True) — convergence alone
                # can't prove the fault bit. Wait for a completed repair
                # first, then for convergence.
                deadline = asyncio.get_event_loop().time() + 20.0
                while _stats()["succeeded"] <= before["succeeded"]:
                    assert asyncio.get_event_loop().time() < deadline, \
                        "no repair ever completed under the fault"
                    await asyncio.sleep(0.05)
            await wait_repaired_and_converged(env, names, timeout=30.0)
            await assert_no_leaks(env, names)
        finally:
            replacer.cancel()
        elapsed = asyncio.get_event_loop().time() - t0
        after = _stats()

        if wave:
            # breaker tripped for the whole wave: ZERO force-deletes, and the
            # trip was actually exercised
            assert after["succeeded"] == before["succeeded"], \
                "maintenance wave force-deleted a slice through the breaker"
            assert env.cloud.nodepools.calls["begin_delete"] == deletes0
            assert after["throttled"] > before["throttled"], \
                "breaker never held a repair back"
            assert sum(counts.values()) == 0, counts
            assert inj.injected_total("maintenance:") > 0
        else:
            assert inj.injected_total() > 0, "profile injected nothing"
            assert after["succeeded"] > before["succeeded"], \
                "no repair ever completed under the fault"
            # the budget ceiling: burst + rate·elapsed/interval (+1 slack for
            # the window boundary)
            allowed = 6 + 6.0 * elapsed / 60.0 + 1
            assert after["succeeded"] - before["succeeded"] <= allowed

        if shape == "multi-slice-group" and not wave:
            # slice-group identity re-converged: every group node re-stamped
            # with a coordinator that is a live index-0 worker (stamping
            # rides node watch events; poll a short settle window)
            deadline = asyncio.get_event_loop().time() + 5.0
            while True:
                nodes = await env.client.list(
                    Node, labels={wk.TPU_SLICE_GROUP_LABEL: "g"})
                coords = {n.metadata.labels.get(wk.TPU_COORDINATOR_LABEL)
                          for n in nodes}
                owner = next((n for n in nodes
                              if n.metadata.name in coords), None)
                if (nodes and len(coords) == 1 and owner is not None
                        and owner.metadata.labels.get(
                            wk.TPU_SLICE_INDEX_LABEL) == "0"):
                    break
                assert asyncio.get_event_loop().time() < deadline, \
                    f"coordinator never re-converged: {coords}"
                await asyncio.sleep(0.05)


# ------------------------------------------------ flap bug pin + hysteresis

@async_test
async def test_prepr_flap_bug_pinned_without_hysteresis():
    """Regression pin of today's bug: a node whose Ready oscillates faster
    than the toleration resets the toleration clock on every flip and is
    NEVER repaired by the pre-hysteresis controller (flap_threshold=0)."""
    inj = chaos.node_fault_profile("flapping_node", seed=SEED, duration=30.0)
    inj.heartbeat = False
    async with repair_env(inj, repair_flap_threshold=0,
                          repair_toleration=0.5) as env:
        await env.client.create(make_nodeclaim("fb0"))
        await env.wait_ready("fb0", timeout=15)
        await asyncio.sleep(2.5)  # many flap periods, several tolerations
        assert inj.injected_total("flap:") >= 2, "fault never bit"
        # the claim survived every flip: each Ready=False interval is shorter
        # than the toleration and the transition resets the clock
        nc = await env.client.get(NodeClaim, "fb0")
        assert nc.metadata.name == "fb0"
        assert "fb0" in env.cloud.nodepools.pools


@async_test
async def test_flap_hysteresis_repairs_flapping_node():
    """The same flapping node IS repaired once the condition-history window
    accrues the flips (N transitions inside W == unhealthy), and the repair
    surface is visible on /metrics."""
    from gpu_provisioner_tpu.controllers.metrics import (
        REPAIR_FLAP_DETECTIONS, REPAIR_SUCCEEDED, update_runtime_gauges,
    )

    before = _stats()
    inj = chaos.node_fault_profile("flapping_node", seed=SEED, duration=30.0)
    inj.heartbeat = False
    async with repair_env(inj, repair_flap_threshold=3,
                          repair_toleration=0.5) as env:
        await env.client.create(make_nodeclaim("fh0"))
        await env.wait_ready("fh0", timeout=15)
        await env.wait_gone("fh0", timeout=15)  # hysteresis kills the flapper
        after = _stats()
        assert after["flap_detections"] > before["flap_detections"]
        assert after["succeeded"] > before["succeeded"]
        update_runtime_gauges(env.manager)
        assert REPAIR_FLAP_DETECTIONS._value.get() >= after["flap_detections"]
        assert REPAIR_SUCCEEDED._value.get() >= after["succeeded"]


# ------------------------------- observed-staleness + truncation robustness

@async_test
async def test_none_transition_time_is_anchored_not_ignored():
    """Satellite bugfix: a matching condition with last_transition_time=None
    used to compute elapsed=0.0 forever (requeue on the full toleration,
    never repaired). It is now anchored at first observation and repaired
    once the toleration of OBSERVED unhealthiness elapses."""
    async with repair_env(repair_toleration=0.4) as env:
        await env.client.create(make_nodeclaim("nt0"))
        await env.wait_ready("nt0", timeout=15)
        node = await env.client.get(Node, "gke-kaito-nt0-w0")
        for c in node.status.conditions:
            if c.type == "Ready":
                c.status = "False"
                c.reason = "KubeletDead"
                c.last_transition_time = None
        await env.client.update_status(node)
        await env.wait_gone("nt0", timeout=10)


@async_test
async def test_truncated_transition_time_never_fires_early():
    """Satellite bugfix: metav1.Time is second-resolution, so a freshly
    flipped condition can read up to 1s old — the toleration check must not
    treat that truncation error as elapsed unhealthy time (the same bug
    PR 3 fixed in the GC leak grace)."""
    async with repair_env(repair_toleration=0.8) as env:
        await env.client.create(make_nodeclaim("tt0"))
        await env.wait_ready("tt0", timeout=15)
        node = await env.client.get(Node, "gke-kaito-tt0-w0")
        set_node_ready(node, False, reason="JustFlipped")  # truncated stamp
        await env.client.update_status(node)
        # pre-PR: (now - truncated ltt) could read ~1s > 0.8 immediately →
        # premature repair. Now: label age is slack-adjusted and the
        # observed-for anchor has only just started.
        await asyncio.sleep(0.4)
        nc = await env.client.get(NodeClaim, "tt0")
        assert nc.metadata.name == "tt0", "repair fired inside the toleration"
        # ...but the genuinely-unhealthy node IS repaired once observed long
        # enough
        await env.wait_gone("tt0", timeout=10)


# ----------------------------------------------------- breaker + budget

def test_repair_budget_tokens_concurrency_and_group_serialization():
    b = RepairBudget(rate=2.0, interval=10.0, burst=2, max_concurrent=2)
    assert b.try_start("n1", "g1", 0.0) is None
    # same slice group: serialized no matter the budget
    why = b.try_start("n2", "g1", 0.0)
    assert why and "slice group" in why
    assert b.try_start("n2", "g2", 0.0) is None
    # concurrency cap
    why = b.try_start("n3", "g3", 0.0)
    assert why and "in flight" in why
    # release frees the group and the slot, but tokens are spent
    b.release("n1")
    b.release("n2")
    why = b.try_start("n3", "g3", 0.0)
    assert why and "rate budget" in why
    # tokens refill over time
    assert b.try_start("n3", "g3", 6.0) is None
    # re-entry of an active repair consumes nothing
    assert b.try_start("n3", "g3", 6.0) is None
    assert b.started_total == 3


@async_test
async def test_circuit_breaker_verdict_memoized_on_labeled_index():
    """Satellite: the breaker must ride the label inverted index (managed
    nodes only) and answer a repair WAVE from one memoized list, not one
    kube list per repair decision."""
    calls = []

    class CountingClient:
        async def list(self, cls, labels=None, **kw):
            calls.append(labels)
            return []

    class CP:
        def repair_policies(self):
            return []

    hc = NodeHealthController(
        CountingClient(), CP(),
        options=HealthOptions(max_unhealthy_fraction=0.5, breaker_ttl=10.0))
    assert not await hc._circuit_broken(0.0)
    assert not await hc._circuit_broken(1.0)
    assert not await hc._circuit_broken(9.9)
    assert len(calls) == 1, "breaker listed once per decision, not per TTL"
    assert calls[0] == {wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME}
    assert not await hc._circuit_broken(10.1)
    assert len(calls) == 2, "memo never expired"


@async_test
async def test_budget_caps_a_correlated_repair_wave():
    """Three independently-sick slices, budget of ONE repair: exactly one
    claim is repaired inside the horizon, the rest are throttled (visible on
    the metric), and nothing leaks."""
    before = _stats()
    async with repair_env(repair_toleration=0.2, repair_rate=1.0,
                          repair_rate_interval=600.0, repair_burst=1,
                          repair_max_concurrent=1) as env:
        names = ["bw0", "bw1", "bw2"]
        for n in names:
            await env.client.create(make_nodeclaim(n))
        for n in names:
            await env.wait_ready(n, timeout=15)
        for n in names:
            node = await env.client.get(Node, f"gke-kaito-{n}-w0")
            set_node_condition(node, "AcceleratorHealthy", "False",
                               reason="HardwareFault")
            await env.client.update_status(node)
        await asyncio.sleep(2.0)  # several tolerations + throttle requeues
        survivors = []
        for n in names:
            try:
                await env.client.get(NodeClaim, n)
                survivors.append(n)
            except NotFoundError:
                pass
        after = _stats()
        assert len(survivors) == 2, \
            f"budget of 1 allowed {3 - len(survivors)} repairs"
        assert after["succeeded"] - before["succeeded"] == 1
        assert after["throttled"] > before["throttled"]


# ------------------------------------------------- repair × crash recovery

@async_test
async def test_mid_repair_crash_then_restart_converges_without_double_delete():
    """Satellite: crash the operator at the new mid_repair cut line (node
    cordoned, budget token consumed in-memory, claim not yet deleted) inside
    a multi-slice group. The restarted incarnation — fresh budget state plus
    the PR 3 startup resync — must finish the repair exactly once, never
    touch the healthy member, and re-stamp the group coordinator on the
    replacement."""
    # a big budget: EVERY repair attempt of the doomed incarnation crashes
    # before its force-delete — otherwise a sibling health worker could
    # finish the repair between the first crash and the restart
    crashes = chaos.CrashPoints(at={"mid_repair": 1000}, seed=SEED)
    opts = EnvtestOptions(gc_interval=0.1, leak_grace=0.1, crashes=crashes,
                          repair_toleration=0.3,
                          repair_max_unhealthy_fraction=0.0,
                          repair_drain_deadline=0.6,
                          repair_drain_requeue=0.05)
    opts.lifecycle.launch_timeout = 20.0
    renv = RestartableEnv(opts)
    await renv.start()
    try:
        for name in ("g0", "g1"):
            await renv.client.create(_claim(name, "tpu-v5e-16", "g"))
        for name in ("g0", "g1"):
            await renv.wait_ready(name, timeout=20)
        g1_uid = (await renv.client.get(NodeClaim, "g1")).metadata.uid
        # a pod makes the drain-first path non-trivial across the crash
        await renv.client.create(Pod(
            metadata=ObjectMeta(name="payload", namespace="default"),
            spec=PodSpec(node_name="gke-kaito-g0-w0")))
        node = await renv.client.get(Node, "gke-kaito-g0-w0")
        set_node_condition(node, "AcceleratorHealthy", "False",
                           reason="HardwareFault")
        await renv.client.update_status(node)

        await asyncio.wait_for(crashes.crashed.wait(), 15)
        assert crashes.fired["mid_repair"] >= 1
        deletes_before_restart = renv.cloud.nodepools.calls["begin_delete"]
        assert deletes_before_restart == 0, "claim deleted before the crash"

        crashes.disarm()      # the next incarnation runs clean
        await renv.restart()
        await renv.wait_gone("g0", timeout=20)  # repair completes exactly once
        # KAITO recreates the repaired claim; identity must re-converge
        await renv.client.create(_claim("g0", "tpu-v5e-16", "g"))
        await renv.wait_ready("g0", timeout=25)

        assert renv.cloud.nodepools.calls["begin_delete"] == 1, \
            "repair double-deleted through the restart"
        g1 = await renv.client.get(NodeClaim, "g1")
        assert g1.metadata.uid == g1_uid, "healthy group member was replaced"

        async def coordinator_restamped():
            nodes = await renv.client.list(
                Node, labels={wk.TPU_SLICE_GROUP_LABEL: "g"})
            coords = {n.metadata.labels.get(wk.TPU_COORDINATOR_LABEL)
                      for n in nodes}
            return (len(nodes) == 4 and coords == {"gke-kaito-g0-w0"})
        deadline = asyncio.get_event_loop().time() + 10
        while not await coordinator_restamped():
            assert asyncio.get_event_loop().time() < deadline, \
                "slice-group coordinator never re-stamped after repair"
            await asyncio.sleep(0.05)
        pools = set(renv.cloud.nodepools.pools)
        assert pools == {"g0", "g1"}, pools
    finally:
        await renv.crash()


# ------------------------------------------- slice-group coordinator hygiene

@async_test
async def test_stale_coordinator_label_cleared_while_slice0_absent():
    """While slice 0 is gone (mid-repair window), the group's nodes must not
    keep advertising the dead coordinator — the label is stripped, then
    re-stamped once a replacement takes index 0."""
    async with Env(EnvtestOptions()) as env:
        for name in ("s0", "s1"):
            await env.client.create(_claim(name, "tpu-v5e-16", "g2"))
        for name in ("s0", "s1"):
            await env.wait_ready(name, timeout=15)
        await env.client.delete(NodeClaim, "s0")
        await env.wait_gone("s0", timeout=15)

        async def coordinator_dropped():
            nodes = await env.client.list(
                Node, labels={wk.TPU_SLICE_GROUP_LABEL: "g2"})
            return nodes and all(
                wk.TPU_COORDINATOR_LABEL not in n.metadata.labels
                for n in nodes)
        deadline = asyncio.get_event_loop().time() + 10
        while not await coordinator_dropped():
            assert asyncio.get_event_loop().time() < deadline, \
                "stale coordinator label survived slice-0 deletion"
            await asyncio.sleep(0.05)


# --------------------------------------------------------- repair hygiene

@async_test
async def test_never_heartbeated_kubelet_caught_by_persistent_anchor():
    """A kubelet that dies before its FIRST status report leaves
    ``lastHeartbeatTime=None`` forever. The (node, "hb") observed-since
    anchor used to be popped with the condition anchors on every healthy
    reconcile, restarting the clock each pass so the bound could never
    elapse — the anchor must survive healthy passes (nothing here ever
    stamps a heartbeat, so repair firing proves it did)."""
    before = _stats()
    async with repair_env(repair_heartbeat_bound=1.5) as env:
        await env.client.create(make_nodeclaim("hb0"))
        await env.wait_ready("hb0", timeout=15)
        node = await env.client.get(Node, "gke-kaito-hb0-w0")
        assert node.ready_condition().last_heartbeat_time is None
        await env.wait_gone("hb0", timeout=10)
        assert _stats()["succeeded"] > before["succeeded"]


@async_test
async def test_replacement_node_with_new_uid_resets_flap_history():
    """A repaired claim's replacement node reuses the SAME name; when the
    delete and add watch events coalesce in the workqueue, no NotFound
    reconcile ever runs ``_forget`` — the uid flip must reset the per-node
    condition history so the healthy replacement isn't insta-diagnosed with
    its predecessor's flaps and wrongly repaired."""
    from collections import deque

    from gpu_provisioner_tpu.fake.builders import make_node
    from gpu_provisioner_tpu.runtime import Request

    class CP:
        def repair_policies(self):
            return []

    node = make_node("r1", ready=True)
    node.metadata.uid = "uid-old"

    class StubClient:
        async def get(self, cls, name, namespace=""):
            return node

    hc = NodeHealthController(
        StubClient(), CP(),
        options=HealthOptions(flap_threshold=3, flap_window=600.0,
                              max_unhealthy_fraction=0.0, max_cache_age=0.0))
    mono = asyncio.get_event_loop().time()
    hc._node_uid["r1"] = "uid-old"
    hc._transitions["r1"] = deque([mono] * 3)
    hc._flapping.add("r1")
    node.metadata.uid = "uid-new"
    await hc.reconcile(Request(name="r1"))
    assert "r1" not in hc._flapping, \
        "replacement node inherited its predecessor's flap verdict"
    assert not hc._transitions.get("r1"), "flap history survived the uid flip"
    assert hc._node_uid["r1"] == "uid-new"


@async_test
async def test_breaker_counts_flapping_and_silent_nodes():
    """Flapping and silently-dead nodes both read Ready=True at list time;
    the breaker numerator must still see them or the mass-delete protection
    never engages for exactly the fault classes this PR introduces."""
    from gpu_provisioner_tpu.fake.builders import make_node

    class CP:
        def repair_policies(self):
            return []

    nodes = [make_node(f"n{i}", ready=True) for i in range(4)]

    class StubClient:
        async def list(self, cls, labels=None, **kw):
            return nodes

    hc = NodeHealthController(
        StubClient(), CP(),
        options=HealthOptions(max_unhealthy_fraction=0.5,
                              breaker_min_unhealthy=2, breaker_ttl=0.0))
    assert not await hc._circuit_broken(0.0)
    hc._flapping.update({"n0", "n1", "n2"})
    assert await hc._circuit_broken(1.0), \
        "a fleet-wide flap storm is invisible to the breaker"


# --------------------------------------------------------- silent death

@async_test
async def test_silent_kubelet_death_repaired_via_stale_heartbeat():
    """The fault no watch event announces: heartbeats stop while Ready stays
    a stale True. The stale-heartbeat policy (with its healthy-node re-poll
    cadence) is the only path that can see it."""
    before = _stats()
    inj = chaos.node_fault_profile("silent_death", seed=SEED, duration=20.0)
    async with repair_env(inj, repair_heartbeat_bound=0.5) as env:
        await env.client.create(make_nodeclaim("sd0"))
        await env.wait_ready("sd0", timeout=15)
        # Ready still True on the victim; nothing flips the condition
        node = await env.client.get(Node, "gke-kaito-sd0-w0")
        assert node.is_ready()
        await env.wait_gone("sd0", timeout=15)
        assert inj.injected_total("silent:") >= 1
        assert _stats()["succeeded"] > before["succeeded"]
