"""Read-through instance cache (providers/cache.py): TTL, singleflight,
negative caching, max-age guard, invalidation-vs-inflight races — and the
provider-level correctness the ISSUE pins: a cached entry must never hide a
deletion from get()/list(), and the cache/cloud-call counters must surface
at /metrics."""

import asyncio

import pytest
from prometheus_client import REGISTRY, generate_latest

from gpu_provisioner_tpu.cloudprovider.errors import NodeClaimNotFoundError
from gpu_provisioner_tpu.controllers.metrics import update_runtime_gauges
from gpu_provisioner_tpu.fake import FakeCloud, make_nodeclaim
from gpu_provisioner_tpu.providers.cache import (
    CACHE_STATS, CLOUD_CALLS, CountingAPI, ReadThroughCache,
)
from gpu_provisioner_tpu.providers.gcp import APIError
from gpu_provisioner_tpu.providers.instance import (
    InstanceProvider, ProviderConfig,
)
from gpu_provisioner_tpu.runtime import InMemoryClient

from .conftest import async_test


class Backend:
    """Scriptable fetch target: per-key values, errors, latency, call log."""

    def __init__(self):
        self.values: dict[str, object] = {}
        self.latency = 0.0
        self.calls: list[str] = []

    async def fetch(self, key: str):
        # snapshot at request time (a real GET answers from the state the
        # server held when it received the request), then simulate the RTT
        self.calls.append(key)
        missing = key not in self.values
        value = self.values.get(key)
        if self.latency:
            await asyncio.sleep(self.latency)
        if missing:
            raise APIError(f"{key} not found", code=404)
        if isinstance(value, Exception):
            raise value
        return value


# --- unit: ReadThroughCache -------------------------------------------------

@async_test
async def test_cache_hit_miss_and_ttl_expiry():
    b = Backend()
    b.values["a"] = 1
    c = ReadThroughCache("t.hitmiss", b.fetch, ttl=0.05, negative_ttl=0.05)
    assert await c.get("a") == 1 and c.stats["misses"] == 1
    assert await c.get("a") == 1 and c.stats["hits"] == 1
    assert b.calls == ["a"]
    await asyncio.sleep(0.06)              # past the TTL
    b.values["a"] = 2
    assert await c.get("a") == 2 and c.stats["misses"] == 2


@async_test
async def test_singleflight_coalesces_concurrent_readers():
    b = Backend()
    b.values["k"] = "v"
    b.latency = 0.03
    c = ReadThroughCache("t.sf", b.fetch, ttl=1.0)
    got = await asyncio.gather(*(c.get("k") for _ in range(8)))
    assert got == ["v"] * 8
    assert len(b.calls) == 1, "8 concurrent readers must share one fetch"
    assert c.stats["misses"] == 1 and c.stats["coalesced"] == 7


@async_test
async def test_singleflight_with_ttl_zero_still_coalesces():
    """ttl=0 (the queued-resource mode) keeps coalescing but stores nothing:
    sequential reads each refetch."""
    b = Backend()
    b.values["k"] = "v"
    b.latency = 0.02
    c = ReadThroughCache("t.sf0", b.fetch, ttl=0.0)
    await asyncio.gather(*(c.get("k") for _ in range(4)))
    assert len(b.calls) == 1
    await c.get("k")
    assert len(b.calls) == 2, "ttl=0 must not serve a stored entry"


@async_test
async def test_negative_caching_and_error_passthrough():
    b = Backend()
    c = ReadThroughCache("t.neg", b.fetch, ttl=1.0, negative_ttl=0.5)
    with pytest.raises(APIError):
        await c.get("ghost")
    with pytest.raises(APIError):
        await c.get("ghost")               # served from the negative entry
    assert len(b.calls) == 1 and c.stats["negative_hits"] == 1
    # non-NotFound errors are never cached
    b.values["flaky"] = APIError("boom", code=503)
    with pytest.raises(APIError):
        await c.get("flaky")
    b.values["flaky"] = "ok"
    assert await c.get("flaky") == "ok", "5xx must not stick in the cache"


@async_test
async def test_max_age_guard_bounds_misconfigured_ttl():
    b = Backend()
    b.values["a"] = 1
    c = ReadThroughCache("t.maxage", b.fetch, ttl=3600.0, max_age=0.05)
    await c.get("a")
    await asyncio.sleep(0.06)
    await c.get("a")
    assert len(b.calls) == 2, "max_age must override a huge ttl"


@async_test
async def test_invalidate_detaches_inflight_fetch():
    """A read racing a delete must not re-populate the cache with
    pre-delete state: invalidate() detaches the in-flight fetch, so its
    result is returned to its waiters but never stored."""
    b = Backend()
    b.values["p"] = "pre-delete"
    b.latency = 0.05
    c = ReadThroughCache("t.race", b.fetch, ttl=60.0)
    reader = asyncio.ensure_future(c.get("p"))
    await asyncio.sleep(0.01)              # fetch in flight
    c.invalidate("p")                      # the delete lands
    del b.values["p"]
    assert await reader == "pre-delete"    # racer gets its answer …
    with pytest.raises(APIError):          # … but nothing was cached
        await c.get("p")
    assert len(b.calls) == 2


@async_test
async def test_waiter_cancellation_does_not_kill_shared_fetch():
    b = Backend()
    b.values["k"] = "v"
    b.latency = 0.05
    c = ReadThroughCache("t.cancel", b.fetch, ttl=1.0)
    first = asyncio.ensure_future(c.get("k"))
    await asyncio.sleep(0.01)
    second = asyncio.ensure_future(c.get("k"))
    await asyncio.sleep(0.01)
    first.cancel()
    assert await second == "v", "surviving waiter must still get the fetch"
    assert len(b.calls) == 1


# --- unit: CountingAPI ------------------------------------------------------

@async_test
async def test_counting_api_counts_and_passes_through():
    kube = InMemoryClient()
    cloud = FakeCloud(kube, create_latency=0.0)
    before = CLOUD_CALLS.get("nodepools.list", 0)
    api = CountingAPI(cloud.nodepools, "nodepools")
    assert await api.list() == []
    assert api.calls["list"] == 1 and api.total() == 1
    assert CLOUD_CALLS["nodepools.list"] == before + 1
    assert api.pools == {}                       # non-coroutine passthrough
    api.fail("get", APIError("x", code=404))     # fake helper passthrough
    with pytest.raises(APIError):
        await api.get("nope")


# --- provider integration ---------------------------------------------------

def provider_setup(**cfg):
    kube = InMemoryClient()
    cloud = FakeCloud(kube, create_latency=0.01, delete_latency=0.01)
    provider = InstanceProvider(
        cloud.nodepools, kube,
        ProviderConfig(node_wait_attempts=20, node_wait_interval=0.01, **cfg),
        queued=cloud.queuedresources)
    return kube, cloud, provider


@async_test
async def test_provider_get_serves_from_cache_within_ttl():
    kube, cloud, provider = provider_setup(cache_ttl=60.0)
    inst = await provider.create(make_nodeclaim("c0", "tpu-v5e-8"))
    gets = cloud.nodepools.calls["get"]
    for _ in range(5):
        got = await provider.get(inst.id)
        assert got.name == "c0"
    assert cloud.nodepools.calls["get"] == gets, \
        "gets within the TTL must not hit the cloud"
    assert provider._pool_cache.stats["hits"] >= 5


@async_test
async def test_provider_concurrent_gets_coalesce():
    kube, cloud, provider = provider_setup(cache_ttl=0.0)  # coalesce-only
    inst = await provider.create(make_nodeclaim("c1", "tpu-v5e-8"))
    gets = cloud.nodepools.calls["get"]
    await asyncio.gather(*(provider.get(inst.id) for _ in range(8)))
    assert cloud.nodepools.calls["get"] - gets <= 2, \
        "a concurrent reconcile burst must share in-flight cloud GETs"


@async_test
async def test_delete_then_get_and_list_within_ttl_observe_deletion():
    """The acceptance-criteria invariant: a cached entry must never serve a
    deleted pool — get() is invalidated by delete(), and list() (the GC
    feed) never reads through the point cache at all."""
    kube, cloud, provider = provider_setup(cache_ttl=3600.0)
    inst = await provider.create(make_nodeclaim("d0", "tpu-v5e-8"))
    assert (await provider.get(inst.id)).name == "d0"   # hot in cache
    await provider.delete("d0")
    with pytest.raises(NodeClaimNotFoundError):
        await provider.get(inst.id)
    assert [i.name for i in await provider.list()] == []


@async_test
async def test_negative_cache_bounds_ghost_probes():
    kube, cloud, provider = provider_setup(cache_ttl=60.0,
                                           cache_negative_ttl=60.0)
    pid = "gce://test-project/us-central2-b/gke-kaito-ghost-w0"
    gets = cloud.nodepools.calls["get"]
    for _ in range(4):
        with pytest.raises(NodeClaimNotFoundError):
            await provider.get(pid)
    assert cloud.nodepools.calls["get"] == gets + 1, \
        "repeated ghost probes must be served by the negative entry"
    assert provider._pool_cache.stats["negative_hits"] >= 3


@async_test
async def test_queued_cleanup_still_runs_with_cached_qr_view():
    """delete() must perform queued-resource cleanup first even when the QR
    cache holds a (possibly negative) entry for the claim."""
    from gpu_provisioner_tpu.providers.instance import (
        PROVISIONING_MODE_ANNOTATION,
    )
    kube, cloud, provider = provider_setup(cache_negative_ttl=60.0)
    cloud.qr_step_latency = 999  # wedge the ladder: claim never completes
    nc = make_nodeclaim("q0", annotations={
        PROVISIONING_MODE_ANNOTATION: "queued"})
    with pytest.raises(Exception):
        await provider.create(nc)            # QR created, pool never exists
    assert "q0" in cloud.queuedresources.resources
    with pytest.raises(NodeClaimNotFoundError):
        await provider.delete("q0")          # no pool → NotFound, but…
    assert "q0" not in cloud.queuedresources.resources, \
        "queued cleanup must have run before the pool lookup"
    # and a retried delete (cache now negative for q0) must not resurrect it
    with pytest.raises(NodeClaimNotFoundError):
        await provider.delete("q0")
    assert "q0" not in cloud.queuedresources.resources


# --- bulk list fast path ----------------------------------------------------

@async_test
async def test_list_issues_one_bulk_node_list():
    kube, cloud, provider = provider_setup()
    for i in range(4):
        await provider.create(make_nodeclaim(f"bl{i}", "tpu-v5e-8"))
    counts = {"node_lists": 0}
    inner_list = kube.list

    async def counted(cls, labels=None, namespace=None, index=None):
        from gpu_provisioner_tpu.apis.core import Node
        if cls is Node:
            counts["node_lists"] += 1
        return await inner_list(cls, labels=labels, namespace=namespace,
                                index=index)

    kube.list = counted
    provider.kube = kube
    instances = await provider.list()
    assert sorted(i.name for i in instances) == [f"bl{i}" for i in range(4)]
    assert all(i.node_provider_ids for i in instances)
    assert counts["node_lists"] == 1, \
        f"fast path must do ONE bulk Node list, did {counts['node_lists']}"


@async_test
async def test_list_fast_path_matches_legacy_output():
    kube, cloud, provider = provider_setup()
    await provider.create(make_nodeclaim("eq0", "tpu-v5e-8"))
    await provider.create(make_nodeclaim("eq1", "tpu-v5p-32"))
    fast = {i.name: i for i in await provider.list()}
    provider.cfg.legacy_list = True
    legacy = {i.name: i for i in await provider.list()}
    assert fast.keys() == legacy.keys()
    for name in fast:
        assert fast[name] == legacy[name], f"divergence on {name}"


# --- metrics export ---------------------------------------------------------

@async_test
async def test_cache_and_cloud_call_metrics_exported():
    kube, cloud, provider = provider_setup(cache_ttl=60.0)
    inst = await provider.create(make_nodeclaim("m0", "tpu-v5e-8"))
    await provider.get(inst.id)            # a hit
    await provider.list()                  # a cloud list call
    assert CACHE_STATS["nodepools.get"]["hits"] >= 1
    assert CLOUD_CALLS["nodepools.list"] >= 1
    update_runtime_gauges(object())        # no manager: registry gauges only
    text = generate_latest(REGISTRY).decode()
    assert 'tpu_provisioner_instance_cache_hits{cache="nodepools.get"}' in text
    assert 'tpu_provisioner_instance_cache_misses{cache="nodepools.get"}' in text
    assert 'tpu_provisioner_instance_cache_coalesced{cache="nodepools.get"}' in text
    assert 'tpu_provisioner_cloud_api_calls{endpoint="nodepools.list"}' in text
    assert 'tpu_provisioner_cloud_api_calls{endpoint="nodepools.begin_create"}' in text
