"""Instance provider vs fake cloud — mirrors the scenarios of the reference's
pkg/providers/instance/instance_test.go (create success incl. node-wait retry,
create failure, get/list/delete, pool-object construction) plus the TPU
extensions: multi-host waits and the queued-resource state machine."""

import pytest

from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.core import Node
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.cloudprovider.errors import (
    CreateError, InsufficientCapacityError, NodeClaimNotFoundError,
)
from gpu_provisioner_tpu.fake import FakeCloud, make_nodeclaim
from gpu_provisioner_tpu.providers.gcp import APIError, NP_ERROR, NP_STOPPING
from gpu_provisioner_tpu.providers.instance import (
    PROVISIONING_MODE_ANNOTATION, InstanceProvider, ProviderConfig,
    STATE_SUCCEEDED, nodepool_name_valid, parse_nodepool_from_provider_id,
)
from gpu_provisioner_tpu.runtime import InMemoryClient

from .conftest import async_test


def setup():
    kube = InMemoryClient()
    cloud = FakeCloud(kube, create_latency=0.01, delete_latency=0.01)
    provider = InstanceProvider(
        cloud.nodepools, kube,
        ProviderConfig(node_wait_attempts=20, node_wait_interval=0.01),
        queued=cloud.queuedresources)
    return kube, cloud, provider


# --- create ---------------------------------------------------------------

@async_test
async def test_create_single_host_success():
    kube, cloud, provider = setup()
    inst = await provider.create(make_nodeclaim("ws0", "tpu-v5e-8", storage="100Gi"))
    assert inst.state == STATE_SUCCEEDED
    assert inst.hosts == 1 and inst.chips == 8 and inst.topology == "2x4"
    assert inst.id.startswith("gce://test-project/")
    pool = cloud.nodepools.pools["ws0"]
    assert pool.config.disk_size_gb == 100
    assert pool.config.labels[wk.NODEPOOL_LABEL] == wk.KAITO_NODEPOOL_NAME
    assert pool.config.labels[wk.KAITO_MACHINE_TYPE_LABEL] == "tpu"
    assert wk.KAITO_CREATION_TIMESTAMP_LABEL in pool.config.labels
    assert pool.placement_policy.tpu_topology == "2x4"
    nodes = await kube.list(Node)
    assert len(nodes) == 1 and nodes[0].status.capacity[wk.TPU_RESOURCE_NAME] == "8"


@async_test
async def test_create_multi_host_waits_for_all_hosts():
    kube, cloud, provider = setup()
    cloud.node_join_delay = 0.01  # hosts join staggered, after pool RUNNING
    inst = await provider.create(make_nodeclaim("big", "tpu-v5p-32"))
    assert inst.hosts == 4 and len(inst.node_provider_ids) == 4
    # worker indices consistent and ordered (SURVEY §7 hard part 1)
    nodes = sorted(await kube.list(Node),
                   key=lambda n: n.metadata.labels[wk.TPU_WORKER_INDEX_LABEL])
    assert [n.metadata.labels[wk.TPU_WORKER_INDEX_LABEL] for n in nodes] == list("0123")
    assert inst.id == nodes[0].spec.provider_id


@async_test
async def test_create_invalid_name_rejected():
    _, _, provider = setup()
    with pytest.raises(CreateError) as e:
        await provider.create(make_nodeclaim("Invalid_Name!"))
    assert e.value.reason == "InvalidName"


@async_test
async def test_create_stockout_maps_to_insufficient_capacity():
    _, cloud, provider = setup()
    cloud.nodepools.fail("begin_create", APIError("out of stock", code=429))
    with pytest.raises(InsufficientCapacityError):
        await provider.create(make_nodeclaim())


@async_test
async def test_create_tolerates_inflight_operation():
    # Crash-restart: create already in progress → fall through to node wait
    # (reference instance.go:106-110).
    kube, cloud, provider = setup()
    # pre-seed the pool as the previous incarnation's create ...
    from gpu_provisioner_tpu.catalog import lookup
    op = await cloud.nodepools.begin_create(
        provider._new_nodepool_object(make_nodeclaim(), lookup("tpu-v5e-8"),
                                      wk.CAPACITY_TYPE_ON_DEMAND))
    await op.result()
    # ... then the restarted controller's create hits "in progress"
    cloud.nodepools.fail("begin_create", APIError("in progress", code=409))
    inst = await provider.create(make_nodeclaim())
    assert inst.state == STATE_SUCCEEDED


@async_test
async def test_conflict_fall_through_surfaces_degraded_pool():
    """Satellite fix for the blind wait: a conflicting create whose pool
    sits in ERROR is a terminal CreateError NOW — not a full node-wait
    against a pool that will never produce nodes."""
    kube, cloud, provider = setup()
    from gpu_provisioner_tpu.catalog import lookup
    op = await cloud.nodepools.begin_create(
        provider._new_nodepool_object(make_nodeclaim(), lookup("tpu-v5e-8"),
                                      wk.CAPACITY_TYPE_ON_DEMAND))
    await op.result()
    # the adopted create's pool lands in ERROR (op-error carcass shape)
    cloud.nodepools.pools["ws0"].status = NP_ERROR
    cloud.nodepools.pools["ws0"].status_message = "instance exhausted"
    cloud.nodepools.fail("begin_create", APIError("in progress", code=409))
    calls_before = cloud.nodepools.calls.get("get", 0)
    with pytest.raises(CreateError) as e:
        await provider.create(make_nodeclaim())
    assert e.value.reason == "DegradedPool"
    assert "instance exhausted" in str(e.value)
    # one state poll, not a node-wait's worth of them
    assert cloud.nodepools.calls.get("get", 0) - calls_before <= 2


@async_test
async def test_conflict_fall_through_requeues_on_stuck_provisioning():
    """Adopting an in-flight create that never settles gives the workqueue
    a retryable CreateError after the wait budget — never a silent wedge."""
    kube, cloud, provider = setup()
    cloud.create_latency = 999  # the other incarnation's LRO never finishes
    from gpu_provisioner_tpu.catalog import lookup
    await cloud.nodepools.begin_create(
        provider._new_nodepool_object(make_nodeclaim(), lookup("tpu-v5e-8"),
                                      wk.CAPACITY_TYPE_ON_DEMAND))
    with pytest.raises(CreateError) as e:
        await provider.create(make_nodeclaim())  # real 409 from the fake
    assert e.value.reason == "CreateInProgress"


@async_test
async def test_fake_begin_create_conflicts_on_live_pool_replaces_error():
    """GKE 409s a live pool; only an ERROR carcass is re-creatable in place
    (the op-error replace-never-duplicate contract)."""
    kube, cloud, provider = setup()
    from gpu_provisioner_tpu.catalog import lookup
    pool_obj = provider._new_nodepool_object(
        make_nodeclaim(), lookup("tpu-v5e-8"), wk.CAPACITY_TYPE_ON_DEMAND)
    op = await cloud.nodepools.begin_create(pool_obj)
    await op.result()  # RUNNING
    with pytest.raises(APIError) as e:
        await cloud.nodepools.begin_create(pool_obj)
    assert e.value.conflict
    cloud.nodepools.pools["ws0"].status = NP_ERROR
    op2 = await cloud.nodepools.begin_create(pool_obj)  # replace carcass
    await op2.result()
    assert cloud.nodepools.pools["ws0"].status == "RUNNING"


@async_test
async def test_create_node_never_appears_times_out():
    kube, cloud, provider = setup()
    cloud.node_join_delay = 99  # way past the wait budget
    provider.cfg.node_wait_attempts = 3
    with pytest.raises(CreateError) as e:
        await provider.create(make_nodeclaim())
    assert e.value.reason == "NodesNotReady"


# --- queued resources -----------------------------------------------------

@async_test
async def test_queued_mode_requeues_until_active():
    kube, cloud, provider = setup()
    cloud.qr_step_latency = 0.03
    nc = make_nodeclaim("qr0", "tpu-v5p-32",
                        annotations={PROVISIONING_MODE_ANNOTATION: "queued"})
    with pytest.raises(CreateError) as e:
        await provider.create(nc)
    assert e.value.reason == "QueuedProvisioning"
    # wait out the ladder, then create proceeds
    import asyncio
    await asyncio.sleep(0.12)
    inst = await provider.create(nc)
    assert inst.state == STATE_SUCCEEDED and inst.hosts == 4


@async_test
async def test_queued_suspended_is_insufficient_capacity():
    kube, cloud, provider = setup()
    cloud.qr_step_latency = 999
    nc = make_nodeclaim("qr1", annotations={PROVISIONING_MODE_ANNOTATION: "queued"})
    with pytest.raises(CreateError):
        await provider.create(nc)
    cloud.queuedresources.suspend("qr1")
    with pytest.raises(InsufficientCapacityError):
        await provider.create(nc)


# --- get/list/delete ------------------------------------------------------

@async_test
async def test_get_by_provider_id_and_not_found():
    kube, cloud, provider = setup()
    inst = await provider.create(make_nodeclaim())
    got = await provider.get(inst.id)
    assert got.name == "ws0" and got.state == STATE_SUCCEEDED
    with pytest.raises(NodeClaimNotFoundError):
        await provider.get("gce://test-project/us-central2-b/gke-kaito-ghost-w0")


@async_test
async def test_list_filters_non_kaito_pools():
    kube, cloud, provider = setup()
    await provider.create(make_nodeclaim("mine"))
    # a pool not owned by kaito (no nodepool label) must be ignored
    from gpu_provisioner_tpu.providers.gcp import NodePool, NodePoolConfig
    op = await cloud.nodepools.begin_create(NodePool(
        name="other", config=NodePoolConfig(machine_type="n2-standard-4")))
    await op.result()
    instances = await provider.list()
    assert [i.name for i in instances] == ["mine"]


@async_test
async def test_delete_and_not_found_mapping():
    kube, cloud, provider = setup()
    await provider.create(make_nodeclaim())
    await provider.delete("ws0")
    assert "ws0" not in cloud.nodepools.pools
    assert await kube.list(Node) == []  # node objects gone with the pool
    with pytest.raises(NodeClaimNotFoundError):
        await provider.delete("ws0")


@async_test
async def test_delete_skips_already_deleting():
    kube, cloud, provider = setup()
    await provider.create(make_nodeclaim())
    cloud.nodepools.pools["ws0"].status = NP_STOPPING
    await provider.delete("ws0")  # returns without calling begin_delete
    assert cloud.nodepools.calls["begin_delete"] == 0


# --- name/id utils --------------------------------------------------------

def test_nodepool_name_validation():
    assert nodepool_name_valid("ws0")
    assert nodepool_name_valid("a")
    assert nodepool_name_valid("a-b-3")
    assert not nodepool_name_valid("Aa")
    assert not nodepool_name_valid("-a")
    assert not nodepool_name_valid("a-")
    assert not nodepool_name_valid("a" * 41)


def test_parse_nodepool_from_provider_id():
    pid = "gce://proj/us-central2-b/gke-kaito-myws-w3"
    assert parse_nodepool_from_provider_id(pid, "kaito") == "myws"
    assert parse_nodepool_from_provider_id(pid, "other") is None
    assert parse_nodepool_from_provider_id("azure:///x", "kaito") is None


# --- multi-slice identity (slice-index / num-slices / coordinator) ---------

def _identity(cloud, pool):
    labels = cloud.nodepools.pools[pool].config.labels
    return (labels.get(wk.TPU_SLICE_INDEX_LABEL),
            labels.get(wk.TPU_NUM_SLICES_LABEL),
            labels.get(wk.TPU_COORDINATOR_LABEL))


@async_test
async def test_multislice_identity_deterministic_any_create_order():
    """All group members exist before reconcile (KAITO creates the group
    together); indices follow (creationTimestamp, name) order regardless of
    which reconciler runs first, and everyone agrees on the coordinator."""
    kube, cloud, provider = setup()
    claims = [make_nodeclaim(f"sl{i}", "tpu-v5e-16",
                             labels={wk.TPU_SLICE_GROUP_LABEL: "g1"})
              for i in range(3)]
    for c in claims:
        await kube.create(c)
    await provider.create(claims[2])   # out-of-order reconcile
    await provider.create(claims[0])
    await provider.create(claims[1])
    assert _identity(cloud, "sl0") == ("0", "3", "gke-kaito-sl0-w0")
    assert _identity(cloud, "sl1") == ("1", "3", "gke-kaito-sl0-w0")
    assert _identity(cloud, "sl2") == ("2", "3", "gke-kaito-sl0-w0")


@async_test
async def test_multislice_identity_sticky_and_fills_gaps():
    """An index stamped on an existing pool is authoritative; new members
    take the lowest free index."""
    kube, cloud, provider = setup()
    a = make_nodeclaim("aa", "tpu-v5e-16",
                       labels={wk.TPU_SLICE_GROUP_LABEL: "g2"})
    b = make_nodeclaim("bb", "tpu-v5e-16",
                       labels={wk.TPU_SLICE_GROUP_LABEL: "g2"})
    await kube.create(a)
    await provider.create(a)                     # aa -> 0
    assert _identity(cloud, "aa")[0] == "0"
    await kube.create(b)
    await provider.create(b)                     # bb -> 1 (0 taken)
    assert _identity(cloud, "bb")[0] == "1"
    # re-reconcile of aa keeps its index (sticky), even though bb now exists
    identity = await provider._slice_group_identity(a)
    assert identity[wk.TPU_SLICE_INDEX_LABEL] == "0"
    assert identity[wk.TPU_COORDINATOR_LABEL] == "gke-kaito-aa-w0"


@async_test
async def test_multislice_identity_declared_group_size_wins():
    kube, cloud, provider = setup()
    nc = make_nodeclaim("solo", "tpu-v5e-16",
                        labels={wk.TPU_SLICE_GROUP_LABEL: "g3",
                                wk.TPU_NUM_SLICES_LABEL: "4"})
    await kube.create(nc)
    await provider.create(nc)
    assert _identity(cloud, "solo") == ("0", "4", "gke-kaito-solo-w0")


@async_test
async def test_multislice_identity_concurrent_create_storm():
    """N grouped claims racing through create() concurrently: indices come
    out distinct, gap-free, and sticky on re-derivation — and the provider
    does ~one pool LIST per burst (the TTL'd snapshot), not one per member
    (VERDICT r3: the O(n²) listing would not survive the reference's
    1000-concurrency lifecycle regime)."""
    import asyncio

    kube, cloud, provider = setup()
    n = 16
    calls = {"lists": 0}
    inner_list = cloud.nodepools.list

    async def counted_list():
        calls["lists"] += 1
        return await inner_list()

    cloud.nodepools.list = counted_list
    claims = [make_nodeclaim(f"storm-{i:02d}", "tpu-v5e-16",
                             labels={wk.TPU_SLICE_GROUP_LABEL: "gs"})
              for i in range(n)]
    for c in claims:
        await kube.create(c)
    await asyncio.gather(*(provider.create(c) for c in claims))

    idx = {name: int(p.config.labels[wk.TPU_SLICE_INDEX_LABEL])
           for name, p in cloud.nodepools.pools.items()}
    assert sorted(idx.values()) == list(range(n))       # distinct + gap-free
    nums = {p.config.labels[wk.TPU_NUM_SLICES_LABEL]
            for p in cloud.nodepools.pools.values()}
    assert nums == {str(n)}
    for c in claims:                                    # sticky
        ident = await provider._slice_group_identity(c)
        assert int(ident[wk.TPU_SLICE_INDEX_LABEL]) == idx[c.metadata.name]
    assert calls["lists"] <= 3, calls


@async_test
async def test_multislice_identity_survives_member_deletion_mid_burst():
    """A member deleted inside the snapshot TTL must not make a later
    member re-derive a colliding index from the shrunken claim order — the
    per-group claim-name FINGERPRINT forces a snapshot refresh whenever the
    live claim set differs from the one recorded at list time (plus a
    belt-and-braces drop on the provider's own pool deletes), so the
    survivor sees the stamped pools fresh (code-review r4 finding)."""
    kube, cloud, provider = setup()
    claims = [make_nodeclaim(f"del{i}", "tpu-v5e-16",
                             labels={wk.TPU_SLICE_GROUP_LABEL: "gd"})
              for i in range(3)]
    for c in claims:
        await kube.create(c)
    await provider.create(claims[0])              # del0 → 0
    await provider.create(claims[1])              # del1 → 1
    await kube.delete(NodeClaim, "del0")          # member leaves the group
    await provider.delete("del0")                 # (claim AND pool)
    await provider.create(claims[2])              # must not collide with 1
    idx = {n: p.config.labels[wk.TPU_SLICE_INDEX_LABEL]
           for n, p in cloud.nodepools.pools.items()}
    assert idx["del1"] == "1"                     # sticky
    assert idx["del2"] != idx["del1"]             # no collision
    assert idx["del2"] == "0"                     # lowest free index reused


@async_test
async def test_no_slice_group_no_identity_labels():
    kube, cloud, provider = setup()
    await provider.create(make_nodeclaim("plain", "tpu-v5e-8"))
    assert _identity(cloud, "plain") == (None, None, None)


# --- providerID index path (fast _pool_name_for) ----------------------------

class _ListSpy:
    """Records every list() call's (labels, index) so tests can assert the
    full-scan fallback was never taken."""

    def __init__(self, inner):
        self.inner = inner
        self.store = getattr(inner, "store", None)
        self.node_list_args = []

    def __getattr__(self, name):
        return getattr(self.inner, name)

    async def list(self, cls, labels=None, namespace=None, index=None):
        if cls is Node:
            self.node_list_args.append((labels, index))
        return await self.inner.list(cls, labels=labels, namespace=namespace,
                                     index=index)


@async_test
async def test_pool_name_for_takes_index_path_not_full_scan():
    """With the spec.providerID index registered (envtest/operator wiring),
    _pool_name_for must resolve through the index — never the O(nodes)
    unfiltered Node scan."""
    kube, cloud, provider = setup()
    kube.store.add_index(Node, "spec.providerID",
                         lambda o: [o.spec.provider_id])
    inst = await provider.create(make_nodeclaim("ix0", "tpu-v5e-8"))
    spy = _ListSpy(kube)
    provider.kube = spy
    got = await provider.get(inst.id)
    assert got.name == "ix0"
    full_scans = [a for a in spy.node_list_args if a == (None, None)]
    assert not full_scans, f"index exists but full scan taken: {spy.node_list_args}"
    assert any(index is not None for _, index in spy.node_list_args)


@async_test
async def test_envtest_informer_wiring_registers_provider_id_index():
    """Satellite check: the cached client the envtest (and real operator)
    hands the provider must carry the providerID index, and has_index must
    see it through the wrapper layers (chaos included)."""
    from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
    from gpu_provisioner_tpu.providers.instance import has_index
    from gpu_provisioner_tpu import chaos as chaos_mod

    env = Env(EnvtestOptions(use_informer=True))
    assert has_index(env.provider.kube), \
        "cached client must expose the spec.providerID index"
    env2 = Env(EnvtestOptions(use_informer=True,
                              chaos=chaos_mod.ChaosPolicy(seed=1)))
    assert has_index(env2.provider.kube), \
        "index must be visible through informer+chaos layering"
    env3 = Env(EnvtestOptions(chaos=chaos_mod.ChaosPolicy(seed=1)))
    assert has_index(env3.provider.kube), \
        "index must be visible through a bare chaos wrapper"
