"""Lease leader-election tests (reference: operator.go:157-164 via client-go),
plus the crash-restart PR's takeover-race and fencing coverage: expired-lease
steal under conflict contention, voluntary-release handoff latency,
clock-skew tolerance, the renew-deadline anchoring that makes local fencing
sound, and FencingToken invalidation."""

import asyncio
from datetime import timedelta

import pytest

from gpu_provisioner_tpu.apis.core import Lease, LeaseSpec
from gpu_provisioner_tpu.apis.meta import ObjectMeta
from gpu_provisioner_tpu.apis.serde import now
from gpu_provisioner_tpu.runtime import ConflictError, InMemoryClient
from gpu_provisioner_tpu.runtime.leaderelection import (
    FencedError, LeaderElector,
)

from .conftest import async_test

# second-resolution Lease timestamps (metav1.Time) bound how fast these run
FAST = dict(lease_duration=2.0, renew_interval=0.4, retry_interval=0.1)


class _Gate:
    """Per-elector client over a shared store whose Lease traffic can be
    blackholed — simulates THIS replica losing the apiserver while rivals
    (their own clients) keep working."""

    def __init__(self, store):
        self.inner = InMemoryClient(store)
        self.gated = False

    def _check(self, cls):
        if self.gated and cls is Lease:
            raise ConflictError("gated: lease traffic blackholed")

    async def get(self, cls, name, namespace=""):
        self._check(cls)
        return await self.inner.get(cls, name, namespace)

    async def create(self, obj):
        self._check(type(obj))
        return await self.inner.create(obj)

    async def update(self, obj):
        self._check(type(obj))
        return await self.inner.update(obj)


@async_test
async def test_single_elector_acquires_and_renews():
    client = InMemoryClient()
    el = LeaderElector(client, identity="a", **FAST)
    await el.run_until_leading()
    assert el.leading.is_set()
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.holder_identity == "a"
    first_renew = lease.spec.renew_time
    await asyncio.sleep(1.5)
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.renew_time > first_renew  # renew loop is live
    await el.stop()
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.holder_identity == ""  # voluntary release


@async_test
async def test_second_elector_waits_then_takes_over():
    client = InMemoryClient()
    a = LeaderElector(client, identity="a", **FAST)
    b = LeaderElector(client, identity="b", **FAST)
    await a.run_until_leading()

    b_task = asyncio.create_task(b.run_until_leading())
    await asyncio.sleep(0.5)
    assert not b.leading.is_set()  # blocked while a holds the lease

    await a.stop()                 # release → b should win promptly
    await asyncio.wait_for(b_task, 5)
    assert b.leading.is_set()
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.holder_identity == "b"
    assert lease.spec.lease_transitions >= 0
    await b.stop()


@async_test
async def test_expired_lease_is_stolen():
    client = InMemoryClient()
    a = LeaderElector(client, identity="a", **FAST)
    await a.run_until_leading()
    # a dies without releasing (crash): cancel renewals only
    a._task.cancel()
    b = LeaderElector(client, identity="b", **FAST)
    t0 = asyncio.get_event_loop().time()
    await asyncio.wait_for(b.run_until_leading(), 10)
    waited = asyncio.get_event_loop().time() - t0
    assert waited >= 1.0  # had to wait out most of the 2s lease
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.holder_identity == "b"
    assert lease.spec.lease_transitions == 1
    await b.stop()


@async_test
async def test_renew_deadline_anchored_at_last_renew():
    """Satellite fix: the give-up deadline runs from the LAST SUCCESSFUL
    renew, not the start of the retry loop — the old code granted itself a
    fresh lease_duration measured from renew_interval AFTER the last renew,
    so a rival could legally steal the lease while this replica still
    believed it led (the dual-writer window). Assert no overlap: A declares
    loss no later than B acquires."""
    client = InMemoryClient()
    gate = _Gate(client.store)
    loop = asyncio.get_event_loop()
    a_lost = {}
    a = LeaderElector(gate, identity="a",
                      on_lost=lambda: a_lost.setdefault("t", loop.time()),
                      **FAST)
    await a.run_until_leading()
    gate.gated = True  # apiserver gone for A; last renew ≈ acquisition
    b = LeaderElector(client, identity="b", **FAST)
    b_task = asyncio.create_task(b.run_until_leading())
    await asyncio.wait_for(b_task, 15)
    b_acquired = loop.time()
    await asyncio.sleep(0.3)  # let A's loop reach its verdict if it hasn't
    assert "t" in a_lost, "A never declared loss"
    assert not a.leading.is_set()
    # single-writer: A stopped leading before (or within jitter of) B's win
    assert a_lost["t"] <= b_acquired + 0.15, \
        f"dual-leader window: A lost at {a_lost['t']}, B won at {b_acquired}"
    await b.stop()


@async_test
async def test_expired_steal_race_single_winner_under_conflict():
    """Two candidates race an expired foreign lease: optimistic-concurrency
    conflicts must leave EXACTLY one holder and push the loser back into
    candidacy (not an error, not a second leader)."""
    client = InMemoryClient()
    await client.create(Lease(
        metadata=ObjectMeta(name="tpu-provisioner", namespace="default"),
        spec=LeaseSpec(holder_identity="dead", lease_duration_seconds=2,
                       renew_time=now() - timedelta(seconds=60))))
    a = LeaderElector(client, identity="a", **FAST)
    b = LeaderElector(client, identity="b", **FAST)
    ta = asyncio.create_task(a.run_until_leading())
    tb = asyncio.create_task(b.run_until_leading())
    done, pending = await asyncio.wait((ta, tb), timeout=10,
                                       return_when=asyncio.FIRST_COMPLETED)
    assert done, "neither candidate stole the expired lease"
    lease = await client.get(Lease, "tpu-provisioner", "default")
    winner = lease.spec.holder_identity
    assert winner in ("a", "b")
    assert a.leading.is_set() != b.leading.is_set(), "two leaders"
    assert lease.spec.lease_transitions == 1
    for t in pending:
        t.cancel()
    await (a if winner == "a" else b).stop()


@async_test
async def test_voluntary_release_hands_over_within_retry_interval():
    """A clean shutdown releases the lease; the next candidate must win at
    its retry cadence — never by waiting out the full lease duration."""
    client = InMemoryClient()
    a = LeaderElector(client, identity="a", **FAST)
    b = LeaderElector(client, identity="b", **FAST)
    await a.run_until_leading()
    b_task = asyncio.create_task(b.run_until_leading())
    await asyncio.sleep(0.3)  # b is parked in candidacy
    t0 = asyncio.get_event_loop().time()
    await a.stop()
    await asyncio.wait_for(b_task, 5)
    waited = asyncio.get_event_loop().time() - t0
    assert waited < FAST["lease_duration"] / 2, \
        f"handoff took {waited:.2f}s — waited out the lease instead of " \
        "taking the release"
    await b.stop()


@async_test
async def test_future_renew_time_does_not_wedge_candidacy():
    """Clock skew: a holder whose renew_time is AHEAD of our clock must not
    extend its term by the skew — staleness is judged by how long WE have
    observed the (holder, renew_time) pair unchanged."""
    client = InMemoryClient()
    await client.create(Lease(
        metadata=ObjectMeta(name="tpu-provisioner", namespace="default"),
        spec=LeaseSpec(holder_identity="skewed", lease_duration_seconds=2,
                       renew_time=now() + timedelta(seconds=30))))
    b = LeaderElector(client, identity="b", **FAST)
    t0 = asyncio.get_event_loop().time()
    await asyncio.wait_for(b.run_until_leading(), 10)
    waited = asyncio.get_event_loop().time() - t0
    # observed-staleness expiry: ~lease_duration, NOT skew + lease_duration
    assert waited < FAST["lease_duration"] + 1.5, \
        f"candidacy wedged {waited:.2f}s behind a future renew_time"
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.holder_identity == "b"
    await b.stop()


@async_test
async def test_fencing_token_tracks_generation_and_loss():
    """fence() captures the leadership generation: invalid the instant the
    lease is lost, and NEVER valid again — even after the same replica
    re-wins (a new term mints a new generation)."""
    client = InMemoryClient()
    a = LeaderElector(client, identity="a", **FAST)
    await a.run_until_leading()
    tok = a.fence()
    assert tok.valid()
    tok.check()  # no raise while leading

    # usurper rewrites the lease; A notices at its renew deadline
    lease = await client.get(Lease, "tpu-provisioner", "default")
    lease.spec.holder_identity = "usurper"
    await client.update(lease)
    deadline = asyncio.get_event_loop().time() + 10
    while a.leading.is_set():
        assert asyncio.get_event_loop().time() < deadline
        await asyncio.sleep(0.05)
    assert not tok.valid()
    with pytest.raises(FencedError):
        tok.check()

    # the usurper dies; A re-wins — the OLD token must stay fenced
    lease = await client.get(Lease, "tpu-provisioner", "default")
    lease.spec.holder_identity = ""
    lease.spec.renew_time = None
    await client.update(lease)
    await asyncio.wait_for(a.run_until_leading(), 10)
    assert a.leading.is_set()
    assert not tok.valid(), "a stale-term token validated after re-election"
    tok2 = a.fence()
    assert tok2.valid() and tok2.generation > tok.generation
    await a.stop()
    with pytest.raises(RuntimeError):
        a.fence()  # no leadership, no token


@async_test
async def test_lost_leadership_fires_callback():
    client = InMemoryClient()
    lost = asyncio.Event()
    a = LeaderElector(client, identity="a", on_lost=lost.set, **FAST)
    await a.run_until_leading()
    # usurper rewrites the lease out from under a
    lease = await client.get(Lease, "tpu-provisioner", "default")
    lease.spec.holder_identity = "usurper"
    await client.update(lease)
    await asyncio.wait_for(lost.wait(), 10)
    assert not a.leading.is_set()
