"""Lease leader-election tests (reference: operator.go:157-164 via client-go)."""

import asyncio

from gpu_provisioner_tpu.apis.core import Lease
from gpu_provisioner_tpu.runtime import InMemoryClient
from gpu_provisioner_tpu.runtime.leaderelection import LeaderElector

from .conftest import async_test

# second-resolution Lease timestamps (metav1.Time) bound how fast these run
FAST = dict(lease_duration=2.0, renew_interval=0.4, retry_interval=0.1)


@async_test
async def test_single_elector_acquires_and_renews():
    client = InMemoryClient()
    el = LeaderElector(client, identity="a", **FAST)
    await el.run_until_leading()
    assert el.leading.is_set()
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.holder_identity == "a"
    first_renew = lease.spec.renew_time
    await asyncio.sleep(1.5)
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.renew_time > first_renew  # renew loop is live
    await el.stop()
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.holder_identity == ""  # voluntary release


@async_test
async def test_second_elector_waits_then_takes_over():
    client = InMemoryClient()
    a = LeaderElector(client, identity="a", **FAST)
    b = LeaderElector(client, identity="b", **FAST)
    await a.run_until_leading()

    b_task = asyncio.create_task(b.run_until_leading())
    await asyncio.sleep(0.5)
    assert not b.leading.is_set()  # blocked while a holds the lease

    await a.stop()                 # release → b should win promptly
    await asyncio.wait_for(b_task, 5)
    assert b.leading.is_set()
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.holder_identity == "b"
    assert lease.spec.lease_transitions >= 0
    await b.stop()


@async_test
async def test_expired_lease_is_stolen():
    client = InMemoryClient()
    a = LeaderElector(client, identity="a", **FAST)
    await a.run_until_leading()
    # a dies without releasing (crash): cancel renewals only
    a._task.cancel()
    b = LeaderElector(client, identity="b", **FAST)
    t0 = asyncio.get_event_loop().time()
    await asyncio.wait_for(b.run_until_leading(), 10)
    waited = asyncio.get_event_loop().time() - t0
    assert waited >= 1.0  # had to wait out most of the 2s lease
    lease = await client.get(Lease, "tpu-provisioner", "default")
    assert lease.spec.holder_identity == "b"
    assert lease.spec.lease_transitions == 1
    await b.stop()


@async_test
async def test_lost_leadership_fires_callback():
    client = InMemoryClient()
    lost = asyncio.Event()
    a = LeaderElector(client, identity="a", on_lost=lost.set, **FAST)
    await a.run_until_leading()
    # usurper rewrites the lease out from under a
    lease = await client.get(Lease, "tpu-provisioner", "default")
    lease.spec.holder_identity = "usurper"
    await client.update(lease)
    await asyncio.wait_for(lost.wait(), 10)
    assert not a.leading.is_set()
