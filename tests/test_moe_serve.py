"""MoE KV-cache serving (models/moe_serve.py) — the MoE twin of
tests/test_decode.py. Reference behavior being matched: serving parity for
every model family the provisioned slices host (SURVEY.md §2c)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from gpu_provisioner_tpu.models.decode import generate, init_kv_cache
from gpu_provisioner_tpu.models.moe import (MoEConfig, init_moe_model,
                                            moe_forward)
from gpu_provisioner_tpu.models.moe_serve import (moe_cached_forward,
                                                  moe_prefill)

# f32 + generous capacity: no expert drops anywhere, so the cached path
# must be EXACTLY the full forward (drops are the one legitimate source of
# teacher-forcing divergence — see moe_serve docstring)
CFG = MoEConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
                hidden_dim=128, max_seq_len=256, n_experts=4,
                experts_per_token=2, capacity_factor=8.0, dtype="float32")


def _setup(B=2, S0=16, seed=0):
    params = init_moe_model(jax.random.key(seed), CFG)
    prompt = jax.random.randint(jax.random.key(seed + 1), (B, S0), 0,
                                CFG.vocab_size)
    return params, prompt


def test_moe_prefill_matches_full_forward():
    params, prompt = _setup()
    full, _aux = moe_forward(params, prompt, CFG)
    cache = init_kv_cache(CFG, prompt.shape[0], 64)
    cached, cache2 = moe_cached_forward(params, prompt, cache, CFG)
    assert int(cache2.length) == prompt.shape[1]
    np.testing.assert_allclose(np.asarray(cached), np.asarray(full),
                               atol=1e-4, rtol=1e-4)


def test_moe_incremental_decode_matches_teacher_forcing():
    """Feed tokens one at a time through the cache; logits must equal the
    full forward at every position (capacity high enough that the full
    forward drops nothing — otherwise divergence is expected and allowed)."""
    params, prompt = _setup(B=1, S0=12)
    full, _ = moe_forward(params, prompt, CFG)
    cache = init_kv_cache(CFG, 1, 32)
    logits, cache = moe_cached_forward(params, prompt[:, :4], cache, CFG)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full[:, :4]),
                               atol=1e-4, rtol=1e-4)
    for i in range(4, 12):
        logits, cache = moe_cached_forward(params, prompt[:, i:i + 1],
                                           cache, CFG)
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full[:, i]),
                                   atol=1e-4, rtol=1e-4)


def test_moe_generate_greedy_and_flash_parity():
    params, prompt = _setup()
    toks_d = generate(params, prompt, CFG, max_new_tokens=8, max_len=128)
    assert toks_d.shape == (2, 8)
    assert ((toks_d >= 0) & (toks_d < CFG.vocab_size)).all()
    cfg_f = dataclasses.replace(CFG, attn_impl="flash")
    toks_f = generate(params, prompt, cfg_f, max_new_tokens=8, max_len=128)
    assert (toks_d == toks_f).all()


def test_moe_generate_sampling_reproducible():
    params, prompt = _setup()
    kw = dict(max_new_tokens=8, max_len=128, temperature=0.9, top_k=20,
              top_p=0.95, key=jax.random.key(3))
    a = generate(params, prompt, CFG, **kw)
    b = generate(params, prompt, CFG, **kw)
    assert (a == b).all()
    assert ((a >= 0) & (a < CFG.vocab_size)).all()


def test_moe_padded_row_matches_solo_generation():
    """Left-padded ragged batch: pad tokens must not claim expert capacity
    (token_mask) nor shift RoPE/attention — a padded row generates exactly
    what it does alone."""
    params, _ = _setup()
    PAD = 7
    p0 = jax.random.randint(jax.random.key(9), (1, 20), 0, CFG.vocab_size)
    p1 = jax.random.randint(jax.random.key(10), (1, 12), 0, CFG.vocab_size)
    batch = jnp.concatenate(
        [p0, jnp.concatenate([jnp.full((1, 8), PAD, jnp.int32), p1], 1)], 0)
    got = generate(params, batch, CFG, max_new_tokens=6, max_len=64,
                   pad_id=PAD)
    solo0 = generate(params, p0, CFG, max_new_tokens=6, max_len=64)
    solo1 = generate(params, p1, CFG, max_new_tokens=6, max_len=64)
    assert (got[0] == solo0[0]).all()
    assert (got[1] == solo1[0]).all()


def test_moe_int8_cache_serves():
    params, prompt = _setup()
    cfg_q = dataclasses.replace(CFG, kv_cache_dtype="int8")
    toks_q = generate(params, prompt, cfg_q, max_new_tokens=8, max_len=128)
    toks_d = generate(params, prompt, CFG, max_new_tokens=8, max_len=128)
    assert toks_q.shape == (2, 8)
    # int8 is lossy; require strong top-1 agreement, not equality
    assert float((toks_q == toks_d).mean()) > 0.7


def test_moe_prefill_then_continue_multiturn():
    """Multi-turn: prefill, decode, prefill again on the same cache —
    the general cached forward must continue a partially-filled cache."""
    params, prompt = _setup(B=1, S0=8)
    cache = init_kv_cache(CFG, 1, 64)
    logits1, cache = moe_prefill(params, prompt, cache, CFG)
    assert logits1.shape == (1, CFG.vocab_size)
    nxt = jnp.argmax(logits1, axis=-1).astype(jnp.int32)[:, None]
    _, cache = moe_cached_forward(params, nxt, cache, CFG)
    turn2 = jax.random.randint(jax.random.key(4), (1, 8), 0, CFG.vocab_size)
    logits2, cache = moe_prefill(params, turn2, cache, CFG)
    assert int(cache.length) == 8 + 1 + 8
    # reference: one full forward over the concatenated stream
    stream = jnp.concatenate([prompt, nxt, turn2], axis=1)
    full, _ = moe_forward(params, stream, CFG)
    np.testing.assert_allclose(np.asarray(logits2), np.asarray(full[:, -1]),
                               atol=1e-4, rtol=1e-4)


def test_moe_chunked_prefill_matches_single_shot():
    """Chunked MoE prefill == single-shot at drop-free capacity (per-chunk
    and whole-prompt routing agree exactly when neither drops)."""
    from gpu_provisioner_tpu.models.decode import prefill_chunked

    params, prompt = _setup(B=1, S0=16)
    single, c1 = moe_prefill(params, prompt,
                             init_kv_cache(CFG, 1, 64), CFG)
    chunked, c2 = prefill_chunked(params, prompt,
                                  init_kv_cache(CFG, 1, 64), CFG, chunk=5)
    assert int(c2.length) == 16
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(single),
                               atol=1e-4, rtol=1e-4)
