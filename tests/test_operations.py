"""Non-blocking provisioning: the operation tracker (LRO multiplexer), the
shared BackoffLadder, the resumable create/delete state machine, and the
lifecycle integration (requeue_after + tracker-completion early wake).

The PR 4 contract under test:

- ``InstanceProvider.create()/delete()`` with a tracker never park the
  caller: they register the LRO and raise/return immediately; the tracker's
  single poller drives every wait off ONE batched ``nodepools.list`` per
  tick (zero per-op ``nodepools.get`` polls, zero client-side LRO polls);
- the lifecycle controller turns ``CreateInProgress`` into
  ``Result(requeue_after=...)`` — no failure counters, no backoff climb —
  and converges with ``reconcile_timeout`` set far below a slice-create
  duration (the acceptance criterion the blocking shape made impossible);
- the tracker's poller task never outlives its Env (teardown gate).
"""

import asyncio

import pytest

from gpu_provisioner_tpu.apis.core import Node
from gpu_provisioner_tpu.envtest import Env, EnvtestOptions
from gpu_provisioner_tpu.errors import CreateError, NodeClaimNotFoundError
from gpu_provisioner_tpu.fake import FakeCloud, make_nodeclaim
from gpu_provisioner_tpu.providers.instance import (
    InstanceProvider, ProviderConfig,
)
from gpu_provisioner_tpu.providers.operations import (
    OP_CREATE, OP_DELETE, PHASE_FAILED, PHASE_IN_PROGRESS, PHASE_SUCCEEDED,
    BackoffLadder, OperationTracker,
)
from gpu_provisioner_tpu.runtime import InMemoryClient

from .conftest import async_test


# ------------------------------------------------------------ BackoffLadder

def test_ladder_growth_caps_at_quarter_budget():
    ladder = BackoffLadder(budget=40.0, base=1.0, rng=lambda: 0.0)
    delays = [ladder.next_delay() for _ in range(10)]
    # ×1.5 growth from base, hard-capped at budget/4
    assert delays[0] == 1.0
    assert delays[1] == 1.5
    assert max(delays) == 10.0 == ladder.cap
    assert delays[-1] == 10.0  # stays pinned at the cap


def test_ladder_jitter_bounds_and_determinism():
    top = BackoffLadder(budget=8.0, base=1.0, jitter=0.5, rng=lambda: 1.0)
    bottom = BackoffLadder(budget=8.0, base=1.0, jitter=0.5, rng=lambda: 0.0)
    # jitter stretches a delay by at most (1 + jitter); never shrinks it
    assert top.next_delay() == 1.5
    assert bottom.next_delay() == 1.0
    # jitter applies to the delay only — the ladder position is unaffected
    assert top.interval == bottom.interval == 1.5


def test_ladder_cap_never_below_base():
    # a tiny budget must not produce a cap under the base interval (the
    # old inline ladders had the same budget/4 floor implicitly via min())
    ladder = BackoffLadder(budget=0.1, base=1.0, rng=lambda: 0.0)
    assert ladder.cap == 1.0
    assert ladder.next_delay() == 1.0


@async_test
async def test_ladder_reset_and_expiry():
    ladder = BackoffLadder(budget=0.05, base=0.01, rng=lambda: 0.0)
    assert not ladder.expired()
    ladder.next_delay()
    ladder.next_delay()
    assert ladder.interval > 0.01
    ladder.reset()
    assert ladder.interval == 0.01
    await asyncio.sleep(0.06)
    assert ladder.expired()


# --------------------------------------------------------- tracker plumbing

def _provider(cloud, kube, tracker=None, **cfg_kw):
    cfg = ProviderConfig(node_wait_interval=0.02, node_wait_attempts=30,
                        cache_ttl=0.0, **cfg_kw)
    return InstanceProvider(cloud.nodepools, kube, cfg,
                            queued=cloud.queuedresources, tracker=tracker)


async def _tracked_env(create_latency=0.05, interval=0.02):
    kube = InMemoryClient()
    cloud = FakeCloud(kube, create_latency=create_latency,
                      delete_latency=0.03)
    provider = _provider(cloud, kube)
    tracker = OperationTracker(provider.nodepools, kube, interval=interval)
    provider.tracker = tracker
    tracker.start()
    return kube, cloud, provider, tracker


@async_test
async def test_tracker_idles_without_operations():
    kube, cloud, provider, tracker = await _tracked_env()
    try:
        await asyncio.sleep(0.15)
        assert tracker.poll_batches == 0, \
            "an idle tracker must issue zero cloud polls"
        assert cloud.nodepools.calls.get("list", 0) == 0
    finally:
        await tracker.stop()


@async_test
async def test_tracker_poller_exits_on_stop_flag_even_when_cancel_is_eaten():
    """py3.10's wait_for can swallow a cancellation that races a completed
    inner future (bpo-42130), leaving the poller alive and parked on _wake
    while stop() awaits it forever (Env teardown hang, seen flakily under
    repair-churn teardown). The stop flag + wake must terminate the loop
    WITHOUT relying on the cancel being delivered."""
    kube, cloud, provider, tracker = await _tracked_env()
    await asyncio.sleep(0)          # let the poller park on _wake
    # simulate the eaten cancel: no task.cancel() at all — flag + wake only
    tracker._stopping = True
    tracker._wake.set()
    await asyncio.wait_for(tracker._task, 2.0)
    tracker._task = None            # consumed; nothing left for stop()


@async_test
async def test_create_registers_then_completes_via_batched_list():
    kube, cloud, provider, tracker = await _tracked_env()
    try:
        nc = make_nodeclaim("op0", "tpu-v5e-8")
        with pytest.raises(CreateError) as ei:
            await provider.create(nc)
        assert ei.value.reason == "CreateInProgress"
        op = tracker.poke("op0")
        assert op is not None and op.kind == OP_CREATE
        assert op.phase == PHASE_IN_PROGRESS
        assert tracker.inflight() == {OP_CREATE: 1, OP_DELETE: 0}

        # a re-driven reconcile while in flight: zero additional cloud calls
        begin_creates = cloud.nodepools.calls["begin_create"]
        gets = cloud.nodepools.calls["get"]
        with pytest.raises(CreateError):
            await provider.create(nc)
        assert cloud.nodepools.calls["begin_create"] == begin_creates
        assert cloud.nodepools.calls["get"] == gets

        await asyncio.wait_for(op.done.wait(), 5)
        assert op.phase == PHASE_SUCCEEDED
        inst = await provider.create(nc)   # consumes the tracked outcome
        assert inst.name == "op0" and inst.state == "Succeeded"
        assert inst.node_provider_ids, "nodes must be up before completion"
        assert tracker.poke("op0") is None, "terminal op must be consumed"
        # the multiplexed wait never polled per-op: no nodepools.get (one
        # final get reads the created pool), no client-side LRO polls
        assert cloud.nodepools.calls.get("operation_poll", 0) == 0
        assert cloud.nodepools.calls["get"] <= 1
        assert tracker.poll_batches >= 1
        assert cloud.nodepools.calls["list"] == tracker.poll_batches
    finally:
        await tracker.stop()


@async_test
async def test_blocking_baseline_polls_per_operation():
    """The shape the tracker replaces (and the bench baseline): a
    tracker-less provider still blocks and polls its own LRO."""
    kube = InMemoryClient()
    cloud = FakeCloud(kube, create_latency=0.05)
    provider = _provider(cloud, kube)
    inst = await provider.create(make_nodeclaim("bl0", "tpu-v5e-8"))
    assert inst.state == "Succeeded"
    assert cloud.nodepools.calls["operation_poll"] >= 1


@async_test
async def test_nonblocking_delete_registers_and_reports_gone():
    kube, cloud, provider, tracker = await _tracked_env()
    try:
        await provider.create_and_wait(make_nodeclaim("del0", "tpu-v5e-8"))
        await provider.delete("del0")          # begin_delete + register
        op = tracker.poke("del0")
        assert op is not None and op.kind == OP_DELETE
        assert "del0" in cloud.nodepools.pools  # LRO not settled yet

        gets = cloud.nodepools.calls["get"]
        await provider.delete("del0")          # "still terminating"
        assert cloud.nodepools.calls["get"] == gets, \
            "an in-flight tracked delete must not re-read the pool"

        await asyncio.wait_for(op.done.wait(), 5)
        assert op.phase == PHASE_SUCCEEDED
        with pytest.raises(NodeClaimNotFoundError):
            await provider.delete("del0")      # consumes the outcome
        assert "del0" not in cloud.nodepools.pools
        assert cloud.nodepools.calls.get("operation_poll", 0) == 0
    finally:
        await tracker.stop()


@async_test
async def test_delete_supersedes_inflight_create():
    kube, cloud, provider, tracker = await _tracked_env(create_latency=0.3)
    try:
        with pytest.raises(CreateError):
            await provider.create(make_nodeclaim("sup0", "tpu-v5e-8"))
        create_op = tracker.poke("sup0")
        assert create_op.kind == OP_CREATE
        await provider.delete("sup0")
        op = tracker.poke("sup0")
        assert op.kind == OP_DELETE and op.in_progress
        # the displaced create resolved (a create_and_wait waiter wakes)
        assert create_op.phase == PHASE_FAILED
        assert create_op.reason == "Superseded"
        await asyncio.wait_for(op.done.wait(), 5)
        assert "sup0" not in cloud.nodepools.pools
    finally:
        await tracker.stop()


@async_test
async def test_tracker_deadline_fails_op_retryably():
    kube, cloud, provider, tracker = await _tracked_env(create_latency=60.0)
    try:
        # budget at this config: 2 × 30 × 0.02 = 1.2s ≪ the 60s "LRO"
        with pytest.raises(CreateError):
            await provider.create(make_nodeclaim("slow0", "tpu-v5e-8"))
        op = tracker.poke("slow0")
        await asyncio.wait_for(op.done.wait(), 10)
        assert op.phase == PHASE_FAILED
        assert op.reason == "CreateInProgress", \
            "deadline expiry must stay retryable (requeue + re-adopt)"
        with pytest.raises(CreateError) as ei:
            await provider.create(make_nodeclaim("slow0", "tpu-v5e-8"))
        assert ei.value.reason == "CreateInProgress"
    finally:
        await tracker.stop()


@async_test
async def test_tracker_completion_notifies_subscribers():
    kube, cloud, provider, tracker = await _tracked_env()
    completed = []

    async def on_complete(op):
        completed.append((op.kind, op.name, op.phase))

    tracker.subscribe(on_complete)
    try:
        with pytest.raises(CreateError):
            await provider.create(make_nodeclaim("sub0", "tpu-v5e-8"))
        op = tracker.poke("sub0")
        await asyncio.wait_for(op.done.wait(), 5)
        await asyncio.sleep(0)  # let the fire-and-forget callback land
        assert (OP_CREATE, "sub0", PHASE_SUCCEEDED) in completed
        assert op.wait_seconds > 0
    finally:
        await tracker.stop()


@async_test
async def test_delete_of_vanished_pool_discards_parked_op():
    """Claim churn hygiene: when delete() proves the pool is gone, any op
    parked under the name is discarded — terminal ops whose claim died must
    not accumulate in the tracker forever."""
    kube, cloud, provider, tracker = await _tracked_env(create_latency=0.3)
    try:
        with pytest.raises(CreateError):
            await provider.create(make_nodeclaim("van0", "tpu-v5e-8"))
        assert tracker.poke("van0") is not None
        # out-of-band teardown: the pool disappears without our delete LRO
        cloud.nodepools.pools.pop("van0")
        cloud.nodepools._pending.pop("van0", None)
        with pytest.raises(NodeClaimNotFoundError):
            await provider.delete("van0")
        assert tracker.poke("van0") is None, \
            "a parked op for a proven-gone pool must be discarded"
    finally:
        await tracker.stop()


@async_test
async def test_reused_name_after_reaped_delete_is_not_wedged():
    """Regression: GC/recovery reap a claimless pool through delete() and
    never call delete() again — the resolved delete op sits parked under
    the name with no consumer. A NodeClaim reusing that name (KAITO
    recreating a workspace) must pop it and provision fresh, not see
    "being deleted" forever."""
    kube, cloud, provider, tracker = await _tracked_env()
    try:
        await provider.create_and_wait(make_nodeclaim("ru0", "tpu-v5e-8"))
        await provider.delete("ru0")               # the reap: exactly one call
        op = tracker.poke("ru0")
        await asyncio.wait_for(op.done.wait(), 5)
        assert op.phase == PHASE_SUCCEEDED
        # nobody consumed the outcome; a new claim reuses the name
        inst = await provider.create_and_wait(
            make_nodeclaim("ru0", "tpu-v5e-8"), timeout=10)
        assert inst.state == "Succeeded"
        assert "ru0" in cloud.nodepools.pools
    finally:
        await tracker.stop()


@async_test
async def test_persistent_create_failure_still_climbs_backoff_ladder():
    """Regression: the CreateInProgress lap rides the success path but must
    PRESERVE failure history (Result.preserve_failures) — a pool that lands
    ERROR on every create alternates fail → re-register, and forgetting the
    counter each lap would pin its begin_create cadence flat forever."""
    from gpu_provisioner_tpu import chaos
    from gpu_provisioner_tpu.runtime import Request

    policy = chaos.ChaosPolicy(3, partial={"op_error": 1.0})
    opts = EnvtestOptions(chaos=policy, create_latency=0.03)
    opts.lifecycle.launch_timeout = 600.0  # liveness must not end the test
    async with Env(opts) as env:
        await env.client.create(make_nodeclaim("err0", "tpu-v5e-8"))
        lifecycle = next(c for c in env.manager.controllers
                         if c.name == "nodeclaim.lifecycle")
        req = Request(name="err0")
        deadline = asyncio.get_event_loop().time() + 8
        while lifecycle.queue.num_requeues(req) < 3:
            assert asyncio.get_event_loop().time() < deadline, \
                "failure counter never climbed across in-progress laps"
            await asyncio.sleep(0.05)


@async_test
async def test_track_create_is_idempotent():
    kube, cloud, provider, tracker = await _tracked_env(create_latency=0.3)
    try:
        with pytest.raises(CreateError):
            await provider.create(make_nodeclaim("idem0", "tpu-v5e-8"))
        op1 = tracker.poke("idem0")
        op2 = tracker.track_create("idem0", 1, 10.0)
        assert op1 is op2, "re-registering an in-flight create is a no-op"
        assert tracker.registered[OP_CREATE] == 1
    finally:
        await tracker.stop()


@async_test
async def test_create_and_wait_drives_state_machine():
    kube, cloud, provider, tracker = await _tracked_env()
    try:
        inst = await provider.create_and_wait(
            make_nodeclaim("caw0", "tpu-v5e-8"), timeout=10)
        assert inst.state == "Succeeded"
    finally:
        await tracker.stop()


@async_test
async def test_tracker_poll_errors_still_enforce_deadlines():
    """A dead cloud (every list fails) must not wedge tracked ops past
    their deadlines — the deadline check runs on the error path too."""
    from gpu_provisioner_tpu.providers.gcp import APIError

    kube, cloud, provider, tracker = await _tracked_env(create_latency=60.0)
    try:
        with pytest.raises(CreateError):
            await provider.create(make_nodeclaim("dead0", "tpu-v5e-8"))
        cloud.nodepools.fail("list", APIError("outage", code=503), times=10_000)
        op = tracker.poke("dead0")
        await asyncio.wait_for(op.done.wait(), 10)
        assert op.phase == PHASE_FAILED
        assert tracker.poll_errors >= 1
    finally:
        await tracker.stop()


# ------------------------------------------------- lifecycle integration

@async_test
async def test_lifecycle_converges_with_reconcile_timeout_below_create():
    """The acceptance criterion PR 4 exists for: with creates taking 0.5s,
    a 0.15s per-reconcile deadline — impossible under the blocking shape,
    where one create pinned a worker for the whole duration — converges
    cleanly, and the deadline never fires."""
    opts = EnvtestOptions(create_latency=0.5, node_ready_delay=0.05,
                          reconcile_timeout=0.15)
    async with Env(opts) as env:
        await env.client.create(make_nodeclaim("fast0", "tpu-v5e-8"))
        nc = await env.wait_ready("fast0", timeout=15)
        assert nc.status.provider_id
        lifecycle = next(c for c in env.manager.controllers
                         if c.name == "nodeclaim.lifecycle")
        assert lifecycle.timeouts_total == 0, \
            "non-blocking reconciles must fit far inside the deadline"
        # in-progress requeues ride the success path: no failure counters
        assert lifecycle.queue.retrying() == 0


@async_test
async def test_inprogress_wave_does_not_climb_backoff_ladder():
    """CreateInProgress is progress, not failure: while an op is in flight
    the claim's workqueue failure counter stays at zero (the error path
    would climb the exponential ladder and stretch every wave)."""
    opts = EnvtestOptions(create_latency=0.4)
    async with Env(opts) as env:
        await env.client.create(make_nodeclaim("wv0", "tpu-v5e-8"))
        lifecycle = next(c for c in env.manager.controllers
                         if c.name == "nodeclaim.lifecycle")
        deadline = asyncio.get_event_loop().time() + 0.35
        while asyncio.get_event_loop().time() < deadline:
            assert lifecycle.queue.retrying() == 0
            await asyncio.sleep(0.02)
        await env.wait_ready("wv0", timeout=15)


@async_test
async def test_env_teardown_reaps_tracker_task():
    opts = EnvtestOptions()
    env = Env(opts)
    async with env:
        assert env.tracker is not None
        assert env.tracker.task_alive()
    assert not env.tracker.task_alive(), \
        "the tracker poller must die with its Env"
    leaked = [t for t in asyncio.all_tasks()
              if t.get_name().startswith("operation-tracker")
              and not t.done()]
    assert not leaked, f"leaked tracker tasks: {leaked}"


@async_test
async def test_blocking_create_option_restores_baseline_shape():
    opts = EnvtestOptions(blocking_create=True)
    async with Env(opts) as env:
        assert env.tracker is None and env.provider.tracker is None
        await env.client.create(make_nodeclaim("bc0", "tpu-v5e-8"))
        await env.wait_ready("bc0", timeout=15)
        assert env.cloud.nodepools.calls["operation_poll"] >= 1, \
            "the baseline must still poll its LROs client-side"


# ------------------------------------------------------------------ metrics

@async_test
async def test_operation_metrics_sampled_at_scrape():
    from gpu_provisioner_tpu.controllers.metrics import (
        INFLIGHT_OPERATIONS, OPERATION_POLL_BATCHES, OPERATION_WAIT,
        update_runtime_gauges,
    )

    opts = EnvtestOptions()
    async with Env(opts) as env:
        await env.client.create(make_nodeclaim("mx0", "tpu-v5e-8"))
        await env.wait_ready("mx0", timeout=15)
        waits0 = OPERATION_WAIT.labels("create")._sum.get()
        update_runtime_gauges(env.manager)
        assert OPERATION_POLL_BATCHES._value.get() >= env.tracker.poll_batches
        assert OPERATION_WAIT.labels("create")._sum.get() > waits0, \
            "completed create duration must land in the histogram"
        # steady state: THIS env's tracker has nothing in flight (the gauge
        # itself aggregates every live tracker in the process — other
        # tests' not-yet-collected trackers may contribute)
        assert env.tracker.inflight() == {"create": 0, "delete": 0}
        assert INFLIGHT_OPERATIONS.labels("create")._value.get() >= 0
