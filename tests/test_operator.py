"""Operator runtime: options/env parsing, auth config, logging, servers."""

import json
import io
import logging

import pytest

from gpu_provisioner_tpu.auth import (
    Config, ConfigError, FederatedTokenCredential, StaticTokenCredential,
    build_config, new_credential,
)
from gpu_provisioner_tpu.auth.credentials import MetadataServerCredential
from gpu_provisioner_tpu.operator.logging import setup_logging
from gpu_provisioner_tpu.operator.options import parse_feature_gates, parse_options

from .conftest import async_test


# --- options ---------------------------------------------------------------

def test_options_env_fallback_and_flags():
    env = {"METRICS_PORT": "9090", "DISABLE_LEADER_ELECTION": "false",
           "FEATURE_GATES": "NodeRepair=false", "LAUNCH_TIMEOUT_SECONDS": "600"}
    o = parse_options(argv=["--health-probe-port", "9091"], env=env)
    assert o.metrics_port == 9090
    assert o.health_probe_port == 9091
    assert o.disable_leader_election is False
    assert o.feature_gates.node_repair is False
    assert o.launch_timeout_seconds == 600


def test_feature_gate_parsing_tolerates_junk():
    fg = parse_feature_gates("garbage,,NodeRepair=true,=x",
                             parse_options(argv=[], env={}).feature_gates)
    assert fg.node_repair is True


# --- auth config (pkg/auth/config_test.go analog) --------------------------

def test_config_parse_trim_validate():
    cfg = build_config({"PROJECT_ID": " p1 ", "LOCATION": "us-central2-b",
                        "CLUSTER_NAME": "kaito"})
    assert cfg.project_id == "p1"
    assert cfg.deployment_mode == "managed"


def test_config_missing_vars_actionable():
    with pytest.raises(ConfigError) as e:
        build_config({"PROJECT_ID": "p"})
    assert "LOCATION" in str(e.value) or "location" in str(e.value)


def test_config_self_hosted_requires_token_file():
    with pytest.raises(ConfigError):
        build_config({"PROJECT_ID": "p", "LOCATION": "l", "CLUSTER_NAME": "c",
                      "DEPLOYMENT_MODE": "self-hosted"})
    cfg = build_config({"PROJECT_ID": "p", "LOCATION": "l", "CLUSTER_NAME": "c",
                        "DEPLOYMENT_MODE": "self-hosted",
                        "GOOGLE_FEDERATED_TOKEN_FILE": "/var/run/token"})
    assert isinstance(new_credential(cfg), FederatedTokenCredential)
    cfg2 = build_config({"PROJECT_ID": "p", "LOCATION": "l", "CLUSTER_NAME": "c"})
    assert isinstance(new_credential(cfg2), MetadataServerCredential)
    from gpu_provisioner_tpu.auth.credentials import ImpersonatedCredential
    cfg3 = build_config({"PROJECT_ID": "p", "LOCATION": "l", "CLUSTER_NAME": "c",
                         "DEPLOYMENT_MODE": "self-hosted",
                         "GOOGLE_FEDERATED_TOKEN_FILE": "/var/run/token",
                         "GOOGLE_SERVICE_ACCOUNT": "sa@p.iam.gserviceaccount.com"})
    assert isinstance(new_credential(cfg3), ImpersonatedCredential)


@async_test
async def test_federated_credential_rereads_file(tmp_path):
    import httpx
    calls = []

    def handler(request: httpx.Request) -> httpx.Response:
        calls.append(dict(request.headers))
        body = dict(pair.split("=", 1) for pair in
                    request.content.decode().split("&"))
        return httpx.Response(200, json={"access_token": "tok-" + body[
            "subject_token"][-1]})

    tf = tmp_path / "token"
    tf.write_text("jwt1")
    cred = FederatedTokenCredential(
        str(tf), "aud", http=httpx.AsyncClient(transport=httpx.MockTransport(handler)))
    assert await cred.token() == "tok-1"
    tf.write_text("jwt2")
    assert await cred.token() == "tok-1"  # cached within re-read interval
    cred._expires = 0  # age out the cache → file re-read picks up rotation
    assert await cred.token() == "tok-2"


@async_test
async def test_static_credential():
    assert await StaticTokenCredential("t").token() == "t"


# --- logging ---------------------------------------------------------------

def test_json_logging_shape():
    buf = io.StringIO()
    setup_logging("debug", stream=buf)
    logging.getLogger("x.y").info("hello", extra={"nodeclaim": "ws0"})
    line = json.loads(buf.getvalue().strip())
    assert line["level"] == "info" and line["logger"] == "x.y"
    assert line["msg"] == "hello" and line["nodeclaim"] == "ws0"
    logging.getLogger().handlers.clear()


# --- servers ---------------------------------------------------------------

@async_test
async def test_metrics_and_health_servers():
    from aiohttp.test_utils import TestClient, TestServer
    from gpu_provisioner_tpu.operator.server import build_apps
    from gpu_provisioner_tpu.runtime import InMemoryClient, Manager

    mgr = Manager(InMemoryClient())
    metrics_app, health_app = build_apps(mgr, enable_profiling=True)

    async with TestClient(TestServer(health_app)) as hc:
        r = await hc.get("/healthz")
        assert r.status == 200
        r = await hc.get("/readyz")
        assert r.status == 503  # manager not started
        await mgr.start()
        r = await hc.get("/readyz")
        assert r.status == 200
        await mgr.stop()

    async with TestClient(TestServer(metrics_app)) as mc:
        r = await mc.get("/metrics")
        text = await r.text()
        assert "karpenter_cloudprovider_duration_seconds" in text
        r = await mc.get("/debug/tasks")
        assert r.status == 200


@async_test
async def test_profiling_endpoints():
    """pprof parity (operator.go:185-200): heap snapshot arms then reports;
    CPU profile samples off-thread and emits collapsed stacks."""
    import threading
    import time as _time

    from aiohttp.test_utils import TestClient, TestServer
    from gpu_provisioner_tpu.operator.server import build_apps
    from gpu_provisioner_tpu.runtime import InMemoryClient, Manager

    mgr = Manager(InMemoryClient())
    metrics_app, _health_app = build_apps(mgr, enable_profiling=True)

    async with TestClient(TestServer(metrics_app)) as mc:
        # heap: first hit arms tracemalloc, second reports sites
        r = await mc.get("/debug/pprof/heap")
        assert r.status == 200
        _garbage = [bytearray(4096) for _ in range(64)]
        r = await mc.get("/debug/pprof/heap")
        body = await r.text()
        assert "KiB" in body and "blocks" in body

        # profile: run a busy worker thread so the sampler has something
        # unmistakable to catch
        stop = threading.Event()

        def spin():
            while not stop.is_set():
                sum(i * i for i in range(1000))
                _time.sleep(0)

        t = threading.Thread(target=spin, name="spinner", daemon=True)
        t.start()
        try:
            r = await mc.get("/debug/pprof/profile?seconds=0.5&hz=200")
            prof = await r.text()
        finally:
            stop.set()
            t.join(timeout=2)
        assert prof.startswith("# cpu profile:")
        assert "spin" in prof  # the worker's frames were sampled

        # goroutine-dump alias serves the task dump
        r = await mc.get("/debug/pprof/goroutine")
        assert r.status == 200
