"""Pallas flash-attention kernel tests (interpret mode on the CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from gpu_provisioner_tpu.ops import flash_attention
from gpu_provisioner_tpu.parallel import make_mesh
from gpu_provisioner_tpu.parallel.ring import dense_attention


def _qkv(B=2, S=256, Hq=4, Hkv=2, D=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(0), 3)
    return (jax.random.normal(ks[0], (B, S, Hq, D), dtype),
            jax.random.normal(ks[1], (B, S, Hkv, D), dtype),
            jax.random.normal(ks[2], (B, S, Hkv, D), dtype))


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("kv_heads", [4, 2, 1])  # MHA + two GQA ratios
def test_flash_matches_dense(causal, kv_heads):
    q, k, v = _qkv(Hkv=kv_heads)
    ref = dense_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("kv_heads", [2, 1])  # MHA and GQA (group=2)
def test_flash_gradients_match_dense(causal, kv_heads):
    """Pallas per-block-recompute backward vs dense autodiff."""
    q, k, v = _qkv(B=1, S=128, Hq=2, Hkv=kv_heads, D=32)
    gf = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal=causal) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.sum(dense_attention(*a, causal=causal) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_streaming_gradients_match_dense(monkeypatch):
    """Backward on the streaming-forward path (lse from scratch carries)."""
    import importlib
    fa_mod = importlib.import_module("gpu_provisioner_tpu.ops.flash_attention")
    monkeypatch.setattr(fa_mod, "RESIDENT_KV_BUDGET", 0)
    q, k, v = _qkv(B=1, S=256, Hq=2, Hkv=1, D=32)
    gf = jax.grad(lambda *a: jnp.sum(fa_mod.flash_attention(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.sum(dense_attention(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_streaming_variant_matches_dense(monkeypatch):
    """Force the O(block)-VMEM streaming kernel (normally long-S only)."""
    import importlib
    # the package re-export shadows the submodule attribute; resolve the module
    fa_mod = importlib.import_module("gpu_provisioner_tpu.ops.flash_attention")
    monkeypatch.setattr(fa_mod, "RESIDENT_KV_BUDGET", 0)
    for causal in (True, False):
        q, k, v = _qkv(S=256, Hkv=2)
        ref = dense_attention(q, k, v, causal=causal)
        out = fa_mod.flash_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_flash_triangular_streaming_matches_dense(monkeypatch):
    """Flattened-triangle causal grids (opt-in) vs dense, forward AND
    gradients — the grad path runs the triangular dq kernel (lower
    triangle) and dkv kernel (reversed triangle, _tri_decode_rev), so this
    is the primary numeric gate for all three tri kernels."""
    import importlib
    fa_mod = importlib.import_module("gpu_provisioner_tpu.ops.flash_attention")
    monkeypatch.setattr(fa_mod, "RESIDENT_KV_BUDGET", 0)
    q, k, v = _qkv(B=1, S=384, Hq=2, Hkv=1, D=32)
    ref = dense_attention(q, k, v)
    out = fa_mod.flash_attention(q, k, v, triangular=True, block_q=128,
                                 block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gf = jax.grad(lambda *a: jnp.sum(fa_mod.flash_attention(
        *a, triangular=True, block_q=128, block_k=128) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.sum(dense_attention(*a) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_tri_decode_roundtrips():
    """The float-sqrt triangular index decode must be exact over a whole
    large grid (the ±1 corrections do the real work)."""
    from gpu_provisioner_tpu.ops.flash_attention import _tri_decode
    n = 181                              # odd, > any realistic block grid
    t = jnp.arange(n * (n + 1) // 2)
    qi, kj = jax.vmap(lambda x: _tri_decode(x, n))(t)
    expect = [(i, j) for i in range(n) for j in range(i + 1)]
    got = list(zip(np.asarray(qi).tolist(), np.asarray(kj).tolist()))
    assert got == expect


def test_flash_falls_back_on_non_tiling_shapes():
    # S=100 doesn't tile into 128/64-blocks cleanly → silent dense fallback
    q, k, v = _qkv(S=100)
    ref = dense_attention(q, k, v)
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("start", [0, 37, 130, 384])
def test_cached_flash_matches_dense_masked_sweep(start):
    """flash_attention_cached (scalar-prefetch start, dynamic causal
    frontier) vs the dense S×max_len masked sweep it replaces."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import (
        cached_flash_supported, flash_attention_cached)

    B, S, ML, Hq, Hkv, D = 2, 128, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    k_cache = jax.random.normal(ks[1], (B, Hkv, ML, D))   # head-major
    v_cache = jax.random.normal(ks[2], (B, Hkv, ML, D))
    assert cached_flash_supported(S, ML, Hq, Hkv)
    scale = D ** -0.5
    start = jnp.asarray(start, jnp.int32)
    out = flash_attention_cached(q, k_cache, v_cache, start, scale=scale)
    ref = _cached_attention(q, k_cache, v_cache, start, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_cached_flash_under_jit_traced_start():
    """start is traced in the serving loop — the kernel must accept it."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_cached

    B, S, ML, Hq, Hkv, D = 1, 128, 256, 2, 1, 32
    ks = jax.random.split(jax.random.key(4), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))        # head-major
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    f = jax.jit(lambda s: flash_attention_cached(q, kc, vc, s))
    for s in (0, 65, 128):
        ref = _cached_attention(q, kc, vc, jnp.asarray(s), D ** -0.5)
        np.testing.assert_allclose(np.asarray(f(jnp.asarray(s, jnp.int32))),
                                   np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_cached_flash_int8_matches_dense_dequant():
    """int8-cache kernel mode (in-VMEM dequant) vs the dense dequantizing
    sweep it replaces."""
    from gpu_provisioner_tpu.models.decode import (_cached_attention,
                                                   _quantize_kv)
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_cached

    B, S, ML, Hq, Hkv, D = 2, 128, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(5), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    # token-major [B, ML, Hkv, D] → quantize → head-major cache layout
    k_tm = jax.random.normal(ks[1], (B, ML, Hkv, D))
    v_tm = jax.random.normal(ks[2], (B, ML, Hkv, D))
    kq, kscl = _quantize_kv(k_tm)
    vq, vscl = _quantize_kv(v_tm)
    hm = lambda x: x.transpose(0, 2, 1, 3)
    kc, vc = hm(kq), hm(vq)
    ksc, vsc = hm(kscl), hm(vscl)
    start = jnp.asarray(130, jnp.int32)
    scale = D ** -0.5
    out = flash_attention_cached(q, kc, vc, start, scale=scale,
                                 k_scale=ksc, v_scale=vsc)
    ref = _cached_attention(q, kc, vc, start, scale,
                            k_scale=ksc, v_scale=vsc)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("start", [0, 37, 130, 511])
def test_decode_flash_matches_dense_sweep(start):
    """flash_attention_decode (S=1, scalar-prefetch start, per-kv-head grid)
    vs the dense masked sweep it replaces."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import (
        decode_flash_supported, flash_attention_decode)

    B, ML, Hq, Hkv, D = 2, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(11), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))    # head-major
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    assert decode_flash_supported(ML, Hq, Hkv)
    scale = D ** -0.5
    s = jnp.asarray(start, jnp.int32)
    out = flash_attention_decode(q, kc, vc, s, scale=scale)
    ref = _cached_attention(q, kc, vc, s, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_flash_padded_rows_match_dense():
    """pad_lens in-kernel: row b attends only to positions ≥ pad_lens[b]
    (left-padded ragged serving); leading all-pad blocks are skipped."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_decode

    B, ML, Hq, Hkv, D = 3, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(12), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    pad = jnp.asarray([0, 7, 300], jnp.int32)
    scale = D ** -0.5
    s = jnp.asarray(384, jnp.int32)
    out = flash_attention_decode(q, kc, vc, s, scale=scale, pad_lens=pad)
    ref = _cached_attention(q, kc, vc, s, scale, pad_lens=pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_flash_int8_matches_dense_dequant():
    from gpu_provisioner_tpu.models.decode import (_cached_attention,
                                                   _quantize_kv)
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_decode

    B, ML, Hq, Hkv, D = 2, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(13), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    k_tm = jax.random.normal(ks[1], (B, ML, Hkv, D))
    v_tm = jax.random.normal(ks[2], (B, ML, Hkv, D))
    kq, kscl = _quantize_kv(k_tm)
    vq, vscl = _quantize_kv(v_tm)
    hm = lambda x: x.transpose(0, 2, 1, 3)
    s = jnp.asarray(200, jnp.int32)
    scale = D ** -0.5
    out = flash_attention_decode(q, hm(kq), hm(vq), s, scale=scale,
                                 k_scale=hm(kscl), v_scale=hm(vscl))
    ref = _cached_attention(q, hm(kq), hm(vq), s, scale,
                            k_scale=hm(kscl), v_scale=hm(vscl))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_flash_int8_padded_matches_dense():
    """int8 cache × left-padded ragged rows — the scale refs ride AFTER the
    kv refs while the pad mask indexes the prefetched meta; the combination
    must stay wired (a supported serving config: quantized cache server
    taking ragged batches)."""
    from gpu_provisioner_tpu.models.decode import (_cached_attention,
                                                   _quantize_kv)
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_decode

    B, ML, Hq, Hkv, D = 3, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(15), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    k_tm = jax.random.normal(ks[1], (B, ML, Hkv, D))
    v_tm = jax.random.normal(ks[2], (B, ML, Hkv, D))
    kq, kscl = _quantize_kv(k_tm)
    vq, vscl = _quantize_kv(v_tm)
    hm = lambda x: x.transpose(0, 2, 1, 3)
    pad = jnp.asarray([0, 37, 300], jnp.int32)
    s = jnp.asarray(384, jnp.int32)
    scale = D ** -0.5
    out = flash_attention_decode(q, hm(kq), hm(vq), s, scale=scale,
                                 k_scale=hm(kscl), v_scale=hm(vscl),
                                 pad_lens=pad)
    ref = _cached_attention(q, hm(kq), hm(vq), s, scale,
                            k_scale=hm(kscl), v_scale=hm(vscl),
                            pad_lens=pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_flash_under_jit_traced_start():
    """start is traced in generate's scan — the kernel must accept it."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_decode

    B, ML, Hq, Hkv, D = 1, 256, 2, 1, 32
    ks = jax.random.split(jax.random.key(14), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    f = jax.jit(lambda s: flash_attention_decode(q, kc, vc, s))
    for s in (0, 65, 255):
        ref = _cached_attention(q, kc, vc, jnp.asarray(s), D ** -0.5)
        np.testing.assert_allclose(np.asarray(f(jnp.asarray(s, jnp.int32))),
                                   np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_decode_flash_per_row_starts_match_rowwise_dense():
    """Vector ``start`` (per-row cache lengths — batched speculative
    decoding): both the decode kernel and the dense sweep must equal each
    row computed ALONE at its own scalar start."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_decode

    B, ML, Hq, Hkv, D = 3, 256, 4, 2, 32
    ks = jax.random.split(jax.random.key(31), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    scale = D ** -0.5
    starts = jnp.asarray([37, 0, 255], jnp.int32)
    want = jnp.concatenate([
        _cached_attention(q[b:b + 1], kc[b:b + 1], vc[b:b + 1],
                          starts[b], scale) for b in range(B)])
    for got in (flash_attention_decode(q, kc, vc, starts, scale=scale),
                _cached_attention(q, kc, vc, starts, scale)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)
    # traced vector start under jit (the batched speculative loop's shape)
    f = jax.jit(lambda s: flash_attention_decode(q, kc, vc, s, scale=scale))
    np.testing.assert_allclose(np.asarray(f(starts)), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_decode_flash_short_blocks_match_dense():
    """S>1 short query blocks (speculative verify / tiny continuations)
    through the decode/verify kernel: every query row gets its own causal
    frontier (position start+i) — must match the dense sweep across S,
    GQA widths, pads, window/sinks, int8, and per-row starts."""
    from gpu_provisioner_tpu.models.decode import (_cached_attention,
                                                   _quantize_kv)
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_decode

    B, ML, Hkv, D = 2, 256, 2, 32
    ks = jax.random.split(jax.random.key(33), 3)
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    scale = D ** -0.5
    for S, group in ((3, 2), (5, 1), (16, 4)):
        Hq = Hkv * group
        q = jax.random.normal(ks[0], (B, S, Hq, D))
        s = jnp.asarray(100, jnp.int32)
        out = flash_attention_decode(q, kc, vc, s, scale=scale)
        ref = _cached_attention(q, kc, vc, s, scale)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5,
                                   err_msg=f"S={S} group={group}")
    # pads + window + sinks + per-row starts, S=4
    q = jax.random.normal(ks[0], (B, 4, 4, D))
    pads = jnp.asarray([0, 11], jnp.int32)
    starts = jnp.asarray([130, 40], jnp.int32)
    out = flash_attention_decode(q, kc, vc, starts, scale=scale,
                                 pad_lens=pads, window=64, sinks=2)
    ref = _cached_attention(q, kc, vc, starts, scale, pad_lens=pads,
                            window=64, sinks=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # int8 cache mode, S=3
    k_tm = jax.random.normal(ks[1], (B, ML, Hkv, D))
    v_tm = jax.random.normal(ks[2], (B, ML, Hkv, D))
    kq, kscl = _quantize_kv(k_tm)
    vq, vscl = _quantize_kv(v_tm)
    hm = lambda x: x.transpose(0, 2, 1, 3)
    q3 = jax.random.normal(ks[0], (B, 3, 4, D))
    s = jnp.asarray(77, jnp.int32)
    out = flash_attention_decode(q3, hm(kq), hm(vq), s, scale=scale,
                                 k_scale=hm(kscl), v_scale=hm(vscl))
    ref = _cached_attention(q3, hm(kq), hm(vq), s, scale,
                            k_scale=hm(kscl), v_scale=hm(vscl))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_decode_flash_per_row_starts_with_pads():
    """Per-row starts compose with per-row left-pads (ragged batched
    speculation): row b attends keys in [pad_b, start_b]."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_decode

    B, ML, Hq, Hkv, D = 2, 256, 4, 2, 32
    ks = jax.random.split(jax.random.key(32), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    scale = D ** -0.5
    starts = jnp.asarray([130, 40], jnp.int32)
    pads = jnp.asarray([0, 17], jnp.int32)
    want = jnp.concatenate([
        _cached_attention(q[b:b + 1], kc[b:b + 1], vc[b:b + 1], starts[b],
                          scale, pad_lens=pads[b:b + 1])
        for b in range(B)])
    for got in (flash_attention_decode(q, kc, vc, starts, scale=scale,
                                       pad_lens=pads),
                _cached_attention(q, kc, vc, starts, scale, pad_lens=pads)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=2e-5, rtol=2e-5)


def test_cached_flash_padded_matches_dense_on_real_rows():
    """pad_lens in the PREFILL kernel: key positions below each row's pad
    length are masked and leading all-pad blocks un-fetched. Pad-QUERY
    rows are unread garbage that legitimately differs between impls
    (kernel: zero; dense: uniform V-average) — compare real rows only."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_cached

    B, S, ML, Hq, Hkv, D = 3, 128, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(16), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    pad = jnp.asarray([0, 17, 300], jnp.int32)
    scale = D ** -0.5
    for start in (0, 320):
        s = jnp.asarray(start, jnp.int32)
        out = flash_attention_cached(q, kc, vc, s, scale=scale,
                                     pad_lens=pad)
        ref = _cached_attention(q, kc, vc, s, scale, pad_lens=pad)
        for b in range(B):
            # query position of row i is start+i; real iff >= pad[b]
            real = np.asarray(s + jnp.arange(S) >= pad[b])
            np.testing.assert_allclose(np.asarray(out[b])[real],
                                       np.asarray(ref[b])[real],
                                       atol=2e-5, rtol=2e-5)


def test_cached_flash_padded_int8_matches_dense_on_real_rows():
    from gpu_provisioner_tpu.models.decode import (_cached_attention,
                                                   _quantize_kv)
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_cached

    B, S, ML, Hq, Hkv, D = 2, 128, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(17), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    k_tm = jax.random.normal(ks[1], (B, ML, Hkv, D))
    v_tm = jax.random.normal(ks[2], (B, ML, Hkv, D))
    kq, kscl = _quantize_kv(k_tm)
    vq, vscl = _quantize_kv(v_tm)
    hm = lambda x: x.transpose(0, 2, 1, 3)
    pad = jnp.asarray([5, 140], jnp.int32)
    s = jnp.asarray(256, jnp.int32)
    scale = D ** -0.5
    out = flash_attention_cached(q, hm(kq), hm(vq), s, scale=scale,
                                 k_scale=hm(kscl), v_scale=hm(vscl),
                                 pad_lens=pad)
    ref = _cached_attention(q, hm(kq), hm(vq), s, scale,
                            k_scale=hm(kscl), v_scale=hm(vscl),
                            pad_lens=pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_generate_ragged_flash_prefill_matches_solo():
    """Integration: a left-padded ragged batch under attn_impl='flash' with
    a BLOCK-SIZED prompt (so the padded prefill takes the kernel) generates
    exactly what each row generates alone."""
    import dataclasses
    from gpu_provisioner_tpu.models.decode import generate
    from gpu_provisioner_tpu.models.llama import LlamaConfig, init_params

    cfg = LlamaConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                      dtype="float32", attn_impl="flash")
    params = init_params(jax.random.key(20), cfg)
    PAD = 3
    p0 = jax.random.randint(jax.random.key(21), (1, 128), 4, 128)
    p1 = jax.random.randint(jax.random.key(22), (1, 96), 4, 128)
    batch = jnp.concatenate(
        [p0, jnp.concatenate([jnp.full((1, 32), PAD, jnp.int32), p1], 1)], 0)
    got = generate(params, batch, cfg, max_new_tokens=4, max_len=256,
                   pad_id=PAD)
    cfg_d = dataclasses.replace(cfg, attn_impl="dense")
    solo0 = generate(params, p0, cfg_d, max_new_tokens=4, max_len=256)
    solo1 = generate(params, p1, cfg_d, max_new_tokens=4, max_len=256)
    assert (got[0] == solo0[0]).all()
    assert (got[1] == solo1[0]).all()


@pytest.mark.parametrize("window", [64, 200, 1000])
def test_cached_flash_windowed_matches_dense(window):
    """Sliding-window prefill kernel vs the dense windowed sweep — incl. a
    window larger than the live prefix (degenerates to plain causal)."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_cached

    B, S, ML, Hq, Hkv, D = 2, 128, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(18), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    scale = D ** -0.5
    for start in (0, 320):
        s = jnp.asarray(start, jnp.int32)
        out = flash_attention_cached(q, kc, vc, s, scale=scale,
                                     window=window)
        ref = _cached_attention(q, kc, vc, s, scale, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_cached_flash_windowed_padded_matches_dense():
    """window × pad_lens: both lower bounds compose (max of the two)."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_cached

    B, S, ML, Hq, Hkv, D = 2, 128, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(19), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    pad = jnp.asarray([5, 250], jnp.int32)
    s = jnp.asarray(256, jnp.int32)
    scale = D ** -0.5
    out = flash_attention_cached(q, kc, vc, s, scale=scale, pad_lens=pad,
                                 window=100)
    ref = _cached_attention(q, kc, vc, s, scale, pad_lens=pad, window=100)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [64, 1000])
def test_decode_flash_windowed_matches_dense(window):
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_decode

    B, ML, Hq, Hkv, D = 2, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(20), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    scale = D ** -0.5
    for start in (0, 130, 400):
        s = jnp.asarray(start, jnp.int32)
        out = flash_attention_decode(q, kc, vc, s, scale=scale,
                                     window=window)
        ref = _cached_attention(q, kc, vc, s, scale, window=window)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_decode_flash_windowed_padded_matches_dense():
    """window × pad_lens in the DECODE kernel: the lower bound is the max
    of the pad edge and the window edge, in both the mask and the DMA
    clamp (the standard left-padded SWA serving layout)."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import flash_attention_decode

    B, ML, Hq, Hkv, D = 3, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(23), 3)
    q = jax.random.normal(ks[0], (B, 1, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    # pad edge below, inside, and above the window's lower edge
    pad = jnp.asarray([0, 250, 400], jnp.int32)
    s = jnp.asarray(420, jnp.int32)
    scale = D ** -0.5
    out = flash_attention_decode(q, kc, vc, s, scale=scale, pad_lens=pad,
                                 window=128)
    ref = _cached_attention(q, kc, vc, s, scale, pad_lens=pad, window=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_windowed_int8_kernels_match_dense():
    """window × int8 cache in BOTH kernels: dequant happens before the
    windowed tile mask; the composition must stay wired."""
    from gpu_provisioner_tpu.models.decode import (_cached_attention,
                                                   _quantize_kv)
    from gpu_provisioner_tpu.ops.flash_attention import (
        flash_attention_cached, flash_attention_decode)

    B, S, ML, Hq, Hkv, D = 2, 128, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(24), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    k_tm = jax.random.normal(ks[1], (B, ML, Hkv, D))
    v_tm = jax.random.normal(ks[2], (B, ML, Hkv, D))
    kq, kscl = _quantize_kv(k_tm)
    vq, vscl = _quantize_kv(v_tm)
    hm = lambda x: x.transpose(0, 2, 1, 3)
    scale = D ** -0.5
    s = jnp.asarray(320, jnp.int32)
    out = flash_attention_cached(q, hm(kq), hm(vq), s, scale=scale,
                                 k_scale=hm(kscl), v_scale=hm(vscl),
                                 window=100)
    ref = _cached_attention(q, hm(kq), hm(vq), s, scale,
                            k_scale=hm(kscl), v_scale=hm(vscl), window=100)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    q1 = jax.random.normal(ks[0], (B, 1, Hq, D))
    out = flash_attention_decode(q1, hm(kq), hm(vq), s, scale=scale,
                                 k_scale=hm(kscl), v_scale=hm(vscl),
                                 window=100)
    ref = _cached_attention(q1, hm(kq), hm(vq), s, scale,
                            k_scale=hm(kscl), v_scale=hm(vscl), window=100)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_sliding_window_validation():
    from gpu_provisioner_tpu.models.llama import resolve_attn
    with pytest.raises(ValueError, match="sliding_window must be positive"):
        resolve_attn("dense", 0)
    with pytest.raises(ValueError, match="sliding_window must be positive"):
        resolve_attn("flash", -4)


def test_dense_attention_window_mask():
    """dense_attention(window=...) against a brute-force masked softmax."""
    q, k, v = _qkv(B=1, S=64, Hq=2, Hkv=2, D=16)
    W = 16
    out = dense_attention(q, k, v, causal=True, window=W)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (16 ** -0.5)
    qp = jnp.arange(64)[:, None]
    kp = jnp.arange(64)[None, :]
    mask = (qp >= kp) & (kp > qp - W)
    s = jnp.where(mask[None, None], s, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_cached_flash_supported_gates():
    from gpu_provisioner_tpu.ops.flash_attention import cached_flash_supported
    assert cached_flash_supported(128, 512, 4, 2)
    assert not cached_flash_supported(1, 512, 4, 2)      # decode step
    assert not cached_flash_supported(100, 512, 4, 2)    # ragged prompt
    assert cached_flash_supported(128, 300, 4, 2)        # ≤512: one full block
    assert not cached_flash_supported(128, 600, 4, 2)    # ragged long cache
    assert not cached_flash_supported(128, 512, 4, 3)    # GQA doesn't divide


def test_flash_under_shard_map_on_mesh():
    """impl="flash" path of make_attn_fn: per-device kernel on (data, model)
    shards, seq unsharded."""
    from gpu_provisioner_tpu.models.train import make_attn_fn
    mesh = make_mesh(8, sp=1, tp=2)
    attn = make_attn_fn(mesh, impl="flash")
    q, k, v = _qkv(B=4, S=128, Hq=4, Hkv=2, D=32)
    spec = P(("slice", "data"), "seq", "model", None)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, spec))
    out = jax.jit(attn)(put(q), put(k), put(v))
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_attention_sinks_kernels_match_dense():
    """StreamingLLM sinks: first-N keys stay attendable beyond the window,
    in both serving kernels and the dense sweep (incl. a start where the
    window has moved far past the sinks — the regime sinks exist for)."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import (
        flash_attention_cached, flash_attention_decode)

    B, S, ML, Hq, Hkv, D = 2, 128, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(25), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    scale = D ** -0.5
    W, SK = 64, 4
    s = jnp.asarray(320, jnp.int32)      # window floor 320-64 >> sinks
    out = flash_attention_cached(q, kc, vc, s, scale=scale, window=W,
                                 sinks=SK)
    ref = _cached_attention(q, kc, vc, s, scale, window=W, sinks=SK)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # the sinks must actually matter at this start
    ref_nosink = _cached_attention(q, kc, vc, s, scale, window=W)
    assert float(jnp.max(jnp.abs(ref - ref_nosink))) > 1e-3

    q1 = jax.random.normal(ks[0], (B, 1, Hq, D))
    out = flash_attention_decode(q1, kc, vc, s, scale=scale, window=W,
                                 sinks=SK)
    ref = _cached_attention(q1, kc, vc, s, scale, window=W, sinks=SK)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_attention_sinks_padded_rows():
    """Ragged rows: the sinks are the first REAL tokens (after the pads) —
    per-row sink ranges in both kernels match the dense reference."""
    from gpu_provisioner_tpu.models.decode import _cached_attention
    from gpu_provisioner_tpu.ops.flash_attention import (
        flash_attention_cached, flash_attention_decode)

    B, S, ML, Hq, Hkv, D = 3, 128, 512, 4, 2, 32
    ks = jax.random.split(jax.random.key(26), 3)
    q = jax.random.normal(ks[0], (B, S, Hq, D))
    kc = jax.random.normal(ks[1], (B, Hkv, ML, D))
    vc = jax.random.normal(ks[2], (B, Hkv, ML, D))
    pad = jnp.asarray([0, 17, 140], jnp.int32)
    scale = D ** -0.5
    s = jnp.asarray(320, jnp.int32)
    out = flash_attention_cached(q, kc, vc, s, scale=scale, pad_lens=pad,
                                 window=64, sinks=4)
    ref = _cached_attention(q, kc, vc, s, scale, pad_lens=pad, window=64,
                            sinks=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    q1 = jax.random.normal(ks[0], (B, 1, Hq, D))
    out = flash_attention_decode(q1, kc, vc, s, scale=scale, pad_lens=pad,
                                 window=64, sinks=4)
    ref = _cached_attention(q1, kc, vc, s, scale, pad_lens=pad, window=64,
                            sinks=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_attention_sinks_validation_and_dense():
    from gpu_provisioner_tpu.models.llama import resolve_attn
    with pytest.raises(ValueError, match="requires sliding_window"):
        resolve_attn("dense", None, 4)
    with pytest.raises(ValueError, match="attn_sinks must be"):
        resolve_attn("dense", 32, -1)
    # dense self-attention reference vs brute force
    q, k, v = _qkv(B=1, S=64, Hq=2, Hkv=2, D=16)
    W, SK = 16, 2
    out = dense_attention(q, k, v, causal=True, window=W, sinks=SK)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (16 ** -0.5)
    qp = jnp.arange(64)[:, None]
    kp = jnp.arange(64)[None, :]
    mask = (qp >= kp) & ((kp > qp - W) | (kp < SK))
    s = jnp.where(mask[None, None], s, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(s, axis=-1), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("window", [32, 100, 1000])
def test_flash_windowed_self_attention_matches_dense(window):
    """Windowed flash SELF-attention (training path): forward and the
    per-block-recompute backward vs the dense windowed mask — resident
    variant, windows smaller than / straddling / larger than S."""
    # explicit 128-blocks at S=512: the grid has dead/partial blocks, so
    # the band arithmetic (lo_blocks, live gates, index clamps) is real
    q, k, v = _qkv(B=1, S=512, Hq=2, Hkv=1, D=32)
    ref = dense_attention(q, k, v, window=window)
    out = flash_attention(q, k, v, window=window, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gf = jax.grad(lambda *a: jnp.sum(
        flash_attention(*a, window=window, block_q=128,
                        block_k=128) ** 2), argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.sum(
        dense_attention(*a, window=window) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_windowed_streaming_matches_dense(monkeypatch):
    """Streaming grid with window: live gates + kv index clamps prune to
    the band; forward and backward must stay exact."""
    import importlib
    fa_mod = importlib.import_module("gpu_provisioner_tpu.ops.flash_attention")
    monkeypatch.setattr(fa_mod, "RESIDENT_KV_BUDGET", 0)
    q, k, v = _qkv(B=1, S=512, Hq=2, Hkv=1, D=32)
    W = 100
    ref = dense_attention(q, k, v, window=W)
    out = fa_mod.flash_attention(q, k, v, window=W, block_q=128,
                                 block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    gf = jax.grad(lambda *a: jnp.sum(
        fa_mod.flash_attention(*a, window=W, block_q=128,
                               block_k=128) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda *a: jnp.sum(
        dense_attention(*a, window=W) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)


def test_flash_windowed_lse_and_resolve():
    """with_lse carries the windowed logsumexp; resolve_attn routes
    impl='flash' + window to the kernel (and sinks back to dense)."""
    from gpu_provisioner_tpu.models.llama import resolve_attn
    from gpu_provisioner_tpu.ops.flash_attention import (
        flash_attention, flash_attention_with_lse)
    from gpu_provisioner_tpu.parallel.ring import dense_attention_with_lse

    q, k, v = _qkv(B=1, S=256, Hq=2, Hkv=2, D=32)
    of, lf = flash_attention_with_lse(q, k, v, window=64)
    od, ld = dense_attention_with_lse(q, k, v, window=64)
    np.testing.assert_allclose(np.asarray(of), np.asarray(od),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(ld),
                               atol=2e-5, rtol=2e-5)
    fn = resolve_attn("flash", 64)
    assert fn.func is flash_attention              # real kernel routing
    np.testing.assert_allclose(np.asarray(fn(q, k, v)), np.asarray(od),
                               atol=2e-5, rtol=2e-5)
