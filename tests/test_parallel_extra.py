"""MoE expert parallelism + pipeline parallelism tests (8-dev CPU mesh)."""

import copy

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from gpu_provisioner_tpu.models.llama import PRESETS, forward, init_params
from gpu_provisioner_tpu.models.moe import (PRESETS_MOE, capacity,
                                            init_moe_model,
                                            make_moe_train_state,
                                            make_moe_train_step, moe_forward,
                                            route)
from gpu_provisioner_tpu.models.train import (BATCH_SPEC, default_optimizer,
                                              make_pipeline_train_step,
                                              pipeline_param_specs)
from gpu_provisioner_tpu.parallel import make_mesh

CFG = PRESETS["tiny"]
MOE = PRESETS_MOE["tiny-moe"]


# --- MoE routing -----------------------------------------------------------

def test_route_top1_ample_capacity_places_every_token():
    logits = jax.random.normal(jax.random.key(0), (2, 16, 4))
    dispatch, combine = route(logits, 1, cap=16)
    assert float(dispatch.sum()) == 2 * 16
    np.testing.assert_allclose(np.asarray(combine.sum(axis=(2, 3))), 1.0,
                               atol=1e-5)


def test_route_capacity_drops_overflow():
    # every token prefers expert 0 → only `cap` fit, rest dropped
    logits = jnp.zeros((1, 8, 4)).at[:, :, 0].set(10.0)
    dispatch, _ = route(logits, 1, cap=2)
    assert float(dispatch[..., 0, :].sum()) == 2.0
    assert float(dispatch.sum()) == 2.0


def test_moe_forward_shapes_and_aux():
    params = init_moe_model(jax.random.key(0), MOE)
    logits, aux = moe_forward(params, jnp.zeros((2, 16), jnp.int32), MOE)
    assert logits.shape == (2, 16, MOE.vocab_size)
    assert set(aux) == {"load_balance", "router_z"}
    assert float(aux["load_balance"]) >= 1.0  # ≥ 1 by construction (Switch)


def test_moe_train_step_ep_tp_mesh_loss_decreases():
    mesh = make_mesh(8, ep=2, tp=2)
    assert dict(mesh.shape)["expert"] == 2
    params, opt_state, opt = make_moe_train_state(jax.random.key(0), MOE, mesh)
    step = make_moe_train_step(mesh, MOE, opt)
    toks = jax.random.randint(jax.random.key(1), (8, 65), 0, MOE.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state,
                                       put(toks[:, :-1]), put(toks[:, 1:]))
        losses.append(float(loss))
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


# --- pipeline --------------------------------------------------------------
#
# Pipeline tests run f32 activations: XLA CPU's ChangeOpDataType pass
# CHECK-fails cloning bf16 collectives out of the partial-manual region
# (pipe manual, everything else GSPMD) — a CPU-backend compiler bug; the
# TPU path runs bf16. Forward-only bf16 is still covered below.

from dataclasses import replace

from gpu_provisioner_tpu.models.train import (loss_fn,
                                              make_pipeline_train_state)
from gpu_provisioner_tpu.parallel.pipeline import (from_pipeline_layout,
                                                   interleave_layer_order,
                                                   to_pipeline_layout)
from gpu_provisioner_tpu.parallel.ring import dense_attention

CFG4 = replace(CFG, n_layers=4, dtype="float32")


def test_interleave_layer_order_roundtrip():
    order = interleave_layer_order(8, 2, 2)
    # stage 0 holds virtual stages 0,2 (layers 0,1 then 4,5); stage 1 holds
    # virtual stages 1,3 (layers 2,3 then 6,7)
    assert order == [0, 1, 4, 5, 2, 3, 6, 7]
    blocks = {"w": jnp.arange(8)}
    rt = from_pipeline_layout(to_pipeline_layout(blocks, 8, 2, 2), 8, 2, 2)
    np.testing.assert_array_equal(np.asarray(rt["w"]), np.arange(8))


def test_pipelined_forward_matches_plain():
    from gpu_provisioner_tpu.models.llama import _block, _rmsnorm
    from gpu_provisioner_tpu.parallel.pipeline import pipelined_blocks
    from gpu_provisioner_tpu.parallel.ring import dense_attention

    mesh = make_mesh(8, pp=2)
    host = init_params(jax.random.key(0), CFG)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        copy.deepcopy(host), pipeline_param_specs(CFG))
    toks = jax.random.randint(jax.random.key(1), (8, 32), 0, CFG.vocab_size)

    def piped(params, tokens):
        ad = CFG.act_dtype
        pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)
        x = params["embed"].astype(ad)[tokens]
        apply = pipelined_blocks(
            lambda lp, h: _block(h, lp, CFG, pos, dense_attention),
            mesh, CFG.n_layers, n_micro=2)
        x = apply(params["blocks"], x)
        x = _rmsnorm(x, params["ln_final"], CFG.norm_eps)
        return x.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)

    piped_logits = jax.jit(piped)(
        params, jax.device_put(toks, NamedSharding(mesh, BATCH_SPEC)))
    plain = forward(host, toks, CFG)
    np.testing.assert_allclose(np.asarray(piped_logits), np.asarray(plain),
                               atol=6e-2, rtol=6e-2)  # bf16 activations


def _check_pipeline_matches_plain(mesh, n_chunks, n_micro=2, cfg=CFG4,
                                  batch=8, seq=32, steps=3):
    """First-step loss must equal the plain (non-pipelined) path on the
    same params/batch, and training must make progress."""
    host = init_params(jax.random.key(0), cfg)
    params = copy.deepcopy(host)
    params["blocks"] = to_pipeline_layout(
        params["blocks"], cfg.n_layers, mesh.shape["pipe"], n_chunks)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, pipeline_param_specs(cfg))
    opt = default_optimizer()
    opt_state = jax.jit(opt.init)(params)
    step = make_pipeline_train_step(mesh, cfg, n_micro=n_micro,
                                    n_chunks=n_chunks, optimizer=opt)
    toks = jax.random.randint(jax.random.key(1), (batch, seq + 1), 0,
                              cfg.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    # reference loss: plain non-pipelined path with DENSE attention — a
    # flash cfg must still agree (kernel equivalence ride-along)
    want = float(loss_fn(host, toks[:, :-1], toks[:, 1:], cfg,
                         dense_attention))
    losses = []
    for _ in range(steps):
        params, opt_state, loss = step(params, opt_state,
                                       put(toks[:, :-1]), put(toks[:, 1:]))
        losses.append(float(loss))
    assert abs(losses[0] - want) < 1e-2, (losses[0], want)
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


def test_pipeline_composes_with_tensor_parallel():
    """pp2 x tp2 x dp2: stage weights Megatron-sharded over ``model``
    INSIDE the pipe region (partial-manual shard_map) — loss identical to
    the plain path."""
    _check_pipeline_matches_plain(make_mesh(8, pp=2, tp=2), n_chunks=1)


def test_pipeline_composes_with_sequence_parallel():
    """pp2 x sp2 x dp2: the old sp=1 restriction is lifted — seq stays a
    GSPMD axis inside stages (dense attention, k/v all-gathered)."""
    _check_pipeline_matches_plain(make_mesh(8, pp=2, sp=2), n_chunks=1)


def test_pipeline_interleaved_schedule_matches_plain():
    """pp2 x tp2, n_chunks=2 (Megatron-interleaved): each stage holds two
    non-contiguous layer chunks, micros ride the ring twice — same loss,
    v-fold smaller ramp waste."""
    _check_pipeline_matches_plain(make_mesh(8, pp=2, tp=2), n_chunks=2)


def test_pipeline_composes_with_flash_attention():
    """pp2 x tp2 with attn_impl="flash": stage bodies call the Pallas kernel
    under auto_axes (S=128 so it tiles — shorter S falls back to dense).
    First-step loss must match the plain non-pipelined dense path."""
    _check_pipeline_matches_plain(make_mesh(8, pp=2, tp=2), n_chunks=1,
                                  cfg=replace(CFG4, attn_impl="flash"),
                                  batch=4, seq=128, steps=2)


def test_pipeline_train_step_loss_decreases():
    mesh = make_mesh(8, pp=2)  # dp4 x pipe2
    params, opt_state, opt = make_pipeline_train_state(
        jax.random.key(0), CFG4, mesh)
    step = make_pipeline_train_step(mesh, CFG4, n_micro=2, optimizer=opt)
    toks = jax.random.randint(jax.random.key(1), (8, 33), 0, CFG4.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state,
                                       put(toks[:, :-1]), put(toks[:, 1:]))
        losses.append(float(loss))
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


def test_moe_train_step_with_zigzag_seq_parallel():
    """The seq_schedule knob reaches the MoE step: zigzag + sp2 trains with
    a finite, plain-path-consistent loss."""
    mesh = make_mesh(8, sp=2, ep=2)
    cfg = replace(MOE, seq_schedule="zigzag")
    params, opt_state, opt = make_moe_train_state(jax.random.key(0), cfg, mesh)
    step = make_moe_train_step(mesh, cfg, opt)
    toks = jax.random.randint(jax.random.key(1), (8, 65), 0, cfg.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    losses = []
    for _ in range(3):
        params, opt_state, loss = step(params, opt_state,
                                       put(toks[:, :-1]), put(toks[:, 1:]))
        losses.append(float(loss))
    assert all(np.isfinite(losses)) and losses[-1] < losses[0]
