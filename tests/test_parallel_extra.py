"""MoE expert parallelism + pipeline parallelism tests (8-dev CPU mesh)."""

import copy

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from gpu_provisioner_tpu.models.llama import PRESETS, forward, init_params
from gpu_provisioner_tpu.models.moe import (PRESETS_MOE, capacity,
                                            init_moe_model,
                                            make_moe_train_state,
                                            make_moe_train_step, moe_forward,
                                            route)
from gpu_provisioner_tpu.models.train import (BATCH_SPEC, default_optimizer,
                                              make_pipeline_train_step,
                                              pipeline_param_specs)
from gpu_provisioner_tpu.parallel import make_mesh

CFG = PRESETS["tiny"]
MOE = PRESETS_MOE["tiny-moe"]


# --- MoE routing -----------------------------------------------------------

def test_route_top1_ample_capacity_places_every_token():
    logits = jax.random.normal(jax.random.key(0), (2, 16, 4))
    dispatch, combine = route(logits, 1, cap=16)
    assert float(dispatch.sum()) == 2 * 16
    np.testing.assert_allclose(np.asarray(combine.sum(axis=(2, 3))), 1.0,
                               atol=1e-5)


def test_route_capacity_drops_overflow():
    # every token prefers expert 0 → only `cap` fit, rest dropped
    logits = jnp.zeros((1, 8, 4)).at[:, :, 0].set(10.0)
    dispatch, _ = route(logits, 1, cap=2)
    assert float(dispatch[..., 0, :].sum()) == 2.0
    assert float(dispatch.sum()) == 2.0


def test_moe_forward_shapes_and_aux():
    params = init_moe_model(jax.random.key(0), MOE)
    logits, aux = moe_forward(params, jnp.zeros((2, 16), jnp.int32), MOE)
    assert logits.shape == (2, 16, MOE.vocab_size)
    assert set(aux) == {"load_balance", "router_z"}
    assert float(aux["load_balance"]) >= 1.0  # ≥ 1 by construction (Switch)


def test_moe_train_step_ep_tp_mesh_loss_decreases():
    mesh = make_mesh(8, ep=2, tp=2)
    assert dict(mesh.shape)["expert"] == 2
    params, opt_state, opt = make_moe_train_state(jax.random.key(0), MOE, mesh)
    step = make_moe_train_step(mesh, MOE, opt)
    toks = jax.random.randint(jax.random.key(1), (8, 65), 0, MOE.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state,
                                       put(toks[:, :-1]), put(toks[:, 1:]))
        losses.append(float(loss))
    assert losses[-1] < losses[0] and all(np.isfinite(losses))


# --- pipeline --------------------------------------------------------------

def _pipeline_params(mesh):
    params = init_params(jax.random.key(0), CFG)
    specs = pipeline_param_specs(CFG)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def test_pipelined_forward_matches_plain():
    from gpu_provisioner_tpu.models.llama import _block, _rmsnorm
    from gpu_provisioner_tpu.parallel.pipeline import pipelined_blocks
    from gpu_provisioner_tpu.parallel.ring import dense_attention

    mesh = make_mesh(8, pp=2)
    host = init_params(jax.random.key(0), CFG)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        copy.deepcopy(host), pipeline_param_specs(CFG))
    toks = jax.random.randint(jax.random.key(1), (8, 32), 0, CFG.vocab_size)

    def piped(params, tokens):
        ad = CFG.act_dtype
        pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)
        x = params["embed"].astype(ad)[tokens]
        apply = pipelined_blocks(
            lambda lp, h: _block(h, lp, CFG, pos, dense_attention),
            mesh, CFG.n_layers, n_micro=2)
        x = apply(params["blocks"], x)
        x = _rmsnorm(x, params["ln_final"], CFG.norm_eps)
        return x.astype(jnp.float32) @ params["lm_head"].astype(jnp.float32)

    piped_logits = jax.jit(piped)(
        params, jax.device_put(toks, NamedSharding(mesh, BATCH_SPEC)))
    plain = forward(host, toks, CFG)
    np.testing.assert_allclose(np.asarray(piped_logits), np.asarray(plain),
                               atol=3e-2, rtol=3e-2)  # bf16 activations


def test_pipeline_train_step_loss_decreases():
    mesh = make_mesh(8, pp=2)  # dp4 × pipe2
    params = _pipeline_params(mesh)
    opt = default_optimizer()
    opt_state = jax.jit(opt.init)(params)
    step = make_pipeline_train_step(mesh, CFG, n_micro=2, optimizer=opt)
    toks = jax.random.randint(jax.random.key(1), (8, 33), 0, CFG.vocab_size)
    put = lambda x: jax.device_put(x, NamedSharding(mesh, BATCH_SPEC))
    losses = []
    for _ in range(4):
        params, opt_state, loss = step(params, opt_state,
                                       put(toks[:, :-1]), put(toks[:, 1:]))
        losses.append(float(loss))
    assert losses[-1] < losses[0] and all(np.isfinite(losses))
