"""Capacity-aware placement soaks (PR 10).

Four scenario groups over the zone × generation × tier placement walk:

1. **Zonal stockout survival** — a 50-claim wave with one of three zones
   chaos-dry: every claim lands in a surviving zone, nothing wedges, and the
   stockout memo holds the dry zone to one probe per TTL window.
2. **Spot preemption reclaim** — the cloud preempts every spot slice in a
   wave; the SpotPreempted repair path replaces them within budget, the
   mass-delete breaker never trips, and on-demand neighbors are untouched.
3. **Crash × fallback matrix** — the operator dies mid-fallback-walk; the
   durable attempt annotation + conflict adoption resume the walk at the
   right candidate with no duplicate pool and no re-probe of verdicted zones.
4. **Zero capacity everywhere** — exhausted across every candidate is the
   terminal ``CreateError(reason=Stockout)``: Warning Event, claim deleted,
   and followers inside the memo TTL terminate at zero cloud probes.

Deterministic for a fixed seed, like the chaos suite (CHAOS_SEED=<n>
make capacity reproduces a failure).
"""

import asyncio
from collections import defaultdict

import pytest

from gpu_provisioner_tpu import catalog, chaos
from gpu_provisioner_tpu.apis import karpenter as kv1
from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.core import Event, Node
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import CONDITION_READY
from gpu_provisioner_tpu.chaos import SPOT_PREEMPTED
from gpu_provisioner_tpu.controllers.health import REPAIR_STATS
from gpu_provisioner_tpu.controllers.metrics import (
    FALLBACK_PLACEMENTS_TOTAL, SPOT_PREEMPTIONS_TOTAL, STOCKOUTS_TOTAL,
    update_runtime_gauges,
)
from gpu_provisioner_tpu.envtest import EnvtestOptions, RestartableEnv
from gpu_provisioner_tpu.errors import REASON_STOCKOUT
from gpu_provisioner_tpu.fake import make_nodeclaim
from gpu_provisioner_tpu.providers import placement
from gpu_provisioner_tpu.providers.instance import (
    PLACEMENT_ATTEMPTS_ANNOTATION,
)
from gpu_provisioner_tpu.providers.placement import (
    PlacementEngine, note_spot_preemption,
)
from gpu_provisioner_tpu.runtime.client import NotFoundError

from .conftest import async_test
from .test_catalog import reqs
from .test_chaos import (
    SEED, assert_no_leaks_and_drained, chaos_env, converge,
)

pytestmark = pytest.mark.capacity

ZONE_A = "us-central2-a"
ZONE_B = "us-central2-b"      # the chaos-dry zone in zonal_stockout (*-b)
ZONE_C = "us-central2-c"


def spot_claim(name: str) -> NodeClaim:
    """A claim pinned to the spot capacity tier."""
    nc = make_nodeclaim(name)
    nc.spec.requirements.append(kv1.NodeSelectorRequirement(
        key=wk.CAPACITY_TYPE_LABEL, operator=kv1.IN,
        values=[wk.CAPACITY_TYPE_SPOT]))
    return nc


# ------------------------------------------------------------- engine units

def test_candidates_zone_varies_fastest_and_first_is_legacy():
    eng = PlacementEngine(["pz-a", "pz-b"])
    r = reqs((wk.INSTANCE_TYPE_LABEL, kv1.IN, ["tpu-v5e-8"]))
    cands = eng.candidates(r)
    assert [c.zone for c in cands[:2]] == ["pz-a", "pz-b"]
    # first candidate is byte-identical to the legacy single answer
    assert cands[0].shape.name == catalog.resolve(r).name
    assert cands[0].tier == wk.CAPACITY_TYPE_ON_DEMAND
    # an explicit zone requirement is both a ranking and a filter
    r2 = reqs((wk.INSTANCE_TYPE_LABEL, kv1.IN, ["tpu-v5e-8"]),
              (wk.ZONE_LABEL, kv1.IN, ["pz-b"]))
    assert [c.zone for c in eng.candidates(r2)] == ["pz-b"]


@async_test
async def test_spot_demotion_hysteresis_sinks_flapping_zone():
    """Enough preemptions inside the window demote a spot zone to the back
    of the candidate order — demoted, not excluded."""
    eng = PlacementEngine(["dz-a", "dz-b"], demote_threshold=2,
                          demote_window=60.0)
    try:
        note_spot_preemption("dz-a")
        assert not eng.spot_demoted("dz-a"), "one preemption is not a flap"
        note_spot_preemption("dz-a")
        assert eng.spot_demoted("dz-a")
        r = reqs((wk.INSTANCE_TYPE_LABEL, kv1.IN, ["tpu-v5e-8"]),
                 (wk.CAPACITY_TYPE_LABEL, kv1.IN, [wk.CAPACITY_TYPE_SPOT]))
        assert [c.zone for c in eng.candidates(r)] == ["dz-b", "dz-a"]
        # the demotion only reorders the SPOT tier
        r_od = reqs((wk.INSTANCE_TYPE_LABEL, kv1.IN, ["tpu-v5e-8"]))
        assert [c.zone for c in eng.candidates(r_od)] == ["dz-a", "dz-b"]
    finally:
        placement._PREEMPT_TIMES.pop("dz-a", None)


# ------------------------------------------------- zonal stockout survival

WAVE = 50


@async_test
async def test_zonal_stockout_wave_routes_around_dry_zone():
    """One of three zones dries up mid-wave: 100% of the wave lands in the
    surviving zones, zero claims wedge or terminate, and the stockout memo
    holds the dry zone to ≤ 1 probe per TTL window.

    Reconciles are serialized (one worker) so the probe count is exact: the
    first claim to walk past the drained preferred zone pays ONE probe of
    the dry zone; every follower is memo-suppressed."""
    policy = chaos.profile("zonal_stockout", seed=SEED)
    zones = {
        ZONE_A: {"v5e": 8},        # room for exactly one v5e-8 slice
        ZONE_B: {"v5e": 10_000},   # ample chips — but chaos-dry
        ZONE_C: {"v5e": 10_000},
    }
    stockouts_b0 = placement.STOCKOUTS.get(ZONE_B, 0)
    fallbacks0 = placement.FALLBACKS.get((ZONE_A, ZONE_C), 0)
    ctr_stockout0 = STOCKOUTS_TOTAL.labels(ZONE_B)._value.get()
    ctr_fallback0 = FALLBACK_PLACEMENTS_TOTAL.labels(ZONE_A, ZONE_C)._value.get()
    names = [f"zs{i}" for i in range(WAVE)]
    async with chaos_env(policy, launch_timeout=30.0, zones=zones,
                         stockout_memo_ttl=30.0,
                         max_concurrent_reconciles=1) as env:
        # the first claim drains zone a, so the rest of the wave has to walk
        # through the chaos-dry zone b before landing in c
        await env.client.create(make_nodeclaim(names[0]))
        await env.wait_ready(names[0], timeout=20)
        for n in names[1:]:
            await env.client.create(make_nodeclaim(n))
        ready, gone = await converge(env, names, timeout=45.0)
        assert ready == set(names), f"claims lost to the dry zone: {sorted(gone)}"
        # 100% placed in surviving zones — read the zone off every node
        nodes = await env.client.list(Node)
        landed = {n.metadata.labels.get(wk.ZONE_LABEL) for n in nodes}
        assert landed <= {ZONE_A, ZONE_C}, f"nodes in the dry zone: {landed}"
        assert ZONE_C in landed, "the fallback zone never received the wave"
        # ≤ 1 probe of the dry zone per memo TTL (whole wave fits one window)
        dry_probes = env.cloud.nodepools.calls[f"begin_create:{ZONE_B}"]
        assert dry_probes == 1, f"dry zone probed {dry_probes}× in one TTL"
        # preferred zone: one filling create + one exhausted probe
        assert env.cloud.nodepools.calls[f"begin_create:{ZONE_A}"] <= 2
        await assert_no_leaks_and_drained(env, ready)
        update_runtime_gauges(env.manager)
    assert placement.STOCKOUTS.get(ZONE_B, 0) > stockouts_b0
    assert placement.FALLBACKS.get((ZONE_A, ZONE_C), 0) > fallbacks0
    assert STOCKOUTS_TOTAL.labels(ZONE_B)._value.get() > ctr_stockout0
    assert (FALLBACK_PLACEMENTS_TOTAL.labels(ZONE_A, ZONE_C)._value.get()
            > ctr_fallback0)


# ------------------------------------------------- spot preemption reclaim

def _start_replacer(env, builders):
    """KAITO simulation (tests/test_health.py idiom): repair deletes a
    NodeClaim; the workspace controller recreates it — spot claims come back
    as spot claims."""
    counts = defaultdict(int)

    async def run():
        # provlint: disable=unbounded-sleep-poll — not a poll-until: this
        # simulator runs until the test cancels the returned task
        while True:
            for name, build in builders.items():
                try:
                    await env.client.get(NodeClaim, name)
                except NotFoundError:
                    try:
                        await env.client.create(build(name))
                        counts[name] += 1
                    except Exception:  # noqa: BLE001 — create race; next lap
                        pass
                except Exception:  # noqa: BLE001 — transient read error
                    pass
            await asyncio.sleep(0.05)

    return asyncio.create_task(run()), counts


async def _wait_wave_recovered(env, policy, names, timeout=25.0):
    """Wave fired, every claim Ready again, no preemption notice left."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        ok = policy.injected_total("spot_preempt:") >= 1
        if ok:
            for name in names:
                try:
                    nc = await env.client.get(NodeClaim, name)
                except NotFoundError:
                    ok = False
                    break
                if not nc.status_conditions.is_true(CONDITION_READY):
                    ok = False
                    break
        if ok:
            nodes = await env.client.list(Node)
            if any(c.type == SPOT_PREEMPTED and c.status == "True"
                   for n in nodes for c in n.status.conditions):
                ok = False
        if ok:
            return
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(
                f"spot wave never recovered: injected="
                f"{policy.injected_total('spot_preempt:')}")
        await asyncio.sleep(0.05)


@async_test
async def test_spot_reclaim_wave_replaced_within_repair_budget():
    """The spot_reclaim profile preempts every spot slice during its wave:
    the SpotPreempted repair path (tight spot toleration) replaces the
    claims, replacements created after the wave closes survive, the
    mass-delete breaker never trips, and on-demand neighbors are never
    touched."""
    policy = chaos.profile("spot_reclaim", seed=SEED)
    spot_names = ["sp0", "sp1"]
    od_names = ["od0", "od1"]
    started0 = REPAIR_STATS["started"]
    throttled0 = REPAIR_STATS["throttled"]
    preempt0 = placement.SPOT_PREEMPTIONS.get(ZONE_B, 0)
    ctr_preempt0 = SPOT_PREEMPTIONS_TOTAL.labels(ZONE_B)._value.get()
    replacer = None
    async with chaos_env(policy, launch_timeout=20.0,
                         repair_toleration=0.2,
                         spot_reclaim_grace=1.0) as env:
        try:
            for n in od_names:
                await env.client.create(make_nodeclaim(n))
            for n in spot_names:
                await env.client.create(spot_claim(n))
            builders = {n: spot_claim for n in spot_names}
            builders.update({n: make_nodeclaim for n in od_names})
            replacer, counts = _start_replacer(env, builders)
            await _wait_wave_recovered(env, policy, spot_names + od_names)
            # spot pools really are spot-tier (the preemption sweep's gate)
            for n in spot_names:
                assert env.cloud.nodepools.pools[n].config.spot
            # repair replaced at least one preempted slice; never throttled
            assert REPAIR_STATS["started"] > started0, \
                "preemption notice never reached the repair path"
            assert REPAIR_STATS["throttled"] == throttled0, \
                "breaker/budget tripped on an uncorrelated spot wave"
            # on-demand claims rode out the wave untouched
            assert all(counts[n] == 0 for n in od_names), dict(counts)
            assert any(counts[n] > 0 for n in spot_names), \
                "no spot claim was ever replaced"
        finally:
            if replacer is not None:
                replacer.cancel()
        await assert_no_leaks_and_drained(
            env, set(spot_names + od_names))
        update_runtime_gauges(env.manager)
    assert placement.SPOT_PREEMPTIONS.get(ZONE_B, 0) > preempt0
    assert SPOT_PREEMPTIONS_TOTAL.labels(ZONE_B)._value.get() > ctr_preempt0


# ------------------------------------------------- crash × fallback matrix

@pytest.mark.parametrize("point", ["after_pool_begin_create",
                                   "before_lro_done"])
@async_test
async def test_stockout_crash_resumes_walk_without_duplicate_pool(point):
    """Die mid-fallback (the preferred zone already verdicted dry, the
    fallback create in flight): restart must resume the walk at the right
    candidate — the durable attempt annotation skips the dry zone without a
    re-probe, and conflict adoption resumes the in-flight create instead of
    double-creating."""
    crashes = chaos.CrashPoints(at=point, seed=SEED)
    zones = {ZONE_A: {"v5e": 0},       # dry from the start
             ZONE_C: {"v5e": 64}}
    opts = EnvtestOptions(gc_interval=0.1, leak_grace=0.1, zones=zones,
                          stockout_memo_ttl=30.0, crashes=crashes)
    opts.lifecycle.launch_timeout = 20.0
    opts.lifecycle.registration_timeout = 20.0
    renv = RestartableEnv(opts)
    await renv.start()
    try:
        await renv.client.create(make_nodeclaim("cr0"))
        await asyncio.wait_for(crashes.crashed.wait(), 15)
        assert crashes.last == (point, "cr0")
        nc = await renv.client.get(NodeClaim, "cr0")
        attempts = nc.metadata.annotations.get(
            PLACEMENT_ATTEMPTS_ANNOTATION, "")
        assert f"{ZONE_A}/tpu-v5e-8/{wk.CAPACITY_TYPE_ON_DEMAND}" in attempts
        probes_a = renv.cloud.nodepools.calls[f"begin_create:{ZONE_A}"]
        assert probes_a == 1

        await renv.restart()
        nc = await renv.wait_ready("cr0", timeout=25)
        assert nc.status.provider_id
        # exactly one pool, landed in the fallback zone
        assert set(renv.cloud.nodepools.pools) == {"cr0"}
        pool = renv.cloud.nodepools.pools["cr0"]
        assert pool.config.labels[wk.ZONE_LABEL] == ZONE_C
        # the verdicted zone was never re-probed (annotation, not memo — the
        # restarted incarnation's memo starts empty), and the fallback zone
        # saw ONE placement probe: the resume adopted via 409, which the
        # fake deliberately does not count as a probe
        assert renv.cloud.nodepools.calls[f"begin_create:{ZONE_A}"] == probes_a
        assert renv.cloud.nodepools.calls[f"begin_create:{ZONE_C}"] == 1
        assert renv.incarnations == 2
    finally:
        await renv.crash()


# ------------------------------------------------- zero capacity anywhere

@async_test
async def test_zero_capacity_everywhere_is_terminal_with_event():
    """Exhausted across EVERY candidate: the claim gets the terminal
    ``CreateError(reason=Stockout)`` treatment — Warning Event, claim
    deleted, nothing leaked — and a follower inside the memo TTL terminates
    at ZERO additional cloud probes."""
    zones = {ZONE_A: {"v5e": 0}, ZONE_C: {"v5e": 0}}
    async with chaos_env(None, launch_timeout=10.0, zones=zones,
                         stockout_memo_ttl=30.0) as env:
        await env.client.create(make_nodeclaim("zc0"))
        await env.wait_gone("zc0", timeout=10)
        events = await env.client.list(Event)
        assert any(e.reason == REASON_STOCKOUT for e in events), \
            [e.reason for e in events]
        probes = {z: env.cloud.nodepools.calls[f"begin_create:{z}"]
                  for z in zones}
        assert probes == {ZONE_A: 1, ZONE_C: 1}, probes
        # follower: both zones memo-suppressed — terminal without a probe
        await env.client.create(make_nodeclaim("zc1"))
        await env.wait_gone("zc1", timeout=10)
        for z, n in probes.items():
            assert env.cloud.nodepools.calls[f"begin_create:{z}"] == n, \
                f"memo failed to suppress a re-probe of {z}"
        await assert_no_leaks_and_drained(env, set())
