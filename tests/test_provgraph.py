"""provgraph: interprocedural rule tests over package-shaped fixtures,
waiver semantics with the ``provgraph`` tag, the CLI, provlint's
``--changed`` mode, and the enforcement test that keeps the real tree
clean.

Unlike provlint's single-file snippets, each fixture here is a miniature
*package* under tests/analysis_fixtures/provgraph/ — the rules are
relations between modules (import edges, wake producers, call paths, doc
entries), so the fixture has to be the whole relation, not one side of
it."""

import json
import os
import subprocess
from pathlib import Path

import pytest

from gpu_provisioner_tpu.analysis import provgraph
from gpu_provisioner_tpu.analysis.provlint import changed_py_files
from gpu_provisioner_tpu.analysis.provlint import main as provlint_main

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "analysis_fixtures" / "provgraph"
PACKAGE = REPO / "gpu_provisioner_tpu"
REAL_DOC = REPO / "docs" / "OBSERVABILITY.md"


def analyze(pkg: str):
    root = FIXTURES / pkg
    doc = root / "OBSERVABILITY.md"
    return provgraph.analyze(root, doc if doc.is_file() else None)


# One (rule, fixture-pair, expected-finding-count) row per rule.
CASES = [
    ("PG001", "pg001", 3),   # runtime↑, cloud-specific, providers→controllers
    ("PG002", "pg002", 1),
    ("PG003", "pg003", 1),
    ("PG004", "pg004", 2),   # one per direction
    ("PG005", "pg005", 1),   # shard seam imported from outside it
]


@pytest.mark.parametrize("rule_id,stem,expected",
                         CASES, ids=[c[0] for c in CASES])
def test_rule_fires_on_bad_fixture(rule_id, stem, expected):
    findings = analyze(f"{stem}_bad")
    assert [f.rule for f in findings] == [rule_id] * expected, findings


@pytest.mark.parametrize("rule_id,stem,expected",
                         CASES, ids=[c[0] for c in CASES])
def test_rule_abstains_on_good_fixture(rule_id, stem, expected):
    assert analyze(f"{stem}_good") == []


def test_pg003_flags_the_call_site_not_the_helper():
    # The helper's own unfenced begin_create is PL003's jurisdiction; the
    # graph rule must anchor on the laundering CALL in launch().
    (finding,) = analyze("pg003_bad")
    assert finding.path.endswith("providers/instance.py")
    assert "_do_create" in finding.message
    assert finding.line == 12


def test_pg002_anchors_comment_annotations_on_their_code_line():
    (finding,) = analyze("pg002_bad")
    assert finding.line == 5  # the return, not a dangling comment line


def test_pg004_reports_both_directions():
    paths = sorted(f.path for f in analyze("pg004_bad"))
    assert paths[0].endswith("OBSERVABILITY.md")      # documented ghost
    assert paths[1].endswith("metrics.py")            # undocumented family


def test_waiver_with_reason_silences_the_rule():
    assert analyze("pg001_waived") == []


def test_malformed_waivers_are_pg000():
    findings = analyze("pg000_bad")
    assert [f.rule for f in findings] == ["PG000", "PG000"]
    assert "mandatory" in findings[0].message          # reason missing
    assert "pg999" in findings[1].message              # unknown rule


def test_waiver_tags_do_not_cross_match():
    # A provgraph waiver must not silence provlint and vice versa: the same
    # fixture parsed under the provlint tag yields no waivers at all.
    from gpu_provisioner_tpu.analysis.provlint import parse_waivers
    lines = (FIXTURES / "pg001_waived" / "controllers" /
             "recovery.py").read_text().splitlines()
    known = {"pg001", "layering-violation"}
    graph = parse_waivers(lines, known, tag="provgraph")
    lint = parse_waivers(lines, known, tag="provlint")
    assert graph.exact and not graph.malformed
    assert not lint.exact and not lint.by_line and not lint.malformed


def test_graph_resolves_relative_imports_and_refines_aliases():
    g = provgraph.build_graph(FIXTURES / "pg001_bad")
    edges = {(e.src, e.dst) for e in g.import_edges}
    # `from ..controllers import loops` records the refined module edge
    assert ("pg001_bad.providers.instance",
            "pg001_bad.controllers.loops") in edges
    assert ("pg001_bad.controllers.recovery",
            "pg001_bad.providers.gcp") in edges


def test_whole_tree_is_clean():
    """The enforcement gate: zero unwaived findings across the real
    package + the real metrics catalog. Layering debt must be waived in
    place with a reason (the recovery.py GCP-constant import carries the
    ROADMAP item-4 pointer), not left silent."""
    findings = provgraph.analyze(PACKAGE, REAL_DOC)
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------------------------------------ CLI

def test_cli_list_rules(capsys):
    assert provgraph.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("PG001", "PG002", "PG003", "PG004"):
        assert rid in out


def test_cli_exit_codes(capsys):
    bad = str(FIXTURES / "pg001_bad")
    assert provgraph.main([bad, "--docs", ""]) == 1
    assert provgraph.main([str(FIXTURES / "pg001_good"),
                           "--docs", ""]) == 0
    assert provgraph.main([str(FIXTURES / "missing"), "--docs", ""]) == 2
    capsys.readouterr()


def test_cli_select_and_json(capsys):
    bad = str(FIXTURES / "pg001_bad")
    # PG002 alone finds nothing in a layering fixture
    assert provgraph.main([bad, "--docs", "", "--select", "pg002"]) == 0
    assert provgraph.main([bad, "--docs", "", "--select", "nope"]) == 2
    capsys.readouterr()
    assert provgraph.main([bad, "--docs", "", "--json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert {f["rule"] for f in payload} == {"PG001"} and len(payload) == 3


# ------------------------------------------------- provlint --changed

def _git(cwd, *argv):
    subprocess.run(["git", *argv], cwd=cwd, check=True,
                   capture_output=True,
                   env={**os.environ,
                        "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@t",
                        "GIT_COMMITTER_NAME": "t",
                        "GIT_COMMITTER_EMAIL": "t@t"})


def test_changed_py_files_lists_modified_and_untracked(tmp_path,
                                                       monkeypatch):
    _git(tmp_path, "init", "-q")
    (tmp_path / "clean.py").write_text("A = 1\n")
    (tmp_path / "dirty.py").write_text("B = 1\n")
    (tmp_path / "notes.md").write_text("prose\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (tmp_path / "dirty.py").write_text("B = 2\n")
    (tmp_path / "fresh.py").write_text("C = 3\n")
    (tmp_path / "fresh.md").write_text("prose\n")
    monkeypatch.chdir(tmp_path)
    names = sorted(p.name for p in changed_py_files([tmp_path]))
    assert names == ["dirty.py", "fresh.py"]   # not clean.py, never .md


def test_changed_mode_scopes_and_degrades(tmp_path, tmp_path_factory,
                                          monkeypatch, capsys):
    _git(tmp_path, "init", "-q")
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text("A = 1\n")
    (tmp_path / "outside.py").write_text("B = 1\n")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    (pkg / "mod.py").write_text("A = 2\n")
    (tmp_path / "outside.py").write_text("B = 2\n")
    monkeypatch.chdir(tmp_path)
    # the scope argument narrows the changed set, exactly like a walk
    assert [p.name for p in changed_py_files([pkg])] == ["mod.py"]
    assert provlint_main(["--changed", str(pkg)]) == 0
    capsys.readouterr()
    # outside a git checkout the mode degrades loudly, not silently
    nowhere = tmp_path_factory.mktemp("no-repo")
    monkeypatch.chdir(nowhere)
    assert provlint_main(["--changed", "."]) == 2
    assert "--changed needs a git checkout" in capsys.readouterr().err
