"""provlint: rule-catalog tests over the fixture corpus, waiver semantics,
and the enforcement test that keeps the real tree clean.

Each rule gets ≥1 true-positive and ≥1 true-negative snippet under
tests/analysis_fixtures/ (excluded from normal lint walks). Roles are forced
per fixture so a controllers-scoped rule can be exercised against a snippet
that lives in the test tree.
"""

from pathlib import Path

import pytest

from gpu_provisioner_tpu.analysis import RULES, lint_file, lint_paths
from gpu_provisioner_tpu.analysis.provlint import (
    ROLE_CONTROLLERS, ROLE_PACKAGE, ROLE_PROVIDERS, ROLE_RUNTIME, ROLE_TESTS,
    infer_roles, main,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "analysis_fixtures"

CONTROL_PLANE = frozenset({ROLE_PACKAGE, ROLE_CONTROLLERS, ROLE_PROVIDERS,
                           ROLE_RUNTIME})


def rules_fired(path: Path, roles) -> set[str]:
    return {f.rule for f in lint_file(path, roles=frozenset(roles))}


# One (rule, fixture-pair, forced-roles, expected-finding-count) row per rule.
CASES = [
    ("PL001", "pl001", {ROLE_PACKAGE, ROLE_RUNTIME}, 3),
    ("PL002", "pl002", {ROLE_PACKAGE}, 3),
    ("PL003", "pl003", {ROLE_PACKAGE, ROLE_PROVIDERS}, 3),
    ("PL004", "pl004", {ROLE_PACKAGE, ROLE_CONTROLLERS}, 4),
    ("PL005", "pl005", {ROLE_PACKAGE}, 2),
    ("PL006", "pl006", {ROLE_PACKAGE}, 1),
    ("PL007", "pl007", {ROLE_PACKAGE}, 2),
    ("PL008", "pl008", {ROLE_PACKAGE, ROLE_CONTROLLERS}, 4),
    ("PL009", "pl009", {ROLE_PACKAGE, ROLE_PROVIDERS}, 2),
    ("PL010", "pl010", {ROLE_TESTS}, 1),
    ("PL011", "pl011", {ROLE_TESTS}, 1),
    ("PL012", "pl012", {ROLE_PACKAGE}, 2),
    ("PL013", "pl013", {ROLE_PACKAGE}, 3),
    ("PL014", "pl014", {ROLE_CONTROLLERS}, 2),
    ("PL015", "pl015", {ROLE_RUNTIME}, 2),
]


@pytest.mark.parametrize("rule_id,stem,roles,expected",
                         CASES, ids=[c[0] for c in CASES])
def test_rule_fires_on_bad_fixture(rule_id, stem, roles, expected):
    findings = [f for f in lint_file(FIXTURES / f"{stem}_bad.py",
                                     roles=frozenset(roles))
                if f.rule == rule_id]
    assert len(findings) == expected, (
        f"{rule_id} expected {expected} finding(s), got: {findings}")


@pytest.mark.parametrize("rule_id,stem,roles,expected",
                         CASES, ids=[c[0] for c in CASES])
def test_rule_abstains_on_good_fixture(rule_id, stem, roles, expected):
    findings = [f for f in lint_file(FIXTURES / f"{stem}_good.py",
                                     roles=frozenset(roles))
                if f.rule == rule_id]
    assert findings == [], f"{rule_id} false positives: {findings}"


def test_controller_calling_mutation_is_flagged_even_with_fence():
    # PL003's controller arm: controllers never call cloud mutations at
    # all — a fence in the same function doesn't excuse the layering.
    findings = [f for f in lint_file(
        FIXTURES / "pl003_good.py",
        roles=frozenset({ROLE_PACKAGE, ROLE_CONTROLLERS}))
        if f.rule == "PL003"]
    assert len(findings) == 3
    assert all("provider seam" in f.message for f in findings)


# ------------------------------------------------------------------ waivers

def test_waiver_semantics():
    findings = lint_file(FIXTURES / "waivers.py",
                         roles=frozenset({ROLE_PACKAGE, ROLE_CONTROLLERS}))
    by_rule = {}
    for f in findings:
        by_rule.setdefault(f.rule, []).append(f)
    # trailing and comment-only waivers suppressed their violations …
    waived_lines = {5, 11}
    assert not any(f.line in waived_lines for f in by_rule.get("PL008", []))
    # … the two unwaived violations remain …
    assert len(by_rule.get("PL008", [])) == 2
    # … and the malformed waivers (no reason / unknown rule) are findings.
    pl000 = by_rule.get("PL000", [])
    assert len(pl000) == 2
    assert any("mandatory" in f.message for f in pl000)
    assert any("unknown rule" in f.message for f in pl000)


# ------------------------------------------------------------- engine bits

def test_role_inference():
    assert ROLE_CONTROLLERS in infer_roles(
        REPO / "gpu_provisioner_tpu" / "controllers" / "health.py")
    assert ROLE_PACKAGE in infer_roles(
        REPO / "gpu_provisioner_tpu" / "envtest.py")
    assert infer_roles(REPO / "tests" / "test_provlint.py") == frozenset(
        {ROLE_TESTS})


def test_role_inference_survives_repo_dir_named_like_the_package():
    """Review-pass regression: a checkout directory named like the package
    must not shadow the package dir — first-occurrence matching silently
    dropped the controllers role (and with it PL001/PL003/PL004/PL008)."""
    path = Path("/home/u/gpu_provisioner_tpu/gpu_provisioner_tpu/"
                "controllers/health.py")
    assert ROLE_CONTROLLERS in infer_roles(path)


def test_select_subset_keeps_foreign_waivers_valid():
    """Review-pass regression: --select derived waiver validity from the
    filtered rule set, so a pristine tree exited 1 with PL000 noise for
    every waiver naming an unselected rule."""
    assert main(["--select", "PL001",
                 str(REPO / "gpu_provisioner_tpu"),
                 str(REPO / "tests")]) == 0


def test_pl004_catches_from_imported_clock(tmp_path):
    """Review-pass regression: `from time import monotonic` evaded PL004 —
    the import style must not be the bypass."""
    f = tmp_path / "ctrl.py"
    f.write_text("from time import monotonic\ncutoff = monotonic()\n")
    findings = lint_file(f, roles=frozenset({ROLE_PACKAGE,
                                             ROLE_CONTROLLERS}))
    assert [x.rule for x in findings] == ["PL004"]


def test_waiver_syntax_inside_string_literal_is_inert(tmp_path):
    """Review-pass regression: waiver-looking text in a docstring/string
    must neither waive the next line nor count as malformed."""
    f = tmp_path / "doc.py"
    f.write_text(
        'import time\n'
        'DOC = """example: # provlint: disable=naked-wall-clock — x"""\n'
        'a = time.monotonic()\n'
        'BAD = "# provlint: disable=nonsense"\n')
    findings = lint_file(f, roles=frozenset({ROLE_PACKAGE,
                                             ROLE_CONTROLLERS}))
    assert [(x.rule, x.line) for x in findings] == [("PL004", 3)]


def test_comment_waiver_does_not_bleed_past_its_target_line(tmp_path):
    """Review-pass regression: a comment-only waiver covered the line
    AFTER its target code line too, silently hiding a second violation."""
    f = tmp_path / "two_clocks.py"
    f.write_text(
        "import time\n"
        "# provlint: disable=naked-wall-clock — first one is measured\n"
        "a = time.monotonic()\n"
        "b = time.monotonic()\n")
    findings = lint_file(f, roles=frozenset({ROLE_PACKAGE,
                                             ROLE_CONTROLLERS}))
    assert [(x.rule, x.line) for x in findings] == [("PL004", 4)]


def test_catalog_has_at_least_fourteen_rules():
    assert len(RULES) >= 14
    assert len({r.id for r in RULES}) == len(RULES)
    assert len({r.name for r in RULES}) == len(RULES)


def test_cli(tmp_path, capsys):
    assert main(["--list-rules"]) == 0
    bad = tmp_path / "gpu_provisioner_tpu" / "controllers" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import time\ncutoff = time.monotonic()\n")
    assert main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "PL004" in out and "naked-wall-clock" in out
    assert main([str(tmp_path / "nope")]) == 2


def test_syntax_error_is_a_finding(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def broken(:\n")
    findings = lint_file(f, roles=frozenset({ROLE_PACKAGE}))
    assert findings and findings[0].rule == "PL000"


# -------------------------------------------------------------- enforcement

def test_whole_tree_is_clean():
    """The acceptance gate, run on every tier-1 pass: provlint over the
    real package + tests must stay at zero findings (waivers carry their
    reasons inline). A regression in any enforced invariant fails HERE."""
    findings = lint_paths([REPO / "gpu_provisioner_tpu", REPO / "tests"])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_fixture_corpus_is_excluded_from_tree_walks():
    findings = lint_paths([FIXTURES.parent])
    assert not any("analysis_fixtures" in f.path for f in findings)
