"""Crash-restart recovery soaks: kill the operator at a named cut line,
boot a fresh incarnation against the SAME kube store + fake cloud, and
prove convergence with zero leaked cloud resources.

Three layers under test (docs/FAILURE_MODES.md "Crash & restart taxonomy"):

1. **Crash points** (`chaos.CrashPoints`): SimulatedCrash raised through the
   operator at the cut lines that strand the most interesting state.
2. **Restart harness** (`envtest.RestartableEnv`): incarnation teardown
   cancels every operator task and drops all in-memory caches; cloud + kube
   state — including in-flight LROs the fake keeps driving server-side —
   persist.
3. **Recovery mechanisms**: idempotent create + conflict adoption, the
   startup resync/orphan-adoption pass (controllers/recovery.py), and
   fenced leader failover (runtime/leaderelection.py).

The heavy matrix and failover soaks are marked ``slow`` (excluded from the
tier-1 gate, run via ``make recover``); the smoke is also marked ``chaos``
so ``make chaos`` exercises one restart profile.
"""

import asyncio
import os
from datetime import timedelta

import pytest

from gpu_provisioner_tpu import chaos
from gpu_provisioner_tpu.apis import labels as wk
from gpu_provisioner_tpu.apis.core import Lease, Node, Pod, PodSpec
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import ObjectMeta
from gpu_provisioner_tpu.apis.serde import now
from gpu_provisioner_tpu.controllers.metrics import (
    RECOVERY_ADOPTED, RECOVERY_REAPED, RECOVERY_RESUMED,
)
from gpu_provisioner_tpu.envtest import Env, EnvtestOptions, RestartableEnv
from gpu_provisioner_tpu.fake import make_nodeclaim
from gpu_provisioner_tpu.providers.gcp import (
    NodePool, NodePoolConfig, NP_RUNNING, QueuedResource,
)
from gpu_provisioner_tpu.providers.instance import (
    PROVISIONING_MODE_ANNOTATION, ts_label,
)
from gpu_provisioner_tpu.runtime import InMemoryClient
from gpu_provisioner_tpu.runtime.leaderelection import (
    FencedError, LeaderElector,
)

from .conftest import async_test

pytestmark = pytest.mark.recovery

SEED = int(os.environ.get("CHAOS_SEED", "7"))
QUEUED = {PROVISIONING_MODE_ANNOTATION: "queued"}


def _opts(**kw) -> EnvtestOptions:
    """Envtest tuned like the chaos soaks: fast GC, short liveness budgets."""
    kw.setdefault("gc_interval", 0.1)
    kw.setdefault("leak_grace", 0.1)
    opts = EnvtestOptions(**kw)
    opts.lifecycle.launch_timeout = 20.0
    opts.lifecycle.registration_timeout = 20.0
    return opts


async def _assert_no_leaks(renv: RestartableEnv, pools: set[str],
                           qrs: set[str] = frozenset(),
                           timeout: float = 10.0) -> None:
    """Settle loop over the leak invariant: the fake cloud's pools and
    queued resources exactly match the surviving claims."""
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        have_pools = set(renv.cloud.nodepools.pools)
        have_qrs = set(renv.cloud.queuedresources.resources)
        nodes = await renv.client.list(Node)
        node_pools = {n.metadata.labels.get(wk.GKE_NODEPOOL_LABEL)
                      for n in nodes}
        if (have_pools == pools and have_qrs == qrs
                and node_pools <= pools | {None}):
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError(
                f"leak invariant violated: pools={sorted(have_pools)} "
                f"(want {sorted(pools)}), qrs={sorted(have_qrs)} "
                f"(want {sorted(qrs)}), orphan-node-pools="
                f"{sorted((node_pools - pools) - {None}, key=str)}")
        await asyncio.sleep(0.05)


# ------------------------------------------------------------------ smoke

@pytest.mark.chaos
@async_test
async def test_crash_restart_smoke():
    """The one-restart profile `make chaos` runs: die right after the create
    LRO is issued, restart, adopt the in-flight create, converge, zero
    leaks — and the recovery pass counts the adoption."""
    adopted0 = RECOVERY_ADOPTED.labels("pool")._value.get()
    crashes = chaos.CrashPoints(at="after_pool_begin_create", seed=SEED)
    renv = RestartableEnv(_opts(crashes=crashes))
    await renv.start()
    try:
        await renv.client.create(make_nodeclaim("sm0"))
        await asyncio.wait_for(crashes.crashed.wait(), 15)
        assert crashes.fired["after_pool_begin_create"] == 1
        assert crashes.last == ("after_pool_begin_create", "sm0")

        await renv.restart()
        nc = await renv.wait_ready("sm0", timeout=25)
        assert nc.status.provider_id
        await _assert_no_leaks(renv, {"sm0"})
        assert renv.incarnations == 2
        assert RECOVERY_ADOPTED.labels("pool")._value.get() > adopted0, \
            "startup resync pass never counted the adoption"
    finally:
        await renv.crash()


# ----------------------------------------------------------- crash matrix

# (scenario, crash point, queued-mode) — every crash point crossed with the
# lifecycle phase it can strand (a queued-mode delete exercises the same
# mid-delete cut lines plus the QR cleanup that precedes them).
MATRIX = [
    ("mid-create", "after_pool_begin_create", False),
    ("mid-create", "before_lro_done", False),
    ("queued", "after_qr_create", True),
    ("queued", "after_pool_begin_create", True),
    ("queued", "before_lro_done", True),
    ("mid-delete", "mid_delete_after_pool_delete", False),
    ("mid-delete", "mid_drain", False),
    ("mid-delete", "mid_delete_after_pool_delete", True),
]


@pytest.mark.slow
@pytest.mark.parametrize("scenario,point,queued", MATRIX)
@async_test
async def test_crash_restart_matrix(scenario, point, queued):
    """For every crash point × scenario: a restarted incarnation converges
    the claim (Ready, or fully deleted for mid-delete) with zero leaked
    pools/queued resources."""
    crashes = chaos.CrashPoints(seed=SEED)
    # queued scenarios slow the QR ladder so the restart genuinely lands
    # mid-ladder (the wall-clock ladder would otherwise finish during the
    # restart gap and hide the resume path)
    opts = _opts(crashes=crashes,
                 qr_step_latency=0.3 if queued else 0.02)
    renv = RestartableEnv(opts)
    await renv.start()
    try:
        ann = QUEUED if queued else None
        if scenario == "mid-delete":
            await renv.client.create(make_nodeclaim("cr0", annotations=ann))
            await renv.wait_ready("cr0", timeout=25)
            if point == "mid_drain":
                # a pod on the node makes the drain non-trivial
                await renv.client.create(Pod(
                    metadata=ObjectMeta(name="payload", namespace="default"),
                    spec=PodSpec(node_name="gke-kaito-cr0-w0")))
            crashes.arm(point)
            await renv.client.delete(NodeClaim, "cr0")
        else:
            crashes.arm(point)
            await renv.client.create(make_nodeclaim("cr0", annotations=ann))

        await asyncio.wait_for(crashes.crashed.wait(), 20)
        assert crashes.fired[point] == 1, crashes.fired

        resumed0 = RECOVERY_RESUMED.labels("qr")._value.get()
        await renv.restart()

        if scenario == "mid-delete":
            await renv.wait_gone("cr0", timeout=25)
            await _assert_no_leaks(renv, set())
        else:
            await renv.wait_ready("cr0", timeout=30)
            await _assert_no_leaks(renv, {"cr0"},
                                   qrs={"cr0"} if queued else frozenset())
            if point == "after_qr_create":
                assert RECOVERY_RESUMED.labels("qr")._value.get() > resumed0, \
                    "mid-ladder queued resource not counted as resumed"
    finally:
        await renv.crash()


# ------------------------------------------------- startup resync / orphans

@async_test
async def test_recovery_pass_reaps_orphans_at_boot():
    """Cloud state with no NodeClaim behind it is reaped by the startup
    resync pass immediately — not a GC interval later (GC is disabled here
    to prove attribution)."""
    reaped0 = sum(RECOVERY_REAPED.labels(k)._value.get()
                  for k in ("pool", "qr"))
    renv = RestartableEnv(_opts(gc_interval=600.0))
    # a dead incarnation's leftovers: an old claimless pool + queued resource
    pool = NodePool(
        name="orphan",
        config=NodePoolConfig(machine_type="ct5lp-hightpu-4t", labels={
            wk.NODEPOOL_LABEL: wk.KAITO_NODEPOOL_NAME,
            wk.KAITO_CREATION_TIMESTAMP_LABEL:
                ts_label(now() - timedelta(seconds=120)),
        }),
        initial_node_count=1, status=NP_RUNNING)
    renv.cloud.nodepools.pools["orphan"] = pool
    renv.cloud.queuedresources.resources["orphanq"] = QueuedResource(
        name="orphanq")
    await renv.start()
    try:
        deadline = asyncio.get_event_loop().time() + 10
        while (renv.cloud.nodepools.pools
               or renv.cloud.queuedresources.resources):
            assert asyncio.get_event_loop().time() < deadline, (
                f"recovery never reaped: pools="
                f"{list(renv.cloud.nodepools.pools)} "
                f"qrs={list(renv.cloud.queuedresources.resources)}")
            await asyncio.sleep(0.05)
        reaped = sum(RECOVERY_REAPED.labels(k)._value.get()
                     for k in ("pool", "qr"))
        assert reaped >= reaped0 + 2
    finally:
        await renv.crash()


@async_test
async def test_fake_cloud_drives_lros_server_side():
    """The restart substrate itself: an LRO whose poller died still
    completes — a stranded create turns RUNNING and joins nodes, a stranded
    delete removes the pool and its nodes."""
    from gpu_provisioner_tpu.fake import FakeCloud

    kube = InMemoryClient()
    cloud = FakeCloud(kube, create_latency=0.05, delete_latency=0.05)
    pool = NodePool(name="lro0", config=NodePoolConfig(
        machine_type="ct5lp-hightpu-4t",
        labels={wk.INSTANCE_TYPE_LABEL: "tpu-v5e-8"}))
    await cloud.nodepools.begin_create(pool)  # op dropped: poller "died"
    assert cloud.nodepools.pools["lro0"].status == "PROVISIONING"
    await asyncio.sleep(0.06)
    got = await cloud.nodepools.get("lro0")   # any API touch settles
    assert got.status == NP_RUNNING
    assert len(await kube.list(Node)) == 1, "kubelets joined without a poller"

    await cloud.nodepools.begin_delete("lro0")  # op dropped again
    await asyncio.sleep(0.06)
    pools = await cloud.nodepools.list()
    assert pools == [] and await kube.list(Node) == []


# ------------------------------------------- crash points × operation tracker

# The PR 3 cut lines whose stranded state is an in-flight LRO — after a
# restart the new incarnation must RE-REGISTER that LRO with its operation
# tracker (recovery resync resume_create / conflict adoption / STOPPING
# delete adoption) and converge through batched polling, never a blind
# blocking wait.
TRACKER_MATRIX = [
    ("mid-create", "after_pool_begin_create"),
    ("mid-create", "before_lro_done"),
    ("mid-delete", "mid_delete_after_pool_delete"),
]


@pytest.mark.parametrize("scenario,point", TRACKER_MATRIX)
@async_test
async def test_crash_restart_reregisters_lro_with_tracker(scenario, point):
    from gpu_provisioner_tpu.providers.operations import OP_CREATE, OP_DELETE

    crashes = chaos.CrashPoints(seed=SEED)
    # a slow delete LRO so the restarted incarnation genuinely observes the
    # stranded delete mid-flight (STOPPING) instead of finding it settled
    opts = _opts(crashes=crashes,
                 delete_latency=1.0 if scenario == "mid-delete" else 0.02)
    renv = RestartableEnv(opts)
    await renv.start()
    try:
        if scenario == "mid-delete":
            await renv.client.create(make_nodeclaim("tr0"))
            await renv.wait_ready("tr0", timeout=25)
            crashes.arm(point)
            await renv.client.delete(NodeClaim, "tr0")
        else:
            crashes.arm(point)
            await renv.client.create(make_nodeclaim("tr0"))
        await asyncio.wait_for(crashes.crashed.wait(), 20)

        env2 = await renv.restart()
        kind = OP_DELETE if scenario == "mid-delete" else OP_CREATE
        deadline = asyncio.get_event_loop().time() + 15
        while env2.tracker.registered[kind] < 1:
            assert asyncio.get_event_loop().time() < deadline, \
                f"stranded {kind} LRO never re-registered with the tracker"
            await asyncio.sleep(0.02)

        if scenario == "mid-delete":
            await renv.wait_gone("tr0", timeout=25)
            await _assert_no_leaks(renv, set())
        else:
            await renv.wait_ready("tr0", timeout=30)
            await _assert_no_leaks(renv, {"tr0"})
        # the whole scenario — both incarnations — must never have polled
        # an LRO client-side: resumption went through the multiplexer, not
        # a blind node wait/poll loop
        assert renv.cloud.nodepools.calls.get("operation_poll", 0) == 0
    finally:
        await renv.crash()


# -------------------------------------------------------- fenced failover

FAST = dict(lease_duration=2.0, renew_interval=0.4, retry_interval=0.1)


class _GatedClient:
    """Client for the doomed elector: when ``gated``, Lease traffic fails —
    the zombie's renew loop sees a dead apiserver while its reconcile tasks
    keep running (the half-dead process fencing exists for)."""

    def __init__(self, store):
        self.inner = InMemoryClient(store)
        self.gated = False

    def _check(self, cls):
        if self.gated and cls is Lease:
            from gpu_provisioner_tpu.runtime.client import ConflictError
            raise ConflictError("gated: lease traffic blackholed")

    async def get(self, cls, name, namespace=""):
        self._check(cls)
        return await self.inner.get(cls, name, namespace)

    async def create(self, obj):
        self._check(type(obj))
        return await self.inner.create(obj)

    async def update(self, obj):
        self._check(type(obj))
        return await self.inner.update(obj)


def _mutations(provider) -> dict:
    """Snapshot of the cloud-MUTATING endpoint counters for one provider
    (one incarnation) — the single-writer assertion currency."""
    out = {f"np.{k}": v for k, v in provider.nodepools.calls.items()
           if k in ("begin_create", "begin_delete")}
    if provider.queued is not None:
        out.update({f"qr.{k}": v for k, v in provider.queued.calls.items()
                    if k in ("create", "delete")})
    return out


@pytest.mark.slow
# mid_repair only fires with an unhealthy node in play; its crash × recovery
# composition is covered by tests/test_health.py's mid-repair restart test.
@pytest.mark.parametrize(
    "point", [p for p in chaos.CRASH_POINTS if p != "mid_repair"])
@async_test
async def test_failover_soak_single_writer(point):
    """Kill the leader at each crash point, keep its half-dead incarnation
    RUNNING (zombie), fail over to a rival elector: the new incarnation
    converges with zero leaks and the fenced zombie performs ZERO cloud
    mutations after its fencing token is invalidated."""
    crashes = chaos.CrashPoints(seed=SEED)
    queued = point == "after_qr_create"
    mid_delete = point in ("mid_delete_after_pool_delete", "mid_drain")
    opts = _opts(crashes=crashes, qr_step_latency=0.3 if queued else 0.02)
    renv = RestartableEnv(opts)

    lost = asyncio.Event()
    gate = _GatedClient(renv.client.store)
    a = LeaderElector(gate, identity="a", on_lost=lost.set, **FAST)
    await a.run_until_leading()
    token_a = a.fence()
    env_a = await renv.start(fence=token_a)

    name = "fo0"
    ann = QUEUED if queued else None
    if mid_delete:
        await renv.client.create(make_nodeclaim(name, annotations=ann))
        await renv.wait_ready(name, timeout=25)
        if point == "mid_drain":
            await renv.client.create(Pod(
                metadata=ObjectMeta(name="payload", namespace="default"),
                spec=PodSpec(node_name=f"gke-kaito-{name}-w0")))
        # a big budget: the zombie keeps crashing on every retry, so it can
        # never finish this work itself — the rival must
        crashes.arm(point, times=1000)
        await renv.client.delete(NodeClaim, name)
    else:
        crashes.arm(point, times=1000)
        await renv.client.create(make_nodeclaim(name, annotations=ann))
    await asyncio.wait_for(crashes.crashed.wait(), 20)

    # The "crash" took the renew path with it: lease traffic blackholes.
    # The zombie's OTHER tasks keep running — that is the scenario.
    gate.gated = True
    await asyncio.wait_for(lost.wait(), 15)
    assert not token_a.valid()
    with pytest.raises(FencedError):
        token_a.check()
    await asyncio.sleep(0.3)  # drain reconciles that pre-dated the fence flip
    baseline = _mutations(env_a.provider)

    # rival steals the expired lease; the crash schedule is disarmed for it
    crashes.disarm()
    b = LeaderElector(InMemoryClient(renv.client.store), identity="b", **FAST)
    await asyncio.wait_for(b.run_until_leading(), 15)
    env_b = Env(opts, client=renv.client, cloud=renv.cloud, fence=b.fence())
    await env_b.__aenter__()
    try:
        if mid_delete:
            await env_b.wait_gone(name, timeout=30)
            await _assert_no_leaks(renv, set())
        else:
            await env_b.wait_ready(name, timeout=30)
            await _assert_no_leaks(
                renv, {name}, qrs={name} if queued else frozenset())
        # soak past several zombie retry windows: a fenced dequeue must
        # never reach the cloud
        await asyncio.sleep(1.0)
        assert _mutations(env_a.provider) == baseline, \
            "deposed leader mutated the cloud after fencing invalidation"
        # the rival's convergence generated watch events the zombie's pumps
        # also saw — every one of those dequeues must have been fenced
        fenced = sum(c.fenced_total for c in env_a.manager.controllers)
        assert fenced > 0, "zombie never exercised the fence drop path"
    finally:
        await env_b.__aexit__()
        await b.stop()
        await renv.crash()   # finally kill the zombie
        await a.stop()
