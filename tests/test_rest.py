"""REST layer tests: kube RestClient, GKE/CloudTPU clients, transport retry.

Mirrors the reference's mock-the-wire approach (pkg/fake mocks the 4-method
ARM seam; here httpx.MockTransport mocks the HTTP boundary itself, one level
lower, so path building and error-taxonomy mapping are covered too).
"""

import asyncio
import json

import httpx
import pytest

from gpu_provisioner_tpu.apis.core import Node, Pod
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.auth.credentials import StaticTokenCredential
from gpu_provisioner_tpu.providers.gcp import (APIError, NodePool,
                                               NodePoolConfig, PlacementPolicy,
                                               QueuedResource)
from gpu_provisioner_tpu.providers.rest import (CloudTPUQueuedResourcesClient,
                                                GKENodePoolsClient)
from gpu_provisioner_tpu.runtime.client import (AlreadyExistsError,
                                                ConflictError,
                                                EvictionBlockedError,
                                                NotFoundError)
from gpu_provisioner_tpu.runtime.rest import (KubeConnection, RestClient,
                                              resource_path)
from gpu_provisioner_tpu.runtime.store import ADDED, DELETED, MODIFIED
from gpu_provisioner_tpu.transport import TransportOptions, request_with_retries

from .conftest import async_test

FAST = TransportOptions(max_retries=2, backoff_base=0.01, backoff_cap=0.02)


def make_kube_client(handler) -> RestClient:
    conn = KubeConnection(server="https://kube.test", token="tok")
    http = httpx.AsyncClient(transport=httpx.MockTransport(handler),
                             base_url="https://kube.test")
    return RestClient(conn, transport=FAST, http=http)


# --- path building ---------------------------------------------------------

def test_resource_paths():
    assert resource_path(NodeClaim) == "/apis/karpenter.sh/v1/nodeclaims"
    assert resource_path(NodeClaim, name="x") == "/apis/karpenter.sh/v1/nodeclaims/x"
    assert resource_path(Node, name="n1") == "/api/v1/nodes/n1"
    assert resource_path(Pod, "ns1", "p") == "/api/v1/namespaces/ns1/pods/p"
    assert resource_path(Pod) == "/api/v1/pods"  # all-namespaces list


# --- CRUD + error taxonomy -------------------------------------------------

@async_test
async def test_kube_crud_roundtrip():
    store: dict[str, dict] = {}

    def handler(req: httpx.Request) -> httpx.Response:
        assert req.headers["Authorization"] == "Bearer tok"
        path = req.url.path
        if req.method == "POST":
            obj = json.loads(req.content)
            name = obj["metadata"]["name"]
            if name in store:
                return httpx.Response(409, text="exists")
            store[name] = obj
            return httpx.Response(201, json=obj)
        if req.method == "PUT":
            name = path.rsplit("/", 2)[-2] if path.endswith("/status") \
                else path.rsplit("/", 1)[-1]
            store[name] = json.loads(req.content)
            return httpx.Response(200, json=store[name])
        if req.method == "DELETE":
            name = path.rsplit("/", 1)[-1]
            return httpx.Response(200) if store.pop(name, None) \
                else httpx.Response(404, text="nope")
        name = path.rsplit("/", 1)[-1]
        if name == "nodeclaims":  # list
            sel = req.url.params.get("labelSelector", "")
            items = list(store.values())
            if sel:
                k, v = sel.split("=", 1)
                items = [o for o in items
                         if o["metadata"].get("labels", {}).get(k) == v]
            return httpx.Response(200, json={"items": items,
                                             "metadata": {"resourceVersion": "9"}})
        if name in store:
            return httpx.Response(200, json=store[name])
        return httpx.Response(404, text="nope")

    c = make_kube_client(handler)
    nc = NodeClaim()
    nc.metadata.name = "w0"
    nc.metadata.labels = {"kaito.sh/workspace": "ws"}
    created = await c.create(nc)
    assert created.metadata.name == "w0"
    with pytest.raises(AlreadyExistsError):
        await c.create(nc)

    got = await c.get(NodeClaim, "w0")
    assert got.metadata.labels["kaito.sh/workspace"] == "ws"

    got.metadata.labels["x"] = "y"
    await c.update(got)
    await c.update_status(got)

    assert len(await c.list(NodeClaim, labels={"kaito.sh/workspace": "ws"})) == 1
    assert await c.list(NodeClaim, labels={"kaito.sh/workspace": "zz"}) == []

    await c.delete(NodeClaim, "w0")
    with pytest.raises(NotFoundError):
        await c.get(NodeClaim, "w0")
    with pytest.raises(NotFoundError):
        await c.delete(NodeClaim, "w0")


@async_test
async def test_kube_conflict_on_put():
    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(409, text="stale rv")

    c = make_kube_client(handler)
    nc = NodeClaim()
    nc.metadata.name = "w0"
    with pytest.raises(ConflictError):
        await c.update(nc)


@async_test
async def test_kube_index_filters_client_side():
    node = {"kind": "Node", "apiVersion": "v1",
            "metadata": {"name": "n1"}, "spec": {"providerID": "gce://p/z/i"}}

    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(200, json={"items": [node]})

    c = make_kube_client(handler)
    c.add_index(Node, "spec.providerID", lambda o: [o.spec.provider_id])
    hit = await c.list(Node, index=("spec.providerID", "gce://p/z/i"))
    miss = await c.list(Node, index=("spec.providerID", "gce://other"))
    assert [n.metadata.name for n in hit] == ["n1"] and miss == []


# --- watch -----------------------------------------------------------------

@async_test
async def test_kube_watch_replays_then_streams():
    existing = {"kind": "NodeClaim", "apiVersion": "karpenter.sh/v1",
                "metadata": {"name": "old", "resourceVersion": "1"}}
    update = {"type": "MODIFIED",
              "object": {"kind": "NodeClaim", "apiVersion": "karpenter.sh/v1",
                         "metadata": {"name": "old", "resourceVersion": "2"}}}

    def handler(req: httpx.Request) -> httpx.Response:
        if req.url.params.get("watch") == "true":
            assert req.url.params.get("resourceVersion") == "5"
            return httpx.Response(200, content=json.dumps(update) + "\n")
        return httpx.Response(200, json={
            "items": [existing], "metadata": {"resourceVersion": "5"}})

    c = make_kube_client(handler)
    w = c.watch(NodeClaim)
    ev1 = await asyncio.wait_for(w.__anext__(), 5)
    assert ev1.type == ADDED and ev1.object.metadata.name == "old"
    ev2 = await asyncio.wait_for(w.__anext__(), 5)
    assert ev2.type == MODIFIED
    assert ev2.object.metadata.resource_version == "2"
    w.close()
    with pytest.raises(StopAsyncIteration):
        await w.__anext__()


@async_test
async def test_kube_watch_synthesizes_delete_tombstones_on_relist():
    """Objects that vanish while the watch stream is down must come back as
    DELETED tombstones when the re-list replays (client-go reflector
    Replace() parity) — otherwise informer caches hold them until resync."""
    item = lambda n, rv: {"kind": "NodeClaim",
                          "apiVersion": "karpenter.sh/v1",
                          "metadata": {"name": n, "resourceVersion": rv}}
    state = {"lists": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        if req.url.params.get("watch") == "true":
            # every stream dies with 410 Gone → re-list path
            return httpx.Response(410, text="gone")
        state["lists"] += 1
        if state["lists"] == 1:
            return httpx.Response(200, json={
                "items": [item("a", "1"), item("b", "2")],
                "metadata": {"resourceVersion": "5"}})
        return httpx.Response(200, json={      # "b" deleted during outage
            "items": [item("a", "1")], "metadata": {"resourceVersion": "7"}})

    c = make_kube_client(handler)
    w = c.watch(NodeClaim)
    evs = [await asyncio.wait_for(w.__anext__(), 5) for _ in range(4)]
    w.close()
    assert [(e.type, e.object.metadata.name) for e in evs] == [
        (ADDED, "a"), (ADDED, "b"),   # first list
        (ADDED, "a"),                 # re-list replay after the 410
        (DELETED, "b"),               # tombstone for the vanished object
    ]


# --- kubeconfig parsing ----------------------------------------------------

def test_kubeconnection_from_kubeconfig(tmp_path):
    kc = {
        "current-context": "c1",
        "contexts": [{"name": "c1", "context": {
            "cluster": "cl", "user": "u", "namespace": "ns9"}}],
        "clusters": [{"name": "cl", "cluster": {
            "server": "https://1.2.3.4",
            "certificate-authority-data":
                __import__("base64").b64encode(b"CA PEM").decode()}}],
        "users": [{"name": "u", "user": {"token": "sekrit"}}],
    }
    p = tmp_path / "kubeconfig"
    import yaml
    p.write_text(yaml.safe_dump(kc))
    conn = KubeConnection.from_kubeconfig(str(p))
    assert conn.server == "https://1.2.3.4"
    assert conn.token == "sekrit" and conn.namespace == "ns9"
    assert open(conn.ca_file, "rb").read() == b"CA PEM"


# --- transport retry -------------------------------------------------------

@async_test
async def test_transport_retries_transient_then_succeeds():
    calls = {"n": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        calls["n"] += 1
        return httpx.Response(503 if calls["n"] < 3 else 200, json={})

    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    resp = await request_with_retries(http, "GET", "https://x.test/y", opts=FAST)
    assert resp.status_code == 200 and calls["n"] == 3


@async_test
async def test_transport_does_not_retry_4xx():
    calls = {"n": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        calls["n"] += 1
        return httpx.Response(404)

    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    resp = await request_with_retries(http, "GET", "https://x.test/y", opts=FAST)
    assert resp.status_code == 404 and calls["n"] == 1


# --- GKE node pools client -------------------------------------------------

def gke_client(handler) -> GKENodePoolsClient:
    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    return GKENodePoolsClient(StaticTokenCredential("gcp-tok"), "proj",
                              "us-central2-b", "cl", transport=FAST, http=http)


def sample_pool() -> NodePool:
    return NodePool(
        name="ws0pool",
        config=NodePoolConfig(machine_type="ct5p-hightpu-4t", disk_size_gb=100,
                              labels={"a": "b"}, spot=True, reservation="res1",
                              taints=[{"key": "google.com/tpu",
                                       "value": "present",
                                       "effect": "NO_SCHEDULE"}]),
        initial_node_count=4,
        placement_policy=PlacementPolicy(type="COMPACT", tpu_topology="2x2x4"))


@async_test
async def test_gke_create_polls_operation_and_fetches_pool():
    ops = {"n": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        assert req.headers["Authorization"] == "Bearer gcp-tok"
        path = req.url.path
        if req.method == "POST":
            body = json.loads(req.content)["nodePool"]
            # seam→wire translation checks
            assert body["config"]["machineType"] == "ct5p-hightpu-4t"
            assert body["config"]["reservationAffinity"]["values"] == ["res1"]
            assert body["placementPolicy"]["tpuTopology"] == "2x2x4"
            assert body["initialNodeCount"] == 4
            return httpx.Response(200, json={"name": "op-1", "status": "RUNNING"})
        if "/operations/" in path:
            ops["n"] += 1
            done = ops["n"] >= 2
            return httpx.Response(200, json={
                "name": "op-1", "status": "DONE" if done else "RUNNING"})
        if path.endswith("/nodePools/ws0pool"):
            wire = json.loads(json.dumps({
                "name": "ws0pool", "status": "RUNNING",
                "initialNodeCount": 4,
                "config": {"machineType": "ct5p-hightpu-4t",
                           "reservationAffinity": {"values": ["res1"]},
                           "spot": True},
                "placementPolicy": {"type": "COMPACT", "tpuTopology": "2x2x4"}}))
            return httpx.Response(200, json=wire)
        raise AssertionError(f"unexpected {req.method} {path}")

    c = gke_client(handler)
    op = await c.begin_create(sample_pool())
    assert not await op.done()
    assert await op.done()
    pool = await op.result()
    assert pool.status == "RUNNING"
    assert pool.config.reservation == "res1"
    assert pool.placement_policy.tpu_topology == "2x2x4"


@pytest.mark.parametrize("err", [
    {"code": 8, "message": "no v5p capacity"},          # real google.rpc.Status
    {"status": "RESOURCE_EXHAUSTED", "message": "no v5p capacity"},
])
@async_test
async def test_gke_stockout_surfaces_as_exhausted(err):
    def handler(req: httpx.Request) -> httpx.Response:
        if req.method == "POST":
            return httpx.Response(200, json={
                "name": "op-1", "status": "DONE", "error": err})
        raise AssertionError("no polling needed")

    c = gke_client(handler)
    op = await c.begin_create(sample_pool())
    assert await op.done()
    with pytest.raises(APIError) as ei:
        await op.result()
    assert ei.value.exhausted and "v5p" in str(ei.value)


@async_test
async def test_gke_http_429_is_not_retried_and_maps_to_exhausted():
    """A synchronous 429 from the create POST is a stockout answer, not
    throttling — must surface immediately as APIError.exhausted."""
    calls = {"n": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        calls["n"] += 1
        return httpx.Response(429, text="out of v5e capacity")

    c = gke_client(handler)
    with pytest.raises(APIError) as ei:
        await c.begin_create(sample_pool())
    assert ei.value.exhausted and calls["n"] == 1


@async_test
async def test_gke_get_404_maps_to_apierror():
    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(404, text="not found")

    with pytest.raises(APIError) as ei:
        await gke_client(handler).get("ghost")
    assert ei.value.not_found


# --- Cloud TPU queued resources client ------------------------------------

@async_test
async def test_queued_resource_create_wire_shape_and_state():
    created = {}

    def handler(req: httpx.Request) -> httpx.Response:
        path = req.url.path
        if req.method == "POST":
            body = json.loads(req.content)
            created.update(body)
            assert req.url.params["queuedResourceId"] == "qr1"
            spec = body["tpu"]["nodeSpec"][0]
            assert spec["node"]["acceleratorType"] == "v5p-32"
            assert body["reservationName"] == "res9"
            return httpx.Response(200, json={"name": "operations/qr-op"})
        if path.endswith("/queuedResources/qr1"):
            return httpx.Response(200, json={
                "name": "projects/p/locations/l/queuedResources/qr1",
                "tpu": created.get("tpu", {}),
                "reservationName": "res9",
                "state": {"state": "WAITING_FOR_RESOURCES"}})
        raise AssertionError(f"unexpected {req.method} {path}")

    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    c = CloudTPUQueuedResourcesClient(StaticTokenCredential("t"), "p", "l",
                                      transport=FAST, http=http)
    qr = await c.create(QueuedResource(name="qr1", accelerator_type="v5p-32",
                                       reservation="res9", node_pool="np1"))
    assert qr.state == "WAITING_FOR_RESOURCES"
    assert qr.name == "qr1" and qr.node_pool == "np1"


@async_test
async def test_kube_list_paginates_with_limit_continue():
    """Every LIST is chunked (limit/continue) — the client must walk all
    pages and the watch's initial list must too."""
    total = 7
    calls = []

    def handler(req: httpx.Request) -> httpx.Response:
        limit = int(req.url.params.get("limit", "0") or 0)
        start = int(req.url.params.get("continue", "0") or 0)
        calls.append((start, limit))
        assert limit > 0, "client must request bounded pages"
        items = [{"metadata": {"name": f"n{i}"}} for i in range(total)]
        page = items[start:start + limit]
        meta = {"resourceVersion": "42"}
        if start + limit < total:
            meta["continue"] = str(start + limit)
        return httpx.Response(200, json={"items": page, "metadata": meta})

    c = make_kube_client(handler)
    c.LIST_PAGE_SIZE = 3
    items = await c.list(NodeClaim)
    assert sorted(o.metadata.name for o in items) == [f"n{i}" for i in range(total)]
    assert calls == [(0, 3), (3, 3), (6, 3)]


@async_test
async def test_evict_429_maps_to_blocked_without_transport_retry():
    """A 429 from the eviction subresource is a PDB verdict: it must surface
    as EvictionBlockedError on the FIRST response (no transport retry — the
    eviction queue owns the backoff), while other verbs still retry 429s."""
    calls = {"evict": 0}

    def handler(req: httpx.Request) -> httpx.Response:
        assert req.url.path.endswith("/pods/p/eviction")
        calls["evict"] += 1
        return httpx.Response(429, text="disruption budget violated")

    client = make_kube_client(handler)
    with pytest.raises(EvictionBlockedError):
        await client.evict("p", "ns1")
    assert calls["evict"] == 1


def test_kubeconfig_exec_plugin_auth(tmp_path):
    """A gcloud-style kubeconfig authenticates via an exec credential plugin
    (client-go exec auth): the plugin's ExecCredential token becomes the
    bearer, cached like the projected-token path."""
    counter = tmp_path / "invocations"
    plugin = tmp_path / "fake-auth-plugin"
    plugin.write_text(
        "#!/bin/sh\n"
        f'echo x >> "{counter}"\n'
        f'N=$(wc -l < "{counter}" | tr -d " ")\n'
        'echo "{\\"apiVersion\\": \\"client.authentication.k8s.io/v1\\", '
        '\\"kind\\": \\"ExecCredential\\", '
        '\\"status\\": {\\"token\\": \\"exec-tok-$PLUGIN_SUFFIX-$N\\"}}"\n')
    plugin.chmod(0o755)
    kc = tmp_path / "kubeconfig"
    kc.write_text(json.dumps({
        "current-context": "gke",
        "contexts": [{"name": "gke",
                      "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": {"server": "https://k8s.test"}}],
        "users": [{"name": "u", "user": {"exec": {
            "apiVersion": "client.authentication.k8s.io/v1",
            "command": str(plugin),
            "args": [],
            "env": [{"name": "PLUGIN_SUFFIX", "value": "42"}],
        }}}],
    }))
    conn = KubeConnection.from_kubeconfig(str(kc))
    assert conn.exec_argv == (str(plugin),)
    assert conn.bearer(0.0) == "exec-tok-42-1"
    # cached — inside the reread window the plugin does NOT run again (the
    # token embeds an invocation counter, so a re-run would change it)
    assert conn.bearer(1.0) == "exec-tok-42-1"
    assert counter.read_text().count("x") == 1
    # past the window it refreshes and picks up the new credential
    assert conn.bearer(1000.0) == "exec-tok-42-2"


# --- HTTP status → error-taxonomy mapping (provider level) -----------------
# The full path a real failure takes: wire status → APIError code →
# providers/instance.py taxonomy (errors.py) that controllers branch on.

def _provider_over_rest(handler):
    from gpu_provisioner_tpu.providers.instance import (InstanceProvider,
                                                        ProviderConfig)
    from gpu_provisioner_tpu.runtime.client import InMemoryClient
    gke = gke_client(handler)
    kube = InMemoryClient()
    return InstanceProvider(gke, kube, ProviderConfig(
        node_wait_attempts=2, node_wait_interval=0.01))


@async_test
async def test_provider_maps_http_429_to_insufficient_capacity():
    from gpu_provisioner_tpu.errors import InsufficientCapacityError
    from gpu_provisioner_tpu.fake import make_nodeclaim

    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(429, text="out of v5e capacity")

    with pytest.raises(InsufficientCapacityError):
        await _provider_over_rest(handler).create(make_nodeclaim("oom"))


@async_test
async def test_provider_maps_operation_resource_exhausted_to_insufficient_capacity():
    """Async stockout: create POST succeeds but the LRO completes with a
    google.rpc RESOURCE_EXHAUSTED error — same terminal taxonomy as a
    synchronous 429."""
    from gpu_provisioner_tpu.errors import InsufficientCapacityError
    from gpu_provisioner_tpu.fake import make_nodeclaim

    def handler(req: httpx.Request) -> httpx.Response:
        if req.method == "POST":
            return httpx.Response(200, json={
                "name": "op-1", "status": "DONE",
                "error": {"status": "RESOURCE_EXHAUSTED",
                          "message": "no capacity"}})
        raise AssertionError("no polling expected")

    with pytest.raises(InsufficientCapacityError):
        await _provider_over_rest(handler).create(make_nodeclaim("oom2"))


@async_test
async def test_provider_maps_4xx_to_create_error_with_reason():
    from gpu_provisioner_tpu.errors import CreateError
    from gpu_provisioner_tpu.fake import make_nodeclaim

    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(400, text="bad placementPolicy")

    with pytest.raises(CreateError) as ei:
        await _provider_over_rest(handler).create(make_nodeclaim("bad"))
    assert ei.value.reason == "LaunchFailed"
    assert "placementPolicy" in str(ei.value)


@async_test
async def test_provider_maps_404_to_nodeclaim_not_found():
    from gpu_provisioner_tpu.errors import NodeClaimNotFoundError

    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(404, text="no such pool")

    with pytest.raises(NodeClaimNotFoundError):
        await _provider_over_rest(handler).delete("ghost")


@async_test
async def test_429_split_kube_retries_gcp_does_not():
    """The documented 429 split (transport.py): the kube apiserver's 429 is
    throttling → transport retries it away; the cloud API's 429 is a
    stockout answer → surfaces on the FIRST response, never retried."""
    kube_calls = {"n": 0}

    def kube_handler(req: httpx.Request) -> httpx.Response:
        kube_calls["n"] += 1
        if kube_calls["n"] == 1:
            return httpx.Response(429, text="throttled")
        return httpx.Response(200, json={
            "apiVersion": "v1", "kind": "Node",
            "metadata": {"name": "n1", "resourceVersion": "1"}})

    kube = make_kube_client(kube_handler)
    node = await kube.get(Node, "n1")
    assert node.metadata.name == "n1"
    assert kube_calls["n"] == 2, "kube 429 must be transport-retried"

    gcp_calls = {"n": 0}

    def gcp_handler(req: httpx.Request) -> httpx.Response:
        gcp_calls["n"] += 1
        return httpx.Response(429, text="stockout")

    with pytest.raises(APIError) as ei:
        await gke_client(gcp_handler).get("p1")
    assert ei.value.exhausted
    assert gcp_calls["n"] == 1, "cloud 429 must surface without retry"
