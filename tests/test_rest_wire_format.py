"""Golden wire-format fixtures for providers/rest.py (VERDICT r4 item 5).

The production REST clients hand-build container/v1 and tpu/v2 payloads and
were previously validated only against this repo's own fakes — a field-name
or casing mismatch against the real Google APIs would have passed every
test. This module pins the EXACT wire shapes: each fixture is transcribed
verbatim from the public API references —

  container/v1: NodePool / NodeConfig / NodeTaint / ReservationAffinity /
    PlacementPolicy / Operation messages and the
    projects.locations.clusters.nodePools + projects.locations.operations
    REST resources (cloud.google.com/kubernetes-engine/docs/reference/rest)
  tpu/v2: QueuedResource / Node / SchedulingConfig messages and the
    projects.locations.queuedResources REST resource
    (cloud.google.com/tpu/docs/reference/rest)

and asserted with EXACT dict equality against what the client puts on the
wire (request path, query, envelope, body) and how it parses responses.
Any drift — a renamed field, a k8s-style enum value where the GCP enum is
required, a lost envelope key — fails here even though the fakes
(tests/e2e/backends.py) can't see it.

Reference-parity anchor: the reference's client layer is generated from
Azure API specs so its wire shapes are correct by construction
(azure_client.go:42-47); this hand-built layer earns the same confidence
via these fixtures.
"""

import json

import httpx

from gpu_provisioner_tpu.auth.credentials import StaticTokenCredential
from gpu_provisioner_tpu.providers.gcp import (APIError, NodePool,
                                               NodePoolConfig,
                                               PlacementPolicy,
                                               QueuedResource)
from gpu_provisioner_tpu.providers.rest import (CloudTPUQueuedResourcesClient,
                                                GKENodePoolsClient)
from gpu_provisioner_tpu.transport import TransportOptions

from .conftest import async_test

FAST = TransportOptions(max_retries=2, backoff_base=0.01, backoff_cap=0.02)


def _gke(handler) -> GKENodePoolsClient:
    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    return GKENodePoolsClient(StaticTokenCredential("tok"), "proj-1",
                              "us-west4-a", "cl-1", transport=FAST,
                              http=http)


def _tpu(handler) -> CloudTPUQueuedResourcesClient:
    http = httpx.AsyncClient(transport=httpx.MockTransport(handler))
    return CloudTPUQueuedResourcesClient(StaticTokenCredential("tok"),
                                         "proj-1", "us-central2-b",
                                         transport=FAST, http=http)


# --- container/v1 golden fixtures ------------------------------------------

# CreateNodePoolRequest body — container/v1 REST reference,
# projects.locations.clusters.nodePools.create: the NodePool rides under
# the "nodePool" envelope key; NodeConfig.taints[].effect uses the GCP
# NodeTaint enum (NO_SCHEDULE — NOT k8s's "NoSchedule"), reservationAffinity
# uses consumeReservationType=SPECIFIC_RESERVATION with the documented
# magic key, placementPolicy.tpuTopology is the TPU slice topology string.
GOLDEN_CREATE_NODEPOOL_BODY = {
    "nodePool": {
        "name": "np-a1",
        "config": {
            "machineType": "ct5lp-hightpu-4t",
            "labels": {"kaito.sh/workspace": "ws1"},
            "diskSizeGb": 100,
            "taints": [{"key": "google.com/tpu", "value": "present",
                        "effect": "NO_SCHEDULE"}],
            "spot": True,
            "imageType": "COS_CONTAINERD",
            "reservationAffinity": {
                "consumeReservationType": "SPECIFIC_RESERVATION",
                "key": "compute.googleapis.com/reservation-name",
                "values": ["res-1"],
            },
        },
        "initialNodeCount": 2,
        "placementPolicy": {"type": "COMPACT", "tpuTopology": "2x4"},
    }
}

# container/v1 Operation — its OWN message (status enum PENDING/RUNNING/
# DONE/ABORTING + operationType enum), NOT google.longrunning.Operation
GOLDEN_OPERATION_RUNNING = {
    "name": "operation-1700000000000-abcdef12",
    "operationType": "CREATE_NODE_POOL",
    "status": "RUNNING",
    "selfLink": ("https://container.googleapis.com/v1/projects/proj-1/"
                 "locations/us-west4-a/operations/"
                 "operation-1700000000000-abcdef12"),
    "targetLink": ("https://container.googleapis.com/v1/projects/proj-1/"
                   "locations/us-west4-a/clusters/cl-1/nodePools/np-a1"),
}

GOLDEN_OPERATION_DONE = dict(GOLDEN_OPERATION_RUNNING, status="DONE")

# Operation.error is a google.rpc.Status: INTEGER code (8 =
# RESOURCE_EXHAUSTED), message, details
GOLDEN_OPERATION_STOCKOUT = dict(
    GOLDEN_OPERATION_RUNNING, status="DONE",
    error={"code": 8,
           "message": ("Insufficient quota to satisfy the request: "
                       "resource exhausted")})

# NodePool resource as container/v1 returns it (status is the NodePool
# Status enum; statusMessage is the deprecated-but-still-served field)
GOLDEN_NODEPOOL_RESPONSE = {
    "name": "np-a1",
    "config": {
        "machineType": "ct5lp-hightpu-4t",
        "diskSizeGb": 100,
        "labels": {"kaito.sh/workspace": "ws1"},
        "taints": [{"key": "google.com/tpu", "value": "present",
                    "effect": "NO_SCHEDULE"}],
        "spot": True,
        "imageType": "COS_CONTAINERD",
        "reservationAffinity": {
            "consumeReservationType": "SPECIFIC_RESERVATION",
            "key": "compute.googleapis.com/reservation-name",
            "values": ["res-1"],
        },
    },
    "initialNodeCount": 2,
    "placementPolicy": {"type": "COMPACT", "tpuTopology": "2x4"},
    "status": "PROVISIONING",
    "statusMessage": "",
    "selfLink": ("https://container.googleapis.com/v1/projects/proj-1/"
                 "locations/us-west4-a/clusters/cl-1/nodePools/np-a1"),
}

# googleapis HTTP error envelope (code + message + canonical status string)
GOLDEN_HTTP_404 = {
    "error": {"code": 404,
              "message": ("Not found: projects/proj-1/locations/us-west4-a/"
                          "clusters/cl-1/nodePools/np-a1."),
              "status": "NOT_FOUND"}
}


def _full_pool() -> NodePool:
    return NodePool(
        name="np-a1",
        config=NodePoolConfig(
            machine_type="ct5lp-hightpu-4t",
            disk_size_gb=100,
            labels={"kaito.sh/workspace": "ws1"},
            taints=[{"key": "google.com/tpu", "value": "present",
                     "effect": "NO_SCHEDULE"}],
            spot=True,
            image_type="COS_CONTAINERD",
            reservation="res-1"),
        initial_node_count=2,
        placement_policy=PlacementPolicy(type="COMPACT", tpu_topology="2x4"))


@async_test
async def test_gke_create_request_matches_golden_fixture():
    """EXACT equality of method, URL, query, headers and body against the
    transcribed CreateNodePoolRequest — any extra, missing or renamed
    field fails."""
    seen = {}

    def handler(req: httpx.Request) -> httpx.Response:
        if req.method == "POST":
            seen["method"] = req.method
            seen["url"] = str(req.url)
            seen["auth"] = req.headers["Authorization"]
            seen["ctype"] = req.headers["Content-Type"]
            seen["body"] = json.loads(req.content)
            return httpx.Response(200, json=GOLDEN_OPERATION_DONE)
        return httpx.Response(200, json=GOLDEN_NODEPOOL_RESPONSE)

    client = _gke(handler)
    op = await client.begin_create(_full_pool())
    assert await op.done()
    await op.result()
    assert seen["method"] == "POST"
    assert seen["url"] == ("https://container.googleapis.com/v1/projects/"
                           "proj-1/locations/us-west4-a/clusters/cl-1/"
                           "nodePools")
    assert seen["auth"] == "Bearer tok"
    assert seen["ctype"] == "application/json"
    assert seen["body"] == GOLDEN_CREATE_NODEPOOL_BODY
    await client.aclose()


@async_test
async def test_gke_minimal_pool_omits_optional_fields():
    """A minimal pool must serialize WITHOUT the optional keys — sending
    diskSizeGb=0 or empty taints would be a (tolerated but wrong) shape;
    sending placementPolicy={} would be rejected."""
    pool = NodePool(name="np-min",
                    config=NodePoolConfig(machine_type="e2-medium"),
                    initial_node_count=1)
    wire = GKENodePoolsClient._to_wire(None, pool)
    assert wire == {"name": "np-min",
                    "config": {"machineType": "e2-medium", "labels": {}},
                    "initialNodeCount": 1}


@async_test
async def test_gke_parses_golden_nodepool_response():
    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(200, json=GOLDEN_NODEPOOL_RESPONSE)

    client = _gke(handler)
    pool = await client.get("np-a1")
    assert pool.name == "np-a1"
    assert pool.config.machine_type == "ct5lp-hightpu-4t"
    assert pool.config.disk_size_gb == 100
    assert pool.config.labels == {"kaito.sh/workspace": "ws1"}
    assert pool.config.taints == [{"key": "google.com/tpu",
                                   "value": "present",
                                   "effect": "NO_SCHEDULE"}]
    assert pool.config.spot is True
    assert pool.config.image_type == "COS_CONTAINERD"
    assert pool.config.reservation == "res-1"
    assert pool.initial_node_count == 2
    assert pool.placement_policy.type == "COMPACT"
    assert pool.placement_policy.tpu_topology == "2x4"
    assert pool.status == "PROVISIONING"
    await client.aclose()


@async_test
async def test_gke_operation_poll_path_and_error_status():
    """LRO polling hits projects.locations.operations/{name} (the
    container/v1 operations resource) and a google.rpc.Status error with
    integer code 8 maps to the exhausted taxonomy."""
    polls = []

    def handler(req: httpx.Request) -> httpx.Response:
        if req.method == "POST":
            return httpx.Response(200, json=GOLDEN_OPERATION_RUNNING)
        polls.append(str(req.url))
        return httpx.Response(200, json=GOLDEN_OPERATION_STOCKOUT)

    client = _gke(handler)
    op = await client.begin_create(_full_pool())
    assert await op.done()
    assert polls == [("https://container.googleapis.com/v1/projects/proj-1/"
                      "locations/us-west4-a/operations/"
                      "operation-1700000000000-abcdef12")]
    try:
        await op.result()
        raise AssertionError("stockout must raise")
    except APIError as e:
        assert e.code == 429
    await client.aclose()


@async_test
async def test_gke_delete_and_list_routes():
    calls = []

    def handler(req: httpx.Request) -> httpx.Response:
        calls.append((req.method, str(req.url)))
        if req.method == "DELETE":
            return httpx.Response(200, json=GOLDEN_OPERATION_DONE)
        return httpx.Response(
            200, json={"nodePools": [GOLDEN_NODEPOOL_RESPONSE]})

    client = _gke(handler)
    await client.begin_delete("np-a1")
    pools = await client.list()
    assert pools[0].name == "np-a1"
    assert calls == [
        ("DELETE", "https://container.googleapis.com/v1/projects/proj-1/"
                   "locations/us-west4-a/clusters/cl-1/nodePools/np-a1"),
        ("GET", "https://container.googleapis.com/v1/projects/proj-1/"
                "locations/us-west4-a/clusters/cl-1/nodePools"),
    ]
    await client.aclose()


@async_test
async def test_gke_http_error_envelope_maps_to_not_found():
    def handler(req: httpx.Request) -> httpx.Response:
        return httpx.Response(404, json=GOLDEN_HTTP_404)

    client = _gke(handler)
    try:
        await client.get("np-a1")
        raise AssertionError("404 must raise")
    except APIError as e:
        assert e.code == 404 and e.not_found
    await client.aclose()


# --- tpu/v2 golden fixtures ------------------------------------------------

# queuedResources.create body — tpu/v2 REST reference: the node spec rides
# tpu.nodeSpec[] with a FULL parent path and nodeId; Node.schedulingConfig
# carries the spot flag; reserved capacity = reservationName +
# guaranteed.reserved (Guaranteed message)
GOLDEN_CREATE_QR_BODY = {
    "tpu": {"nodeSpec": [{
        "parent": "projects/proj-1/locations/us-central2-b",
        "nodeId": "np-b2",
        "node": {
            "acceleratorType": "v5litepod-8",
            "runtimeVersion": "tpu-ubuntu2204-base",
            "schedulingConfig": {"spot": True},
        },
    }]},
    "reservationName": ("projects/proj-1/locations/us-central2-b/"
                        "reservations/res-1"),
    "guaranteed": {"reserved": True},
}

# QueuedResource as tpu/v2 returns it: full resource name, state.state is
# the QueuedResourceState enum (WAITING_FOR_RESOURCES while queued)
GOLDEN_QR_RESPONSE = {
    "name": ("projects/proj-1/locations/us-central2-b/queuedResources/"
             "qr-b2"),
    "tpu": {"nodeSpec": [{
        "parent": "projects/proj-1/locations/us-central2-b",
        "nodeId": "np-b2",
        "node": {
            "acceleratorType": "v5litepod-8",
            "runtimeVersion": "tpu-ubuntu2204-base",
            "schedulingConfig": {"spot": True},
        },
    }]},
    "reservationName": ("projects/proj-1/locations/us-central2-b/"
                        "reservations/res-1"),
    "guaranteed": {"reserved": True},
    "state": {"state": "WAITING_FOR_RESOURCES"},
}


@async_test
async def test_tpu_create_request_matches_golden_fixture():
    seen = {}

    def handler(req: httpx.Request) -> httpx.Response:
        if req.method == "POST":
            seen["url"] = str(req.url)
            seen["body"] = json.loads(req.content)
            # create returns a google.longrunning.Operation; the client
            # polls the RESOURCE instead (queued state machine), so a
            # minimal op body is all the real API needs to send
            return httpx.Response(200, json={
                "name": ("projects/proj-1/locations/us-central2-b/"
                         "operations/operation-qr-1"),
                "done": False})
        return httpx.Response(200, json=GOLDEN_QR_RESPONSE)

    client = _tpu(handler)
    qr = await client.create(QueuedResource(
        name="qr-b2", accelerator_type="v5litepod-8",
        runtime_version="tpu-ubuntu2204-base", node_pool="np-b2",
        reservation=("projects/proj-1/locations/us-central2-b/"
                     "reservations/res-1"),
        spot=True))
    # queuedResourceId rides as a QUERY param (the id is not in the body)
    assert seen["url"] == ("https://tpu.googleapis.com/v2/projects/proj-1/"
                           "locations/us-central2-b/queuedResources"
                           "?queuedResourceId=qr-b2")
    assert seen["body"] == GOLDEN_CREATE_QR_BODY
    # the parsed model round-trips the golden response
    assert qr.name == "qr-b2"            # short name, not the full path
    assert qr.state == "WAITING_FOR_RESOURCES"
    assert qr.accelerator_type == "v5litepod-8"
    assert qr.node_pool == "np-b2"
    assert qr.spot is True
    await client.aclose()


@async_test
async def test_tpu_delete_uses_force_query_param():
    calls = []

    def handler(req: httpx.Request) -> httpx.Response:
        calls.append(str(req.url))
        return httpx.Response(200, json={"name": "op", "done": True})

    client = _tpu(handler)
    await client.delete("qr-b2")
    assert calls == [("https://tpu.googleapis.com/v2/projects/proj-1/"
                      "locations/us-central2-b/queuedResources/qr-b2"
                      "?force=true")]
    await client.aclose()


@async_test
async def test_tpu_list_envelope_key():
    def handler(req: httpx.Request) -> httpx.Response:
        assert str(req.url) == ("https://tpu.googleapis.com/v2/projects/"
                                "proj-1/locations/us-central2-b/"
                                "queuedResources")
        return httpx.Response(200, json={
            "queuedResources": [GOLDEN_QR_RESPONSE]})

    client = _tpu(handler)
    qrs = await client.list()
    assert [q.name for q in qrs] == ["qr-b2"]
    await client.aclose()
