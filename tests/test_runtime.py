"""Controller runtime: store semantics, workqueue, controller loops."""

import asyncio

import pytest

from gpu_provisioner_tpu.apis.core import Node
from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import ObjectMeta
from gpu_provisioner_tpu.runtime import (
    Controller, InMemoryClient, Manager, NotFoundError, ConflictError,
    RateLimitingQueue, Request, Result, Singleton,
)
from gpu_provisioner_tpu.runtime.client import patch_retry
from gpu_provisioner_tpu.runtime.store import ADDED, DELETED, MODIFIED

from .conftest import async_test


async def eventually(predicate, timeout=5.0, interval=0.01):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        r = predicate()
        if asyncio.iscoroutine(r):
            r = await r
        if r:
            return
        if asyncio.get_event_loop().time() > deadline:
            raise AssertionError("condition never became true")
        await asyncio.sleep(interval)


def nc(name="a", finalizers=None):
    return NodeClaim(metadata=ObjectMeta(name=name, finalizers=finalizers or []))


# --- store -----------------------------------------------------------------

@async_test
async def test_store_crud_and_conflict():
    c = InMemoryClient()
    created = await c.create(nc())
    assert created.metadata.uid and created.metadata.resource_version
    stale = await c.get(NodeClaim, "a")
    fresh = await c.get(NodeClaim, "a")
    fresh.metadata.labels["x"] = "1"
    await c.update(fresh)
    stale.metadata.labels["y"] = "2"
    with pytest.raises(ConflictError):
        await c.update(stale)
    with pytest.raises(NotFoundError):
        await c.get(NodeClaim, "missing")


@async_test
async def test_generation_bumps_on_spec_only():
    c = InMemoryClient()
    await c.create(nc())
    obj = await c.get(NodeClaim, "a")
    obj.status.provider_id = "gce://p/z/i"
    obj = await c.update_status(obj)
    assert obj.metadata.generation == 1  # status write → no bump
    obj.spec.termination_grace_period = "30s"
    obj = await c.update(obj)
    assert obj.metadata.generation == 2


@async_test
async def test_finalizer_semantics():
    c = InMemoryClient()
    await c.create(nc(finalizers=["karpenter.sh/termination"]))
    await c.delete(NodeClaim, "a")
    obj = await c.get(NodeClaim, "a")  # still there, deletion timestamp set
    assert obj.metadata.deletion_timestamp is not None
    obj.metadata.finalizers = []
    await c.update(obj)
    with pytest.raises(NotFoundError):
        await c.get(NodeClaim, "a")


@async_test
async def test_watch_stream():
    c = InMemoryClient()
    w = c.watch(NodeClaim)
    await c.create(nc())
    obj = await c.get(NodeClaim, "a")
    obj.metadata.labels["x"] = "1"
    await c.update(obj)
    await c.delete(NodeClaim, "a")
    evs = [await asyncio.wait_for(w.__anext__(), 1) for _ in range(3)]
    assert [e.type for e in evs] == [ADDED, MODIFIED, DELETED]
    w.close()


@async_test
async def test_field_index():
    c = InMemoryClient()
    c.store.add_index(Node, "spec.providerID", lambda o: [o.spec.provider_id])
    n = Node(metadata=ObjectMeta(name="n0"))
    n.spec.provider_id = "gce://p/z/i0"
    await c.create(n)
    await c.create(Node(metadata=ObjectMeta(name="n1")))
    hits = await c.list(Node, index=("spec.providerID", "gce://p/z/i0"))
    assert [h.metadata.name for h in hits] == ["n0"]


@async_test
async def test_patch_retry_on_conflict():
    c = InMemoryClient()
    await c.create(nc())

    calls = 0

    def mutate(obj):
        nonlocal calls
        calls += 1
        obj.metadata.labels["x"] = str(calls)

    # sneak a concurrent write in by wrapping update to collide once
    real_update = c.update
    raced = False

    async def racing_update(obj):
        nonlocal raced
        if not raced:
            raced = True
            other = await c.get(NodeClaim, "a")
            other.metadata.annotations["r"] = "1"
            await real_update(other)
        return await real_update(obj)

    c.update = racing_update
    out = await patch_retry(c, NodeClaim, "a", mutate)
    assert out.metadata.labels["x"] == "2" and calls == 2


# --- workqueue -------------------------------------------------------------

@async_test
async def test_workqueue_dedup_and_processing_readd():
    q = RateLimitingQueue()
    await q.add("a")
    await q.add("a")
    assert len(q) == 1
    item = await q.get()
    await q.add("a")          # re-added while processing
    assert len(q) == 0        # goes to dirty, not queue
    await q.done(item)
    assert len(q) == 1        # re-queued after done


@async_test
async def test_workqueue_backoff_and_forget():
    q = RateLimitingQueue(base_delay=0.01, max_delay=1.0)
    await q.add_rate_limited("a")
    assert q.num_requeues("a") == 1
    item = await asyncio.wait_for(q.get(), 2)
    assert item == "a"
    await q.forget("a")
    assert q.num_requeues("a") == 0


@async_test
async def test_workqueue_add_after_ordering():
    q = RateLimitingQueue()
    await q.add_after("slow", 0.05)
    await q.add("fast")
    assert await q.get() == "fast"
    assert await asyncio.wait_for(q.get(), 2) == "slow"


# --- controller/manager ----------------------------------------------------

class CountingReconciler:
    def __init__(self, fail_times=0):
        self.seen: list[Request] = []
        self.fail_times = fail_times

    async def reconcile(self, req: Request) -> Result:
        self.seen.append(req)
        if self.fail_times > 0:
            self.fail_times -= 1
            raise RuntimeError("boom")
        return Result()


@async_test
async def test_controller_watch_drives_reconcile():
    c = InMemoryClient()
    r = CountingReconciler()
    mgr = Manager(c).register(Controller("test", r).watches(NodeClaim))
    await mgr.start()
    try:
        await c.create(nc("x"))
        await eventually(lambda: any(s.name == "x" for s in r.seen))
    finally:
        await mgr.stop()


@async_test
async def test_controller_error_retries_with_backoff():
    c = InMemoryClient()
    r = CountingReconciler(fail_times=2)
    ctrl = Controller("test", r).watches(NodeClaim)
    ctrl.queue.base_delay = 0.01
    mgr = Manager(c).register(ctrl)
    await mgr.start()
    try:
        await c.create(nc("x"))
        await eventually(lambda: len(r.seen) >= 3)  # 2 failures + 1 success
    finally:
        await mgr.stop()


class _Fence:
    def __init__(self, valid=False):
        self._valid = valid

    def valid(self):
        return self._valid


@async_test
async def test_fenced_dequeue_forgets_failure_counter():
    """Regression: the fenced drop path called queue.done but never
    queue.forget, so a deposed-then-re-elected incarnation resumed items
    with stale failure counters pinned at max backoff. A fenced drop is
    not a failure — the counter must clear."""
    c = InMemoryClient()
    r = CountingReconciler()
    ctrl = Controller("test", r).watches(NodeClaim)
    ctrl.queue.base_delay = 0.001
    ctrl.fence = _Fence(valid=False)
    req = Request(name="x")
    # the item arrives carrying failure history from before deposition
    for _ in range(5):
        await ctrl.queue.add_rate_limited(req)
    assert ctrl.queue.num_requeues(req) == 5
    mgr = Manager(c).register(ctrl)
    await mgr.start()
    try:
        await eventually(lambda: ctrl.fenced_total >= 1)
        await eventually(lambda: ctrl.queue.num_requeues(req) == 0)
        assert r.seen == [], "a fenced worker must not reconcile"
        # re-election: the item reconciles with a clean slate
        ctrl.fence = _Fence(valid=True)
        await ctrl.queue.add(req)
        await eventually(lambda: req in r.seen)
        assert ctrl.queue.num_requeues(req) == 0
    finally:
        await mgr.stop()


@async_test
async def test_controller_inject_wakes_reconcile():
    """The tracker-completion early-wake seam: inject() enqueues a request
    outside the watch stream, with workqueue dedup semantics."""
    c = InMemoryClient()
    r = CountingReconciler()
    ctrl = Controller("test", r).watches(NodeClaim)
    mgr = Manager(c).register(ctrl)
    await mgr.start()
    try:
        await ctrl.inject("woken")
        await eventually(lambda: any(s.name == "woken" for s in r.seen))
    finally:
        await mgr.stop()


@async_test
async def test_singleton_self_requeues():
    runs = []

    async def tick() -> float:
        runs.append(1)
        return 0.01

    mgr = Manager(InMemoryClient()).register(
        Controller("gc", Singleton(tick), max_concurrent=1).as_singleton())
    await mgr.start()
    try:
        await eventually(lambda: len(runs) >= 3)
    finally:
        await mgr.stop()


# --- informer cache --------------------------------------------------------

def _informer_test_objs():
    from gpu_provisioner_tpu.apis.core import Node, NodeSpec
    from gpu_provisioner_tpu.apis.meta import ObjectMeta
    return [Node(metadata=ObjectMeta(name=f"n{i}", labels={"grp": "a" if i < 2 else "b"}),
                 spec=NodeSpec(provider_id=f"gce://p/z/i{i}"))
            for i in range(3)]


@async_test
async def test_informer_serves_lists_and_tracks_watch():
    from gpu_provisioner_tpu.apis.core import Node
    from gpu_provisioner_tpu.runtime import InMemoryClient
    from gpu_provisioner_tpu.runtime.informer import CachedListClient

    inner = InMemoryClient()
    for n in _informer_test_objs():
        await inner.create(n)
    client = CachedListClient(inner, (Node,))
    client.add_index(Node, "spec.providerID", lambda o: [o.spec.provider_id])

    # before start: falls through to the inner client
    assert len(await client.list(Node)) == 3

    await client.start()
    try:
        assert len(await client.list(Node)) == 3
        assert len(await client.list(Node, labels={"grp": "a"})) == 2
        (hit,) = await client.list(
            Node, index=("spec.providerID", "gce://p/z/i1"))
        assert hit.metadata.name == "n1"

        # watch maintenance: create/update/delete reflect without re-listing
        from gpu_provisioner_tpu.apis.core import NodeSpec
        from gpu_provisioner_tpu.apis.meta import ObjectMeta
        await inner.create(Node(metadata=ObjectMeta(name="n9"),
                                spec=NodeSpec()))
        await inner.delete(Node, "n0")
        got = await inner.get(Node, "n1")
        got.metadata.labels["grp"] = "b"
        await inner.update(got)
        await asyncio.sleep(0.05)  # let the pump drain
        names = sorted(n.metadata.name for n in await client.list(Node))
        assert names == ["n1", "n2", "n9"]
        assert len(await client.list(Node, labels={"grp": "b"})) == 2

        # cache isolation: mutating a listed object must not poison the cache
        (n1,) = [x for x in await client.list(Node)
                 if x.metadata.name == "n1"]
        n1.metadata.labels["grp"] = "MUTATED"
        fresh = [x for x in await client.list(Node)
                 if x.metadata.name == "n1"][0]
        assert fresh.metadata.labels["grp"] == "b"
    finally:
        await client.stop()


@async_test
async def test_informer_relay_orders_cache_before_handler():
    """controller-runtime parity: a watch handed out by CachedListClient
    delivers each event only AFTER the informer cache reflects it, and a
    late subscription replays the current cache as synthesized ADDED
    events. Pumps riding the raw store instead saw the PR 11 stale-read
    race: a Node-ready event enqueued a reconcile whose slice_nodes LIST
    hit the not-yet-updated informer cache and parked on a timer whose
    wake was already consumed."""
    from gpu_provisioner_tpu.apis.core import Node, NodeSpec, Pod
    from gpu_provisioner_tpu.apis.meta import ObjectMeta
    from gpu_provisioner_tpu.runtime import InMemoryClient
    from gpu_provisioner_tpu.runtime.informer import CachedListClient
    from gpu_provisioner_tpu.runtime.store import ADDED

    inner = InMemoryClient()
    for n in _informer_test_objs():
        await inner.create(n)
    client = CachedListClient(inner, (Node,))
    await client.start()
    try:
        w = client.watch(Node)
        # late subscription: current cache replayed as ADDED, store-watch
        # initial_list parity
        replay = sorted([(await w.__anext__()).object.metadata.name
                         for _ in range(3)])
        assert replay == ["n0", "n1", "n2"]

        await inner.create(Node(metadata=ObjectMeta(name="n9"),
                                spec=NodeSpec()))
        # the informer's own startup watch may re-apply the initial objects
        # (idempotent upserts); consumers are level-triggered, so skip any
        # such duplicates until the live event arrives
        for _ in range(8):
            ev = await asyncio.wait_for(w.__anext__(), 2.0)
            if ev.object.metadata.name == "n9":
                break
        assert ev.type == ADDED and ev.object.metadata.name == "n9"
        # the ordering guarantee: at delivery the cached LIST already
        # serves the event's object — no sleep, checked synchronously
        assert any(n.metadata.name == "n9" for n in await client.list(Node))

        # close is idempotent and ends iteration
        w.close()
        w.close()
        try:
            await asyncio.wait_for(w.__anext__(), 2.0)
            assert False, "closed relay kept yielding"
        except StopAsyncIteration:
            pass

        # uncached kinds fall through to the inner client's watch
        pw = client.watch(Pod)
        assert type(pw).__name__ != "RelayWatch"
        pw.close()
    finally:
        await client.stop()


@async_test
async def test_watch_try_next_nonblocking_drain():
    """Watch.try_next: buffered events come back without awaiting, an empty
    queue returns None (never blocks), and a closed watch returns None —
    the informer pump's burst-drain contract."""
    from gpu_provisioner_tpu.apis.core import Node, NodeSpec
    from gpu_provisioner_tpu.apis.meta import ObjectMeta
    from gpu_provisioner_tpu.runtime import InMemoryClient

    inner = InMemoryClient()
    w = inner.watch(Node)
    assert w.try_next() is None  # empty, not blocked
    for i in range(3):
        await inner.create(Node(metadata=ObjectMeta(name=f"t{i}"),
                                spec=NodeSpec()))
    got = []
    ev = w.try_next()
    while ev is not None:
        got.append(ev.object.metadata.name)
        ev = w.try_next()
    assert got == ["t0", "t1", "t2"]
    w.close()
    assert w.try_next() is None


@async_test
async def test_cached_list_client_index_follows_updates():
    """Field-index and label-index bookkeeping across updates: an updated
    providerID/label must be discoverable under its new value and gone from
    the old one (stale index entries would feed _pool_name_for wrong pools)."""
    from gpu_provisioner_tpu.apis.core import Node
    from gpu_provisioner_tpu.runtime import InMemoryClient
    from gpu_provisioner_tpu.runtime.informer import CachedListClient

    inner = InMemoryClient()
    for n in _informer_test_objs():
        await inner.create(n)
    client = CachedListClient(inner, (Node,))
    client.add_index(Node, "spec.providerID", lambda o: [o.spec.provider_id])
    await client.start()
    try:
        got = await inner.get(Node, "n2")
        got.spec.provider_id = "gce://p/z/moved"
        got.metadata.labels["grp"] = "a"
        await inner.update(got)
        await asyncio.sleep(0.05)
        (hit,) = await client.list(Node, index=("spec.providerID",
                                                "gce://p/z/moved"))
        assert hit.metadata.name == "n2"
        assert await client.list(Node, index=("spec.providerID",
                                              "gce://p/z/i2")) == []
        # and the lookup is served by the inverted map, not a key_fn scan
        inf = client._informers[Node]
        assert ("spec.providerID", "gce://p/z/moved") in inf._by_index
        assert not inf._by_index.get(("spec.providerID", "gce://p/z/i2"))
        assert len(await client.list(Node, labels={"grp": "a"})) == 3
        assert await client.list(Node, labels={"grp": "b"}) == []
        # removal: a deleted object leaves no index residue
        await inner.delete(Node, "n2")
        await asyncio.sleep(0.05)
        assert await client.list(Node, index=("spec.providerID",
                                              "gce://p/z/moved")) == []
        assert len(await client.list(Node, labels={"grp": "a"})) == 2
    finally:
        await client.stop()


@async_test
async def test_cached_list_client_cache_age_staleness():
    """cache_age: 0.0 for uncached/unsynced kinds (reads pass through and
    are always fresh), small once synced, and growing when the watch goes
    quiet — the signal GC's _cache_too_stale bound consumes."""
    from gpu_provisioner_tpu.apis.core import Node, Pod
    from gpu_provisioner_tpu.runtime import InMemoryClient
    from gpu_provisioner_tpu.runtime.informer import CachedListClient

    inner = InMemoryClient()
    client = CachedListClient(inner, (Node,))
    assert client.cache_age(Pod) == 0.0          # kind not cached
    assert client.cache_age(Node) == 0.0         # not synced yet
    await client.start()
    try:
        assert 0.0 <= client.cache_age(Node) < 1.0
        inf = client._informers[Node]
        inf.last_sync -= 1234.0                  # simulate a wedged watch
        assert client.cache_age(Node) > 1000.0
    finally:
        await client.stop()


@async_test
async def test_cached_list_client_label_list_parity_with_raw_client():
    """list-with-labels through the informer must match the raw client
    byte-for-byte (names + labels) across creates, updates and deletes."""
    from gpu_provisioner_tpu.apis.core import Node, NodeSpec
    from gpu_provisioner_tpu.apis.meta import ObjectMeta
    from gpu_provisioner_tpu.runtime import InMemoryClient
    from gpu_provisioner_tpu.runtime.informer import CachedListClient

    inner = InMemoryClient()
    for i in range(6):
        await inner.create(Node(
            metadata=ObjectMeta(name=f"p{i}", labels={
                "pool": f"pool{i % 3}", "zone": "a" if i % 2 else "b"}),
            spec=NodeSpec(provider_id=f"gce://p/z/p{i}")))
    client = CachedListClient(inner, (Node,))
    await client.start()
    try:
        async def parity(labels):
            raw = sorted(n.metadata.name
                         for n in await inner.list(Node, labels=labels))
            cached = sorted(n.metadata.name
                            for n in await client.list(Node, labels=labels))
            assert cached == raw, f"labels={labels}: {cached} != {raw}"

        for sel in (None, {"pool": "pool0"}, {"zone": "a"},
                    {"pool": "pool1", "zone": "b"}, {"pool": "nope"}):
            await parity(sel)
        await inner.delete(Node, "p0")
        got = await inner.get(Node, "p3")
        got.metadata.labels["pool"] = "pool9"
        await inner.update(got)
        await asyncio.sleep(0.05)
        for sel in (None, {"pool": "pool0"}, {"pool": "pool9"}):
            await parity(sel)
    finally:
        await client.stop()
