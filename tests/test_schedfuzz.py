"""schedfuzz: loop-shim determinism, happens-before checker semantics over
synthetic traces, a clean-tree smoke (tier-1's fuzz gate), and the two
mutation tests that prove the explorer's teeth: each reverts a shipped
ordering fix via monkeypatch — the tree is never touched — and asserts the
DEFAULT seed budget catches it and emits a replay file that reproduces."""

import asyncio
import heapq
import json
import time

from gpu_provisioner_tpu.analysis import schedfuzz
from gpu_provisioner_tpu.analysis.schedfuzz import (
    DEFAULT_SEEDS, FuzzEvent, check_cache_before_deliver,
    check_fence_before_mutate, check_meta_before_status,
    check_stale_timer_requeue, check_stop_before_late_wake, explore,
    replay, run_scenario,
)
from gpu_provisioner_tpu.runtime import workqueue
from gpu_provisioner_tpu.runtime.informer import CachedListClient
from gpu_provisioner_tpu.runtime.wakehub import SOURCE_TIMER


def ev(*args, task=None, **info):
    seq, event, key = args
    return FuzzEvent(seq, event, key, task, info)


# ----------------------------------------------- checker unit semantics

def test_cache_before_deliver_counts_per_key_and_skips_uncached():
    key = ("NodeClaim", "", "ws0")
    ok = [ev(0, "cache-apply", key),
          ev(1, "handler-delivery", key, controller="lifecycle")]
    assert check_cache_before_deliver(ok) == []
    # delivery outrunning the apply for the SAME key is the violation
    bad = list(reversed(ok))
    (v,) = check_cache_before_deliver(bad)
    assert v.checker == "cache-before-deliver" and v.seq == 1
    # kinds with no informer (no cache-apply anywhere) are raw watches
    pod = ("Pod", "", "p0")
    raw = [ev(0, "handler-delivery", pod, controller="gc"),
           ev(1, "cache-apply", key)]
    assert check_cache_before_deliver(raw) == []


def test_stale_timer_requeue_allows_the_drop_path():
    ok = [ev(0, "wq-timer-due", "ws0", stale=True),
          ev(1, "wq-stale-drop", "ws0"),
          ev(2, "wq-timer-due", "ws0", stale=False),
          ev(3, "wq-enqueue", "ws0", source="timer")]
    assert check_stale_timer_requeue(ok) == []
    bad = [ev(0, "wq-timer-due", "ws0", stale=True),
           ev(1, "wq-enqueue", "ws0", source="timer")]
    (v,) = check_stale_timer_requeue(bad)
    assert v.checker == "stale-timer-requeue" and v.seq == 1


def test_fence_before_mutate_is_task_scoped():
    ok = [ev(0, "fence-check", None, task="t1"),
          ev(1, "cloud-mutate", "nodepools.begin_create", task="t1")]
    assert check_fence_before_mutate(ok) == []
    # a fence on ANOTHER task does not cover this mutation
    bad = [ev(0, "fence-check", None, task="t1"),
           ev(1, "cloud-mutate", "nodepools.begin_create", task="t2")]
    (v,) = check_fence_before_mutate(bad)
    assert v.checker == "fence-before-mutate"


def test_meta_before_status_counts_per_claim():
    ok = [ev(0, "meta-patch", "a"), ev(1, "status-patch", "a"),
          ev(2, "meta-patch", "b"), ev(3, "status-patch", "b")]
    assert check_meta_before_status(ok) == []
    bad = [ev(0, "meta-patch", "a"), ev(1, "status-patch", "b")]
    (v,) = check_meta_before_status(bad)
    assert v.checker == "meta-before-status" and "'b'" in v.message


def test_stop_before_late_wake():
    ok = [ev(0, "hub-wake", 1, name="ws0", source="lro"),
          ev(1, "hub-stop", 1),
          ev(2, "hub-wake", 2, name="ws0", source="lro")]  # other hub
    assert check_stop_before_late_wake(ok) == []
    bad = ok + [ev(3, "hub-wake", 1, name="late", source="timer")]
    (v,) = check_stop_before_late_wake(bad)
    assert v.checker == "stop-before-late-wake" and "'late'" in v.message


# -------------------------------------------------- loop-shim determinism

def _interleaver():
    async def sample():
        order = []

        async def worker(i):
            for _ in range(4):
                await asyncio.sleep(0)
                order.append(i)

        await asyncio.gather(*(worker(i) for i in range(8)))
        return order

    return sample


def test_same_seed_reproduces_the_decision_stream():
    r1 = run_scenario(_interleaver(), seed=7, checkers={})
    r2 = run_scenario(_interleaver(), seed=7, checkers={})
    assert r1.decisions and r1.decisions == r2.decisions
    assert r1.perturbed_total == r2.perturbed_total


def test_different_seed_explores_a_different_schedule():
    r1 = run_scenario(_interleaver(), seed=7, checkers={})
    r2 = run_scenario(_interleaver(), seed=8, checkers={})
    assert r1.decisions != r2.decisions


def test_scenario_exception_is_a_finding_not_a_crash():
    async def boom():
        raise RuntimeError("interleaving-induced")

    res = run_scenario(boom, seed=0, checkers={})
    assert res.error == "RuntimeError: interleaving-induced"
    assert not res.ok


# ------------------------------------------------------- clean-tree smoke

def test_clean_tree_wave_smoke():
    """Tier-1's fuzz gate: one seed of the wave scenario under the
    perturbed loop, all checkers armed — the full `make fuzz` sweep runs
    under `make chaos` with the real seed budget."""
    res = run_scenario(schedfuzz.scenario_wave, seed=3)
    assert res.error is None, res.error
    assert res.violations == [], res.violations
    # the run actually observed orderings and actually perturbed them
    assert len(res.events) > 50 and res.perturbed_total > 10


# --------------------------------------------------------- mutation tests

def _raw_store_watch(self, cls):
    # PR 11 regression, reverted: hand controllers the raw store watch
    # instead of the informer's post-cache-apply relay.
    return self.inner.watch(cls)


def test_mutation_raw_watch_wiring_is_caught(tmp_path, monkeypatch):
    monkeypatch.setattr(CachedListClient, "watch", _raw_store_watch)
    results = explore(schedfuzz.scenario_wave, name="wave",
                      seeds=range(DEFAULT_SEEDS), replay_dir=tmp_path,
                      stop_on_first=True)
    bad = [r for r in results if r.violations]
    assert bad, "raw-watch wiring escaped the default seed budget"
    first = bad[0]
    assert "cache-before-deliver" in {v.checker for v in first.violations}
    # the replay file is complete and re-finds the same contract breach
    data = json.loads(first.replay_path.read_text())
    assert data["format"] == schedfuzz.REPLAY_FORMAT
    assert data["seed"] == first.seed and data["violations"]
    res2 = replay(first.replay_path)
    assert "cache-before-deliver" in {v.checker for v in res2.violations}


def _unguarded_drain(self):
    # PR 11's epoch guard deleted: a stale safety-net timer enqueues a
    # spurious reconcile instead of being dropped. Probes kept — the
    # mutation removes the GUARD, not the observability.
    nxt = None
    now = time.monotonic()
    while self._delayed:
        due, _, item, epoch = self._delayed[0]
        if due <= now:
            heapq.heappop(self._delayed)
            workqueue.probes.emit(
                "wq-timer-due", item,
                stale=epoch != self._epoch.get(item, 0))
            self._add_locked(item, source=SOURCE_TIMER)
        else:
            nxt = due - now
            break
    return nxt


def test_mutation_unguarded_epoch_is_caught(tmp_path, monkeypatch):
    monkeypatch.setattr(workqueue.RateLimitingQueue,
                        "_drain_delayed_locked", _unguarded_drain)
    results = explore(schedfuzz.scenario_churn, name="churn",
                      seeds=range(DEFAULT_SEEDS), replay_dir=tmp_path,
                      stop_on_first=True)
    bad = [r for r in results if r.violations]
    assert bad, "unguarded epoch drain escaped the default seed budget"
    assert "stale-timer-requeue" in {v.checker
                                     for v in bad[0].violations}
    res2 = replay(bad[0].replay_path)
    assert "stale-timer-requeue" in {v.checker for v in res2.violations}
