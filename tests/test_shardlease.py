"""Lease-based claim-range ownership (runtime/shardlease.py): fair-share
split, no-stop rebalance on topology change, expiry adoption after a worker
death, and the dequeue fence that closes the handoff window."""

import asyncio

import pytest

from gpu_provisioner_tpu.apis.core import Lease
from gpu_provisioner_tpu.runtime import Controller, InMemoryClient
from gpu_provisioner_tpu.runtime.controller import Request, Result
from gpu_provisioner_tpu.runtime.shardlease import (
    NUM_RANGES, ShardLeaseTable, holders, range_of,
)
from gpu_provisioner_tpu.runtime.wakehub import (
    SKIPPED_TIMER_ARM, SOURCE_LRO, WAKES, WakeHub,
)

from .conftest import async_test


def fast_table(client, ident, target, **kw):
    kw.setdefault("lease_duration", 0.4)
    kw.setdefault("renew_interval", 0.05)
    return ShardLeaseTable(client, identity=ident,
                           target_workers=target, **kw)


async def all_holders(client):
    return holders(await client.list(Lease, namespace="kube-system"))


def test_range_of_is_stable_and_bounded():
    assert range_of("claim-0") == range_of("claim-0")
    for name in (f"claim-{i}" for i in range(200)):
        assert 0 <= range_of(name) < NUM_RANGES


@async_test
async def test_fair_share_split_covers_every_range():
    client = InMemoryClient()
    a = fast_table(client, "a", 2)
    b = fast_table(client, "b", 2)
    try:
        await a.start()
        await b.start()
        for _ in range(40):
            if len(a.ranges) == 32 and len(b.ranges) == 32:
                break
            await asyncio.sleep(0.05)
        assert len(a.ranges) == 32 and len(b.ranges) == 32
        assert a.ranges | b.ranges == set(range(NUM_RANGES))
        assert not (a.ranges & b.ranges)
        # every claim name has exactly one owner
        for name in (f"claim-{i}" for i in range(100)):
            assert a.owns(name) != b.owns(name)
    finally:
        await a.stop()
        await b.stop()


@async_test
async def test_scale_up_rebalances_without_double_ownership():
    """1 → 2 workers by lease handoff: at every observation point each
    range has at most one holder (CAS guarantees it), and the steady state
    is an exact fair-share split — the no-stop topology change."""
    client = InMemoryClient()
    a = fast_table(client, "a", 1)
    try:
        await a.start()
        assert a.ranges == set(range(NUM_RANGES))
        b = fast_table(client, "b", 2)
        a.set_target_workers(2)
        try:
            await b.start()
            for _ in range(60):
                held = await all_holders(client)
                total = sum(len(v) for v in held.values())
                distinct = set().union(*held.values()) if held else set()
                assert total == len(distinct), f"double-held range: {held}"
                if (len(a.ranges) == 32 and len(b.ranges) == 32
                        and a.ranges | b.ranges == set(range(NUM_RANGES))):
                    break
                await asyncio.sleep(0.05)
            assert len(a.ranges) == 32 and len(b.ranges) == 32
        finally:
            await b.stop()
    finally:
        await a.stop()


@async_test
async def test_shrink_releases_for_instant_takeover():
    """Graceful scale-down: the retiring table releases (renew_time zeroed)
    so the survivor reclaims the ranges on its next tick — no expiry wait."""
    client = InMemoryClient()
    a = fast_table(client, "a", 2)
    b = fast_table(client, "b", 2)
    try:
        await a.start()
        await b.start()
        for _ in range(40):
            if len(a.ranges) == 32 and len(b.ranges) == 32:
                break
            await asyncio.sleep(0.05)
        await b.stop(release=True)
        assert b.released_total >= 32
        a.set_target_workers(1)
        for _ in range(40):
            if a.ranges == set(range(NUM_RANGES)):
                break
            await asyncio.sleep(0.05)
        assert a.ranges == set(range(NUM_RANGES))
        # released-not-expired ranges are plain acquires, not adoptions
        assert a.adopted_total == 0
    finally:
        await a.stop()


@async_test
async def test_dead_worker_ranges_adopted_after_expiry():
    """SIGKILL analog: the table stops renewing WITHOUT releasing. A
    survivor adopts every expired range once the duration passes — claims
    are reclaimed, not orphaned."""
    client = InMemoryClient()
    a = fast_table(client, "a", 1, lease_duration=0.3)
    await a.start()
    await a.stop(release=False)  # death: renew loop gone, leases still held
    b = fast_table(client, "b", 1, lease_duration=0.3)
    try:
        await b.start()
        assert b.ranges == set(), "must not steal an unexpired lease"
        for _ in range(60):
            if b.ranges == set(range(NUM_RANGES)):
                break
            await asyncio.sleep(0.05)
        assert b.ranges == set(range(NUM_RANGES))
        assert b.adopted_total == NUM_RANGES
        held = await all_holders(client)
        assert set(held) == {"b"}
    finally:
        await b.stop()


@async_test
async def test_on_change_fires_with_gained_and_lost_sets():
    client = InMemoryClient()
    events = []
    a = fast_table(client, "a", 1,
                   on_change=lambda g, l: events.append((set(g), set(l))))
    try:
        await a.start()
        assert events and events[0][0] == set(range(NUM_RANGES))
        a.set_target_workers(4)  # share shrinks 64 → 16: ranges released
        for _ in range(40):
            if len(a.ranges) == 16:
                break
            await asyncio.sleep(0.05)
        lost = set().union(*(l for _, l in events))
        assert len(a.ranges) == 16 and len(lost) == 48
    finally:
        await a.stop()


# ---------------------------------------------------------- handoff fences

@async_test
async def test_dequeue_fence_drops_disowned_item_exactly_once():
    """The handoff window: an item enqueued while this worker owned its
    range, dequeued after the lease moved, must DROP (the new owner's
    replay re-drives it) — reconciling would double-write."""
    reconciled = []

    class R:
        async def reconcile(self, req):
            reconciled.append(req.name)
            return Result()

    owned = {"mine"}
    c = Controller("t", R(), max_concurrent=1)
    c.owns = lambda name: name in owned
    await c.queue.add(Request(name="mine"))
    await c.queue.add(Request(name="foreign"))
    tasks = [asyncio.create_task(c._worker())]
    try:
        for _ in range(100):
            if c.disowned_total:
                break
            await asyncio.sleep(0.01)
        assert reconciled == ["mine"]
        assert c.disowned_total == 1
    finally:
        for t in tasks:
            t.cancel()
        for t in tasks:
            with pytest.raises(asyncio.CancelledError):
                await t


@async_test
async def test_timer_diet_skips_arm_for_announced_source():
    """Satellite 1: a park annotated with an event wake source whose
    producer is announced on the hub skips the safety-net timer arm, and
    the skip lands in the WAKES ledger (not as a delivered wake)."""
    parked = asyncio.Event()

    class R:
        async def reconcile(self, req):
            parked.set()
            return Result(requeue_after=30.0, wake_source=SOURCE_LRO)

    hub = WakeHub()
    hub.announce(SOURCE_LRO)
    c = Controller("t", R(), max_concurrent=1)
    c.wake_hub = hub
    before = WAKES.get(SKIPPED_TIMER_ARM, 0)
    await c.queue.add(Request(name="x"))
    task = asyncio.create_task(c._worker())
    try:
        await asyncio.wait_for(parked.wait(), timeout=5)
        await asyncio.sleep(0.05)
        assert WAKES.get(SKIPPED_TIMER_ARM, 0) == before + 1
        assert c.queue.delayed() == 0, "safety-net timer must NOT be armed"
    finally:
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
    await c.queue.shutdown()


@async_test
async def test_timer_diet_arms_fallback_not_full_requeue():
    """The un-sourced residue of a folded park (liveness budget) still
    arms — the diet removes redundant timers, never the last-resort one."""
    parked = asyncio.Event()

    class R:
        async def reconcile(self, req):
            parked.set()
            return Result(requeue_after=30.0, wake_source=SOURCE_LRO,
                          fallback_after=600.0)

    hub = WakeHub()
    hub.announce(SOURCE_LRO)
    c = Controller("t", R(), max_concurrent=1)
    c.wake_hub = hub
    await c.queue.add(Request(name="x"))
    task = asyncio.create_task(c._worker())
    try:
        await asyncio.wait_for(parked.wait(), timeout=5)
        await asyncio.sleep(0.05)
        assert c.queue.delayed() == 1, "fallback deadline must stay armed"
    finally:
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
    await c.queue.shutdown()
