"""Multi-process shard workers end to end: a ShardSupervisor spawning REAL
worker processes (operator/shardworker.py) over the shard IPC socket,
provisioning through the parent's store + fake cloud; then the crash
matrix's process-level analog — SIGKILL a worker, survivors adopt its
leased ranges, zero duplicate cloud mutations."""

import asyncio

from gpu_provisioner_tpu.apis.karpenter import NodeClaim
from gpu_provisioner_tpu.apis.meta import CONDITION_READY
from gpu_provisioner_tpu.fake import make_nodeclaim
from gpu_provisioner_tpu.fake.cloud import FakeCloud
from gpu_provisioner_tpu.operator.supervisor import ShardSupervisor
from gpu_provisioner_tpu.runtime import InMemoryClient

from .conftest import async_test_long

# Worker-side knobs: fast tracker polls so LRO completions land quickly on
# a 1-core host running parent + N workers.
WORKER_OPTS = {"operation_poll_interval": 0.1, "node_wait_interval": 0.1}


def make_supervisor(client, cloud):
    return ShardSupervisor(client, cloud, worker_opts=WORKER_OPTS,
                           lease_duration=1.0, renew_interval=0.2)


async def wait_all_ready(client, names, timeout=60.0):
    deadline = asyncio.get_event_loop().time() + timeout
    pending = set(names)
    while pending:
        for name in sorted(pending):
            nc = await client.get(NodeClaim, name)
            if nc.status_conditions.is_true(CONDITION_READY):
                pending.discard(name)
        if not pending:
            return
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(f"claims not ready after {timeout}s: "
                               f"{sorted(pending)}")
        await asyncio.sleep(0.1)


def create_calls(cloud: FakeCloud) -> int:
    # the fake ledgers each call twice: bare endpoint + zone-suffixed
    return cloud.nodepools.calls.get("begin_create", 0)


@async_test_long
async def test_two_workers_provision_and_survive_sigkill():
    client = InMemoryClient()
    cloud = FakeCloud(client, create_latency=0.05, delete_latency=0.02)
    sup = make_supervisor(client, cloud)
    await sup.start()
    try:
        await sup.spawn(2)
        await sup.wait_covered(timeout=45.0, workers=2)
        # both workers hold a nonempty share — the relay/lease boot worked
        shares = {c.worker: len(c.ranges) for c in sup.server.conns}
        assert len(shares) == 2 and all(shares.values()), shares

        first = [f"pc{i}" for i in range(10)]
        for name in first:
            await client.create(make_nodeclaim(name, "tpu-v5e-8"))
        await wait_all_ready(client, first)
        calls_after_first = create_calls(cloud)
        assert calls_after_first == len(first)

        # hard-kill one worker: no lease release, no goodbye. The
        # supervisor reaps it and shrinks the target; the survivor's next
        # lease tick adopts the expired ranges.
        victim = sorted(sup.procs)[0]
        sup.kill(victim)
        await sup.reap(victim)
        await sup.wait_covered(timeout=45.0, workers=1)

        second = [f"qc{i}" for i in range(6)]
        for name in second:
            await client.create(make_nodeclaim(name, "tpu-v5e-8"))
        await wait_all_ready(client, second)

        # zero duplicate cloud mutations across the handoff: one create per
        # claim (adoption replays reconcile already-Ready claims, which
        # must be cloud-idempotent), one pool per claim, nothing deleted
        assert create_calls(cloud) == len(first) + len(second)
        pools = await cloud.nodepools.list()
        assert len(pools) == len(first) + len(second)
        assert cloud.nodepools.calls.get("begin_delete", 0) == 0

        # cross-process wake transport: the parent routes a sourced wake to
        # the owning worker, which delivers it into its local hub — the
        # wake lands in that worker's ledger under the ORIGINAL source
        routed_before = sup.server.wakes_routed
        sup.server.route_wake("pc0", "inject")
        assert sup.server.wakes_routed == routed_before + 1
        deadline = asyncio.get_event_loop().time() + 10.0
        while True:
            if any(s.get("wakes", {}).get("inject")
                   for s in sup.snapshots().values()):
                break
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError("routed wake never reached a worker hub")
            await asyncio.sleep(0.1)

        # the survivor's snapshots made it to the parent (the /metrics fold
        # and the fleet SLO merge read these)
        snaps = sup.snapshots()
        assert snaps, "no worker snapshots received"
        snap = next(iter(snaps.values()))
        assert snap["lease"]["ranges"], snap
        assert "wakes" in snap and "fleet" in snap
        # the mirror folded worker digests: every ready claim observed
        assert sup.mirror.claims_observed >= len(first + second) // 2
    finally:
        await sup.stop()


@async_test_long
async def test_scale_is_lease_handoff_not_restart():
    """scale(1→2) splits ranges between live workers without dropping a
    claim: work created mid-rebalance still converges, each claim owned by
    exactly one worker at the end."""
    client = InMemoryClient()
    cloud = FakeCloud(client, create_latency=0.05, delete_latency=0.02)
    sup = make_supervisor(client, cloud)
    await sup.start()
    try:
        await sup.spawn(1)
        await sup.wait_covered(timeout=45.0, workers=1)
        names = [f"sc{i}" for i in range(6)]
        for name in names[:3]:
            await client.create(make_nodeclaim(name, "tpu-v5e-8"))
        await sup.scale(2)  # no stop: the original worker keeps running
        for name in names[3:]:
            await client.create(make_nodeclaim(name, "tpu-v5e-8"))
        await sup.wait_covered(timeout=45.0, workers=2)
        await wait_all_ready(client, names)
        assert create_calls(cloud) == len(names)
        shares = {c.worker: set(c.ranges) for c in sup.server.conns}
        assert len(shares) == 2
        owned = set()
        for ranges in shares.values():
            assert not (owned & ranges), "range held by two live workers"
            owned |= ranges
    finally:
        await sup.stop()
