"""Speculative decoding (models/speculative.py): greedy spec-decode must
emit EXACTLY plain greedy's token stream — the acceptance rule only keeps
tokens the target itself argmaxes. The draft only buys latency."""

import jax
import jax.numpy as jnp
import pytest

from gpu_provisioner_tpu.models.decode import generate
from gpu_provisioner_tpu.models.llama import LlamaConfig, init_params
from gpu_provisioner_tpu.models.speculative import speculative_generate

CFG_T = LlamaConfig(vocab_size=128, dim=64, n_layers=4, n_heads=4,
                    n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                    dtype="float32")
CFG_D = LlamaConfig(vocab_size=128, dim=32, n_layers=2, n_heads=2,
                    n_kv_heads=1, hidden_dim=64, max_seq_len=512,
                    dtype="float32")


def _models(seed=0):
    return (init_params(jax.random.key(seed), CFG_T),
            init_params(jax.random.key(seed + 1), CFG_D))


def test_speculative_equals_plain_greedy():
    """The core guarantee, with an unrelated draft (worst case: most
    proposals rejected — still exact, just fewer tokens per round)."""
    params, draft = _models()
    prompt = jax.random.randint(jax.random.key(5), (1, 24), 0, 128)
    want = generate(params, prompt, CFG_T, max_new_tokens=24, max_len=256)
    got, stats = speculative_generate(params, draft, prompt, CFG_T, CFG_D,
                                      max_new_tokens=24, spec_k=4)
    assert (got == want).all(), (got, want)
    assert int(stats["target_calls"]) <= 24


def test_speculative_self_draft_max_acceptance():
    """Draft == target: every proposal is accepted, so each round emits
    spec_k+1 tokens and target calls collapse to ~max_new/(spec_k+1)."""
    params, _ = _models()
    prompt = jax.random.randint(jax.random.key(6), (1, 16), 0, 128)
    want = generate(params, prompt, CFG_T, max_new_tokens=20, max_len=256)
    got, stats = speculative_generate(params, params, prompt, CFG_T, CFG_T,
                                      max_new_tokens=20, spec_k=4)
    assert (got == want).all()
    # 20 tokens / 5-per-round = 4 rounds + 1 prefill-emitted token
    assert int(stats["target_calls"]) <= 5


def test_speculative_under_jit():
    params, draft = _models(seed=2)
    prompt = jax.random.randint(jax.random.key(7), (1, 16), 0, 128)
    f = jax.jit(lambda p, d, t: speculative_generate(
        p, d, t, CFG_T, CFG_D, max_new_tokens=12, spec_k=3))
    got, stats = f(params, draft, prompt)
    want = generate(params, prompt, CFG_T, max_new_tokens=12, max_len=256)
    assert (got == want).all()


def test_speculative_validation():
    params, draft = _models()
    import dataclasses
    bad_vocab = dataclasses.replace(CFG_D, vocab_size=64)
    with pytest.raises(ValueError, match="vocabulary"):
        speculative_generate(params, draft, jnp.zeros((1, 8), jnp.int32),
                             CFG_T, bad_vocab, max_new_tokens=4)
    with pytest.raises(ValueError, match="max_len"):
        speculative_generate(params, draft, jnp.zeros((1, 8), jnp.int32),
                             CFG_T, CFG_D, max_new_tokens=16, max_len=20)


def test_speculative_batched_equals_plain_greedy():
    """Batched speculation (per-row acceptance lengths / per-row cache
    lengths) emits row-for-row exactly what plain batched greedy decoding
    emits — VERDICT r4 item 4."""
    params, draft = _models(seed=6)
    prompt = jax.random.randint(jax.random.key(20), (4, 16), 0, 128)
    want = generate(params, prompt, CFG_T, max_new_tokens=24, max_len=256)
    got, stats = speculative_generate(params, draft, prompt, CFG_T, CFG_D,
                                      max_new_tokens=24, spec_k=3)
    assert got.shape == (4, 24)
    assert (got == want).all()
    # rows accept at different rates, yet rounds ≤ what the SLOWEST row
    # would need alone; self-draft still fully accepts per row
    got2, stats2 = speculative_generate(params, params, prompt, CFG_T,
                                        CFG_T, max_new_tokens=24, spec_k=3)
    assert (got2 == want).all()
    assert int(stats2["target_calls"]) <= 7   # ceil((24-1)/4) + 1


def test_speculative_batched_ragged_pad_id():
    """Left-padded ragged batch: each padded row generates exactly what
    plain generate's pad_id path emits for it."""
    PAD = 0
    params, draft = _models(seed=7)
    prompt = jax.random.randint(jax.random.key(21), (3, 20), 1, 128)
    pads = jnp.asarray([0, 5, 11])
    col = jnp.arange(20)[None, :]
    prompt = jnp.where(col < pads[:, None], PAD, prompt)
    want = generate(params, prompt, CFG_T, max_new_tokens=16, max_len=256,
                    pad_id=PAD)
    got, _ = speculative_generate(params, draft, prompt, CFG_T, CFG_D,
                                  max_new_tokens=16, pad_id=PAD, spec_k=3)
    assert (got == want).all()


def test_speculative_batched_moe_target():
    """Batched speculation composes with the dropless MoE verify: per-row
    cache lengths through moe_cached_forward, Mixtral-style capacity."""
    from gpu_provisioner_tpu.models.moe import MoEConfig, init_moe_model

    moe_cfg = MoEConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                        n_experts=8, experts_per_token=2,
                        capacity_factor=1.25, dtype="float32")
    moe_params = init_moe_model(jax.random.key(22), moe_cfg)
    _, draft = _models()
    prompt = jax.random.randint(jax.random.key(23), (3, 16), 0, 128)
    want = generate(moe_params, prompt, moe_cfg, max_new_tokens=12,
                    max_len=256)
    got, _ = speculative_generate(moe_params, draft, prompt, moe_cfg,
                                  CFG_D, max_new_tokens=12, spec_k=2)
    assert (got == want).all()


def test_speculative_batched_int8_target():
    """Batched speculation against an int8-cache target: the per-row
    verify writes land VALUES AND SCALES at per-row offsets (the scale
    buffers ride the same vmapped scatter) — stream equals plain int8
    decode row-for-row."""
    import dataclasses

    cfg8 = dataclasses.replace(CFG_T, kv_cache_dtype="int8")
    params, draft = _models(seed=10)
    prompt = jax.random.randint(jax.random.key(30), (3, 16), 0, 128)
    want = generate(params, prompt, cfg8, max_new_tokens=12, max_len=256)
    got, _ = speculative_generate(params, draft, prompt, cfg8, CFG_D,
                                  max_new_tokens=12, spec_k=3)
    assert (got == want).all()


def test_speculative_batched_sampled_in_vocab_reproducible():
    """Sampled batched speculation: deterministic under a fixed key, all
    tokens in-vocab, per-row token counts correct."""
    params, draft = _models(seed=8)
    prompt = jax.random.randint(jax.random.key(24), (3, 12), 0, 128)
    kw = dict(max_new_tokens=12, spec_k=3, temperature=0.9, top_k=40,
              key=jax.random.key(25))
    a, sa = speculative_generate(params, draft, prompt, CFG_T, CFG_D, **kw)
    b, sb = speculative_generate(params, draft, prompt, CFG_T, CFG_D, **kw)
    assert (a == b).all()
    assert ((a >= 0) & (a < 128)).all()
    assert sa["tokens"].shape == (3,)
    assert (sa["tokens"] == 12).all()


def test_speculative_batched_eos_per_row():
    """eos finishing is PER ROW: a row that hits eos stops contributing
    (its tail reads eos_id) while other rows keep generating — matching
    generate()'s row-wise finish semantics."""
    params, draft = _models(seed=9)
    prompt = jax.random.randint(jax.random.key(26), (4, 12), 0, 128)
    # pick an eos that actually appears early in some row's greedy stream
    free = generate(params, prompt, CFG_T, max_new_tokens=16, max_len=256)
    eos = int(free[0, 3])
    want = generate(params, prompt, CFG_T, max_new_tokens=16, max_len=256,
                    eos_id=eos)
    got, stats = speculative_generate(params, draft, prompt, CFG_T, CFG_D,
                                      max_new_tokens=16, spec_k=3,
                                      eos_id=eos)
    assert (got == want).all()
    assert stats["tokens"].shape == (4,)


def test_spec_accept_preserves_target_distribution():
    """The correctness theorem, measured: with proposals drawn from the
    draft distribution, the first emitted token's empirical law must be
    the TARGET distribution — regardless of how different the draft is."""
    import numpy as np

    from gpu_provisioner_tpu.models.speculative import _spec_accept

    V, K, N = 7, 3, 20000
    kd, kt = jax.random.split(jax.random.key(42))
    p_d = jax.nn.softmax(jax.random.normal(kd, (K, V)) * 1.5, axis=-1)
    p_t = jax.nn.softmax(jax.random.normal(kt, (K + 1, V)) * 1.5, axis=-1)

    def one(key):
        kp, ka = jax.random.split(key)
        # sequential draft draws (independent dists stand in for the
        # prefix-conditioned ones; the acceptance math doesn't care)
        proposal = jax.vmap(
            lambda k, p: jax.random.categorical(k, jnp.log(p)))(
                jax.random.split(kp, K), p_d).astype(jnp.int32)
        m, bonus = _spec_accept(ka, proposal, p_d, p_t)
        return jnp.where(m > 0, proposal[0], bonus)   # first emitted token

    toks = jax.vmap(one)(jax.random.split(jax.random.key(7), N))
    emp = np.bincount(np.asarray(toks), minlength=V) / N
    np.testing.assert_allclose(emp, np.asarray(p_t[0]), atol=0.015)


def test_speculative_sampled_reproducible_in_vocab():
    params, draft = _models(seed=3)
    prompt = jax.random.randint(jax.random.key(8), (1, 16), 0, 128)
    kw = dict(max_new_tokens=16, spec_k=3, temperature=0.9, top_k=40,
              top_p=0.95, key=jax.random.key(11))
    a, sa = speculative_generate(params, draft, prompt, CFG_T, CFG_D, **kw)
    b, sb = speculative_generate(params, draft, prompt, CFG_T, CFG_D, **kw)
    assert (a == b).all()
    assert ((a >= 0) & (a < 128)).all()
    assert int(sa["target_calls"]) <= 16
    with pytest.raises(ValueError, match="PRNG"):
        speculative_generate(params, draft, prompt, CFG_T, CFG_D,
                             max_new_tokens=4, temperature=0.9)


def test_speculative_moe_target_dense_draft():
    """The production pairing: a cheap dense draft speculates for an MoE
    target — output must equal the MoE model's own plain greedy stream."""
    from gpu_provisioner_tpu.models.moe import MoEConfig, init_moe_model

    moe_cfg = MoEConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                        n_experts=4, experts_per_token=2,
                        capacity_factor=8.0, dtype="float32")
    moe_params = init_moe_model(jax.random.key(9), moe_cfg)
    _, draft = _models()
    prompt = jax.random.randint(jax.random.key(10), (1, 16), 0, 128)
    want = generate(moe_params, prompt, moe_cfg, max_new_tokens=12,
                    max_len=256)
    got, stats = speculative_generate(moe_params, draft, prompt, moe_cfg,
                                      CFG_D, max_new_tokens=12, spec_k=3)
    assert (got == want).all()
    # self-draft MoE: full acceptance
    got2, stats2 = speculative_generate(moe_params, moe_params, prompt,
                                        moe_cfg, moe_cfg,
                                        max_new_tokens=12, spec_k=3)
    assert (got2 == want).all()
    assert int(stats2["target_calls"]) <= 4


def test_speculative_moe_target_mixtral_capacity_exact():
    """Mixtral-SHAPED capacity (cf=1.25, k=2, E=8): the training capacity
    for a spec_k+1 verify block is capacity(cfg, 3) = max(1, int(1.25·2·3/8))
    = 1 slot per expert — a block where several tokens pick the same expert
    WOULD drop without the verify-time dropless override. Greedy equality
    with plain decode must hold anyway (VERDICT r4 item 3)."""
    from gpu_provisioner_tpu.models.moe import (MoEConfig, capacity,
                                                init_moe_model)

    moe_cfg = MoEConfig(vocab_size=128, dim=64, n_layers=2, n_heads=4,
                        n_kv_heads=2, hidden_dim=128, max_seq_len=512,
                        n_experts=8, experts_per_token=2,
                        capacity_factor=1.25, dtype="float32")
    # the premise: the training capacity really is dropful for this block
    assert capacity(moe_cfg, 3) < 3
    moe_params = init_moe_model(jax.random.key(13), moe_cfg)
    _, draft = _models()
    prompt = jax.random.randint(jax.random.key(14), (1, 16), 0, 128)
    want = generate(moe_params, prompt, moe_cfg, max_new_tokens=16,
                    max_len=256)
    got, _ = speculative_generate(moe_params, draft, prompt, moe_cfg,
                                  CFG_D, max_new_tokens=16, spec_k=2)
    assert (got == want).all()
    # self-draft at the same capacity: full acceptance AND exactness
    got2, stats2 = speculative_generate(moe_params, moe_params, prompt,
                                        moe_cfg, moe_cfg,
                                        max_new_tokens=16, spec_k=2)
    assert (got2 == want).all()
    assert int(stats2["target_calls"]) <= 6


def test_speculative_swa_sinks_target():
    """Speculation composes with sliding-window + sinks targets: the
    verify/prefill calls route through the windowed serving kernels and
    greedy equality with plain generate still holds."""
    import dataclasses

    cfg_t = dataclasses.replace(CFG_T, sliding_window=16, attn_sinks=2)
    params, draft = _models(seed=5)
    prompt = jax.random.randint(jax.random.key(12), (1, 24), 0, 128)
    want = generate(params, prompt, cfg_t, max_new_tokens=16, max_len=256)
    got, stats = speculative_generate(params, draft, prompt, cfg_t, CFG_D,
                                      max_new_tokens=16, spec_k=3)
    assert (got == want).all()


def test_speculative_eos_matches_generate_and_early_exits():
    """eos_id: emitted stream equals generate()'s finish semantics (every
    position after the first eos reads eos_id) AND speculation stops
    early — fewer target calls than the no-eos run."""
    params, draft = _models(seed=7)
    prompt = jax.random.randint(jax.random.key(13), (1, 16), 0, 128)
    plain = generate(params, prompt, CFG_T, max_new_tokens=20, max_len=256)
    eos = int(plain[0, 4])               # the 5th greedy token → early eos
    want = generate(params, prompt, CFG_T, max_new_tokens=20, max_len=256,
                    eos_id=eos)
    got, stats = speculative_generate(params, draft, prompt, CFG_T, CFG_D,
                                      max_new_tokens=20, spec_k=3,
                                      eos_id=eos)
    assert (got == want).all(), (got, want)
    _, stats_noeos = speculative_generate(params, draft, prompt, CFG_T,
                                          CFG_D, max_new_tokens=20,
                                          spec_k=3)
    assert int(stats["target_calls"]) < int(stats_noeos["target_calls"])


def test_speculative_logprobs_match_generate():
    """Greedy logprobs under the target's unfiltered distribution — must
    equal generate(return_logprobs=True)'s at every emitted position."""
    import numpy as np

    params, draft = _models(seed=8)
    prompt = jax.random.randint(jax.random.key(14), (1, 16), 0, 128)
    want_t, want_lp = generate(params, prompt, CFG_T, max_new_tokens=16,
                               max_len=256, return_logprobs=True)
    got_t, got_lp, stats = speculative_generate(
        params, draft, prompt, CFG_T, CFG_D, max_new_tokens=16, spec_k=3,
        return_logprobs=True)
    assert (got_t == want_t).all()
    np.testing.assert_allclose(np.asarray(got_lp), np.asarray(want_lp),
                               atol=1e-4, rtol=1e-4)


def test_speculative_logprobs_sampled_and_eos():
    """Sampled-mode logprobs: finite, <= 0, consistent with the emitted
    tokens' filtered target distribution (spot-checked at position 0,
    which always comes from the prefill logits); post-eos positions
    report exactly 0.0."""
    import numpy as np

    from gpu_provisioner_tpu.models.decode import (filter_logits, prefill,
                                                   init_kv_cache)

    params, draft = _models(seed=9)
    prompt = jax.random.randint(jax.random.key(15), (1, 16), 0, 128)
    kw = dict(max_new_tokens=12, spec_k=3, temperature=0.9, top_k=40,
              key=jax.random.key(16), return_logprobs=True)
    toks, lps, stats = speculative_generate(params, draft, prompt, CFG_T,
                                            CFG_D, **kw)
    assert np.isfinite(np.asarray(lps)).all() and (np.asarray(lps) <= 0).all()
    # position 0: reported logprob must be the filtered prefill
    # distribution's log-prob of the emitted token
    logits0, _ = prefill(params, prompt, init_kv_cache(CFG_T, 1, 64),
                         CFG_T, fresh=True)
    ld0 = jax.nn.log_softmax(filter_logits(logits0, 0.9, 40, None), -1)
    np.testing.assert_allclose(float(lps[0, 0]),
                               float(ld0[0, toks[0, 0]]), atol=1e-4)

    # eos zeroing: post-eos logprobs are exactly 0.0
    plain = generate(params, prompt, CFG_T, max_new_tokens=12, max_len=256)
    eos = int(plain[0, 2])
    toks_e, lps_e, _ = speculative_generate(
        params, draft, prompt, CFG_T, CFG_D, max_new_tokens=12, spec_k=3,
        eos_id=eos, return_logprobs=True)
    after = np.cumsum(np.asarray(toks_e[0]) == eos) > 1
    first = int(np.argmax(np.asarray(toks_e[0]) == eos))
    assert (np.asarray(lps_e[0])[first + 1:] == 0.0).all()
